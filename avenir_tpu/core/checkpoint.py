"""Checkpoint/resume for streaming folds: durable mid-stream state.

MapReduce recovers a lost task by re-executing it from durable
intermediate state (Dean & Ghemawat, OSDI 2004); the rebuilt streaming
ingest (core.pipeline / core.multiscan) holds ALL of its intermediate
state in memory — the device-resident fold carry, the host stream state
(vocabularies, moment accumulators), and the read position — so a crash
mid-file previously meant starting over.  This module makes a scan
restartable: every ``checkpoint.interval.chunks`` folded chunks the
driver writes a sidecar checkpoint holding

- the BYTE OFFSET of the last folded chunk's end (chunk boundaries are
  deterministic — ``pipeline.row_chunk_ends`` over the whole buffer —
  so a resumed run re-derives the identical chunking and skips whole
  chunks up to the offset),
- the fold carry pulled to host (``jax.block_until_ready`` then
  ``np.asarray`` per leaf),
- the pickled host stream state captured ON THE PRODUCER at the moment
  the checkpointed chunk was produced (encoder vocabularies, moment
  accumulators, quarantine budget counts, multiscan per-spec state +
  withdrawal list) — produce-side capture keeps it consistent with the
  carry even when the prefetch worker runs ahead of the fold,
- an input fingerprint + the chunking parameters, validated at load so
  a checkpoint can never resume against a different file or chunk
  geometry (that would silently break byte parity).

``--resume`` on the CLI (``checkpoint.resume=true``) loads the sidecar
and restarts mid-file; a resumed run is byte-identical to an
uninterrupted one (asserted in tests/test_resilience.py at mesh=1 and
8-way).  A successful run deletes its sidecar, so stale checkpoints
never shadow a completed job.

Generations + corruption fallback (the self-healing half): each save
ROTATES the previous sidecar to ``<path>.1`` (then ``.2``, ...) keeping
the last ``checkpoint.keep`` generations, and ``load`` walks them
newest→oldest — a truncated/corrupt sidecar (surfaced as
:class:`CheckpointCorrupt`, never a raw pickle traceback) falls back to
the next older generation, and when every generation is corrupt the
``checkpoint.fallback`` policy decides: ``cold`` (the default) degrades
to a cold start — a full re-run, trivially byte-identical — while
``fail`` raises for operators who would rather investigate than recount.
Resuming from an OLDER generation just replays more chunks; the fold is
deterministic, so output stays byte-identical (asserted in
tests/test_chaos.py under seeded kill+corrupt schedules).  Recovery
events ride the telemetry registry's ``Durability/*`` counters.

Config surface:

- ``checkpoint.interval.chunks`` — checkpoint every N folded chunks
  (absent/0 = checkpointing disabled)
- ``checkpoint.path``            — sidecar path (default ``<out>.ckpt``)
- ``checkpoint.resume``          — resume from the sidecar if present
  (the CLI ``--resume`` flag sets this)
- ``checkpoint.keep``            — sidecar generations kept (default 2)
- ``checkpoint.fallback``        — ``cold`` | ``fail`` when every
  generation is corrupt (default ``cold``)
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional

from . import faultinject

KEY_INTERVAL = "checkpoint.interval.chunks"
KEY_PATH = "checkpoint.path"
KEY_RESUME = "checkpoint.resume"
KEY_KEEP = "checkpoint.keep"
KEY_FALLBACK = "checkpoint.fallback"

DEFAULT_KEEP = 2
FALLBACK_COLD = "cold"
FALLBACK_FAIL = "fail"

CKPT_VERSION = 1
_FP_HASH_BYTES = 1 << 20       # fingerprint hashes the first 1 MB


class CheckpointMismatch(RuntimeError):
    """The sidecar does not match this run (different input file or
    chunking parameters): resuming would silently break byte parity, so
    fail fast and tell the user to re-run without ``--resume``."""


class CheckpointCorrupt(RuntimeError):
    """A sidecar failed to unpickle (truncated write, disk corruption).
    ``load`` walks older generations past it; this surfaces only under
    ``checkpoint.fallback=fail`` with every generation corrupt."""


def _durability_counters():
    """The process-global ``Durability`` counter group (shared accessor
    in core.io, so recovery events from both layers land in the same
    telemetry registry and ``--metrics-out`` exports them)."""
    from .io import _durability_counters as _dc
    return _dc()


def _fallback_from_config(config) -> str:
    mode = (config.get(KEY_FALLBACK, FALLBACK_COLD)
            or FALLBACK_COLD).strip().lower()
    if mode not in (FALLBACK_COLD, FALLBACK_FAIL):
        raise ValueError(
            f"{KEY_FALLBACK}={mode!r}: use {FALLBACK_COLD} or "
            f"{FALLBACK_FAIL}")
    return mode


def generation_paths(path: str, keep: int) -> List[str]:
    """Sidecar paths newest→oldest: ``path``, ``path.1``, ..."""
    return [path] + [f"{path}.{i}" for i in range(1, max(1, int(keep)))]


def _rotate_generations(path: str, keep: int) -> None:
    """Shift existing sidecar generations one slot older before a new
    save lands at ``path`` (``keep=1`` keeps none — the pre-generation
    behavior)."""
    gens = generation_paths(path, keep)
    for i in range(len(gens) - 1, 0, -1):
        if os.path.exists(gens[i - 1]):
            os.replace(gens[i - 1], gens[i])


def _load_payload(path: str) -> Dict[str, Any]:
    """Unpickle one sidecar, surfacing every corruption mode (truncated
    file, garbled bytes, wrong object shape) as
    :class:`CheckpointCorrupt` instead of a raw pickle traceback."""
    try:
        with open(path, "rb") as fh:
            payload = pickle.load(fh)
    except (OSError, pickle.PickleError, EOFError, AttributeError,
            ImportError, IndexError, MemoryError, UnicodeDecodeError,
            ValueError) as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable "
            f"({type(e).__name__}: {e})") from None
    if not isinstance(payload, dict):
        raise CheckpointCorrupt(
            f"checkpoint {path} does not hold a payload dict "
            f"({type(payload).__name__})")
    return payload


def _maybe_corrupt_sidecar(path: str, save_index: int) -> None:
    """The ``ckpt_corrupt`` fault point: truncate the just-written
    sidecar in place (crash mid-checkpoint-write / disk corruption,
    deterministic per save index — the generation-fallback test)."""
    fi = faultinject.get_injector()
    if fi is None or fi.armed("ckpt_corrupt", index=save_index) is None:
        return
    size = os.path.getsize(path)
    with open(path, "rb+") as fh:
        fh.truncate(max(size // 2, 1))


def input_fingerprint(path: str) -> Dict[str, Any]:
    """A cheap identity for the input file/dir: per-part (name, size)
    plus a hash of the first part's head — enough to catch "resumed
    against a different file" without re-reading gigabytes."""
    from .io import _input_files

    files = _input_files(path)
    parts = [(os.path.basename(fp), os.path.getsize(fp)) for fp in files]
    h = hashlib.sha1()
    if files:
        with open(files[0], "rb") as fh:
            h.update(fh.read(_FP_HASH_BYTES))
    return {"parts": parts, "head_sha1": h.hexdigest()}


class CarryNotPortable(ValueError):
    """A fold carry offered for checkpointing holds a non-host leaf
    (e.g. a live ``jax.Array``): pickling it would bake device/topology
    state into the sidecar, so a resume on a different host or pod
    shape could not replay it.  Raised at SAVE time naming the leaf —
    the runtime twin of the static carry-portability rule."""


def assert_portable_carry(carry: Any, context: str = "carry") -> Any:
    """Validate that every leaf of a carry pytree is host-portable
    (numpy arrays / Python scalars / None): the save path's guard that
    a checkpoint written on this host resumes on ANY host."""
    import numpy as _np

    def walk(obj, path):
        if obj is None or isinstance(obj, (bool, int, float, str, bytes,
                                           _np.generic, _np.ndarray)):
            return
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(v, f"{path}[{k!r}]")
            return
        if isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(v, f"{path}[{i}]")
            return
        raise CarryNotPortable(
            f"{context}: non-host leaf {type(obj).__module__}."
            f"{type(obj).__name__} at {path} — materialize to host "
            f"numpy before checkpointing (device arrays bake host "
            f"topology into the sidecar)")

    walk(carry, context)
    return carry


class CheckpointToken:
    """One checkpoint-due marker, created on the PRODUCER side: the
    chunk index/end-offset plus the host stream state pickled at capture
    time (so later producer-side mutation cannot leak in).  The consumer
    attaches the blocked fold carry and hands it to ``save``."""

    __slots__ = ("chunk_index", "offset", "state_bytes")

    def __init__(self, chunk_index: int, offset: int, state_obj: Any):
        self.chunk_index = int(chunk_index)
        self.offset = int(offset)
        self.state_bytes = pickle.dumps(state_obj,
                                        protocol=pickle.HIGHEST_PROTOCOL)


class StreamCheckpointer:
    """Sidecar writer/loader for one streaming scan."""

    def __init__(self, path: str, interval: int, kind: str, in_path: str,
                 params: Optional[Dict[str, Any]] = None,
                 resume: bool = False, keep: int = DEFAULT_KEEP,
                 fallback: str = FALLBACK_COLD):
        if interval < 1:
            raise ValueError(f"{KEY_INTERVAL} must be >= 1: {interval}")
        self.path = path
        self.interval = int(interval)
        self.kind = kind
        self.in_path = in_path
        self.params = dict(params or {})
        self.resume = bool(resume)
        self.keep = max(1, int(keep))
        self.fallback = fallback
        self.saves = 0
        self._fp = None

    def _fingerprint(self) -> Dict[str, Any]:
        """The input fingerprint, computed once per checkpointer: the
        input cannot change mid-scan (the whole buffer was read up
        front), and re-hashing a megabyte on every periodic save would
        be a measurable per-checkpoint tax."""
        if self._fp is None:
            self._fp = input_fingerprint(self.in_path)
        return self._fp

    @classmethod
    def from_config(cls, config, kind: str, in_path: str, default_path: str,
                    params: Optional[Dict[str, Any]] = None
                    ) -> Optional["StreamCheckpointer"]:
        """The config-driven constructor: None when checkpointing is off
        AND no resume was requested (``--resume`` alone implies the
        default interval, so an interrupted checkpointed run can resume
        without repeating the interval key)."""
        interval = config.get_int(KEY_INTERVAL, 0)
        resume = config.get_boolean(KEY_RESUME, False)
        if interval <= 0 and not resume:
            return None
        return cls(config.get(KEY_PATH, default_path),
                   max(interval, 1) if interval > 0 else 8,
                   kind, in_path, params=params, resume=resume,
                   keep=config.get_int(KEY_KEEP, DEFAULT_KEEP),
                   fallback=_fallback_from_config(config))

    # -- producer side -----------------------------------------------------
    def due(self, chunk_index: int) -> bool:
        return (chunk_index + 1) % self.interval == 0

    def token(self, chunk_index: int, offset: int,
              state_obj: Any) -> CheckpointToken:
        return CheckpointToken(chunk_index, offset, state_obj)

    # -- consumer side -----------------------------------------------------
    def save(self, token: CheckpointToken, carry: Any,
             extra: Optional[Dict[str, Any]] = None) -> None:
        """Atomically write the sidecar (tmp + rename: a crash mid-save
        leaves the previous checkpoint intact), rotating the previous
        sidecar one generation older first (``checkpoint.keep``)."""
        payload = {
            "version": CKPT_VERSION,
            "kind": self.kind,
            "fingerprint": self._fingerprint(),
            "params": self.params,
            "chunk_index": token.chunk_index,
            "offset": token.offset,
            "state": token.state_bytes,
            "carry": assert_portable_carry(
                carry, context=f"{self.kind} checkpoint carry"),
            "extra": dict(extra or {}),
        }
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".ckpt-", dir=d)
        try:
            with os.fdopen(fd, "wb") as fh:
                pickle.dump(payload, fh,
                            protocol=pickle.HIGHEST_PROTOCOL)
            _rotate_generations(self.path, self.keep)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _maybe_corrupt_sidecar(self.path, self.saves)
        self.saves += 1

    # -- resume side -------------------------------------------------------
    def _validate(self, path: str,
                  payload: Dict[str, Any]) -> Dict[str, Any]:
        if payload.get("version") != CKPT_VERSION:
            raise CheckpointMismatch(
                f"checkpoint {path}: version "
                f"{payload.get('version')} != {CKPT_VERSION}")
        if payload.get("kind") != self.kind:
            raise CheckpointMismatch(
                f"checkpoint {path}: kind {payload.get('kind')!r} "
                f"does not match this job ({self.kind!r})")
        fp = input_fingerprint(self.in_path)
        if payload.get("fingerprint") != fp:
            raise CheckpointMismatch(
                f"checkpoint {path} was written against a different "
                f"input than {self.in_path!r} — re-run without --resume")
        if payload.get("params") != self.params:
            raise CheckpointMismatch(
                f"checkpoint {path}: chunking/config params changed "
                f"({payload.get('params')} != {self.params}) — resuming "
                f"would break byte parity; re-run without --resume")
        try:
            payload["state"] = pickle.loads(payload["state"])
        except (KeyError, TypeError, pickle.PickleError, EOFError,
                AttributeError, ImportError, IndexError,
                UnicodeDecodeError, ValueError) as e:
            raise CheckpointCorrupt(
                f"checkpoint {path}: host stream state unreadable "
                f"({type(e).__name__}: {e})") from None
        return payload

    def load(self) -> Optional[Dict[str, Any]]:
        """The newest VALID sidecar generation's payload with ``state``
        unpickled, or None when no sidecar exists (resume degrades to a
        full run — trivially byte-identical).

        A corrupt generation (truncated save, disk damage) falls back to
        the next older one — resuming from an older offset only replays
        more chunks, output stays byte-identical.  Every generation
        corrupt applies ``checkpoint.fallback``: ``cold`` degrades to a
        cold start (None), ``fail`` raises :class:`CheckpointCorrupt`.
        A version/kind/fingerprint/params MISMATCH still raises
        :class:`CheckpointMismatch` — that is a config error, and an
        older generation of the same wrong run cannot repair it."""
        counters = _durability_counters()
        corrupt: List[str] = []
        for i, path in enumerate(generation_paths(self.path, self.keep)):
            if not os.path.exists(path):
                continue
            try:
                payload = self._validate(path, _load_payload(path))
            except CheckpointCorrupt as e:
                counters.incr("Durability", "Checkpoint corrupt")
                corrupt.append(str(e))
                continue
            if corrupt:
                counters.incr("Durability", "Generation fallbacks")
            return payload
        if not corrupt:
            return None                 # no sidecar at all: full run
        if self.fallback == FALLBACK_FAIL:
            raise CheckpointCorrupt(
                f"every checkpoint generation of {self.path} is corrupt "
                f"({'; '.join(corrupt)}) and {KEY_FALLBACK}="
                f"{FALLBACK_FAIL}")
        counters.incr("Durability", "Cold starts")
        return None

    def complete(self) -> None:
        """Remove every sidecar generation after a successful run (stale
        checkpoints must never shadow a completed job's output)."""
        for path in generation_paths(self.path, self.keep):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# stream-offset checkpointing (the exactly-once feedback sidecar)
# ---------------------------------------------------------------------------

OFFSET_CKPT_VERSION = 1


class OffsetCheckpointer:
    """Offset + fold-carry sidecar for an unbounded stream consumer
    (the ``avenir_tpu/stream`` feedback consumer's exactly-once hinge).

    A file-scan checkpoint fingerprints its input file; a stream has no
    file, so identity is the DECLARED stream identity (stream key,
    consumer group, tenant/arm manifest, posterior dtype) — a sidecar
    written against a different stream or manifest raises
    :class:`CheckpointMismatch` instead of silently resuming the wrong
    posterior.  Everything else — atomic tmp+rename saves, generation
    rotation (``checkpoint.keep``), newest→oldest corruption fallback
    surfacing :class:`CheckpointCorrupt`, the ``checkpoint.fallback``
    policy, and the ``ckpt_corrupt`` fault point — is the same machinery
    :class:`StreamCheckpointer` uses, so the chaos soak exercises one
    durability layer, not two.

    The exactly-once contract: the LAST-APPLIED stream entry id and the
    fold carry persist in ONE payload, so a kill anywhere leaves a
    consistent (offset, carry) pair — resume re-reads the stream's
    pending entries and the offset watermark dedupes anything at or
    below it (duplicate delivery), while anything above it was never
    folded into this carry and applies exactly once.  Falling back a
    generation just lowers the watermark: the extra entries replay, and
    the integer-exact fold makes the result byte-identical.
    """

    def __init__(self, path: str, interval_events: int,
                 identity: Dict[str, Any], resume: bool = False,
                 keep: int = DEFAULT_KEEP, fallback: str = FALLBACK_COLD):
        if interval_events < 1:
            raise ValueError(
                f"checkpoint interval must be >= 1 event: {interval_events}")
        self.path = path
        self.interval = int(interval_events)
        self.identity = dict(identity)
        self.resume = bool(resume)
        self.keep = max(1, int(keep))
        self.fallback = fallback
        self.saves = 0

    @classmethod
    def from_config(cls, config, interval_events: int,
                    identity: Dict[str, Any],
                    default_path: str) -> Optional["OffsetCheckpointer"]:
        """None when checkpointing is off AND no resume was requested
        (mirrors :meth:`StreamCheckpointer.from_config`)."""
        resume = config.get_boolean(KEY_RESUME, False)
        if interval_events <= 0 and not resume:
            return None
        return cls(config.get(KEY_PATH, default_path),
                   interval_events if interval_events > 0 else 256,
                   identity, resume=resume,
                   keep=config.get_int(KEY_KEEP, DEFAULT_KEEP),
                   fallback=_fallback_from_config(config))

    def save(self, offset: str, carry: Any, state: Dict[str, Any]) -> None:
        """Atomically write (offset, carry, consumer state) as one
        sidecar, rotating the previous generation older first."""
        payload = {
            "version": OFFSET_CKPT_VERSION,
            "identity": self.identity,
            "offset": str(offset),
            "carry": assert_portable_carry(
                carry, context="stream-offset checkpoint carry"),
            "state": pickle.dumps(dict(state),
                                  protocol=pickle.HIGHEST_PROTOCOL),
        }
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".ckpt-", dir=d)
        try:
            with os.fdopen(fd, "wb") as fh:
                pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
            _rotate_generations(self.path, self.keep)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _maybe_corrupt_sidecar(self.path, self.saves)
        self.saves += 1

    def _validate(self, path: str,
                  payload: Dict[str, Any]) -> Dict[str, Any]:
        if payload.get("version") != OFFSET_CKPT_VERSION:
            raise CheckpointMismatch(
                f"stream checkpoint {path}: version "
                f"{payload.get('version')} != {OFFSET_CKPT_VERSION}")
        if payload.get("identity") != self.identity:
            raise CheckpointMismatch(
                f"stream checkpoint {path} was written against a "
                f"different stream identity ({payload.get('identity')} "
                f"!= {self.identity}) — re-run without --resume")
        try:
            payload["state"] = pickle.loads(payload["state"])
        except (KeyError, TypeError, pickle.PickleError, EOFError,
                AttributeError, ImportError, IndexError,
                UnicodeDecodeError, ValueError) as e:
            raise CheckpointCorrupt(
                f"stream checkpoint {path}: consumer state unreadable "
                f"({type(e).__name__}: {e})") from None
        return payload

    def load(self) -> Optional[Dict[str, Any]]:
        """The newest valid generation's (offset, carry, state), walking
        past corrupt generations exactly like
        :meth:`StreamCheckpointer.load` (an older generation's lower
        watermark just replays more pending entries — byte-identical)."""
        counters = _durability_counters()
        corrupt: List[str] = []
        for path in generation_paths(self.path, self.keep):
            if not os.path.exists(path):
                continue
            try:
                payload = self._validate(path, _load_payload(path))
            except CheckpointCorrupt as e:
                counters.incr("Durability", "Checkpoint corrupt")
                corrupt.append(str(e))
                continue
            if corrupt:
                counters.incr("Durability", "Generation fallbacks")
            return payload
        if not corrupt:
            return None
        if self.fallback == FALLBACK_FAIL:
            raise CheckpointCorrupt(
                f"every stream checkpoint generation of {self.path} is "
                f"corrupt ({'; '.join(corrupt)}) and {KEY_FALLBACK}="
                f"{FALLBACK_FAIL}")
        counters.incr("Durability", "Cold starts")
        return None

    def complete(self) -> None:
        for path in generation_paths(self.path, self.keep):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# stage-granularity checkpointing (the core.dag workflow sidecar)
# ---------------------------------------------------------------------------

WF_CKPT_VERSION = 1


class WorkflowCheckpointer:
    """Stage-completion sidecar for a core.dag workflow run.

    After every completed stage the workflow records the stage's params
    key (a hash of its resolved config + class + paths), a fingerprint
    of EVERY input artifact it consumed — the declared input plus each
    ``@<stage>``-referenced dependency artifact — and its OUTPUT
    fingerprint, then atomically rewrites the sidecar.  A ``--resume``
    run skips a stage only when all three still validate — the stage's
    config is unchanged, every input file (including a dependency
    artifact an upstream stage may have REWRITTEN on this resume) is
    the one it consumed, and its outputs are still on disk intact —
    otherwise the stage re-runs (and its own mid-scan
    :class:`StreamCheckpointer` sidecar, if one survived the kill,
    restarts it mid-file).  A successful workflow deletes the sidecar.
    """

    def __init__(self, path: str, in_path: str, resume: bool = False,
                 keep: int = DEFAULT_KEEP, fallback: str = FALLBACK_COLD):
        self.path = path
        self.in_path = in_path
        self.resume = bool(resume)
        self.keep = max(1, int(keep))
        self.fallback = fallback
        #: set when a corrupt sidecar degraded this resume to a fresh
        #: run — the caller (core.dag) logs it
        self.degraded_reason: Optional[str] = None
        self._stages: Dict[str, Dict[str, Any]] = {}
        if resume:
            self._load_generations()

    @classmethod
    def from_config(cls, config, path: str, in_path: str,
                    resume: bool) -> "WorkflowCheckpointer":
        return cls(path, in_path, resume=resume,
                   keep=config.get_int(KEY_KEEP, DEFAULT_KEEP),
                   fallback=_fallback_from_config(config))

    def _load_generations(self) -> None:
        """Walk the sidecar generations newest→oldest; a corrupt sidecar
        (the bare ``pickle.load`` that used to crash ``dag --resume``
        before any fallback could run) falls back to an older generation,
        and with none valid the run degrades to a FRESH workflow (every
        stage re-runs — always correct) under ``checkpoint.fallback=cold``
        with a ``Durability / Workflow sidecar corrupt`` warning counter,
        or raises under ``fail``."""
        counters = _durability_counters()
        corrupt: List[str] = []
        for path in generation_paths(self.path, self.keep):
            if not os.path.exists(path):
                continue
            try:
                payload = _load_payload(path)
                stages = payload.get("stages")
                if not isinstance(stages, dict):
                    raise CheckpointCorrupt(
                        f"workflow checkpoint {path} has no stages table")
            except CheckpointCorrupt as e:
                counters.incr("Durability", "Workflow sidecar corrupt")
                corrupt.append(str(e))
                continue
            if payload.get("version") != WF_CKPT_VERSION:
                raise CheckpointMismatch(
                    f"workflow checkpoint {path}: version "
                    f"{payload.get('version')} != {WF_CKPT_VERSION}")
            if payload.get("fingerprint") != input_fingerprint(
                    self.in_path):
                raise CheckpointMismatch(
                    f"workflow checkpoint {path} was written against a "
                    f"different input than {self.in_path!r} — re-run "
                    f"without --resume")
            if corrupt:
                counters.incr("Durability", "Generation fallbacks")
            self._stages = stages
            return
        if not corrupt:
            return                      # no sidecar: fresh run, as ever
        if self.fallback == FALLBACK_FAIL:
            raise CheckpointCorrupt(
                f"every workflow checkpoint generation of {self.path} is "
                f"corrupt ({'; '.join(corrupt)}) and {KEY_FALLBACK}="
                f"{FALLBACK_FAIL}")
        counters.incr("Durability", "Cold starts")
        self.degraded_reason = (
            f"workflow checkpoint {self.path} corrupt in every "
            f"generation — degrading to a fresh run (all stages re-run)")

    @staticmethod
    def params_key(obj: Any) -> str:
        import json
        return hashlib.sha1(
            json.dumps(obj, sort_keys=True, default=str).encode()
        ).hexdigest()

    def _fingerprint_ok(self, path: str, recorded) -> bool:
        from .io import TornArtifactError
        try:
            return input_fingerprint(path) == recorded
        except OSError:
            return False
        except TornArtifactError:
            # a torn input/output artifact can never validate a skip —
            # the stage re-runs and republishes it (self-healing)
            return False

    def stage_done(self, sid: str, params_key: str,
                   in_paths: Dict[str, str],
                   out_paths: Dict[str, str]) -> bool:
        """True when ``sid`` completed under the SAME params and EVERY
        recorded input/output still matches its on-disk fingerprint —
        the resume-time skip test.  ``in_paths`` carries the declared
        input plus every dependency artifact path (an upstream stage
        that re-ran and rewrote its artifact at the same path changes
        that fingerprint, so this consumer re-runs too).  Outputs that
        were memory-only (no file sink) record an empty fingerprint and
        validate trivially; a memory-only INPUT never validates — the
        artifact died with the killed process."""
        rec = self._stages.get(sid)
        if rec is None or rec["params"] != params_key:
            return False
        for label, p in in_paths.items():
            want = rec["inputs"].get(label)
            if want is None or want == {}:
                return False
            if not self._fingerprint_ok(p, want):
                return False
        for label, p in out_paths.items():
            want = rec["outputs"].get(label)
            if want is None:
                return False
            if want != {} and not self._fingerprint_ok(p, want):
                return False
        return True

    def record(self, sid: str, params_key: str, in_paths: Dict[str, str],
               out_paths: Dict[str, str]) -> None:
        """Record ``sid`` complete and atomically rewrite the sidecar."""
        outputs = {}
        for label, p in out_paths.items():
            outputs[label] = (input_fingerprint(p)
                              if os.path.exists(p) else {})
        self._stages[sid] = {
            "params": params_key,
            # {} when an input was a memory-only artifact: such a
            # stage can never be skipped on resume (see stage_done)
            "inputs": {label: (input_fingerprint(p)
                               if os.path.exists(p) else {})
                       for label, p in in_paths.items()},
            "outputs": outputs,
        }
        payload = {"version": WF_CKPT_VERSION,
                   "fingerprint": input_fingerprint(self.in_path),
                   "stages": self._stages}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".wfckpt-", dir=d)
        try:
            with os.fdopen(fd, "wb") as fh:
                pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
            _rotate_generations(self.path, self.keep)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _maybe_corrupt_sidecar(self.path, len(self._stages) - 1)

    def complete(self) -> None:
        for path in generation_paths(self.path, self.keep):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

"""Checkpoint/resume for streaming folds: durable mid-stream state.

MapReduce recovers a lost task by re-executing it from durable
intermediate state (Dean & Ghemawat, OSDI 2004); the rebuilt streaming
ingest (core.pipeline / core.multiscan) holds ALL of its intermediate
state in memory — the device-resident fold carry, the host stream state
(vocabularies, moment accumulators), and the read position — so a crash
mid-file previously meant starting over.  This module makes a scan
restartable: every ``checkpoint.interval.chunks`` folded chunks the
driver writes a sidecar checkpoint holding

- the BYTE OFFSET of the last folded chunk's end (chunk boundaries are
  deterministic — ``pipeline.row_chunk_ends`` over the whole buffer —
  so a resumed run re-derives the identical chunking and skips whole
  chunks up to the offset),
- the fold carry pulled to host (``jax.block_until_ready`` then
  ``np.asarray`` per leaf),
- the pickled host stream state captured ON THE PRODUCER at the moment
  the checkpointed chunk was produced (encoder vocabularies, moment
  accumulators, quarantine budget counts, multiscan per-spec state +
  withdrawal list) — produce-side capture keeps it consistent with the
  carry even when the prefetch worker runs ahead of the fold,
- an input fingerprint + the chunking parameters, validated at load so
  a checkpoint can never resume against a different file or chunk
  geometry (that would silently break byte parity).

``--resume`` on the CLI (``checkpoint.resume=true``) loads the sidecar
and restarts mid-file; a resumed run is byte-identical to an
uninterrupted one (asserted in tests/test_resilience.py at mesh=1 and
8-way).  A successful run deletes its sidecar, so stale checkpoints
never shadow a completed job.

Config surface:

- ``checkpoint.interval.chunks`` — checkpoint every N folded chunks
  (absent/0 = checkpointing disabled)
- ``checkpoint.path``            — sidecar path (default ``<out>.ckpt``)
- ``checkpoint.resume``          — resume from the sidecar if present
  (the CLI ``--resume`` flag sets this)
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from typing import Any, Dict, Optional

KEY_INTERVAL = "checkpoint.interval.chunks"
KEY_PATH = "checkpoint.path"
KEY_RESUME = "checkpoint.resume"

CKPT_VERSION = 1
_FP_HASH_BYTES = 1 << 20       # fingerprint hashes the first 1 MB


class CheckpointMismatch(RuntimeError):
    """The sidecar does not match this run (different input file or
    chunking parameters): resuming would silently break byte parity, so
    fail fast and tell the user to re-run without ``--resume``."""


def input_fingerprint(path: str) -> Dict[str, Any]:
    """A cheap identity for the input file/dir: per-part (name, size)
    plus a hash of the first part's head — enough to catch "resumed
    against a different file" without re-reading gigabytes."""
    from .io import _input_files

    files = _input_files(path)
    parts = [(os.path.basename(fp), os.path.getsize(fp)) for fp in files]
    h = hashlib.sha1()
    if files:
        with open(files[0], "rb") as fh:
            h.update(fh.read(_FP_HASH_BYTES))
    return {"parts": parts, "head_sha1": h.hexdigest()}


class CheckpointToken:
    """One checkpoint-due marker, created on the PRODUCER side: the
    chunk index/end-offset plus the host stream state pickled at capture
    time (so later producer-side mutation cannot leak in).  The consumer
    attaches the blocked fold carry and hands it to ``save``."""

    __slots__ = ("chunk_index", "offset", "state_bytes")

    def __init__(self, chunk_index: int, offset: int, state_obj: Any):
        self.chunk_index = int(chunk_index)
        self.offset = int(offset)
        self.state_bytes = pickle.dumps(state_obj,
                                        protocol=pickle.HIGHEST_PROTOCOL)


class StreamCheckpointer:
    """Sidecar writer/loader for one streaming scan."""

    def __init__(self, path: str, interval: int, kind: str, in_path: str,
                 params: Optional[Dict[str, Any]] = None,
                 resume: bool = False):
        if interval < 1:
            raise ValueError(f"{KEY_INTERVAL} must be >= 1: {interval}")
        self.path = path
        self.interval = int(interval)
        self.kind = kind
        self.in_path = in_path
        self.params = dict(params or {})
        self.resume = bool(resume)
        self.saves = 0
        self._fp = None

    def _fingerprint(self) -> Dict[str, Any]:
        """The input fingerprint, computed once per checkpointer: the
        input cannot change mid-scan (the whole buffer was read up
        front), and re-hashing a megabyte on every periodic save would
        be a measurable per-checkpoint tax."""
        if self._fp is None:
            self._fp = input_fingerprint(self.in_path)
        return self._fp

    @classmethod
    def from_config(cls, config, kind: str, in_path: str, default_path: str,
                    params: Optional[Dict[str, Any]] = None
                    ) -> Optional["StreamCheckpointer"]:
        """The config-driven constructor: None when checkpointing is off
        AND no resume was requested (``--resume`` alone implies the
        default interval, so an interrupted checkpointed run can resume
        without repeating the interval key)."""
        interval = config.get_int(KEY_INTERVAL, 0)
        resume = config.get_boolean(KEY_RESUME, False)
        if interval <= 0 and not resume:
            return None
        return cls(config.get(KEY_PATH, default_path),
                   max(interval, 1) if interval > 0 else 8,
                   kind, in_path, params=params, resume=resume)

    # -- producer side -----------------------------------------------------
    def due(self, chunk_index: int) -> bool:
        return (chunk_index + 1) % self.interval == 0

    def token(self, chunk_index: int, offset: int,
              state_obj: Any) -> CheckpointToken:
        return CheckpointToken(chunk_index, offset, state_obj)

    # -- consumer side -----------------------------------------------------
    def save(self, token: CheckpointToken, carry: Any,
             extra: Optional[Dict[str, Any]] = None) -> None:
        """Atomically write the sidecar (tmp + rename: a crash mid-save
        leaves the previous checkpoint intact)."""
        payload = {
            "version": CKPT_VERSION,
            "kind": self.kind,
            "fingerprint": self._fingerprint(),
            "params": self.params,
            "chunk_index": token.chunk_index,
            "offset": token.offset,
            "state": token.state_bytes,
            "carry": carry,
            "extra": dict(extra or {}),
        }
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".ckpt-", dir=d)
        try:
            with os.fdopen(fd, "wb") as fh:
                pickle.dump(payload, fh,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.saves += 1

    # -- resume side -------------------------------------------------------
    def load(self) -> Optional[Dict[str, Any]]:
        """The validated sidecar payload with ``state`` unpickled, or
        None when no sidecar exists (resume degrades to a full run —
        trivially byte-identical).  Raises :class:`CheckpointMismatch`
        on a version/kind/fingerprint/params mismatch."""
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as fh:
            payload = pickle.load(fh)
        if payload.get("version") != CKPT_VERSION:
            raise CheckpointMismatch(
                f"checkpoint {self.path}: version "
                f"{payload.get('version')} != {CKPT_VERSION}")
        if payload.get("kind") != self.kind:
            raise CheckpointMismatch(
                f"checkpoint {self.path}: kind {payload.get('kind')!r} "
                f"does not match this job ({self.kind!r})")
        fp = input_fingerprint(self.in_path)
        if payload.get("fingerprint") != fp:
            raise CheckpointMismatch(
                f"checkpoint {self.path} was written against a different "
                f"input than {self.in_path!r} — re-run without --resume")
        if payload.get("params") != self.params:
            raise CheckpointMismatch(
                f"checkpoint {self.path}: chunking/config params changed "
                f"({payload.get('params')} != {self.params}) — resuming "
                f"would break byte parity; re-run without --resume")
        payload["state"] = pickle.loads(payload["state"])
        return payload

    def complete(self) -> None:
        """Remove the sidecar after a successful run (stale checkpoints
        must never shadow a completed job's output)."""
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


# ---------------------------------------------------------------------------
# stage-granularity checkpointing (the core.dag workflow sidecar)
# ---------------------------------------------------------------------------

WF_CKPT_VERSION = 1


class WorkflowCheckpointer:
    """Stage-completion sidecar for a core.dag workflow run.

    After every completed stage the workflow records the stage's params
    key (a hash of its resolved config + class + paths), a fingerprint
    of EVERY input artifact it consumed — the declared input plus each
    ``@<stage>``-referenced dependency artifact — and its OUTPUT
    fingerprint, then atomically rewrites the sidecar.  A ``--resume``
    run skips a stage only when all three still validate — the stage's
    config is unchanged, every input file (including a dependency
    artifact an upstream stage may have REWRITTEN on this resume) is
    the one it consumed, and its outputs are still on disk intact —
    otherwise the stage re-runs (and its own mid-scan
    :class:`StreamCheckpointer` sidecar, if one survived the kill,
    restarts it mid-file).  A successful workflow deletes the sidecar.
    """

    def __init__(self, path: str, in_path: str, resume: bool = False):
        self.path = path
        self.in_path = in_path
        self.resume = bool(resume)
        self._stages: Dict[str, Dict[str, Any]] = {}
        if resume and os.path.exists(path):
            with open(path, "rb") as fh:
                payload = pickle.load(fh)
            if payload.get("version") != WF_CKPT_VERSION:
                raise CheckpointMismatch(
                    f"workflow checkpoint {path}: version "
                    f"{payload.get('version')} != {WF_CKPT_VERSION}")
            if payload.get("fingerprint") != input_fingerprint(in_path):
                raise CheckpointMismatch(
                    f"workflow checkpoint {path} was written against a "
                    f"different input than {in_path!r} — re-run without "
                    f"--resume")
            self._stages = payload["stages"]

    @staticmethod
    def params_key(obj: Any) -> str:
        import json
        return hashlib.sha1(
            json.dumps(obj, sort_keys=True, default=str).encode()
        ).hexdigest()

    def _fingerprint_ok(self, path: str, recorded) -> bool:
        try:
            return input_fingerprint(path) == recorded
        except OSError:
            return False

    def stage_done(self, sid: str, params_key: str,
                   in_paths: Dict[str, str],
                   out_paths: Dict[str, str]) -> bool:
        """True when ``sid`` completed under the SAME params and EVERY
        recorded input/output still matches its on-disk fingerprint —
        the resume-time skip test.  ``in_paths`` carries the declared
        input plus every dependency artifact path (an upstream stage
        that re-ran and rewrote its artifact at the same path changes
        that fingerprint, so this consumer re-runs too).  Outputs that
        were memory-only (no file sink) record an empty fingerprint and
        validate trivially; a memory-only INPUT never validates — the
        artifact died with the killed process."""
        rec = self._stages.get(sid)
        if rec is None or rec["params"] != params_key:
            return False
        for label, p in in_paths.items():
            want = rec["inputs"].get(label)
            if want is None or want == {}:
                return False
            if not self._fingerprint_ok(p, want):
                return False
        for label, p in out_paths.items():
            want = rec["outputs"].get(label)
            if want is None:
                return False
            if want != {} and not self._fingerprint_ok(p, want):
                return False
        return True

    def record(self, sid: str, params_key: str, in_paths: Dict[str, str],
               out_paths: Dict[str, str]) -> None:
        """Record ``sid`` complete and atomically rewrite the sidecar."""
        outputs = {}
        for label, p in out_paths.items():
            outputs[label] = (input_fingerprint(p)
                              if os.path.exists(p) else {})
        self._stages[sid] = {
            "params": params_key,
            # {} when an input was a memory-only artifact: such a
            # stage can never be skipped on resume (see stage_done)
            "inputs": {label: (input_fingerprint(p)
                               if os.path.exists(p) else {})
                       for label, p in in_paths.items()},
            "outputs": outputs,
        }
        payload = {"version": WF_CKPT_VERSION,
                   "fingerprint": input_fingerprint(self.in_path),
                   "stages": self._stages}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".wfckpt-", dir=d)
        try:
            with os.fdopen(fd, "wb") as fh:
                pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def complete(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

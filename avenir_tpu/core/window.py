"""Sliding-window event-locality analysis + criteria expressions — the
hoidla-equivalent surface (SURVEY §2.0: hoidla is an external pom dependency,
not vendored; its window/criteria classes are implicit spec consumed by
``sequence.SequencePositionalCluster``).

Reference usage (citations into /root/reference):
- ``TimeBoundEventLocalityAnalyzer(windowTimeSpan, timeStep, strategyContext)``
  fed ``ExplicitlyTimetStampedValue(value, timestamp, conditionMet)`` items,
  queried with ``getScore()`` (sequence/SequencePositionalCluster.java:91-160).
- ``EventLocality.Context`` built either from a ``strategy -> weight`` map
  (``weighted.strategies``) or from (minOccurence, maxIntervalAverage,
  maxIntervalMax, preferredStrategies) (:113-132).
- ``Criteria.createCriteriaFromExpression(condExpression)`` +
  ``evaluate(operandValues)`` over ``$<i>`` operands (:136-138, 163-165).

hoidla's exact scoring internals are not part of this repo, so the scores
here are a documented design: each strategy yields a locality score in
[0, 1] over the CONDITION-MEETING events inside the time window —

- ``count``: ``min(1, occurrences / minOccurence)`` — more qualifying events
  in the window = more clustered.
- ``averageInterval``: ``min(1, maxIntervalAverage / avgInterval)`` — smaller
  mean gap between qualifying events = more clustered.
- ``maxInterval``: ``min(1, maxIntervalMax / maxInterval)`` — no large gap
  splitting the cluster.

Unweighted contexts take the max over the preferred strategies; weighted
contexts take the weight-normalized sum.  Single qualifying events score 0
under interval strategies (no interval exists).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass
class TimeStampedValue:
    """hoidla ExplicitlyTimetStampedValue: (value, timestamp, conditionMet)."""
    value: float
    timestamp: int
    condition_met: bool = False


class EventLocalityContext:
    """Strategy configuration (hoidla EventLocality.Context)."""

    STRATEGIES = ("count", "averageInterval", "maxInterval")

    def __init__(self,
                 weighted_strategies: Optional[Dict[str, float]] = None,
                 min_occurence: int = 1,
                 max_interval_average: int = 1,
                 max_interval_max: int = 1,
                 preferred_strategies: Optional[Sequence[str]] = None):
        self.weighted_strategies = weighted_strategies
        self.min_occurence = min_occurence
        self.max_interval_average = max_interval_average
        self.max_interval_max = max_interval_max
        self.preferred_strategies = list(preferred_strategies or [])
        names = (list(weighted_strategies) if weighted_strategies
                 else self.preferred_strategies)
        for s in names:
            if s not in self.STRATEGIES:
                raise ValueError(f"unknown event-locality strategy: {s}")

    def _strategy_score(self, strategy: str, stamps: List[int]) -> float:
        n = len(stamps)
        if strategy == "count":
            return min(1.0, n / self.min_occurence)
        if n < 2:
            return 0.0
        intervals = [b - a for a, b in zip(stamps, stamps[1:])]
        if strategy == "averageInterval":
            avg = sum(intervals) / len(intervals)
            return 1.0 if avg <= 0 else min(1.0, self.max_interval_average / avg)
        if strategy == "maxInterval":
            mx = max(intervals)
            return 1.0 if mx <= 0 else min(1.0, self.max_interval_max / mx)
        raise ValueError(strategy)

    def score(self, stamps: List[int]) -> float:
        if not stamps:
            return 0.0
        if self.weighted_strategies:
            total_w = sum(self.weighted_strategies.values())
            return sum(w * self._strategy_score(s, stamps)
                       for s, w in self.weighted_strategies.items()) / total_w
        if not self.preferred_strategies:
            return 0.0
        return max(self._strategy_score(s, stamps)
                   for s in self.preferred_strategies)


class TimeBoundEventLocalityAnalyzer:
    """Time-span-bound sliding window scoring the positions of
    condition-meeting events (hoidla TimeBoundEventLocalityAnalyzer)."""

    def __init__(self, window_time_span: int, time_step: int,
                 context: EventLocalityContext):
        self.window_time_span = window_time_span
        self.time_step = time_step
        self.context = context
        self.events: List[TimeStampedValue] = []
        self._score = 0.0
        self._last_processed: Optional[int] = None

    def add(self, item: TimeStampedValue) -> None:
        self.events.append(item)
        # evict everything older than the span behind the newest stamp
        horizon = item.timestamp - self.window_time_span
        self.events = [e for e in self.events if e.timestamp > horizon]
        # re-score every processing time step
        if (self._last_processed is None
                or item.timestamp - self._last_processed >= self.time_step):
            stamps = sorted(e.timestamp for e in self.events if e.condition_met)
            self._score = self.context.score(stamps)
            self._last_processed = item.timestamp

    def get_score(self) -> float:
        return self._score


# ---------------------------------------------------------------------------
# criteria expressions (hoidla Predicate/Criteria)
# ---------------------------------------------------------------------------

_COMPARISON = re.compile(
    r"^\s*\$(\d+)\s*(<=|>=|==|!=|<|>)\s*(-?\d+(?:\.\d+)?)\s*$")

_OPS = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


class Criteria:
    """Boolean combination of ``$<ordinal> <op> <literal>`` comparisons over
    an operand array, e.g. ``"$0 > 100 && $0 <= 500"``.  Supports ``&&`` /
    ``||`` (no parentheses — && binds tighter, matching common expression
    semantics)."""

    def __init__(self, or_groups: List[List[Tuple[int, str, float]]],
                 num_predicates: int):
        self._or_groups = or_groups
        self.num_predicates = num_predicates

    @classmethod
    def create_criteria_from_expression(cls, expression: str) -> "Criteria":
        or_groups = []
        count = 0
        for disjunct in expression.split("||"):
            group = []
            for conjunct in disjunct.split("&&"):
                m = _COMPARISON.match(conjunct)
                if not m:
                    raise ValueError(
                        f"bad criteria predicate: {conjunct.strip()!r} "
                        "(expected '$<ordinal> <op> <number>')")
                group.append((int(m.group(1)), m.group(2), float(m.group(3))))
                count += 1
            or_groups.append(group)
        return cls(or_groups, count)

    def get_num_predicates(self) -> int:
        return self.num_predicates

    def evaluate(self, operand_values: Sequence[float]) -> bool:
        return any(
            all(_OPS[op](operand_values[ordinal], literal)
                for ordinal, op, literal in group)
            for group in self._or_groups)

"""Unified tracing + timing metrics: spans, histograms, exporters.

The reference's only driver-visible metric channel is Hadoop counters
(``core.metrics.Counters``) — integer-only, no notion of *where* a slow
job spent its time.  This module adds the two missing representations,
following the Clipper/INFaaS premise that per-stage latency visibility is
the substrate batching and admission decisions ride on:

- **Spans** (:class:`Tracer`): ``with tracer.span("stage", **attrs):``
  produces nested, monotonic-clock span records with per-thread
  parenting (an explicit ``parent=`` or :meth:`Tracer.adopt` carries
  parentage across worker threads).  Finished records land in a bounded
  in-memory ring buffer and export to JSON-lines or the Chrome/Perfetto
  ``trace_event`` format (``--trace out.json`` on the CLI; open in
  ``chrome://tracing`` or https://ui.perfetto.dev).
- **Histograms** (:class:`LatencyHistogram`): fixed log-spaced bucket
  boundaries (mergeable across instances/threads) with p50/p90/p95/p99
  quantile estimation by log-linear interpolation inside the bucket.
- **Registry** (:class:`Metrics`): counters + named histograms + gauges
  behind one ``snapshot()`` — the job/serving stats surface.

Pay-for-what-you-use: the module-level tracer starts DISABLED and
``span()`` then returns a shared no-op context manager — a single
attribute check on the hot path (bench.py ``obs_overhead_pct`` bounds the
disabled-mode cost at < 2% of the NB and serving hot paths).

Config surface (the .properties files every job loads):

- ``obs.trace.enable``       — enable the global tracer (default false;
  the CLI ``--trace <out.json>`` flag forces it on and exports on exit)
- ``obs.trace.buffer.spans`` — ring-buffer capacity in records
  (default 65536; oldest records drop first)
- ``obs.histogram.buckets``  — log buckets across the 1µs..100s span
  (default 96, i.e. 12/decade — ~21% worst-case quantile ratio error)
- ``obs.sample.rate``        — fraction of wire requests that get their
  per-request causal trace recorded while tracing is enabled (default
  1.0; Dapper-style head sampling — errors/shed/poison requests are
  always sampled retroactively at response time)

Causal request tracing (the Dapper shape): every wire request carries a
:class:`TraceContext` — a ``trace_id`` (client-supplied or generated),
the request's pre-allocated root ``span_id``, and the head-sampling
decision.  The context travels WITH the request object across thread
boundaries (frontend I/O shard -> router -> replica batcher worker);
spans created with ``span(..., ctx=ctx)`` parent to the context's root
and stamp its ``trace`` attr, and :meth:`Tracer.adopt` accepts a context
so a worker thread's whole span tree joins the trace.  Micro-batch
fan-in is linked explicitly: the shared ``serve.batch`` span records its
member requests' span ids and each member's ``serve.score`` span records
the batch span id (see serve/batcher.py).
"""

from __future__ import annotations

import bisect
import functools
import itertools
import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from .metrics import Counters

KEY_TRACE_ENABLE = "obs.trace.enable"
KEY_TRACE_BUFFER = "obs.trace.buffer.spans"
KEY_HIST_BUCKETS = "obs.histogram.buckets"
KEY_SAMPLE_RATE = "obs.sample.rate"

DEFAULT_BUFFER_SPANS = 1 << 16
DEFAULT_HIST_BUCKETS = 96
DEFAULT_SAMPLE_RATE = 1.0
HIST_LO_SEC = 1e-6            # smallest resolvable latency bucket edge
HIST_HI_SEC = 100.0           # largest; beyond lands in the overflow bucket


# ---------------------------------------------------------------------------
# trace context (causal request identity)
# ---------------------------------------------------------------------------

class TraceContext:
    """One request's causal identity: the ``trace_id`` shared by every
    span of the request, its pre-allocated root ``span_id`` (so fan-in
    spans can reference the request before its root span is recorded —
    root spans are recorded RETROACTIVELY at response time), and the
    head-sampling decision.  ``sampled`` may be flipped True at response
    time (errors/shed/poison are always sampled)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: Optional[int],
                 sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, span={self.span_id}, "
                f"sampled={self.sampled})")


#: sentinel: this span did not change the thread's current trace id
_NO_RESTORE = object()


# ---------------------------------------------------------------------------
# span records
# ---------------------------------------------------------------------------

class Span:
    """One finished span: [t0_ns, t0_ns + dur_ns) on thread ``tid``."""

    __slots__ = ("name", "span_id", "parent_id", "tid", "thread",
                 "t0_ns", "dur_ns", "attrs")

    def __init__(self, name, span_id, parent_id, tid, thread, t0_ns,
                 dur_ns, attrs):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.tid = tid
        self.thread = thread
        self.t0_ns = t0_ns
        self.dur_ns = dur_ns
        self.attrs = attrs

    def overlaps(self, other: "Span") -> bool:
        return (self.t0_ns < other.t0_ns + other.dur_ns
                and other.t0_ns < self.t0_ns + self.dur_ns)

    def __repr__(self):
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, dur_ns={self.dur_ns})")


class Gauge:
    """One gauge sample (a Chrome-trace counter event)."""

    __slots__ = ("name", "tid", "t_ns", "value")

    def __init__(self, name, tid, t_ns, value):
        self.name = name
        self.tid = tid
        self.t_ns = t_ns
        self.value = value


class _NullSpan:
    """The shared disabled-mode span: enter/exit do nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    """A live span context manager (enabled tracer only).

    ``ctx`` joins the span to a :class:`TraceContext`: without an
    explicit ``span_id`` the span is a CHILD of the context (parent =
    ``ctx.span_id``); with one it IS the context's root span (own id =
    ``ctx.span_id``, parentage from the thread as usual).  Either way
    the thread's current trace id is set for the span's extent, so
    nested spans stamp the same ``trace`` attr."""

    __slots__ = ("_tracer", "name", "attrs", "span_id", "parent_id", "_t0",
                 "_ctx", "_own_id", "_trace_saved")

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional[int], attrs: dict,
                 ctx: Optional[TraceContext] = None,
                 span_id: Optional[int] = None):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.parent_id = parent
        self.span_id = None
        self._t0 = 0
        self._ctx = ctx
        self._own_id = span_id
        self._trace_saved = _NO_RESTORE

    def __enter__(self):
        tr = self._tracer
        stack = tr._stack()
        ctx = self._ctx
        if self.parent_id is None:
            if ctx is not None and self._own_id is None:
                self.parent_id = ctx.span_id
            else:
                self.parent_id = (stack[-1] if stack
                                  else getattr(tr._tls, "base_parent", None))
        if ctx is not None:
            self._trace_saved = getattr(tr._tls, "trace", None)
            tr._tls.trace = ctx.trace_id
            self.attrs.setdefault("trace", ctx.trace_id)
        else:
            t = getattr(tr._tls, "trace", None)
            if t is not None:
                self.attrs.setdefault("trace", t)
        self.span_id = (self._own_id if self._own_id is not None
                        else next(tr._ids))
        stack.append(self.span_id)
        with tr._lock:
            tr._active += 1
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter_ns() - self._t0
        tr = self._tracer
        stack = tr._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        if self._trace_saved is not _NO_RESTORE:
            tr._tls.trace = self._trace_saved
        th = threading.current_thread()
        tr._append(Span(self.name, self.span_id, self.parent_id,
                        th.ident, th.name, self._t0, dur, self.attrs))
        with tr._lock:
            tr._active -= 1
        return False


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------

class Tracer:
    """Thread-safe span recorder with a bounded ring buffer.

    Spans parent to the innermost open span OF THEIR THREAD; a worker
    thread inherits a parent either explicitly (``span(parent=...)``) or
    by calling :meth:`adopt` once with the spawning thread's
    ``current_span_id()``.
    """

    def __init__(self, enabled: bool = False,
                 buffer_spans: int = DEFAULT_BUFFER_SPANS,
                 sample_rate: float = DEFAULT_SAMPLE_RATE):
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self._buf: deque = deque(maxlen=max(int(buffer_spans), 1))
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._active = 0
        self._total = 0
        self._epoch_ns = time.perf_counter_ns()

    # -- recording ---------------------------------------------------------
    def span(self, name: str, parent: Optional[int] = None,
             ctx: Optional[TraceContext] = None,
             span_id: Optional[int] = None, **attrs):
        """Context manager timing the enclosed block.  Disabled-mode cost
        is one attribute check + a shared no-op object.  ``ctx`` joins
        the span to a request trace (see :class:`_SpanCtx`)."""
        if not self.enabled:
            return _NULL_SPAN
        return _SpanCtx(self, name, parent, attrs, ctx=ctx, span_id=span_id)

    def record_span(self, name: str, t0_ns: int, dur_ns: int,
                    parent: Optional[int] = None,
                    ctx: Optional[TraceContext] = None,
                    span_id: Optional[int] = None, **attrs) -> None:
        """Record an already-measured interval (e.g. queue wait computed
        from an enqueue timestamp) without a with-block.  With ``ctx``
        the span stamps the trace id and (unless ``span_id`` names it as
        the context's own root span) parents to the context's root; with
        ``span_id`` the caller owns parentage — ``parent=None`` records
        a detached root."""
        if not self.enabled:
            return
        if ctx is not None:
            attrs.setdefault("trace", ctx.trace_id)
        if parent is None and span_id is None:
            parent = (ctx.span_id if ctx is not None
                      else self.current_span_id())
        th = threading.current_thread()
        self._append(Span(name, span_id if span_id is not None
                          else next(self._ids), parent, th.ident,
                          th.name, int(t0_ns), max(int(dur_ns), 0), attrs))

    def gauge(self, name: str, value) -> None:
        """Record one sample of a numeric time series (queue depth, pad
        fraction, ...) — a Chrome-trace counter event."""
        if not self.enabled:
            return
        self._append(Gauge(name, threading.get_ident(),
                           time.perf_counter_ns(), float(value)))

    def _append(self, rec) -> None:
        # append under the lock: exporters/readers snapshot the deque by
        # iterating it, and a concurrent append during that iteration
        # would raise "deque mutated during iteration"
        with self._lock:
            self._buf.append(rec)
            self._total += 1

    # -- thread parenting --------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_span_id(self) -> Optional[int]:
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1]
        return getattr(self._tls, "base_parent", None)

    def adopt(self, parent, trace: Optional[str] = None) -> None:
        """Seed this thread's root parent: subsequent top-level spans on
        the calling thread parent to ``parent``.  Accepts either a span
        id (optionally with an explicit ``trace`` id so the worker's
        spans join the caller's trace) or a whole :class:`TraceContext`
        — adopt-by-context, the cross-thread half of causal request
        tracing."""
        if isinstance(parent, TraceContext):
            self._tls.base_parent = parent.span_id
            self._tls.trace = parent.trace_id
            return
        self._tls.base_parent = parent
        if trace is not None:
            self._tls.trace = trace

    def current_trace_id(self) -> Optional[str]:
        """The calling thread's current trace id (an enclosing
        ctx-joined span or an adopt-by-context), or None."""
        return getattr(self._tls, "trace", None)

    def current_context(self) -> Optional[TraceContext]:
        """The calling thread's (trace id, innermost span id) as a
        TraceContext — the handle to pass a worker thread's ``adopt``.
        None when no trace is active on this thread."""
        t = getattr(self._tls, "trace", None)
        if t is None:
            return None
        return TraceContext(t, self.current_span_id(), True)

    def sample(self) -> bool:
        """One head-sampling decision at ``obs.sample.rate`` (True only
        while the tracer is enabled)."""
        if not self.enabled:
            return False
        rate = self.sample_rate
        if rate >= 1.0:
            return True
        return rate > 0.0 and random.random() < rate

    # -- inspection --------------------------------------------------------
    def records(self) -> List[object]:
        with self._lock:
            return list(self._buf)

    def spans(self, name: Optional[str] = None) -> List[Span]:
        return [r for r in self.records() if isinstance(r, Span)
                and (name is None or r.name == name)]

    def span_summary(self, name: str) -> Dict[str, float]:
        """Aggregate duration stats for spans named ``name`` — the quick
        way to compare per-chunk host costs (e.g. ``ingest.parse`` vs
        ``ingest.h2d`` across two pipeline configurations) without
        exporting a full trace."""
        spans = self.spans(name)
        total_ns = sum(s.dur_ns for s in spans)
        n = len(spans)
        return {"count": n, "total_ms": total_ns / 1e6,
                "mean_ms": (total_ns / n / 1e6) if n else 0.0}

    def span_summaries(self) -> Dict[str, Dict[str, float]]:
        """``span_summary`` for every span name currently buffered — the
        shape the periodic telemetry exporter ships (count + total/mean
        ms per name, both mergeable across snapshots by count-weighted
        sum)."""
        agg: Dict[str, list] = {}
        for r in self.records():
            if isinstance(r, Span):
                e = agg.setdefault(r.name, [0, 0])
                e[0] += 1
                e[1] += r.dur_ns
        return {k: {"count": c, "total_ms": t / 1e6,
                    "mean_ms": (t / c / 1e6) if c else 0.0}
                for k, (c, t) in sorted(agg.items())}

    def records_since(self, since_total: int):
        """``(new records, new total, dropped)`` — every record appended
        after the ``since_total``-th, for incremental (tail-follow)
        exporters.  ``dropped`` counts records that arrived but already
        rotated out of the ring buffer between calls (the flusher's
        interval bounds it)."""
        with self._lock:
            new = self._total - since_total
            if new <= 0:
                return [], self._total, 0
            buf = list(self._buf)
            have = min(new, len(buf))
            return buf[len(buf) - have:], self._total, new - have

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._active = 0
            self._total = 0

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": self.enabled, "active_spans": self._active,
                    "spans_recorded": self._total,
                    "buffered": len(self._buf),
                    "buffer_spans": self._buf.maxlen}

    def wall_epoch_unix_ns(self) -> int:
        """The tracer's perf-counter epoch expressed on the Unix wall
        clock (ns).  Every exported ``t0_ns``/``t_ns`` is relative to
        the construction-time ``perf_counter_ns`` epoch, which is
        meaningless outside this process — the fleet trace stitcher
        (``fleetobs.stitch``) offsets each process's records by its
        published anchor to place N processes on ONE wall-clock
        timeline.  Re-derived per call (wall clock minus elapsed
        monotonic), so it is stable to perf-counter drift but moves
        with NTP steps; millisecond-grade cross-process alignment is
        the design point, the intra-process ordering stays exact."""
        return time.time_ns() - (time.perf_counter_ns() - self._epoch_ns)

    # -- exporters ---------------------------------------------------------
    def record_dict(self, r) -> dict:
        """One record as the JSONL-exporter dict (shared by the one-shot
        exporter and the periodic incremental trace flusher)."""
        if isinstance(r, Span):
            return {"type": "span", "name": r.name, "id": r.span_id,
                    "parent": r.parent_id, "thread": r.thread,
                    "t0_ns": r.t0_ns - self._epoch_ns,
                    "dur_ns": r.dur_ns, "attrs": r.attrs}
        return {"type": "gauge", "name": r.name,
                "t_ns": r.t_ns - self._epoch_ns, "value": r.value}

    def export_jsonl(self, path: str) -> int:
        """One JSON object per buffered record; returns the line count."""
        recs = self.records()
        with open(path, "w") as fh:
            for r in recs:
                fh.write(json.dumps(self.record_dict(r)) + "\n")
        return len(recs)

    def export_chrome_trace(self, path: str) -> int:
        """Write the buffer as Chrome ``trace_event`` JSON (complete "X"
        events + counter "C" events + thread-name metadata), loadable in
        ``chrome://tracing`` / Perfetto.  Returns the event count."""
        recs = self.records()
        pid = os.getpid()
        events: List[dict] = []
        tid_map: Dict[int, int] = {}

        def tid_of(ident, name=None):
            t = tid_map.get(ident)
            if t is None:
                t = tid_map[ident] = len(tid_map) + 1
                events.append({"ph": "M", "name": "thread_name", "pid": pid,
                               "tid": t,
                               "args": {"name": name or f"thread-{ident}"}})
            return t

        for r in recs:
            if isinstance(r, Span):
                ev = {"name": r.name, "cat": "avenir", "ph": "X",
                      "ts": (r.t0_ns - self._epoch_ns) / 1000.0,
                      "dur": r.dur_ns / 1000.0,
                      "pid": pid, "tid": tid_of(r.tid, r.thread),
                      "args": {"id": r.span_id, "parent": r.parent_id,
                               **r.attrs}}
            else:
                ev = {"name": r.name, "cat": "avenir", "ph": "C",
                      "ts": (r.t_ns - self._epoch_ns) / 1000.0,
                      "pid": pid, "args": {"value": r.value}}
            events.append(ev)
        events.sort(key=lambda e: e.get("ts", -1.0))
        with open(path, "w") as fh:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
        return len(events)


# ---------------------------------------------------------------------------
# latency histograms
# ---------------------------------------------------------------------------

def _log_bounds(n_buckets: int, lo: float, hi: float) -> List[float]:
    ratio = (hi / lo) ** (1.0 / n_buckets)
    return [lo * ratio ** i for i in range(n_buckets + 1)]


def quantile_from_counts(bounds: Sequence[float], counts: Sequence[int],
                         q: float, vmin: Optional[float] = None,
                         vmax: Optional[float] = None) -> Optional[float]:
    """Quantile estimate (seconds) from raw bucket counts against a
    bound ladder — the module-level form of
    :meth:`LatencyHistogram.quantile`, usable on DIFFED counts (the SLO
    monitor's rolling windows subtract two cumulative snapshots, so the
    window's distribution exists only as a counts list, never as a live
    histogram instance)."""
    n = sum(counts)
    if n == 0:
        return None
    if vmin is None or vmax is None:
        # the observed extrema are unknown (diffed counts): bound them by
        # the occupied buckets' edges, so a tiny window's quantile lands
        # in its own bucket instead of collapsing to bounds[0] (a 1-
        # request window must still be able to violate a latency SLO)
        occupied = [i for i, c in enumerate(counts) if c]
        lo_i, hi_i = occupied[0], occupied[-1]
        if vmin is None:
            vmin = bounds[lo_i - 1] if lo_i >= 1 else bounds[0]
        if vmax is None:
            vmax = bounds[hi_i] if hi_i < len(bounds) else bounds[-1]
    target = max(q, 0.0) * n
    if target <= 1.0:
        return vmin
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            lo_e = bounds[i - 1] if i >= 1 else vmin
            hi_e = bounds[i] if i < len(bounds) else vmax
            lo_e = max(lo_e, vmin)
            hi_e = min(hi_e, vmax)
            if hi_e <= lo_e or lo_e <= 0:
                return min(max(hi_e, vmin), vmax)
            frac = (target - cum) / c
            return lo_e * (hi_e / lo_e) ** frac
        cum += c
    return vmax


class LatencyHistogram:
    """Fixed-boundary log-bucketed latency histogram (seconds).

    Boundaries are a geometric ladder ``lo..hi`` shared by every instance
    constructed with the same parameters, so histograms MERGE exactly
    (bucket-wise add) across threads, models, or processes.  Quantiles
    are estimated by locating the target rank's bucket and log-linearly
    interpolating between its edges, clamped to the observed min/max —
    worst-case ratio error is one bucket's growth factor
    (~21% at the default 12 buckets/decade, typically far less).

    Exemplars: ``record(seconds, trace_id=...)`` retains the LAST sampled
    trace id per bucket (with its exact value and epoch timestamp), so a
    bad tail quantile links directly to a trace to open — surfaced as
    OpenMetrics exemplars in the Prometheus exposition
    (``core.telemetry.prometheus_text``) and as ``p99_exemplar`` in
    :meth:`snapshot`.  Exemplars merge latest-timestamp-wins.
    """

    __slots__ = ("bounds", "counts", "n", "total", "vmin", "vmax",
                 "exemplars", "_lock")

    def __init__(self, n_buckets: int = DEFAULT_HIST_BUCKETS,
                 lo: float = HIST_LO_SEC, hi: float = HIST_HI_SEC):
        if n_buckets < 1 or not (0 < lo < hi):
            raise ValueError(f"bad histogram shape: {n_buckets}, {lo}, {hi}")
        self.bounds = _log_bounds(int(n_buckets), float(lo), float(hi))
        self.counts = [0] * (len(self.bounds) + 1)
        self.n = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        # bucket index -> (trace_id, value seconds, epoch ts): the last
        # sampled request that landed in the bucket
        self.exemplars: Dict[int, tuple] = {}
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------
    def record(self, seconds: float, trace_id: Optional[str] = None,
               ts: Optional[float] = None) -> None:
        """Record one sample; ``ts`` overrides the exemplar's epoch
        stamp (deterministic replay — the split-invariance verifier
        feeds explicit stamps so merge properties are exact, and a
        cross-process replayer can preserve original times)."""
        s = float(seconds)
        i = bisect.bisect_right(self.bounds, s)
        with self._lock:
            self.counts[i] += 1
            self.n += 1
            self.total += s
            if s < self.vmin:
                self.vmin = s
            if s > self.vmax:
                self.vmax = s
            if trace_id is not None:
                e = (str(trace_id), s,
                     time.time() if ts is None else float(ts))
                cur = self.exemplars.get(i)
                # SAME retention rule as merge ((ts, trace_id, value)
                # max): a single histogram and a sharded-then-merged
                # one agree exactly even when a replayer stamps ts out
                # of order — the merge==single-run property is exact
                if cur is None or (e[2], e[0], e[1]) > (cur[2], cur[0],
                                                        cur[1]):
                    self.exemplars[i] = e

    def record_ns(self, ns: int, trace_id: Optional[str] = None) -> None:
        self.record(ns * 1e-9, trace_id=trace_id)

    def reset(self) -> None:
        with self._lock:
            self.counts = [0] * (len(self.bounds) + 1)
            self.n = 0
            self.total = 0.0
            self.vmin = float("inf")
            self.vmax = float("-inf")
            self.exemplars = {}

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold ``other`` into this histogram (boundaries must match)."""
        if self.bounds != other.bounds:
            raise ValueError("cannot merge histograms with different "
                             "bucket boundaries")
        counts, n, total, vmin, vmax = other._state()
        ex = other._exemplar_state()
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.n += n
            self.total += total
            self.vmin = min(self.vmin, vmin)
            self.vmax = max(self.vmax, vmax)
            for i, e in ex.items():
                cur = self.exemplars.get(i)
                # (ts, trace_id, value) ordering: exact-ts ties break on
                # content, not merge side, so merge stays commutative
                # (the split-invariance verifier's property)
                if cur is None or (e[2], str(e[0]), e[1]) > (cur[2],
                                                             str(cur[0]),
                                                             cur[1]):
                    self.exemplars[i] = e
        return self

    def _state(self):
        with self._lock:
            return list(self.counts), self.n, self.total, self.vmin, self.vmax

    def _exemplar_state(self) -> Dict[int, tuple]:
        with self._lock:
            return dict(self.exemplars)

    # -- quantiles ---------------------------------------------------------
    def quantile(self, q: float) -> Optional[float]:
        return self.quantiles([q])[0]

    def quantiles(self, qs: Sequence[float]) -> List[Optional[float]]:
        """Estimate several quantiles from ONE consistent snapshot."""
        counts, n, _total, vmin, vmax = self._state()
        return [self._quantile_from(counts, n, vmin, vmax, q) for q in qs]

    def _quantile_from(self, counts, n, vmin, vmax, q: float):
        if n == 0:
            return None
        return quantile_from_counts(self.bounds, counts, q, vmin, vmax)

    # -- surfaces ----------------------------------------------------------
    def percentiles_ms(self) -> dict:
        """The serving stats latency dict (field names byte-compatible
        with the original hand-rolled sample-sort implementation)."""
        counts, n, total, vmin, vmax = self._state()
        if n == 0:
            return {"p50": None, "p95": None, "p99": None, "n": 0}

        def pct(q):
            return round(
                self._quantile_from(counts, n, vmin, vmax, q) * 1000.0, 3)

        return {"p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
                "mean": round(total / n * 1000.0, 3), "n": n}

    def snapshot(self) -> dict:
        """Full histogram state for the stats surface / JSON export."""
        counts, n, total, vmin, vmax = self._state()
        if n == 0:
            return {"n": 0}

        def pct(q):
            return round(
                self._quantile_from(counts, n, vmin, vmax, q) * 1000.0, 4)

        out = {"n": n,
               "mean_ms": round(total / n * 1000.0, 4),
               "min_ms": round(vmin * 1000.0, 4),
               "max_ms": round(vmax * 1000.0, 4),
               "p50_ms": pct(0.50), "p90_ms": pct(0.90),
               "p95_ms": pct(0.95), "p99_ms": pct(0.99)}
        ex = self.exemplar_near(0.99)
        if ex is not None:
            out["p99_exemplar"] = ex
        return out

    def exemplar_near(self, q: float = 0.99) -> Optional[dict]:
        """The retained exemplar closest at-or-below the bucket holding
        the ``q``-quantile rank (nearest above as a fallback) — the
        "p99 is bad, open THIS trace" link in stats/health."""
        counts, n, _total, _vmin, _vmax = self._state()
        ex = self._exemplar_state()
        if n == 0 or not ex:
            return None
        target = max(q, 0.0) * n
        cum = 0
        bucket = len(counts) - 1
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                bucket = i
                break
        order = list(range(bucket, -1, -1)) + list(range(bucket + 1,
                                                         len(counts)))
        for i in order:
            e = ex.get(i)
            if e is not None:
                return {"trace_id": e[0],
                        "value_ms": round(e[1] * 1000.0, 4), "ts": e[2]}
        return None

    def state_dict(self) -> dict:
        """Mergeable raw state: sparse bucket counts + the shape params
        that prove two states share one bound ladder.  This is the form
        the telemetry exporter ships (counts ADD across processes —
        multi-host aggregation is a fold over these dicts; see
        ``core.telemetry.merge_snapshots``)."""
        counts, n, total, vmin, vmax = self._state()
        out = {"n_buckets": len(self.bounds) - 1,
               "lo": self.bounds[0], "hi": self.bounds[-1],
               "counts": {str(i): c for i, c in enumerate(counts) if c},
               "n": n, "total": total,
               "vmin": (vmin if n else None),
               "vmax": (vmax if n else None)}
        ex = self._exemplar_state()
        if ex:
            out["exemplars"] = {
                str(i): {"trace_id": t, "value": v, "ts": ts}
                for i, (t, v, ts) in sorted(ex.items())}
        return out

    @classmethod
    def from_state(cls, state: dict) -> "LatencyHistogram":
        """Rebuild a live histogram from a :meth:`state_dict` (exact
        inverse — used by snapshot consumers that want quantiles out of
        a merged multi-process state)."""
        h = cls(int(state["n_buckets"]), float(state["lo"]),
                float(state["hi"]))
        for i, c in state.get("counts", {}).items():
            h.counts[int(i)] = int(c)
        h.n = int(state.get("n", 0))
        h.total = float(state.get("total", 0.0))
        if h.n:
            h.vmin = float(state["vmin"])
            h.vmax = float(state["vmax"])
        for i, e in (state.get("exemplars") or {}).items():
            h.exemplars[int(i)] = (e["trace_id"], float(e["value"]),
                                   float(e["ts"]))
        return h


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

class Metrics:
    """Counters + named latency histograms + gauges behind one snapshot.

    Extends (does not replace) :class:`core.metrics.Counters`: jobs keep
    returning Counters; a Metrics registry groups that Counters with the
    timing distributions the integer channel cannot carry.
    """

    def __init__(self, counters: Optional[Counters] = None,
                 hist_buckets: int = DEFAULT_HIST_BUCKETS):
        self.counters = counters if counters is not None else Counters()
        self.hist_buckets = int(hist_buckets)
        self._hists: Dict[str, LatencyHistogram] = {}
        self._gauges: Dict[str, tuple] = {}      # name -> (value, epoch ts)
        self._lock = threading.Lock()

    def histogram(self, name: str) -> LatencyHistogram:
        """Get-or-create the named histogram (shared boundaries)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram(self.hist_buckets)
            return h

    def set_gauge(self, name: str, value, ts: Optional[float] = None) -> None:
        """Record one gauge value, stamped with its epoch time — merging
        two snapshots keeps the LATEST sample of each gauge, so every
        set carries when it happened (``ts`` overrides for replayed or
        cross-process samples)."""
        with self._lock:
            self._gauges[name] = (float(value),
                                  float(ts) if ts is not None else time.time())

    def get_gauge(self, name: str, default=None):
        with self._lock:
            g = self._gauges.get(name)
        return g[0] if g is not None else default

    def clear(self) -> None:
        """Drop every histogram and gauge and reset the counters (test
        isolation for the process-global registry)."""
        with self._lock:
            self._hists.clear()
            self._gauges.clear()
            self.counters = Counters()

    def snapshot(self) -> dict:
        """Human-readable snapshot: quantile summaries per histogram,
        gauge values WITH their sample timestamps, and the snapshot's
        own epoch + monotonic stamps (so exported series can be
        plotted/joined — a snapshot knows *when*)."""
        with self._lock:
            hists = dict(self._hists)
            gauges = dict(self._gauges)
        return {"ts": time.time(), "mono": time.monotonic(),
                "counters": self.counters.as_dict(),
                "histograms": {k: h.snapshot() for k, h in
                               sorted(hists.items())},
                "gauges": {k: {"value": v, "ts": t}
                           for k, (v, t) in sorted(gauges.items())}}

    def mergeable_snapshot(self) -> dict:
        """The cross-process form: raw histogram bucket states instead
        of quantile summaries, so N processes' snapshots FOLD into one
        (counters sum, buckets add, gauges latest-timestamp-wins) — see
        ``core.telemetry.merge_snapshots``."""
        with self._lock:
            hists = dict(self._hists)
            gauges = dict(self._gauges)
        return {"ts": time.time(), "mono": time.monotonic(),
                "counters": self.counters.as_dict(),
                "hists": {k: h.state_dict() for k, h in sorted(hists.items())},
                "gauges": {k: {"value": v, "ts": t}
                           for k, (v, t) in sorted(gauges.items())}}


# ---------------------------------------------------------------------------
# global tracer + config plumbing
# ---------------------------------------------------------------------------

_GLOBAL_TRACER = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process-global tracer (disabled until configured)."""
    return _GLOBAL_TRACER


def set_tracer(tracer: Tracer) -> Tracer:
    global _GLOBAL_TRACER
    _GLOBAL_TRACER = tracer
    return tracer


def new_trace_context(trace_id: Optional[str] = None,
                      sampled: Optional[bool] = None) -> TraceContext:
    """Mint one request's :class:`TraceContext` against the global
    tracer: a client-supplied ``trace_id`` propagates (and forces the
    sampling decision — the caller already committed to the trace, the
    Dapper propagation rule); otherwise a random 64-bit hex id is
    generated (``os.urandom`` — thread-safe, collision-free in practice)
    and head sampling applies ``obs.sample.rate``.  The root span id is
    pre-allocated from the tracer's id space so fan-in spans can
    reference the request before its retroactive root span exists."""
    tr = _GLOBAL_TRACER
    client = trace_id is not None
    if trace_id is None:
        trace_id = os.urandom(8).hex()
    if sampled is None:
        sampled = (tr.enabled and client) or tr.sample()
    return TraceContext(str(trace_id), next(tr._ids), bool(sampled))


def configure(enabled: Optional[bool] = None,
              buffer_spans: Optional[int] = None,
              sample_rate: Optional[float] = None) -> Tracer:
    """Reconfigure the global tracer IN PLACE (every call site that
    already fetched it sees the change)."""
    tr = _GLOBAL_TRACER
    with tr._lock:
        if buffer_spans is not None and int(buffer_spans) != tr._buf.maxlen:
            tr._buf = deque(tr._buf, maxlen=max(int(buffer_spans), 1))
        if enabled is not None:
            tr.enabled = bool(enabled)
        if sample_rate is not None:
            tr.sample_rate = float(sample_rate)
    return tr


def configure_from_config(config, force_enable: bool = False) -> Tracer:
    """Apply the ``obs.*`` properties surface to the global tracer."""
    return configure(
        enabled=force_enable or config.get_boolean(KEY_TRACE_ENABLE, False),
        buffer_spans=config.get_int(KEY_TRACE_BUFFER, DEFAULT_BUFFER_SPANS),
        sample_rate=config.get_float(KEY_SAMPLE_RATE, DEFAULT_SAMPLE_RATE))


def histogram_buckets_from_config(config) -> int:
    n = config.get_int(KEY_HIST_BUCKETS, DEFAULT_HIST_BUCKETS)
    if n < 1:
        raise ValueError(f"{KEY_HIST_BUCKETS} must be positive: {n}")
    return n


def traced_run(fn: Callable) -> Callable:
    """Decorator for job drivers' ``run()``: wraps the call in one
    top-level ``job:<ClassName>`` span (a no-op while tracing is
    disabled).  ``tests/test_obs_coverage.py`` asserts every registered
    driver carries it, so new drivers cannot silently opt out."""
    @functools.wraps(fn)
    def run(self, *args, **kwargs):
        tracer = _GLOBAL_TRACER
        if not tracer.enabled:
            return fn(self, *args, **kwargs)
        with tracer.span("job:" + type(self).__name__):
            return fn(self, *args, **kwargs)
    run.__obs_traced__ = True
    return run

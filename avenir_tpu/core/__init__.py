"""Core substrate: schema binding, config, text I/O, ingest, metrics.

The chombo-equivalent layer (SURVEY §2.0): the reference leans on the sister
library chombo for config loading, schema binding, tuple/text formats and
stats helpers; this package owns those capabilities natively.
"""

from .schema import FeatureSchema, FeatureField, CostSchema  # noqa: F401
from .config import JobConfig, parse_properties, parse_cli_args, load_job_config  # noqa: F401
from .io import read_lines, read_records, split_line, write_output, OutputWriter  # noqa: F401
from .binning import DatasetEncoder, EncodedDataset, Vocab  # noqa: F401
from .metrics import Counters, ConfusionMatrix, CostBasedArbitrator  # noqa: F401
from .obs import LatencyHistogram, Metrics, Tracer, get_tracer, traced_run  # noqa: F401

"""Failure flight recorder: the always-on black box behind every anomaly.

The obs/telemetry layers (PR 3, PR 6) can say *that* p99 regressed or a
breaker tripped; this module records *what the system looked like in the
seconds before* — the aviation flight-recorder shape applied to serving
and batch workflows.  An always-on bounded ring collects:

- **wire errors** — every error/shed/poison response the serve layer
  produces (``serve/server.py``'s response chokepoint), stamped with the
  request's ``trace_id`` so a dump links back to the causal trace;
- **periodic metrics snapshots** — the mergeable ``core.telemetry``
  snapshot, captured lazily on the record stream and per telemetry
  exporter tick (``flight.snapshot.interval.sec`` apart);
- **anomaly marks** — every trigger below, whether or not it dumped.

Anomaly triggers — breaker trip, SLO soft-degrade, poison quarantine,
:class:`~avenir_tpu.core.io.TornArtifactError`, systemic scorer failure,
fatal job exceptions (``cli.py``) — call :func:`trigger`, which appends
the anomaly mark and, when ``flight.dump.dir`` is configured, atomically
dumps the ring as a self-contained JSONL file (via the PR-9 atomic
writer) named by trigger + trace_id: a header line, a metrics snapshot
at dump time, the ring records, then a tail of the tracer's recent
spans.  Dumps are rate-limited by ``flight.dump.min.interval.sec``
(forced triggers — process exit, fatal exceptions — bypass the limit).
``tests/test_obs_coverage.py`` lints that every anomaly trigger site in
the package calls this hook (or is excluded with a reason).

Config surface (the .properties files every job loads; README
"Observability"):

- ``flight.dump.dir``              — dump destination directory; unset
  (the default) keeps the ring recording but writes no files — safe for
  tests and libraries, one key to flip on the black box
- ``flight.dump.min.interval.sec`` — min seconds between dumps
  (default 30; forced triggers bypass)
- ``flight.ring.records``          — ring capacity in records
  (default 2048, oldest drop first)
- ``flight.snapshot.interval.sec`` — min seconds between periodic
  metrics snapshots in the ring (default 5; <= 0 disables them)
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Optional

from . import obs, sanitizer

KEY_DUMP_DIR = "flight.dump.dir"
KEY_MIN_INTERVAL = "flight.dump.min.interval.sec"
KEY_RING_RECORDS = "flight.ring.records"
KEY_SNAPSHOT_INTERVAL = "flight.snapshot.interval.sec"

DEFAULT_MIN_INTERVAL_SEC = 30.0
DEFAULT_RING_RECORDS = 2048
DEFAULT_SNAPSHOT_INTERVAL_SEC = 5.0

#: how many of the tracer's most recent records ride along in a dump
SPAN_TAIL_RECORDS = 512

FLIGHT_GROUP = "Flight"

_NAME_SAFE_RE = re.compile(r"[^A-Za-z0-9._-]+")


class FlightRecorder:
    """Bounded in-memory ring + atomic anomaly dumps (thread-safe)."""

    def __init__(self, ring_records: int = DEFAULT_RING_RECORDS,
                 dump_dir: Optional[str] = None,
                 min_interval_sec: float = DEFAULT_MIN_INTERVAL_SEC,
                 snapshot_interval_sec: float = DEFAULT_SNAPSHOT_INTERVAL_SEC):
        self._ring: deque = deque(maxlen=max(int(ring_records), 1))
        self._lock = sanitizer.make_lock("core.flight")
        self.dump_dir = dump_dir
        self.min_interval = float(min_interval_sec)
        self.snapshot_interval = float(snapshot_interval_sec)
        self._last_dump = 0.0       # monotonic; 0.0 = never dumped
        self._last_snap = 0.0
        self.triggers = 0
        self.dumps = 0
        self.suppressed = 0

    # -- the record stream -------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one ring record (cheap; called off the response path
        only for error/shed/poison responses) and lazily capture a
        periodic metrics snapshot when one is due."""
        rec = {"t": time.time(), "kind": kind}
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)
        self.maybe_snapshot()

    def maybe_snapshot(self, force: bool = False) -> bool:
        """Capture one mergeable metrics snapshot into the ring when
        ``flight.snapshot.interval.sec`` has elapsed (driven by the
        record stream and by the serve telemetry exporter's tick)."""
        now = time.monotonic()
        with self._lock:
            if not force:
                if self.snapshot_interval <= 0:
                    return False
                if (self._last_snap
                        and now - self._last_snap < self.snapshot_interval):
                    return False
            self._last_snap = now
        try:
            from . import telemetry
            snap = telemetry.build_snapshot()
        except Exception:                               # noqa: BLE001
            return False
        with self._lock:
            self._ring.append({"t": time.time(), "kind": "metrics.snapshot",
                               "snapshot": snap})
        return True

    # -- anomaly triggers --------------------------------------------------
    def trigger(self, reason: str, trace_id: Optional[str] = None,
                force: bool = False, **detail) -> Optional[str]:
        """One anomaly: mark the ring, and dump it when a dump dir is
        configured and the rate limit allows (``force`` bypasses — exit
        flushes and fatal exceptions must leave the black box behind).
        Returns the dump path, or None when no file was written."""
        mark = {"t": time.time(), "kind": "anomaly", "reason": reason,
                "trace_id": trace_id}
        mark.update(detail)
        now = time.monotonic()
        with self._lock:
            self.triggers += 1
            self._ring.append(mark)
            if self.dump_dir is None:
                return None
            if (not force and self._last_dump
                    and now - self._last_dump < self.min_interval):
                self.suppressed += 1
                return None
            # reserve the rate-limit window (concurrent triggers must
            # not double-dump) but COMMIT it — and count the dump —
            # only on a successful write: an unwritable dump dir must
            # not suppress the next anomaly's retry or make stats claim
            # a black box that never hit disk
            prev_last = self._last_dump
            self._last_dump = now
            ring = list(self._ring)
        path = self._dump(reason, trace_id, ring)
        with self._lock:
            if path is not None:
                self.dumps += 1
            elif self._last_dump == now:
                self._last_dump = prev_last
        return path

    def _dump(self, reason: str, trace_id: Optional[str],
              ring: list) -> Optional[str]:
        # lazy imports: core.io's TornArtifactError hooks back into this
        # module, and telemetry pulls in obs config plumbing
        from .io import atomic_write_text
        from . import telemetry

        tag = trace_id if trace_id else str(int(time.time() * 1000))
        name = (f"flight-{_NAME_SAFE_RE.sub('_', reason)}-"
                f"{_NAME_SAFE_RE.sub('_', str(tag))}.jsonl")
        path = os.path.join(self.dump_dir, name)
        lines = [json.dumps({"kind": "flight.header", "reason": reason,
                             "trace_id": trace_id, "ts": time.time(),
                             "pid": os.getpid(),
                             "ring_records": len(ring)})]
        try:
            snap = telemetry.build_snapshot()
            lines.append(json.dumps({"kind": "metrics.snapshot",
                                     "at": "dump", "snapshot": snap}))
        except Exception:                               # noqa: BLE001
            pass
        for rec in ring:
            lines.append(json.dumps(rec, default=str))
        tr = obs.get_tracer()
        for r in tr.records()[-SPAN_TAIL_RECORDS:]:
            lines.append(json.dumps({"kind": "span.tail",
                                     **tr.record_dict(r)}))
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            atomic_write_text(path, "\n".join(lines) + "\n")
        except OSError:
            # an unwritable dump dir must never escalate the anomaly
            # it was meant to document
            return None
        try:
            telemetry.get_metrics().counters.incr(FLIGHT_GROUP, "Dumps")
        except Exception:                               # noqa: BLE001
            pass
        return path

    # -- reporting ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"ring_records": len(self._ring),
                    "ring_capacity": self._ring.maxlen,
                    "dump_dir": self.dump_dir,
                    "triggers": self.triggers, "dumps": self.dumps,
                    "suppressed": self.suppressed}

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.triggers = self.dumps = self.suppressed = 0
            self._last_dump = self._last_snap = 0.0


# ---------------------------------------------------------------------------
# the process-global recorder + config plumbing
# ---------------------------------------------------------------------------

_GLOBAL_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """The process-global flight recorder (ring always on; dumping off
    until ``flight.dump.dir`` is configured)."""
    return _GLOBAL_RECORDER


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    global _GLOBAL_RECORDER
    _GLOBAL_RECORDER = recorder
    return recorder


def read_dump_header(path: str) -> Optional[dict]:
    """The ``flight.header`` first line of a dump file as a dict
    (reason, trace_id, ts, pid, ring_records), or None when the file is
    missing, truncated, or not a flight dump.  Incident correlators
    (``fleetobs.incidents``) key on the header's ``trace_id``, NOT the
    filename tag — the tag doubles as a millisecond timestamp when the
    trigger carried no trace id, so parsing it back is ambiguous."""
    try:
        with open(path, "r") as fh:
            line = fh.readline()
    except OSError:
        return None
    try:
        doc = json.loads(line)
    except ValueError:
        return None
    if not isinstance(doc, dict) or doc.get("kind") != "flight.header":
        return None
    return doc


def sanitize_lock() -> None:
    """Re-wrap the global recorder's lock through the sanitizer.  The
    recorder is a module-import-time singleton, so its lock predates
    any ``sanitize.locks=true`` enablement; called at configure time
    (before worker threads exist) it brings the anomaly paths — which
    run while other tracked locks are held — into the order graph."""
    r = _GLOBAL_RECORDER
    if sanitizer.enabled() and not isinstance(r._lock,
                                              sanitizer.TrackedLock):
        r._lock = sanitizer.make_lock("core.flight")


def configure_from_config(config) -> FlightRecorder:
    """Apply the ``flight.*`` properties surface to the global recorder
    (called by every CLI entry point next to the obs configure)."""
    r = _GLOBAL_RECORDER
    sanitize_lock()
    r.dump_dir = config.get(KEY_DUMP_DIR) or None
    r.min_interval = config.get_float(KEY_MIN_INTERVAL,
                                      DEFAULT_MIN_INTERVAL_SEC)
    r.snapshot_interval = config.get_float(KEY_SNAPSHOT_INTERVAL,
                                           DEFAULT_SNAPSHOT_INTERVAL_SEC)
    cap = config.get_int(KEY_RING_RECORDS, DEFAULT_RING_RECORDS)
    with r._lock:
        if r._ring.maxlen != max(cap, 1):
            r._ring = deque(r._ring, maxlen=max(cap, 1))
    return r


def record(kind: str, **fields) -> None:
    _GLOBAL_RECORDER.record(kind, **fields)


def trigger(reason: str, trace_id: Optional[str] = None,
            force: bool = False, **detail) -> Optional[str]:
    """Module-level anomaly hook — what every trigger site calls."""
    return _GLOBAL_RECORDER.trigger(reason, trace_id=trace_id, force=force,
                                    **detail)


def fatal(exc: BaseException) -> Optional[str]:
    """A fatal job/serve exception: ring-record it and force a dump so a
    crashed process still leaves its black box behind (CLI entry points
    call this from their except paths)."""
    r = _GLOBAL_RECORDER
    return r.trigger("fatal", force=True, error=f"{type(exc).__name__}: "
                                                f"{exc}")


def flush_on_exit(reason: str = "exit") -> Optional[str]:
    """Final black-box flush for clean shutdowns (``serve_main``'s
    finally/SIGTERM path): force one dump of whatever the ring holds.
    No-op when no dump dir is configured."""
    r = _GLOBAL_RECORDER
    if r.dump_dir is None:
        return None
    return r.trigger(reason, force=True)

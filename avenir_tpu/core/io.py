"""Text I/O: delimited records in, ``part-r-*`` job outputs out.

The reference's jobs consume newline-delimited text split on
``field.delim.regex`` and write delimited text to ``part-r-NNNNN`` files in an
output directory (every driver; conventions visible in e.g.
resource/knn.properties ``bayesian.model.file.path=.../part-r-00000``).
We keep both conventions so the file surface is interchangeable: a model file
written here can be read by reference tooling and vice versa.

Input paths may be a single file or a directory (all non-hidden files inside,
sorted — mirroring how MR consumes every part file of a previous job's output
directory).

Durability contract (README "Fault tolerance"): MapReduce job outputs are
only real once the ``_SUCCESS`` marker lands, and a failed task's partial
output is never trusted (Dean & Ghemawat, OSDI 2004).  This module
enforces that contract on the write AND read side:

- :class:`OutputWriter` stages every part file to a temp path in the same
  directory and publishes it with ``fsync + os.replace`` — a crash
  mid-write leaves the previous artifact intact, never a torn file at the
  final path.  Before ``_SUCCESS`` it writes a ``_MANIFEST`` sidecar
  (per-part byte length + sha1), also atomically.
- Readers (:func:`read_lines`, :func:`read_field_matrix`, the serving
  registry loaders, DAG artifact refs — everything funneling through
  :func:`_input_files`) validate the manifest when one is present: a part
  whose size or checksum disagrees raises :class:`TornArtifactError`
  instead of silently consuming half an artifact.  Validation results are
  cached per (directory, manifest stat) so repeated reads of an unchanged
  artifact hash its parts once.
- ``io.require.success=true`` (:func:`configure_from_config`) adds the
  strict mode: a DIRECTORY input without a ``_SUCCESS`` marker is refused
  with an error naming the path — DAG stage inputs opt in so a
  half-written upstream output fails the consumer fast.
- :func:`atomic_write_text` is the same temp+fsync+replace primitive for
  single-file artifacts written outside :class:`OutputWriter` (the
  decision-tree JSON, regression coefficient history); the tier-2 lint
  (tests/test_resilience_coverage.py) keeps every artifact-path
  ``open(..., "w")`` either atomic or on ``NON_ATOMIC_WRITES`` with a
  written reason.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import tempfile
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from . import faultinject

KEY_REQUIRE_SUCCESS = "io.require.success"

MANIFEST_NAME = "_MANIFEST"
SUCCESS_NAME = "_SUCCESS"
MANIFEST_VERSION = 1


class TornArtifactError(RuntimeError):
    """A job-output artifact failed durability validation (torn part,
    missing/mismatched manifest entry, or — in strict mode — a missing
    ``_SUCCESS`` marker).  The message names the path and the repair
    (re-run the producing job); consumers that hold an older healthy
    version (the serving registry's hot-swap reload) keep serving it.

    Every construction is an anomaly trigger: the flight recorder
    (core.flight) marks its ring — and dumps it when configured — so a
    torn artifact detected anywhere (batch input read, DAG stage skip
    validation, serving reload) leaves the black box behind.  Hooking
    the exception itself covers every raise site; the tier-2 lint in
    tests/test_obs_coverage.py asserts this stays true."""

    def __init__(self, *args):
        super().__init__(*args)
        from . import flight
        try:
            flight.trigger("torn_artifact", detail=str(self))
        except Exception:                               # noqa: BLE001
            pass        # the black box must never mask the real error


_REQUIRE_SUCCESS = False

#: truncate-mode write sites ("module:qualname") that are deliberately
#: NOT routed through the atomic publish layer (OutputWriter /
#: atomic_write_text), each with the reason the torn-on-crash shape is
#: acceptable there.  The tier-2 lint
#: (tests/test_resilience_coverage.py) fails on any ``open(..., "w")``
#: outside the atomic primitives that is not on this list, and on any
#: stale entry whose call site was removed or made atomic.
NON_ATOMIC_WRITES: Dict[str, str] = {
    "core/checkpoint.py:StreamCheckpointer.save":
        "atomic by construction: pickles to a same-dir mkstemp fd and "
        "lands via os.replace (binary payload, so atomic_write_text's "
        "text surface does not fit) — a crash mid-save leaves the "
        "previous generation intact",
    "core/checkpoint.py:WorkflowCheckpointer.record":
        "atomic by construction, same tmp+replace shape as "
        "StreamCheckpointer.save",
    "core/checkpoint.py:OffsetCheckpointer.save":
        "atomic by construction, same tmp+replace shape as "
        "StreamCheckpointer.save (the stream-offset sidecar)",
    "core/obs.py:Tracer.export_jsonl":
        "diagnostic trace export, not a job artifact: no reader "
        "validates it, a torn trace breaks no downstream job, and "
        "re-running with --trace is the recovery path",
    "core/obs.py:Tracer.export_chrome_trace":
        "diagnostic trace export, same contract as export_jsonl",
    "core/resilience.py:RowQuarantine._write":
        "quarantine audit sidecar: first open truncates a stale sidecar "
        "from a previous run, then appends evidence rows as they are "
        "quarantined — an audit trail, not a consumed artifact; the "
        "authoritative recovery object is the job's (atomic) output",
    "datagen/cli.py:main":
        "synthetic dataset generator (input-side dev tooling): "
        "re-generating is the recovery path, and job inputs are "
        "validated by the ingest layer, not published by it",
}


def set_require_success(flag: bool) -> bool:
    """Install the strict ``_SUCCESS``-marker mode for directory inputs;
    returns the previous setting so callers can restore it."""
    global _REQUIRE_SUCCESS
    prev = _REQUIRE_SUCCESS
    _REQUIRE_SUCCESS = bool(flag)
    return prev


def configure_from_config(config) -> None:
    """Apply the ``io.*`` config surface (called by every CLI entry point
    next to the resilience configure)."""
    set_require_success(config.get_boolean(KEY_REQUIRE_SUCCESS, False))


def _durability_counters():
    """The process-global ``Durability`` counter group (rides the
    telemetry registry, so ``--metrics-out`` exports recovery events)."""
    from . import telemetry
    return telemetry.get_metrics().counters


class ArtifactStore:
    """In-memory overlay for job-output artifacts (the core.dag handoff).

    A workflow DAG chains jobs whose intermediate artifacts (a trained
    NB model, an MI feature ranking) are text files only because the
    reference's MR stages had no other channel.  While a store is
    installed (``set_artifact_store``), ``write_output`` to a REGISTERED
    stage output path also records the lines in memory, and
    ``read_lines`` on that path serves them from memory — the downstream
    stage consumes the producer's in-memory artifact and the text file
    becomes a sink, not the transport.  Only registered paths
    participate: unrelated outputs (quarantine sidecars, checkpoints,
    non-workflow jobs in the same process) never enter the overlay.

    ``verify=True`` (the default) asserts, on the FIRST memory read of
    each artifact whose file sink was also written, that the in-memory
    lines are byte-identical to the file round-trip — the parity
    contract that makes skipping the file safe.  Paths registered with
    ``sink_file=False`` skip the file write entirely (the "optional
    sink" mode); their artifacts exist only in memory.
    """

    def __init__(self, verify: bool = True):
        self.verify = verify
        self._registered: Dict[str, bool] = {}     # abspath -> sink_file
        self._lines: Dict[str, List[str]] = {}
        self._verified: set = set()
        self.memory_reads = 0

    # -- registration ------------------------------------------------------
    def register(self, out_path: str, sink_file: bool = True) -> None:
        self._registered[os.path.abspath(out_path)] = sink_file

    def _owner(self, path: str) -> Optional[str]:
        """The registered path governing ``path`` (itself or its
        directory), or None."""
        ap = os.path.abspath(path)
        if ap in self._registered:
            return ap
        parent = os.path.dirname(ap)
        if parent in self._registered:
            return parent
        return None

    # -- producer side (write_output) --------------------------------------
    def wants(self, out_path: str) -> bool:
        return self._owner(out_path) is not None

    def sink_file(self, out_path: str) -> bool:
        owner = self._owner(out_path)
        return True if owner is None else self._registered[owner]

    def put(self, out_path: str, file_path: str, lines: List[str]) -> None:
        for key in {os.path.abspath(out_path), os.path.abspath(file_path)}:
            self._lines[key] = lines

    def peek(self, path: str) -> Optional[List[str]]:
        """The stored lines for ``path`` WITHOUT counting a memory read
        or running the parity check — for size estimation (the core.dag
        cost model measuring a sink-less upstream artifact)."""
        return self._lines.get(os.path.abspath(path))

    # -- consumer side (read_lines) ----------------------------------------
    def get(self, path: str) -> Optional[List[str]]:
        ap = os.path.abspath(path)
        lines = self._lines.get(ap)
        if lines is None:
            return None
        self.memory_reads += 1
        if self.verify and ap not in self._verified:
            if os.path.exists(ap):
                # may raise TornArtifactError (manifest validation) — a
                # failed check must NOT mark the artifact verified, so a
                # later read re-checks after a repair
                on_disk = list(_read_lines_files(ap))
                if on_disk != lines:
                    raise AssertionError(
                        f"artifact store: in-memory lines for {ap} differ "
                        f"from the file round-trip ({len(lines)} vs "
                        f"{len(on_disk)} lines) — handoff parity broken")
            self._verified.add(ap)
        return lines


_ARTIFACTS: Optional[ArtifactStore] = None


def set_artifact_store(store: Optional[ArtifactStore]
                       ) -> Optional[ArtifactStore]:
    """Install (or clear, with None) the process-global artifact overlay;
    returns the previous store so callers can restore it."""
    global _ARTIFACTS
    prev = _ARTIFACTS
    _ARTIFACTS = store
    return prev


def get_artifact_store() -> Optional[ArtifactStore]:
    return _ARTIFACTS


def _sha1_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def load_manifest(dir_path: str) -> Optional[dict]:
    """The directory's ``_MANIFEST`` document, or None when absent.
    An unreadable/garbled manifest IS a torn artifact (the publish died
    between the part replace and the manifest replace can never produce
    one — the manifest write is atomic — so garbage here means external
    corruption)."""
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath, "r") as fh:
            doc = json.load(fh)
        if not isinstance(doc.get("parts"), dict):
            raise ValueError("manifest has no parts table")
        return doc
    except (ValueError, OSError) as e:
        _durability_counters().incr("Durability", "Torn artifacts")
        raise TornArtifactError(
            f"{mpath} is unreadable ({e}) — artifact torn; re-run the "
            f"producing job") from None


#: validation memo: (dir abspath) -> (manifest stat sig, part stat sigs)
#: so repeated reads of an unchanged artifact hash its parts once
_VALIDATED: Dict[str, Tuple] = {}
_VALIDATED_CAP = 256


def _stat_sig(path: str):
    st = os.stat(path)
    return (st.st_size, st.st_mtime_ns)


def validate_artifact_dir(path: str, files: List[str]) -> None:
    """Durability validation for one directory input: the strict
    ``_SUCCESS`` check (``io.require.success=true``), then — when a
    ``_MANIFEST`` is present — per-part byte length + sha1 against it.
    Raises :class:`TornArtifactError` naming the path and part."""
    if _REQUIRE_SUCCESS and not os.path.exists(
            os.path.join(path, SUCCESS_NAME)):
        _durability_counters().incr("Durability", "Unmarked inputs refused")
        raise TornArtifactError(
            f"{path}: no {SUCCESS_NAME} marker — the producing job did not "
            f"complete (half-written upstream output?); re-run the "
            f"producer or unset {KEY_REQUIRE_SUCCESS}")
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return
    ap = os.path.abspath(path)
    sig = (_stat_sig(mpath), tuple(_stat_sig(fp) for fp in files))
    if _VALIDATED.get(ap) == sig:
        return
    doc = load_manifest(path)
    parts = doc["parts"]
    for fp in files:
        name = os.path.basename(fp)
        rec = parts.get(name)
        if not isinstance(rec, dict):
            _durability_counters().incr("Durability", "Torn artifacts")
            raise TornArtifactError(
                f"{path}: part {name} is not in {MANIFEST_NAME} — "
                f"partial overwrite detected; re-run the producing job")
        size = os.path.getsize(fp)
        if size != rec.get("bytes"):
            _durability_counters().incr("Durability", "Torn artifacts")
            raise TornArtifactError(
                f"{path}: part {name} is {size} bytes but {MANIFEST_NAME} "
                f"records {rec.get('bytes')} — torn artifact (crash "
                f"mid-write?); re-run the producing job")
        if _sha1_file(fp) != rec.get("sha1"):
            _durability_counters().incr("Durability", "Torn artifacts")
            raise TornArtifactError(
                f"{path}: part {name} checksum mismatch against "
                f"{MANIFEST_NAME} — torn/corrupt artifact; re-run the "
                f"producing job")
    # the reverse direction: every manifest entry must still exist on
    # disk, or the read silently consumes a PARTIAL artifact
    listed = {os.path.basename(fp) for fp in files}
    lost = sorted(set(parts) - listed)
    if lost:
        _durability_counters().incr("Durability", "Torn artifacts")
        raise TornArtifactError(
            f"{path}: {MANIFEST_NAME} records part(s) {', '.join(lost)} "
            f"that no longer exist — partial artifact (deleted/lost "
            f"part?); re-run the producing job")
    if len(_VALIDATED) >= _VALIDATED_CAP:
        _VALIDATED.clear()
    _VALIDATED[ap] = sig
    _durability_counters().incr("Durability", "Artifacts validated")


def _input_files(path: str) -> List[str]:
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if not f.startswith(("_", ".")) and os.path.isfile(os.path.join(path, f))
        )
        validate_artifact_dir(path, files)
        return files
    return [path]


def _read_lines_files(path: str) -> Iterator[str]:
    for fp in _input_files(path):
        with open(fp, "r") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if line:
                    yield line


def read_lines(path: str) -> Iterator[str]:
    """Yield every record line from a file or job-output directory.

    With an :class:`ArtifactStore` installed holding ``path``, the lines
    come from the in-memory artifact instead of disk (the core.dag
    stage-to-stage handoff); all other paths read normally."""
    store = _ARTIFACTS
    if store is not None:
        lines = store.get(path)
        if lines is not None:
            return iter(lines)
    return _read_lines_files(path)


def is_plain_delim(delim_regex: str) -> bool:
    """True when the configured delimiter regex is a literal single
    character (the overwhelmingly common ``field.delim.regex=,`` case) —
    the predicate every bulk/native fast path gates on."""
    return len(delim_regex) == 1 and delim_regex not in r".^$*+?{}[]\|()"


def split_line(line: str, delim_regex: str = ",") -> List[str]:
    """Split one record on the configured delimiter regex (plain-character
    fast path; regex split otherwise)."""
    if is_plain_delim(delim_regex):
        return line.split(delim_regex)
    return re.split(delim_regex, line)


def read_records(path: str, delim_regex: str = ",") -> Iterator[List[str]]:
    for line in read_lines(path):
        yield split_line(line, delim_regex)


def read_field_matrix(path: str, delim_regex: str = ","):
    """Bulk-load a rectangular delimited file (or part-file directory) as a
    2-D string ndarray with ONE whole-buffer split.

    This is the vectorized replacement for per-line ``read_records`` on the
    ingest hot path (the reference's input format is rectangular CSV in every
    schema-driven job). Returns ``None`` when the fast path does not apply —
    non-trivial delimiter regex or ragged rows — so callers can fall back to
    the record iterator.
    """
    if not is_plain_delim(delim_regex):
        return None
    import numpy as np

    lines: List[str] = []
    for fp in _input_files(path):
        with open(fp, "r") as fh:
            lines.extend(l for l in fh.read().split("\n") if l)
    if not lines:
        return np.empty((0, 0), dtype=str)
    n_delim = lines[0].count(delim_regex)
    # every line must be rectangular — a total-count check alone would let
    # ragged lines that happen to sum right silently shift fields across rows
    if any(l.count(delim_regex) != n_delim for l in lines):
        return None
    flat = delim_regex.join(lines).split(delim_regex)
    return np.asarray(flat, dtype=str).reshape(len(lines), n_delim + 1)


def _fsync_dir(dir_path: str) -> None:
    """Best-effort directory fsync so the rename itself is durable."""
    try:
        fd = os.open(dir_path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: str, text: str) -> None:
    """Crash-safe single-file write: stage to a temp file in the same
    directory, flush + fsync, then ``os.replace`` — a reader (or a
    resumed run) sees either the previous complete content or the new
    complete content, never a torn file.  The atomic primitive for
    artifact files written outside :class:`OutputWriter` (the
    decision-tree JSON checkpoint, the regression coefficient history)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix="." + os.path.basename(path) + ".",
                               dir=d)
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Binary twin of :func:`atomic_write_text` (same temp + fsync +
    ``os.replace`` contract) — for pickled sidecars like the analysis
    engine's parse cache."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix="." + os.path.basename(path) + ".",
                               dir=d)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class OutputWriter:
    """Writes job output in the reference's directory layout,
    crash-safely.

    ``OutputWriter(dir)`` produces ``dir/part-r-00000`` plus, on a
    successful close, a ``_MANIFEST`` sidecar (per-part byte length +
    sha1) and the ``_SUCCESS`` marker.  The part file is STAGED to a temp
    path in the same directory and published with ``fsync +
    os.replace`` — a crash mid-write leaves any previous part intact and
    the stage discarded, never a torn file under the final name (the old
    ``open(path, "w")`` tore in place).  ``shard`` selects the part
    number so callers can emulate partitioned reducer output
    (tree/DataPartitioner.java writes one part file per segment); shard
    manifests merge, so every part of a partitioned output validates.
    With ``as_dir=False`` the path is written as a bare file (atomic
    replace, no manifest/marker) and ``shard`` is rejected.

    ``binary=True`` opens the stage in bytes mode (use
    :meth:`write_bytes`) and ``name`` overrides the part file name —
    the ingest-cache artifact writes raw column matrices this way while
    inheriting the full manifest/_SUCCESS/torn-write machinery.
    ``mark_success=False`` publishes the part + manifest but defers the
    ``_SUCCESS`` marker, so a multi-part artifact's LAST writer commits
    the whole directory atomically (readers gate on ``_SUCCESS``).
    """

    def __init__(self, out_path: str, shard: Optional[int] = None,
                 as_dir: bool = True, name: Optional[str] = None,
                 binary: bool = False, mark_success: bool = True):
        self.out_path = out_path
        self.as_dir = as_dir
        self.mark_success = mark_success
        if as_dir:
            os.makedirs(out_path, exist_ok=True)
            if name is not None and shard is not None:
                raise ValueError("name and shard are mutually exclusive")
            self.file_path = os.path.join(
                out_path, name or f"part-r-{(shard or 0):05d}")
        else:
            if shard is not None:
                raise ValueError("shard is only meaningful with as_dir=True")
            parent = os.path.dirname(out_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self.file_path = out_path
        d = os.path.dirname(self.file_path) or "."
        fd, self._tmp_path = tempfile.mkstemp(
            prefix="." + os.path.basename(self.file_path) + ".", dir=d)
        self._fh = os.fdopen(fd, "wb" if binary else "w")
        self._binary = binary
        self._closed = False

    def write(self, line: str) -> None:
        if self._binary:
            raise TypeError("binary writer: use write_bytes")
        self._fh.write(line)
        self._fh.write("\n")

    def write_bytes(self, data) -> None:
        """Append raw bytes to the staged part (``binary=True`` mode;
        accepts anything exposing the buffer protocol, so numpy arrays
        stream without a copy)."""
        if not self._binary:
            raise TypeError("text writer: use write")
        self._fh.write(data)

    def write_all(self, lines: Iterable[str]) -> None:
        for line in lines:
            self.write(line)

    def _tear(self) -> None:
        """The ``torn_write`` fault point: simulate the LEGACY in-place
        writer crashing mid-write — half the staged bytes land under the
        final name, no manifest update, no ``_SUCCESS`` — then die.  Any
        stale ``_MANIFEST`` from a previous publish now disagrees with
        the torn bytes, which is exactly what reader validation (and the
        torn-artifact reload test) must catch."""
        with open(self._tmp_path, "rb") as fh:
            data = fh.read()
        with open(self.file_path, "wb") as out:
            out.write(data[:max(len(data) // 2, 1)])
        try:
            os.unlink(self._tmp_path)
        except OSError:
            pass
        raise faultinject.InjectedFault(
            f"injected torn write ({self.file_path})")

    def _update_manifest(self) -> None:
        """Merge this part into the directory's ``_MANIFEST`` (other
        shards' entries survive) and rewrite it atomically."""
        parts: Dict[str, dict] = {}
        existing = os.path.join(self.out_path, MANIFEST_NAME)
        if os.path.exists(existing):
            try:
                with open(existing, "r") as fh:
                    doc = json.load(fh)
                if isinstance(doc.get("parts"), dict):
                    parts = doc["parts"]
            except (ValueError, OSError):
                pass        # rewrite from scratch: this part is the truth
        name = os.path.basename(self.file_path)
        parts[name] = {"bytes": os.path.getsize(self.file_path),
                       "sha1": _sha1_file(self.file_path)}
        # drop entries whose part no longer exists (a re-run that writes
        # fewer shards must not leave the manifest naming ghosts)
        parts = {n: rec for n, rec in parts.items()
                 if os.path.exists(os.path.join(self.out_path, n))}
        atomic_write_text(existing, json.dumps(
            {"version": MANIFEST_VERSION, "parts": parts}, indent=1))

    def close(self, success_marker: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        fh = self._fh
        fh.flush()
        if success_marker:
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass
        fh.close()
        if not success_marker:
            # aborted write: discard the stage — any previous artifact
            # at the final path stays intact and validated
            try:
                os.unlink(self._tmp_path)
            except OSError:
                pass
            return
        fi = faultinject.get_injector()
        if fi is not None and fi.armed("torn_write") is not None:
            self._tear()
        os.replace(self._tmp_path, self.file_path)
        _fsync_dir(os.path.dirname(self.file_path))
        _VALIDATED.pop(os.path.abspath(self.out_path), None)
        if self.as_dir:
            self._update_manifest()
            if self.mark_success:
                open(os.path.join(self.out_path, SUCCESS_NAME), "w").close()

    def __enter__(self) -> "OutputWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close(success_marker=exc[0] is None)


def write_output(out_path: str, lines: Iterable[str], shard: Optional[int] = None,
                 as_dir: bool = True) -> str:
    """One-shot job-output write; returns the part file path.

    With an :class:`ArtifactStore` installed and ``out_path`` registered,
    the lines are ALSO recorded in memory for downstream stages; a path
    registered with ``sink_file=False`` skips the disk write entirely
    (the artifact lives only in the overlay)."""
    store = _ARTIFACTS
    if store is not None and store.wants(out_path):
        lines = list(lines)
        file_path = (os.path.join(out_path, f"part-r-{(shard or 0):05d}")
                     if as_dir else out_path)
        store.put(out_path, file_path, lines)
        if not store.sink_file(out_path):
            return file_path
    with OutputWriter(out_path, shard=shard, as_dir=as_dir) as w:
        w.write_all(lines)
    return w.file_path

"""Text I/O: delimited records in, ``part-r-*`` job outputs out.

The reference's jobs consume newline-delimited text split on
``field.delim.regex`` and write delimited text to ``part-r-NNNNN`` files in an
output directory (every driver; conventions visible in e.g.
resource/knn.properties ``bayesian.model.file.path=.../part-r-00000``).
We keep both conventions so the file surface is interchangeable: a model file
written here can be read by reference tooling and vice versa.

Input paths may be a single file or a directory (all non-hidden files inside,
sorted — mirroring how MR consumes every part file of a previous job's output
directory).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, Iterator, List, Optional


class ArtifactStore:
    """In-memory overlay for job-output artifacts (the core.dag handoff).

    A workflow DAG chains jobs whose intermediate artifacts (a trained
    NB model, an MI feature ranking) are text files only because the
    reference's MR stages had no other channel.  While a store is
    installed (``set_artifact_store``), ``write_output`` to a REGISTERED
    stage output path also records the lines in memory, and
    ``read_lines`` on that path serves them from memory — the downstream
    stage consumes the producer's in-memory artifact and the text file
    becomes a sink, not the transport.  Only registered paths
    participate: unrelated outputs (quarantine sidecars, checkpoints,
    non-workflow jobs in the same process) never enter the overlay.

    ``verify=True`` (the default) asserts, on the FIRST memory read of
    each artifact whose file sink was also written, that the in-memory
    lines are byte-identical to the file round-trip — the parity
    contract that makes skipping the file safe.  Paths registered with
    ``sink_file=False`` skip the file write entirely (the "optional
    sink" mode); their artifacts exist only in memory.
    """

    def __init__(self, verify: bool = True):
        self.verify = verify
        self._registered: Dict[str, bool] = {}     # abspath -> sink_file
        self._lines: Dict[str, List[str]] = {}
        self._verified: set = set()
        self.memory_reads = 0

    # -- registration ------------------------------------------------------
    def register(self, out_path: str, sink_file: bool = True) -> None:
        self._registered[os.path.abspath(out_path)] = sink_file

    def _owner(self, path: str) -> Optional[str]:
        """The registered path governing ``path`` (itself or its
        directory), or None."""
        ap = os.path.abspath(path)
        if ap in self._registered:
            return ap
        parent = os.path.dirname(ap)
        if parent in self._registered:
            return parent
        return None

    # -- producer side (write_output) --------------------------------------
    def wants(self, out_path: str) -> bool:
        return self._owner(out_path) is not None

    def sink_file(self, out_path: str) -> bool:
        owner = self._owner(out_path)
        return True if owner is None else self._registered[owner]

    def put(self, out_path: str, file_path: str, lines: List[str]) -> None:
        for key in {os.path.abspath(out_path), os.path.abspath(file_path)}:
            self._lines[key] = lines

    def peek(self, path: str) -> Optional[List[str]]:
        """The stored lines for ``path`` WITHOUT counting a memory read
        or running the parity check — for size estimation (the core.dag
        cost model measuring a sink-less upstream artifact)."""
        return self._lines.get(os.path.abspath(path))

    # -- consumer side (read_lines) ----------------------------------------
    def get(self, path: str) -> Optional[List[str]]:
        ap = os.path.abspath(path)
        lines = self._lines.get(ap)
        if lines is None:
            return None
        self.memory_reads += 1
        if self.verify and ap not in self._verified:
            self._verified.add(ap)
            if os.path.exists(ap):
                on_disk = list(_read_lines_files(ap))
                if on_disk != lines:
                    raise AssertionError(
                        f"artifact store: in-memory lines for {ap} differ "
                        f"from the file round-trip ({len(lines)} vs "
                        f"{len(on_disk)} lines) — handoff parity broken")
        return lines


_ARTIFACTS: Optional[ArtifactStore] = None


def set_artifact_store(store: Optional[ArtifactStore]
                       ) -> Optional[ArtifactStore]:
    """Install (or clear, with None) the process-global artifact overlay;
    returns the previous store so callers can restore it."""
    global _ARTIFACTS
    prev = _ARTIFACTS
    _ARTIFACTS = store
    return prev


def get_artifact_store() -> Optional[ArtifactStore]:
    return _ARTIFACTS


def _input_files(path: str) -> List[str]:
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if not f.startswith(("_", ".")) and os.path.isfile(os.path.join(path, f))
        )
    return [path]


def _read_lines_files(path: str) -> Iterator[str]:
    for fp in _input_files(path):
        with open(fp, "r") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if line:
                    yield line


def read_lines(path: str) -> Iterator[str]:
    """Yield every record line from a file or job-output directory.

    With an :class:`ArtifactStore` installed holding ``path``, the lines
    come from the in-memory artifact instead of disk (the core.dag
    stage-to-stage handoff); all other paths read normally."""
    store = _ARTIFACTS
    if store is not None:
        lines = store.get(path)
        if lines is not None:
            return iter(lines)
    return _read_lines_files(path)


def is_plain_delim(delim_regex: str) -> bool:
    """True when the configured delimiter regex is a literal single
    character (the overwhelmingly common ``field.delim.regex=,`` case) —
    the predicate every bulk/native fast path gates on."""
    return len(delim_regex) == 1 and delim_regex not in r".^$*+?{}[]\|()"


def split_line(line: str, delim_regex: str = ",") -> List[str]:
    """Split one record on the configured delimiter regex (plain-character
    fast path; regex split otherwise)."""
    if is_plain_delim(delim_regex):
        return line.split(delim_regex)
    return re.split(delim_regex, line)


def read_records(path: str, delim_regex: str = ",") -> Iterator[List[str]]:
    for line in read_lines(path):
        yield split_line(line, delim_regex)


def read_field_matrix(path: str, delim_regex: str = ","):
    """Bulk-load a rectangular delimited file (or part-file directory) as a
    2-D string ndarray with ONE whole-buffer split.

    This is the vectorized replacement for per-line ``read_records`` on the
    ingest hot path (the reference's input format is rectangular CSV in every
    schema-driven job). Returns ``None`` when the fast path does not apply —
    non-trivial delimiter regex or ragged rows — so callers can fall back to
    the record iterator.
    """
    if not is_plain_delim(delim_regex):
        return None
    import numpy as np

    lines: List[str] = []
    for fp in _input_files(path):
        with open(fp, "r") as fh:
            lines.extend(l for l in fh.read().split("\n") if l)
    if not lines:
        return np.empty((0, 0), dtype=str)
    n_delim = lines[0].count(delim_regex)
    # every line must be rectangular — a total-count check alone would let
    # ragged lines that happen to sum right silently shift fields across rows
    if any(l.count(delim_regex) != n_delim for l in lines):
        return None
    flat = delim_regex.join(lines).split(delim_regex)
    return np.asarray(flat, dtype=str).reshape(len(lines), n_delim + 1)


class OutputWriter:
    """Writes job output in the reference's directory layout.

    ``OutputWriter(dir)`` produces ``dir/part-r-00000`` (plus ``_SUCCESS`` on
    close). ``shard`` selects the part number so callers can emulate
    partitioned reducer output (tree/DataPartitioner.java writes one part file
    per segment); with ``as_dir=False`` the path is written as a bare file
    (truncating any existing content) and ``shard`` is rejected.
    """

    def __init__(self, out_path: str, shard: Optional[int] = None, as_dir: bool = True):
        self.out_path = out_path
        self.as_dir = as_dir
        if as_dir:
            os.makedirs(out_path, exist_ok=True)
            self.file_path = os.path.join(out_path, f"part-r-{(shard or 0):05d}")
        else:
            if shard is not None:
                raise ValueError("shard is only meaningful with as_dir=True")
            parent = os.path.dirname(out_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self.file_path = out_path
        self._fh = open(self.file_path, "w")

    def write(self, line: str) -> None:
        self._fh.write(line)
        self._fh.write("\n")

    def write_all(self, lines: Iterable[str]) -> None:
        for line in lines:
            self.write(line)

    def close(self, success_marker: bool = True) -> None:
        self._fh.close()
        if self.as_dir and success_marker:
            open(os.path.join(self.out_path, "_SUCCESS"), "w").close()

    def __enter__(self) -> "OutputWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close(success_marker=exc[0] is None)


def write_output(out_path: str, lines: Iterable[str], shard: Optional[int] = None,
                 as_dir: bool = True) -> str:
    """One-shot job-output write; returns the part file path.

    With an :class:`ArtifactStore` installed and ``out_path`` registered,
    the lines are ALSO recorded in memory for downstream stages; a path
    registered with ``sink_file=False`` skips the disk write entirely
    (the artifact lives only in the overlay)."""
    store = _ARTIFACTS
    if store is not None and store.wants(out_path):
        lines = list(lines)
        file_path = (os.path.join(out_path, f"part-r-{(shard or 0):05d}")
                     if as_dir else out_path)
        store.put(out_path, file_path, lines)
        if not store.sink_file(out_path):
            return file_path
    with OutputWriter(out_path, shard=shard, as_dir=as_dir) as w:
        w.write_all(lines)
    return w.file_path

"""Text I/O: delimited records in, ``part-r-*`` job outputs out.

The reference's jobs consume newline-delimited text split on
``field.delim.regex`` and write delimited text to ``part-r-NNNNN`` files in an
output directory (every driver; conventions visible in e.g.
resource/knn.properties ``bayesian.model.file.path=.../part-r-00000``).
We keep both conventions so the file surface is interchangeable: a model file
written here can be read by reference tooling and vice versa.

Input paths may be a single file or a directory (all non-hidden files inside,
sorted — mirroring how MR consumes every part file of a previous job's output
directory).
"""

from __future__ import annotations

import os
import re
from typing import Iterable, Iterator, List, Optional


def _input_files(path: str) -> List[str]:
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if not f.startswith(("_", ".")) and os.path.isfile(os.path.join(path, f))
        )
    return [path]


def read_lines(path: str) -> Iterator[str]:
    """Yield every record line from a file or job-output directory."""
    for fp in _input_files(path):
        with open(fp, "r") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if line:
                    yield line


def is_plain_delim(delim_regex: str) -> bool:
    """True when the configured delimiter regex is a literal single
    character (the overwhelmingly common ``field.delim.regex=,`` case) —
    the predicate every bulk/native fast path gates on."""
    return len(delim_regex) == 1 and delim_regex not in r".^$*+?{}[]\|()"


def split_line(line: str, delim_regex: str = ",") -> List[str]:
    """Split one record on the configured delimiter regex (plain-character
    fast path; regex split otherwise)."""
    if is_plain_delim(delim_regex):
        return line.split(delim_regex)
    return re.split(delim_regex, line)


def read_records(path: str, delim_regex: str = ",") -> Iterator[List[str]]:
    for line in read_lines(path):
        yield split_line(line, delim_regex)


def read_field_matrix(path: str, delim_regex: str = ","):
    """Bulk-load a rectangular delimited file (or part-file directory) as a
    2-D string ndarray with ONE whole-buffer split.

    This is the vectorized replacement for per-line ``read_records`` on the
    ingest hot path (the reference's input format is rectangular CSV in every
    schema-driven job). Returns ``None`` when the fast path does not apply —
    non-trivial delimiter regex or ragged rows — so callers can fall back to
    the record iterator.
    """
    if not is_plain_delim(delim_regex):
        return None
    import numpy as np

    lines: List[str] = []
    for fp in _input_files(path):
        with open(fp, "r") as fh:
            lines.extend(l for l in fh.read().split("\n") if l)
    if not lines:
        return np.empty((0, 0), dtype=str)
    n_delim = lines[0].count(delim_regex)
    # every line must be rectangular — a total-count check alone would let
    # ragged lines that happen to sum right silently shift fields across rows
    if any(l.count(delim_regex) != n_delim for l in lines):
        return None
    flat = delim_regex.join(lines).split(delim_regex)
    return np.asarray(flat, dtype=str).reshape(len(lines), n_delim + 1)


class OutputWriter:
    """Writes job output in the reference's directory layout.

    ``OutputWriter(dir)`` produces ``dir/part-r-00000`` (plus ``_SUCCESS`` on
    close). ``shard`` selects the part number so callers can emulate
    partitioned reducer output (tree/DataPartitioner.java writes one part file
    per segment); with ``as_dir=False`` the path is written as a bare file
    (truncating any existing content) and ``shard`` is rejected.
    """

    def __init__(self, out_path: str, shard: Optional[int] = None, as_dir: bool = True):
        self.out_path = out_path
        self.as_dir = as_dir
        if as_dir:
            os.makedirs(out_path, exist_ok=True)
            self.file_path = os.path.join(out_path, f"part-r-{(shard or 0):05d}")
        else:
            if shard is not None:
                raise ValueError("shard is only meaningful with as_dir=True")
            parent = os.path.dirname(out_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self.file_path = out_path
        self._fh = open(self.file_path, "w")

    def write(self, line: str) -> None:
        self._fh.write(line)
        self._fh.write("\n")

    def write_all(self, lines: Iterable[str]) -> None:
        for line in lines:
            self.write(line)

    def close(self, success_marker: bool = True) -> None:
        self._fh.close()
        if self.as_dir and success_marker:
            open(os.path.join(self.out_path, "_SUCCESS"), "w").close()

    def __enter__(self) -> "OutputWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close(success_marker=exc[0] is None)


def write_output(out_path: str, lines: Iterable[str], shard: Optional[int] = None,
                 as_dir: bool = True) -> str:
    """One-shot job-output write; returns the part file path."""
    with OutputWriter(out_path, shard=shard, as_dir=as_dir) as w:
        w.write_all(lines)
    return w.file_path

"""Deterministic fault injection: seeded, config-driven fault plans.

The reference substrate got fault tolerance for free — Hadoop re-executes
failed map tasks, Storm replays tuples — so the original codebase has no
recovery paths to test.  The TPU rebuild's recovery paths (retry with
backoff, checkpoint/resume, quarantine, serving circuit breakers; see
``core.resilience`` / ``core.checkpoint`` / ``serve.breaker``) only stay
honest if every fault class they claim to handle can be produced ON
DEMAND and REPRODUCIBLY.  This module is that switchboard: a fault plan
parsed from the job config names which fault fires at which occurrence
index of which instrumented point, so a recovery test is an ordinary
deterministic test, not a race.

Config surface (the .properties files every job loads):

- ``fault.inject.plan`` — semicolon/comma-separated entries::

      <point>[<tag>]@<index>[-<index2>|*][x<count>][:<arg>]

  The optional ``[<tag>]`` qualifier restricts an entry to call sites
  firing with that tag (serving batchers tag scorer points with their
  model VARIANT, so ``scorer_slow[f32]@*:40`` slows only the f32
  variant — the router-demotion test); untagged entries fire at every
  site.  e.g. ``read@0-1`` (the first two file-read attempts raise a transient
  I/O error, the third succeeds — the retry path; auto-indexed points
  count every CALL, so consecutive failures are index ranges, while
  ``x<count>`` repeats a fault at one explicit chunk index across
  retries of that same chunk), ``corrupt@3`` (chunk 3's bytes
  are mangled — the quarantine path), ``slow@5:50`` (a 50 ms stall at
  chunk 5), ``h2d@4`` (chunk 4's device transfer raises — fail fast with
  a resumable checkpoint), ``worker_death@6`` (the prefetch worker dies
  WITHOUT relaying an error — the consumer watchdog path),
  ``scorer@0-7`` (the first 8 scorer batches fail — opens the serving
  circuit breaker), ``batcher_death@0`` (a batcher worker thread dies —
  the serving watchdog restart path).
- ``fault.inject.seed`` — seeds the corruption byte generator (default
  2026) so a corrupted chunk is byte-identical across runs.

Instrumented points (grep ``fire(`` / ``mangle(`` call sites):

====================  =====================================================
``read``              file-read attempts (``native._read_buffer``, the
                      line-chunk reader) — raises ``InjectedReadError``
                      (an ``OSError``: retryable)
``corrupt``           byte chunks by chunk index — bytes are overwritten
                      (``mangle``), not raised
``slow``              byte chunks by chunk index — sleeps ``arg`` ms
                      (default 20)
``h2d``               host->device chunk transfers — raises
                      ``InjectedFault`` (non-retryable)
``worker_death``      byte chunks by chunk index, on the prefetch worker
                      — raises ``SimulatedWorkerDeath`` (a BaseException
                      the relay deliberately does NOT catch)
``scorer``            serving scorer batches — raises
                      ``InjectedScorerFault``
``scorer_slow``       serving scorer batches — sleeps ``arg`` ms
                      (default 20): the deterministic slow scorer that
                      drives a windowed p99 past ``serve.slo.p99.ms``
                      (the SLO-violation test in tests/test_slo.py)
``batcher_death``     serving batcher worker loop iterations — raises
                      ``SimulatedWorkerDeath``
``scorer_poison``     serving scorer batches whose lines contain the
                      entry's ``arg`` marker (default "POISON") — raises
                      ``InjectedScorerFault`` for the WHOLE batch, like a
                      real poison row does (the bisect-isolation path in
                      serve/batcher.py; content-based, so every rescored
                      sub-batch containing the row fails too)
``torn_write``        ``OutputWriter.close`` publishes — simulates the
                      legacy in-place writer crashing mid-write: half the
                      staged bytes land at the final path with NO
                      manifest/_SUCCESS update, then ``InjectedFault``
                      (the reader-validation / safe-reload path)
``ckpt_corrupt``      checkpoint sidecar saves by save index — the
                      just-written sidecar is truncated in place after a
                      successful save (crash mid-checkpoint-write /disk
                      corruption; the generation-fallback path)
``feedback_dup``      feedback-consumer read batches by batch index —
                      the delivered entries are delivered AGAIN in the
                      same batch (at-least-once redelivery; the offset
                      watermark must dedupe — ``armed``, enacted by the
                      consumer)
``feedback_reorder``  feedback-consumer read batches by batch index —
                      the delivered entries arrive in reversed order
                      (the consumer's id sort must restore application
                      order — ``armed``, enacted by the consumer)
``feedback_drop``     feedback-consumer read batches by batch index —
                      raises ``InjectedFault`` AFTER the transport
                      delivered the batch but BEFORE any of it was
                      applied (consumer crash: the entries stay pending
                      unacked and must be redelivered on resume with
                      zero drops or double-applies)
``promote_slow``      model-cache promote jobs (serve/modelcache.py),
                      fired with the MODEL NAME as the call-site tag —
                      sleeps ``arg`` ms (default 20): deterministic slow
                      cold starts for the retry_after / deadline tests
``promote_fail``      model-cache promote jobs (tagged by model name) —
                      raises ``InjectedFault`` before any variant group
                      builds: the promote fails structurally and the
                      previously-resident set keeps serving untouched
                      (the chaos test in tests/test_modelcache.py)
====================  =====================================================

Disabled-mode cost: ``get_injector()`` returns None until a plan is
configured, and every call site guards on that — zero work on the hot
path.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import sanitizer

DEFAULT_SEED = 2026

KEY_PLAN = "fault.inject.plan"
KEY_SEED = "fault.inject.seed"

#: the known instrumented points (parse-time typo guard)
POINTS = ("read", "corrupt", "slow", "h2d", "worker_death", "scorer",
          "scorer_slow", "batcher_death", "scorer_poison", "torn_write",
          "ckpt_corrupt", "feedback_dup", "feedback_reorder",
          "feedback_drop", "promote_slow", "promote_fail")


class InjectedReadError(OSError):
    """Injected transient I/O failure — an OSError, so the default
    retry policy (core.resilience) retries it."""


class InjectedFault(RuntimeError):
    """Injected non-retryable failure (e.g. an H2D transfer error): the
    job must fail fast, leaving any checkpoint behind for ``--resume``."""


class InjectedScorerFault(RuntimeError):
    """Injected serving scorer failure (feeds the circuit breaker)."""


class SimulatedWorkerDeath(BaseException):
    """Simulates a worker thread dying WITHOUT running its error relay
    (the hard-death case: the relay itself is what failed).  Derives
    from BaseException so ``except Exception`` handlers — including the
    batcher's per-batch guard — do not swallow it."""


class _Entry:
    __slots__ = ("point", "lo", "hi", "count", "arg", "tag")

    def __init__(self, point: str, lo: int, hi: Optional[int],
                 count: int, arg: Optional[str], tag: Optional[str] = None):
        self.point = point
        self.lo = lo
        self.hi = hi          # None = unbounded (the `*` index)
        self.count = count    # firings per matched index (x<count>)
        self.arg = arg
        self.tag = tag        # None = any call site; else only sites
        #                       firing with this tag (e.g. a serving
        #                       scorer variant: scorer_slow[f32]@*)

    def matches(self, index: int, tag: Optional[str] = None) -> bool:
        if self.tag is not None and tag != self.tag:
            return False
        return index >= self.lo and (self.hi is None or index <= self.hi)

    def __repr__(self):
        hi = "*" if self.hi is None else self.hi
        t = f"[{self.tag}]" if self.tag else ""
        return (f"_Entry({self.point}{t}@{self.lo}-{hi}"
                f"x{self.count}:{self.arg})")


def parse_plan(text: str) -> List[_Entry]:
    """Parse a ``fault.inject.plan`` value into entries (see module
    docstring for the grammar)."""
    entries: List[_Entry] = []
    for raw in text.replace(";", ",").split(","):
        s = raw.strip()
        if not s:
            continue
        if "@" not in s:
            raise ValueError(f"bad fault plan entry (no '@'): {s!r}")
        point, _, spec = s.partition("@")
        point = point.strip()
        tag: Optional[str] = None
        if point.endswith("]") and "[" in point:
            # optional call-site tag qualifier: point[tag]@spec — the
            # entry fires only at sites passing fire(..., tag=<tag>)
            # (e.g. one serving scorer VARIANT: scorer_slow[f32]@*:40)
            point, _, tag = point[:-1].partition("[")
            point = point.strip()
            tag = tag.strip()
            if not tag:
                raise ValueError(f"empty tag qualifier in {s!r}")
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {', '.join(POINTS)}")
        arg: Optional[str] = None
        if ":" in spec:
            spec, _, arg = spec.partition(":")
        count = 1
        if "x" in spec:
            spec, _, cnt = spec.partition("x")
            count = int(cnt)
            if count < 1:
                raise ValueError(f"bad fault count in {s!r}")
        spec = spec.strip()
        if spec == "*":
            lo, hi = 0, None
        elif "-" in spec:
            a, _, b = spec.partition("-")
            lo, hi = int(a), int(b)
        else:
            lo = hi = int(spec)
        entries.append(_Entry(point, lo, hi, count, arg, tag))
    return entries


class FaultInjector:
    """Fires the planned faults; deterministic per (entry, index).

    Call sites pass an explicit index when the point has a natural one
    (chunk index); otherwise the injector keeps a per-point occurrence
    counter (file reads, scorer batches).  Each matched (entry, index)
    fires at most ``entry.count`` times — so a plan like ``read@0x2``
    models a TRANSIENT fault (two failures, then success: the retry
    path) while ``read@0x99`` models a persistent one (the retry budget
    exhausts and the job fails)."""

    def __init__(self, plan: List[_Entry], seed: int = DEFAULT_SEED):
        self.plan = plan
        self.seed = int(seed)
        self._lock = sanitizer.make_lock("core.faultinject")
        self._auto: Dict[str, int] = {}
        self._fired: Dict[Tuple[int, int], int] = {}
        self.fired_log: List[Tuple[str, int]] = []

    # -- index bookkeeping -------------------------------------------------
    def _next_index(self, point: str, tag: Optional[str] = None) -> int:
        # per-(point, tag) occurrence counters so tagged call sites
        # (e.g. two scorer variants) keep deterministic indices no
        # matter how their firings interleave
        key = point if tag is None else f"{point}[{tag}]"
        with self._lock:
            i = self._auto.get(key, 0)
            self._auto[key] = i + 1
            return i

    def _due(self, point: str, index: Optional[int],
             tag: Optional[str] = None):
        """The first still-armed entry matching (point, index, tag),
        consuming one firing; None when nothing fires."""
        if index is None:
            index = self._next_index(point, tag)
        with self._lock:
            for eid, e in enumerate(self.plan):
                if e.point != point or not e.matches(index, tag):
                    continue
                # the fired budget is keyed per call-site tag too: an
                # UNTAGGED entry like scorer@0 fires at each tagged
                # site's own index 0 (deterministic per site) instead
                # of being consumed by whichever site races there first
                k = (eid, index, tag)
                if self._fired.get(k, 0) >= e.count:
                    continue
                self._fired[k] = self._fired.get(k, 0) + 1
                self.fired_log.append((point, index))
                return e
        return None

    # -- the injection points ----------------------------------------------
    def armed(self, point: str, index: Optional[int] = None,
              tag: Optional[str] = None):
        """The armed entry matching (point, index, tag), CONSUMING one
        firing, or None — for points whose fault is enacted by the call
        site itself rather than raised here (``torn_write`` tears the
        staged file, ``ckpt_corrupt`` truncates the just-written
        sidecar)."""
        return self._due(point, index, tag)

    def fire_poison(self, lines, tag: Optional[str] = None) -> None:
        """The ``scorer_poison`` point: raise InjectedScorerFault when
        any of the batch's ``lines`` contains an armed entry's marker
        (``arg``, default "POISON").  Content-based, so the bisect
        isolation in serve/batcher.py deterministically re-fails every
        rescored sub-batch still containing the poison row while its
        cohabitants' sub-batches succeed."""
        matched = [
            (eid, e) for eid, e in enumerate(self.plan)
            if e.point == "scorer_poison"
            and (e.tag is None or e.tag == tag)
            and any((e.arg or "POISON") in l for l in lines)]
        if not matched:
            return
        # one occurrence index per marker-matching batch; the firing
        # budget consumed belongs to the entry whose marker matched (an
        # exhausted entry falls through to the next matching one, so a
        # multi-marker plan's budgets stay independent)
        index = self._next_index("scorer_poison", tag)
        with self._lock:
            for eid, e in matched:
                if not e.matches(index, tag):
                    continue
                k = (eid, index, tag)
                if self._fired.get(k, 0) >= e.count:
                    continue
                self._fired[k] = self._fired.get(k, 0) + 1
                self.fired_log.append(("scorer_poison", index))
                raise InjectedScorerFault(
                    f"injected poison-batch failure "
                    f"(marker {(e.arg or 'POISON')!r} in batch)")

    def fire(self, point: str, index: Optional[int] = None,
             tag: Optional[str] = None) -> None:
        """Raise/sleep per the plan at an instrumented point (no-op when
        no armed entry matches).  ``tag`` identifies the call site for
        tag-qualified plan entries (``point[tag]@...``); untagged
        entries fire regardless of the site's tag."""
        e = self._due(point, index, tag)
        if e is None:
            return
        where = f"{point}@{index if index is not None else 'auto'}"
        if point == "read":
            raise InjectedReadError(f"injected transient read error ({where})")
        if point in ("slow", "scorer_slow", "promote_slow"):
            time.sleep(float(e.arg or 20) / 1000.0)
            return
        if point == "h2d":
            raise InjectedFault(f"injected H2D transfer failure ({where})")
        if point in ("worker_death", "batcher_death"):
            raise SimulatedWorkerDeath(f"injected worker death ({where})")
        if point == "scorer":
            raise InjectedScorerFault(f"injected scorer failure ({where})")
        raise InjectedFault(f"injected fault ({where})")     # corrupt via
        #                                                      mangle() only

    def mangle(self, point: str, index: int, data: bytes) -> bytes:
        """Return ``data`` corrupted per the plan (identity when no armed
        entry matches).  ``arg`` "truncate" drops the tail half of the
        chunk mid-line; the default garbles a seeded window by
        overwriting its alphanumeric bytes with non-ASCII garbage while
        PRESERVING delimiters and newlines — every overlapped row keeps
        its field structure but its numeric fields stop parsing, so the
        corruption is reliably detected row-by-row (the quarantine
        path) instead of occasionally fusing two rows into one
        structurally-valid record that would slip through unlogged."""
        e = self._due(point, index)
        if e is None or not data:
            return data
        if e.arg == "truncate":
            return data[:max(len(data) // 2, 1)]
        rng = random.Random(self.seed * 1_000_003 + index)
        span = min(len(data), 64)
        start = rng.randrange(max(len(data) - span, 1))
        window = bytearray(data[start:start + span])
        for i, b in enumerate(window):
            if (0x30 <= b <= 0x39 or 0x41 <= b <= 0x5A
                    or 0x61 <= b <= 0x7A):
                window[i] = rng.randrange(0x80, 0xFF)
        return data[:start] + bytes(window) + data[start + span:]


_INJECTOR: Optional[FaultInjector] = None


def get_injector() -> Optional[FaultInjector]:
    """The process-global injector, or None when no plan is configured
    (the hot-path guard every call site uses)."""
    return _INJECTOR


def set_injector(inj: Optional[FaultInjector]) -> Optional[FaultInjector]:
    global _INJECTOR
    _INJECTOR = inj
    return inj


def configure_from_config(config) -> Optional[FaultInjector]:
    """Install the injector described by ``fault.inject.plan`` (clears
    any previous injector when the key is absent)."""
    text = config.get(KEY_PLAN)
    if not text:
        return set_injector(None)
    return set_injector(FaultInjector(
        parse_plan(text), seed=config.get_int(KEY_SEED, DEFAULT_SEED)))

"""Labeled count matrices with the reference's normalization/serialization.

Equivalent of avenir's ``StateTransitionProbability`` (extends chombo
``TabularData``; util/StateTransitionProbability.java:28-129): integer count
matrix, row normalization with whole-row Laplace correction, int-scaled or
double output, one comma-joined row per line.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

DELIM = ","


def laplace_correct_rows(counts: np.ndarray) -> np.ndarray:
    """If ANY cell in a row is zero, add 1 to EVERY cell of that row
    (util/StateTransitionProbability.java:66-78 — whole-row correction, not
    per-cell)."""
    counts = np.asarray(counts, dtype=np.int64).copy()
    needs = (counts == 0).any(axis=-1)
    counts[needs] += 1
    return counts


def normalize_rows(counts: np.ndarray, scale: int) -> np.ndarray:
    """Row-normalize with Laplace correction.  ``scale > 1``: integer
    ``(count * scale) // rowSum`` (Java int division,
    StateTransitionProbability.java:89); ``scale == 1``: float division."""
    c = laplace_correct_rows(counts)
    row_sum = c.sum(axis=-1, keepdims=True)
    if scale > 1:
        return (c * scale) // row_sum
    return c / row_sum


def serialize_matrix(mat: np.ndarray) -> List[str]:
    """One comma-joined line per row; ints stay ints, doubles print as Java
    Double.toString-compatible reprs."""
    lines = []
    for row in np.atleast_2d(mat):
        if np.issubdtype(row.dtype, np.integer):
            lines.append(DELIM.join(str(int(v)) for v in row))
        else:
            lines.append(DELIM.join(repr(float(v)) for v in row))
    return lines


def deserialize_matrix(lines: Sequence[str], num_rows: int) -> np.ndarray:
    """Parse ``num_rows`` comma-joined numeric lines into a float matrix
    (the reference loads scaled-int model files into DoubleTable,
    markov/MarkovModel.java:51-62 — everything becomes double)."""
    return np.asarray([[float(v) for v in lines[i].split(DELIM)]
                       for i in range(num_rows)])

"""Production telemetry: periodic export, Prometheus exposition, compile
and device-memory profiling, and count-distribution drift gauges.

PR 3 (core.obs) built the in-process substrate — spans, mergeable
histograms, a ``Metrics`` registry — but every number died with the
process: ``snapshot()`` was a dict you could only see through the serve
``stats`` command or a ``--trace`` file at exit.  This module is the
operational layer on top (the TF-Serving/INFaaS premise from PAPERS.md —
a served model you cannot scrape, alert on, or profile is not
production-grade):

- **Process-global registry** — :func:`get_metrics` is the one
  ``Metrics`` every subsystem feeds (compile counters, device-memory
  gauges, drift gauges, serving overlays); the exporter snapshots it.
- **Periodic exporter** (:class:`TelemetryExporter`) — a background
  thread snapshotting the registry every ``telemetry.interval.sec``
  into an append-only JSONL time-series file (``--metrics-out`` on
  every batch job, ``telemetry.jsonl.path`` on serve).  Snapshots are
  MERGEABLE across processes (:func:`merge_snapshots`: histogram bucket
  counts add, monotonic counters sum, gauges latest-timestamp-wins), so
  multi-host aggregation is a fold, not a redesign.
- **Prometheus text exposition** (:func:`prometheus_text`) — the same
  snapshot rendered in the text exposition format any scraper parses
  (the serve frontend's ``metrics`` command; terminated by ``# EOF``).
- **Profiling hooks** — :func:`profiled_jit` wraps ``jax.jit`` on the
  hot paths (pipeline fold, multiscan, scorer warmup) and bills every
  cache-miss invocation to an ``xla.compile`` span + the cumulative
  ``Telemetry / xla.compile.ms`` counter; :func:`sample_device_memory`
  samples per-device ``memory_stats`` (falling back to summing
  ``jax.live_arrays``) into a ``device.hbm.bytes`` gauge, rate-limited
  for per-chunk/per-batch call sites.
- **Drift gauges** — :func:`count_drift` (symmetrised KL over smoothed
  count distributions) feeds per-feature ``drift.<feature>`` gauges
  when a re-scan trains against a stored baseline count table
  (``telemetry.drift.baseline.path`` on the NB trainer) — the concrete
  sensor ROADMAP item 4's retrain trigger consumes.
- **Incremental trace flush** (:class:`TraceFlusher`) — with
  ``obs.trace.flush.interval.sec`` set, ``--trace`` no longer exports
  only at exit: new span records append to the trace path as JSONL
  every interval, rotating (``out.json.1``, …) past
  ``obs.trace.flush.max.bytes``, so a crashed or long-running job still
  yields a usable trace prefix.

Config surface (the .properties files every job loads; README
"Telemetry & SLOs"):

- ``telemetry.interval.sec``             — exporter tick period
  (default 10; <= 0 disables the thread)
- ``telemetry.jsonl.path``               — append-only JSONL series
  destination (the ``--metrics-out`` CLI flag sets it)
- ``telemetry.device.sample.interval.sec`` — min seconds between
  device-memory samples (default 1.0; <= 0 disables sampling)
- ``telemetry.drift.baseline.path``      — stored baseline NB model
  whose count tables the current fold is diffed against
- ``obs.trace.flush.interval.sec``       — periodic trace flush period
  (default 0 = exit-only export, the pre-PR behavior)
- ``obs.trace.flush.max.bytes``          — rotate the flushed trace
  past this size (default 32 MiB)
- ``obs.trace.flush.keep``               — rotated files kept (default 3)
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from typing import Callable, Dict, Iterable, Mapping, Optional

from . import obs, sanitizer
from .obs import Metrics

KEY_INTERVAL = "telemetry.interval.sec"
KEY_JSONL_PATH = "telemetry.jsonl.path"
KEY_DEVICE_SAMPLE = "telemetry.device.sample.interval.sec"
KEY_DRIFT_BASELINE = "telemetry.drift.baseline.path"
KEY_FLUSH_INTERVAL = "obs.trace.flush.interval.sec"
KEY_FLUSH_MAX_BYTES = "obs.trace.flush.max.bytes"
KEY_FLUSH_KEEP = "obs.trace.flush.keep"

DEFAULT_INTERVAL_SEC = 10.0
DEFAULT_DEVICE_SAMPLE_SEC = 1.0
DEFAULT_FLUSH_MAX_BYTES = 32 << 20
DEFAULT_FLUSH_KEEP = 3

TELEMETRY_GROUP = "Telemetry"
COMPILE_MS = "xla.compile.ms"
COMPILE_COUNT = "xla.compiles"

SNAPSHOT_VERSION = 1

#: thread-name prefixes of every thread this module may start — the
#: shutdown lint (tests/test_obs_coverage.py) asserts none survive stop()
THREAD_PREFIXES = ("avenir-telemetry", "avenir-trace-flush")


# ---------------------------------------------------------------------------
# the process-global registry
# ---------------------------------------------------------------------------

_GLOBAL_METRICS = Metrics()


def get_metrics() -> Metrics:
    """The process-global Metrics registry: compile counters, device
    gauges, drift gauges — everything the periodic exporter snapshots."""
    return _GLOBAL_METRICS


def set_metrics(m: Metrics) -> Metrics:
    global _GLOBAL_METRICS
    _GLOBAL_METRICS = m
    return m


# ---------------------------------------------------------------------------
# mergeable snapshots
# ---------------------------------------------------------------------------

def build_snapshot(registry: Optional[Metrics] = None,
                   tracer=None,
                   identity: Optional[Mapping[str, object]] = None) -> dict:
    """One timestamped, mergeable snapshot: the registry's counters +
    histogram bucket states + stamped gauges, plus the tracer's per-name
    span summaries and breaker-visible tracer stats.

    ``identity`` (optional) stamps a process identity record — role,
    host, pid, start-time nonce (see ``fleetobs.identity``) — so a
    fleet aggregator can attribute the snapshot to its publishing
    process.  Like ``pid``, the section is deliberately NOT carried
    through ``merge_snapshots`` (SNAPSHOT_NON_MERGED)."""
    registry = registry if registry is not None else get_metrics()
    tracer = tracer if tracer is not None else obs.get_tracer()
    snap = registry.mergeable_snapshot()
    snap["v"] = SNAPSHOT_VERSION
    snap["pid"] = os.getpid()
    snap["spans"] = tracer.span_summaries()
    if identity is not None:
        snap["identity"] = dict(identity)
    return snap


def merge_exemplar_states(a: Optional[dict], b: Optional[dict]) -> dict:
    """Latest-timestamp-wins per-bucket merge of two ``state_dict``-form
    exemplar maps (``{bucket: {"trace_id", "value", "ts"}}``) — the ONE
    rule, shared by snapshot merging here and the replica-pool histogram
    aggregation (serve.pool.merged_hist_state).

    Exact-timestamp ties break on (trace_id, value), NOT insertion side:
    the old ``b wins ties`` rule made the merge order-dependent when two
    processes stamped the same clock value, which the split-invariance
    verifier (core.algebra) flags as a commutativity violation."""
    out = dict(a or {})
    for i, e in (b or {}).items():
        cur = out.get(i)
        if cur is None or ((e["ts"], str(e["trace_id"]), e["value"])
                           > (cur["ts"], str(cur["trace_id"]),
                              cur["value"])):
            out[i] = e
    return out


def _merge_hist_state(a: dict, b: dict) -> dict:
    """Bucket-wise add of two histogram state dicts (same ladder);
    exemplars keep the latest-timestamped trace per bucket."""
    for k in ("n_buckets", "lo", "hi"):
        if a[k] != b[k]:
            raise ValueError(
                f"cannot merge histogram states with different bucket "
                f"ladders ({k}: {a[k]} vs {b[k]})")
    counts = dict(a.get("counts", {}))
    for i, c in b.get("counts", {}).items():
        counts[i] = counts.get(i, 0) + c
    n = a["n"] + b["n"]
    out = {"n_buckets": a["n_buckets"], "lo": a["lo"], "hi": a["hi"],
           "counts": counts, "n": n, "total": a["total"] + b["total"],
           "vmin": None, "vmax": None}
    vmins = [s["vmin"] for s in (a, b) if s["n"]]
    vmaxs = [s["vmax"] for s in (a, b) if s["n"]]
    if n:
        out["vmin"] = min(vmins)
        out["vmax"] = max(vmaxs)
    ex = merge_exemplar_states(a.get("exemplars"), b.get("exemplars"))
    if ex:
        out["exemplars"] = ex
    return out


#: snapshot sections DELIBERATELY absent from a merged snapshot, with
#: the reason — the merge-closure rule (avenir-analyze) fails on any
#: section the builders write that is neither merged nor listed here,
#: so a new snapshot field can never be silently dropped by the
#: multi-host fold.
SNAPSHOT_NON_MERGED: Dict[str, str] = {
    "pid":
        "process identity: a merged snapshot spans processes by "
        "definition, so carrying one pid forward would be a lie — "
        "consumers needing lineage read the per-process JSONL lines",
    "identity":
        "fleet process identity record (role/host/pid/start nonce): a "
        "merged snapshot spans processes, so no single identity is "
        "true of it — the fleet fold (fleetobs.aggregate) consumes the "
        "record BEFORE merging (per-process gauge namespacing, feed "
        "staleness attribution) and then drops it, exactly like pid",
}

#: every top-level section merge_snapshots knows how to carry; an input
#: section outside this set (and SNAPSHOT_NON_MERGED) raises so schema
#: growth is loud at the merge point too, not only in static analysis
SNAPSHOT_SECTIONS = frozenset(
    {"v", "ts", "mono", "counters", "gauges", "hists", "spans",
     "resilience"})


def merge_resilience(a: Optional[dict], b: Optional[dict]) -> dict:
    """Max-fold of two ``resilience`` sections — the serve layer's
    breaker state codes (``{"breakers": {model: 0/1/2}}``) and
    quarantined poison-row signatures
    (``{"quarantine": {model: {sig: offenses}}}``).

    Both halves fold by per-key ``max``: a breaker tripped ANYWHERE in
    the fleet must survive the fold (the router pre-demotes on it), and
    a signature's offense count only ever grows, so max is the honest
    union.  Max over non-negative ints with identity 0 is a commutative
    monoid, keeping ``merge_snapshots`` certified-commutative."""
    out = {"breakers": dict((a or {}).get("breakers") or {}),
           "quarantine": {m: dict(sigs or {}) for m, sigs in
                          ((a or {}).get("quarantine") or {}).items()}}
    for model, code in ((b or {}).get("breakers") or {}).items():
        out["breakers"][model] = max(int(out["breakers"].get(model, 0)),
                                     int(code or 0))
    for model, sigs in ((b or {}).get("quarantine") or {}).items():
        dst = out["quarantine"].setdefault(model, {})
        for sig, n in (sigs or {}).items():
            dst[sig] = max(int(dst.get(sig, 0)), int(n or 0))
    return out


def merge_snapshots(a: dict, b: dict) -> dict:
    """Fold two mergeable snapshots into one: counters sum, histogram
    buckets add, gauges latest-timestamp-wins (value breaks exact-ts
    ties deterministically, keeping the merge commutative), span
    summaries count-weighted-sum.  Associative and commutative, and a
    merge of per-process snapshots equals the single-process run
    (asserted in tests/test_telemetry.py) — multi-host aggregation is
    ``functools.reduce(merge_snapshots, snaps)`` over ONE snapshot per
    process (each JSONL line is cumulative for its process, so fold
    each process's latest line, not the whole series).

    An unknown top-level section in either input raises ``ValueError``
    naming the field: silently dropping a section a newer writer added
    is exactly the corruption mode the merge-closure rule exists to
    prevent, and the runtime guard keeps mixed-version fleets honest.
    """
    for snap in (a, b):
        unknown = sorted(set(snap) - SNAPSHOT_SECTIONS
                         - set(SNAPSHOT_NON_MERGED))
        if unknown:
            raise ValueError(
                f"merge_snapshots: unknown snapshot section(s) "
                f"{unknown} — extend the merge (and SNAPSHOT_SECTIONS) "
                f"or document the drop in SNAPSHOT_NON_MERGED")
    counters: Dict[str, Dict[str, int]] = {}
    for snap in (a, b):
        for g, names in (snap.get("counters") or {}).items():
            dst = counters.setdefault(g, {})
            for n, v in names.items():
                dst[n] = dst.get(n, 0) + v

    gauges: Dict[str, dict] = dict(a.get("gauges") or {})
    for name, g in (b.get("gauges") or {}).items():
        cur = gauges.get(name)
        if cur is None or (g["ts"], g["value"]) > (cur["ts"], cur["value"]):
            gauges[name] = g

    hists: Dict[str, dict] = dict(a.get("hists") or {})
    for name, st in (b.get("hists") or {}).items():
        hists[name] = (_merge_hist_state(hists[name], st)
                       if name in hists else st)

    spans: Dict[str, dict] = {k: dict(v)
                              for k, v in (a.get("spans") or {}).items()}
    for name, s in (b.get("spans") or {}).items():
        cur = spans.get(name)
        if cur is None:
            spans[name] = dict(s)
        else:
            cur["count"] += s["count"]
            cur["total_ms"] += s["total_ms"]
            cur["mean_ms"] = (cur["total_ms"] / cur["count"]
                              if cur["count"] else 0.0)

    out = {"v": SNAPSHOT_VERSION,
           "ts": max(a.get("ts", 0.0), b.get("ts", 0.0)),
           "mono": max(a.get("mono", 0.0), b.get("mono", 0.0)),
           "counters": counters, "gauges": gauges, "hists": hists,
           "spans": spans}
    if "resilience" in a or "resilience" in b:
        # present only when an input carried it: batch jobs and routers
        # never export the section, and their merged snapshots must stay
        # byte-identical to the pre-section shape
        out["resilience"] = merge_resilience(a.get("resilience"),
                                             b.get("resilience"))
    return out


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABELED_RE = re.compile(r"^([^{]+)\{(.*)\}$")


def _family(name: str):
    """Split a metric name into (sanitized family, label string).  Names
    may carry Prometheus-style labels inline — ``serve.e2e{model="c"}``
    — which pass through; the family part sanitizes to the exposition
    charset."""
    m = _LABELED_RE.match(name)
    base, labels = (m.group(1), m.group(2)) if m else (name, "")
    fam = _NAME_RE.sub("_", base.strip("."))
    if fam and fam[0].isdigit():
        fam = "_" + fam
    return fam, labels


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def labeled(name: str, **labels) -> str:
    """Attach Prometheus-style labels to a metric name with proper label
    escaping — the ONE way callers should build labeled gauge/histogram
    names (a model name containing a quote or backslash must not produce
    unparseable exposition lines)."""
    inner = ",".join(f'{k}="{_esc(str(v))}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}" if inner else name


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(snapshot: dict, prefix: str = "avenir") -> str:
    """Render a mergeable snapshot in the Prometheus text exposition
    format (one TYPE line per family, counters as ``_total``, histograms
    with cumulative ``le`` buckets + ``_sum``/``_count``), terminated by
    ``# EOF`` so a line-oriented client knows where the scrape ends.
    Golden-parsed by a scraper-grade parser in tests/test_telemetry.py."""
    out = []

    ctrs = snapshot.get("counters") or {}
    if ctrs:
        fam = f"{prefix}_counter_total"
        out.append(f"# HELP {fam} Job/serve counters (group, name labels).")
        out.append(f"# TYPE {fam} counter")
        for g in sorted(ctrs):
            for n in sorted(ctrs[g]):
                out.append(f'{fam}{{group="{_esc(g)}",name="{_esc(n)}"}} '
                           f"{_fmt(ctrs[g][n])}")

    by_fam: Dict[str, list] = {}
    for name, g in sorted((snapshot.get("gauges") or {}).items()):
        fam, labels = _family(name)
        by_fam.setdefault(fam, []).append((labels, g["value"]))
    for fam in sorted(by_fam):
        full = f"{prefix}_{fam}"
        out.append(f"# TYPE {full} gauge")
        for labels, v in by_fam[fam]:
            out.append(f"{full}{{{labels}}} {_fmt(v)}" if labels
                       else f"{full} {_fmt(v)}")

    hist_fams: Dict[str, list] = {}
    for name, st in sorted((snapshot.get("hists") or {}).items()):
        fam, labels = _family(name)
        hist_fams.setdefault(fam, []).append((labels, st))
    for fam in sorted(hist_fams):
        full = f"{prefix}_{fam}_seconds"
        out.append(f"# TYPE {full} histogram")
        for labels, st in hist_fams[fam]:
            lbl = labels + "," if labels else ""
            bounds = obs._log_bounds(st["n_buckets"], st["lo"], st["hi"])
            counts = st.get("counts", {})
            exemplars = st.get("exemplars") or {}

            def _exemplar_suffix(i):
                # OpenMetrics exemplar: ` # {trace_id="..."} value ts` —
                # the last sampled trace that landed in the bucket, so a
                # bad tail bucket links straight to a trace to open.
                # The retained value lies inside its bucket by
                # construction (the OpenMetrics validity rule).
                e = exemplars.get(str(i))
                if not e:
                    return ""
                return (f' # {{trace_id="{_esc(str(e["trace_id"]))}"}} '
                        f'{_fmt(e["value"])} {_fmt(round(e["ts"], 3))}')

            cum = 0
            for i in range(st["n_buckets"] + 2):
                c = counts.get(str(i), 0)
                if not c:
                    continue
                cum += c
                # sparse cumulative buckets: one le edge per bucket that
                # holds samples (+Inf below always closes the series)
                if i <= st["n_buckets"]:
                    edge = bounds[i] if i < len(bounds) else bounds[-1]
                    out.append(f'{full}_bucket{{{lbl}le="{_fmt(edge)}"}} '
                               f"{cum}" + _exemplar_suffix(i))
            out.append(f'{full}_bucket{{{lbl}le="+Inf"}} {st["n"]}'
                       + _exemplar_suffix(st["n_buckets"] + 1))
            out.append(f"{full}_sum{{{labels}}} {_fmt(st['total'])}"
                       if labels else f"{full}_sum {_fmt(st['total'])}")
            out.append(f"{full}_count{{{labels}}} {st['n']}"
                       if labels else f"{full}_count {st['n']}")

    spans = snapshot.get("spans") or {}
    if spans:
        # gauges, NOT counters: summaries aggregate the tracer's bounded
        # ring buffer, so a value may DROP between scrapes once the
        # buffer rotates — typing them counter would make rate() read
        # every rotation as a counter reset
        cfam = f"{prefix}_span_count"
        mfam = f"{prefix}_span_ms"
        out.append(f"# TYPE {cfam} gauge")
        for name in sorted(spans):
            out.append(f'{cfam}{{name="{_esc(name)}"}} '
                       f"{spans[name]['count']}")
        out.append(f"# TYPE {mfam} gauge")
        for name in sorted(spans):
            out.append(f'{mfam}{{name="{_esc(name)}"}} '
                       f"{_fmt(spans[name]['total_ms'])}")

    out.append("# EOF")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# compile + device-memory profiling hooks
# ---------------------------------------------------------------------------

def profiled_jit(fun: Callable, label: str, **jit_kw) -> Callable:
    """``jax.jit`` with compile accounting: any invocation that grows the
    jitted function's executable cache (a fresh shape/spec → an XLA
    compile) is billed — wall time of that call — to an ``xla.compile``
    span and the cumulative ``Telemetry / xla.compile.ms`` counter.  The
    timed call includes the first execution (the standard proxy: XLA
    compiles lazily inside it), so treat the counter as compile-
    dominated, not compile-exact.  Steady-state cost per call is one
    C++ ``_cache_size`` probe (~µs) at chunk/batch granularity."""
    import jax

    jfn = jax.jit(fun, **jit_kw)

    def wrapped(*args, **kwargs):
        try:
            before = jfn._cache_size()
        except Exception:                               # noqa: BLE001
            before = -1
        t0 = time.perf_counter_ns()
        out = jfn(*args, **kwargs)
        if before >= 0:
            try:
                grew = jfn._cache_size() > before
            except Exception:                           # noqa: BLE001
                grew = False
            if grew:
                dur = time.perf_counter_ns() - t0
                m = get_metrics()
                m.counters.incr(TELEMETRY_GROUP, COMPILE_COUNT)
                m.counters.incr(TELEMETRY_GROUP, COMPILE_MS,
                                max(int(round(dur / 1e6)), 1))
                tr = obs.get_tracer()
                if tr.enabled:
                    tr.record_span("xla.compile", t0, dur, label=label)
        return out

    wrapped.__wrapped__ = jfn
    wrapped.__profiled_label__ = label
    return wrapped


_DEVICE_SAMPLE = {"last": 0.0, "interval": DEFAULT_DEVICE_SAMPLE_SEC,
                  "lock": threading.Lock()}


def set_device_sample_interval(seconds: float) -> None:
    """Rate limit for :func:`sample_device_memory` (<= 0 disables)."""
    _DEVICE_SAMPLE["interval"] = float(seconds)


def sample_device_memory(registry: Optional[Metrics] = None,
                         force: bool = False) -> Optional[int]:
    """Sample total device-memory residency into the ``device.hbm.bytes``
    gauge (+ a tracer counter series when tracing): per-device
    ``memory_stats()['bytes_in_use']`` where the backend reports it,
    else the sum of ``jax.live_arrays()`` footprints (the CPU/tunnel
    fallback).  Rate-limited to ``telemetry.device.sample.interval.sec``
    so per-chunk/per-batch call sites stay cheap; returns the sampled
    byte count, or None when skipped/unavailable."""
    interval = _DEVICE_SAMPLE["interval"]
    if interval <= 0 and not force:
        return None
    now = time.monotonic()
    with _DEVICE_SAMPLE["lock"]:
        if not force and now - _DEVICE_SAMPLE["last"] < interval:
            return None
        _DEVICE_SAMPLE["last"] = now
    try:
        import jax
    except Exception:                                   # noqa: BLE001
        return None
    total, seen = 0, False
    try:
        for d in jax.devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:                           # noqa: BLE001
                stats = None
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                seen = True
    except Exception:                                   # noqa: BLE001
        pass
    if not seen:
        try:
            total = sum(int(a.nbytes) for a in jax.live_arrays())
            seen = True
        except Exception:                               # noqa: BLE001
            return None
    if not seen:
        return None
    reg = registry if registry is not None else get_metrics()
    reg.set_gauge("device.hbm.bytes", total)
    tr = obs.get_tracer()
    if tr.enabled:
        tr.gauge("device.hbm.bytes", total)
    return total


# ---------------------------------------------------------------------------
# count-distribution drift
# ---------------------------------------------------------------------------

def count_drift(baseline: Mapping[str, float], current: Mapping[str, float],
                smooth: float = 0.5) -> float:
    """Symmetrised KL divergence between two count distributions over
    the union of their supports, with add-``smooth`` smoothing so a bin
    present on only one side contributes finitely.  0.0 means identical
    distributions; the value grows with distribution shift — the scalar
    a retrain trigger thresholds (ROADMAP item 4)."""
    keys = set(baseline) | set(current)
    if not keys:
        return 0.0
    k = len(keys)
    nb = sum(max(float(v), 0.0) for v in baseline.values()) + smooth * k
    nc = sum(max(float(v), 0.0) for v in current.values()) + smooth * k
    if nb <= 0 or nc <= 0:
        return 0.0
    d = 0.0
    for key in keys:
        p = (max(float(baseline.get(key, 0.0)), 0.0) + smooth) / nb
        q = (max(float(current.get(key, 0.0)), 0.0) + smooth) / nc
        d += 0.5 * (p * math.log(p / q) + q * math.log(q / p))
    return d


# ---------------------------------------------------------------------------
# the periodic exporter
# ---------------------------------------------------------------------------

class TelemetryExporter:
    """Background thread snapshotting the registry every ``interval_sec``
    into an append-only JSONL time-series (one mergeable snapshot per
    line) and/or feeding providers.

    ``providers`` are callables invoked per tick; each may return a
    partial snapshot dict (``gauges``/``hists``/``counters`` sections,
    e.g. the serve layer's per-model latency families + SLO evaluation)
    that overlays the registry snapshot.  ``sinks`` are callables
    invoked per tick with the COMPLETE snapshot (after overlays) —
    additional export destinations beyond the JSONL series, e.g. the
    fleet spool publisher (``fleetobs.publisher``); a raising sink is
    swallowed exactly like a raising provider.  ``identity`` (a
    mapping) stamps every snapshot with a process identity record (see
    :func:`build_snapshot`).  ``stop()`` joins the thread (bounded) and
    takes one final tick so short jobs still export at least one line;
    the thread is verifiably gone afterwards (asserted by the shutdown
    lint)."""

    def __init__(self, interval_sec: float,
                 jsonl_path: Optional[str] = None,
                 registry: Optional[Metrics] = None,
                 tracer=None,
                 providers: Iterable[Callable[[], Optional[dict]]] = (),
                 sinks: Iterable[Callable[[dict], None]] = (),
                 identity: Optional[Mapping[str, object]] = None):
        self.interval = float(interval_sec)
        self.jsonl_path = jsonl_path
        self.registry = registry
        self.tracer = tracer
        self.providers = list(providers)
        self.sinks = list(sinks)
        self.identity = dict(identity) if identity is not None else None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = sanitizer.make_lock("telemetry.exporter")
        self.ticks = 0

    # -- snapshotting ------------------------------------------------------
    def snapshot(self) -> dict:
        """Registry snapshot + provider overlays (no file write) — the
        serve ``metrics`` command renders THIS through
        :func:`prometheus_text`, so a scrape and a JSONL line always
        agree."""
        snap = build_snapshot(self.registry, self.tracer,
                              identity=self.identity)
        for provider in self.providers:
            try:
                extra = provider()
            except Exception:                           # noqa: BLE001
                continue            # a broken provider must not kill export
            if not extra:
                continue
            for section in ("gauges", "hists", "spans"):
                if section in extra:
                    snap.setdefault(section, {}).update(extra[section])
            for g, names in (extra.get("counters") or {}).items():
                dst = snap.setdefault("counters", {}).setdefault(g, {})
                dst.update(names)
            if "resilience" in extra:
                snap["resilience"] = merge_resilience(
                    snap.get("resilience"), extra["resilience"])
        return snap

    def tick(self) -> dict:
        """One export cycle: build the snapshot, append the JSONL line,
        feed every sink."""
        snap = self.snapshot()
        if self.jsonl_path:
            line = json.dumps(snap) + "\n"
            with self._lock:
                with open(self.jsonl_path, "a") as fh:
                    fh.write(line)
        for sink in self.sinks:
            try:
                sink(snap)
            except Exception:                           # noqa: BLE001
                continue        # a broken sink must not kill export
        # under the same lock as the file append: tick() is called by
        # the exporter thread AND by stop()/manual callers, and an
        # unlocked += is exactly the RMW race the lock-discipline rule
        # (avenir-analyze) flags
        with self._lock:
            self.ticks += 1
        return snap

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TelemetryExporter":
        if self.interval <= 0 or self._thread is not None:
            return self

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.tick()
                except Exception:                       # noqa: BLE001
                    # export must never kill the host process; the next
                    # tick retries (e.g. a transiently unwritable path)
                    pass

        self._thread = threading.Thread(target=run, name="avenir-telemetry",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, final_tick: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None
        if final_tick and self.jsonl_path:
            try:
                self.tick()
            except Exception:                           # noqa: BLE001
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# ---------------------------------------------------------------------------
# incremental trace flush
# ---------------------------------------------------------------------------

class TraceFlusher:
    """Periodically appends NEW tracer records to the ``--trace`` path as
    JSONL, rotating past ``max_bytes`` (``path.1`` newest rotation …
    ``path.<keep>`` oldest), so a crashed or still-running job yields a
    usable trace prefix instead of nothing.  The exit-time Chrome-format
    export still overwrites the live path on a clean shutdown."""

    def __init__(self, tracer, path: str, interval_sec: float,
                 max_bytes: int = DEFAULT_FLUSH_MAX_BYTES,
                 keep: int = DEFAULT_FLUSH_KEEP):
        self.tracer = tracer
        self.path = path
        self.interval = float(interval_sec)
        self.max_bytes = int(max_bytes)
        self.keep = max(int(keep), 1)
        self._since = 0
        self.dropped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # one flush at a time: the flusher thread and a manual caller
        # (or the exit path racing a slow tick) would otherwise
        # interleave _since/dropped updates and duplicate records
        self._lock = sanitizer.make_lock("telemetry.flusher")

    def _rotate(self) -> None:
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.1")

    def flush(self) -> int:
        """Append records not yet flushed; returns how many were written."""
        with self._lock:
            recs, self._since, dropped = self.tracer.records_since(
                self._since)
            self.dropped += dropped
            if not recs:
                return 0
            if (os.path.exists(self.path)
                    and os.path.getsize(self.path) >= self.max_bytes):
                self._rotate()
            with open(self.path, "a") as fh:
                for r in recs:
                    fh.write(json.dumps(self.tracer.record_dict(r))
                             + "\n")
            return len(recs)

    def start(self) -> "TraceFlusher":
        if self.interval <= 0 or self._thread is not None:
            return self

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.flush()
                except Exception:                       # noqa: BLE001
                    pass

        self._thread = threading.Thread(target=run,
                                        name="avenir-trace-flush",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None


# ---------------------------------------------------------------------------
# config plumbing (the CLI entry points call these next to obs.configure)
# ---------------------------------------------------------------------------

def configure_from_config(config) -> None:
    """Apply the passive telemetry settings (device-memory sample rate)
    — called by every CLI entry point; thread-owning pieces are built
    explicitly via :func:`exporter_for_job` / :func:`flusher_for_job`."""
    set_device_sample_interval(
        config.get_float(KEY_DEVICE_SAMPLE, DEFAULT_DEVICE_SAMPLE_SEC))


def exporter_for_job(config,
                     metrics_out: Optional[str] = None,
                     providers: Iterable[Callable] = ()
                     ) -> Optional[TelemetryExporter]:
    """A STARTED exporter for a batch job/serve process, or None when
    nothing asked for one (no ``--metrics-out`` flag, no
    ``telemetry.jsonl.path`` key, and no providers)."""
    path = metrics_out or config.get(KEY_JSONL_PATH)
    providers = list(providers)
    if not path and not providers:
        return None
    interval = config.get_float(KEY_INTERVAL, DEFAULT_INTERVAL_SEC)
    exp = TelemetryExporter(interval, jsonl_path=path, providers=providers)
    return exp.start()


def flusher_for_job(config, trace_path: Optional[str]
                    ) -> Optional[TraceFlusher]:
    """A STARTED periodic trace flusher when ``--trace`` is active and
    ``obs.trace.flush.interval.sec`` is configured positive."""
    if not trace_path:
        return None
    interval = config.get_float(KEY_FLUSH_INTERVAL, 0.0)
    if interval <= 0:
        return None
    fl = TraceFlusher(
        obs.get_tracer(), trace_path, interval,
        max_bytes=config.get_int(KEY_FLUSH_MAX_BYTES,
                                 DEFAULT_FLUSH_MAX_BYTES),
        keep=config.get_int(KEY_FLUSH_KEEP, DEFAULT_FLUSH_KEEP))
    return fl.start()

"""PAC computational-learning-theory sample-size calculator.

Port of the reference's resource/comp_learn.py: hypothesis-space sizes for
conjunction-of-terms (comp_learn.py:26-33), k-term-DNF (:35-50), and k-CNF
(:52-58) spaces over categorical feature cardinalities, and the PAC bound
``m >= (1/e) * ln(|H| / p)`` tabulated over error/confidence grids
(:11-23).  Pure host math — a calculator, not a job.
"""

from __future__ import annotations

import math
from itertools import combinations
from typing import List, Sequence, Tuple

DEFAULT_ERRORS = (0.01, 0.02, 0.03, 0.04, 0.05)
DEFAULT_THRESHOLDS = (0.01, 0.02, 0.03, 0.04, 0.05)


def _value_combinations(feature_card: Sequence[int], num_vars: int) -> int:
    """Sum of cardinality products over num_vars-subsets of the features
    (comp_learn.py:60-78 generalized: the reference hand-rolls 3- and
    4-variable loops; combinations() covers every size)."""
    if num_vars == len(feature_card):
        p = 1
        for f in feature_card:
            p *= f
        return p
    total = 0
    for idx in combinations(range(len(feature_card)), num_vars):
        p = 1
        for i in idx:
            p *= feature_card[i]
        total += p
    return total


def terms_hyp_space(feature_card: Sequence[int], class_card: int) -> int:
    """Conjunction of all feature variables: prod(card_i + 1) * classes."""
    n = 1
    for f in feature_card:
        n *= f + 1
    return n * class_card


def dnf_hyp_space(feature_card: Sequence[int], class_card: int,
                  c_size: int, d_size: int) -> int:
    """k-term DNF: C(num_conjunctions, d_size) * classes."""
    n_conj = _value_combinations(feature_card, c_size)
    n = 1
    for i in range(d_size):
        n *= n_conj - i
    f = math.factorial(d_size)
    return (n // f) * class_card


def cnf_hyp_space_ln(feature_card: Sequence[int], class_card: int,
                     d_size: int) -> float:
    """k-CNF: returns ln|H| (the space is too large to materialize)."""
    n_disj = _value_combinations(feature_card, d_size)
    return n_disj / math.log2(math.e) + math.log(class_card)


def sample_sizes(num_hyp: int,
                 errors: Sequence[float] = DEFAULT_ERRORS,
                 thresholds: Sequence[float] = DEFAULT_THRESHOLDS
                 ) -> List[Tuple[float, float, int]]:
    """PAC bound m = ln(|H|/p) / e per (error, confidence) grid point."""
    return [(e, p, int(math.log(num_hyp / p) / e))
            for e in errors for p in thresholds]


def sample_sizes_ln(num_hyp_ln: float,
                    errors: Sequence[float] = DEFAULT_ERRORS,
                    thresholds: Sequence[float] = DEFAULT_THRESHOLDS
                    ) -> List[Tuple[float, float, int]]:
    """Same bound with ln|H| supplied directly (k-CNF path)."""
    return [(e, p, int((num_hyp_ln + math.log(1 / p)) / e))
            for e in errors for p in thresholds]

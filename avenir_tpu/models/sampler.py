"""Sampling jobs: batch bootstrap (bagging) and majority undersampling.

Reference surface:
- ``explore.BaggingSampler`` — buffers ``batch.size`` rows, emits batchSize
  uniform with-replacement draws per batch including the final partial batch
  (BaggingSampler.java:76-124).
- ``explore.UnderSamplingBalancer`` — estimates the class distribution from
  the first ``distr.batch.size`` rows, then emits majority-class rows with
  probability minClassCount/classCount (running counts), minority rows
  always (UnderSamplingBalancer.java:74-160).

The reference uses unseeded ``Math.random()``; we use seeded ``jax.random``
(``sampling.seed`` key) so runs are reproducible — statistical, not bitwise,
equivalence (SURVEY §7.3.5).  Draw generation is vectorized per batch.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters


class BaggingSampler:
    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        batch_size = cfg.get_int("batch.size", 10000)
        rng = np.random.default_rng(cfg.get_int("sampling.seed", 0))

        lines = list(read_lines(in_path))
        out: List[str] = []
        for start in range(0, len(lines), batch_size):
            batch = lines[start:start + batch_size]
            picks = rng.integers(0, len(batch), len(batch))
            out.extend(batch[i] for i in picks)
        write_output(out_path, out)
        counters.set("Sampling", "Emitted", len(out))
        return counters


class UnderSamplingBalancer:
    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        class_ord = cfg.must_int("class.attr.ord")
        distr_batch = cfg.get_int("distr.batch.size", 500)
        rng = np.random.default_rng(cfg.get_int("sampling.seed", 0))

        lines = list(read_lines(in_path))
        class_counts: dict = {}
        buffered: List[str] = []
        out: List[str] = []

        def emit(line: str, cls: str) -> None:
            cnt = class_counts[cls]
            mn = min(class_counts.values())
            if cnt > mn:
                if rng.random() < mn / cnt:
                    out.append(line)
            else:
                out.append(line)

        for row_num, line in enumerate(lines, start=1):
            cls = split_line(line, delim_regex)[class_ord]
            class_counts[cls] = class_counts.get(cls, 0) + 1
            if row_num < distr_batch:
                buffered.append(line)
            elif row_num == distr_batch:
                for b in buffered:
                    emit(b, split_line(b, delim_regex)[class_ord])
                buffered.clear()
                emit(line, cls)
            else:
                emit(line, cls)
        # input smaller than the bootstrap batch: flush everything
        for b in buffered:
            emit(b, split_line(b, delim_regex)[class_ord])
        write_output(out_path, out)
        counters.set("Sampling", "Emitted", len(out))
        return counters

"""Online reinforcement-learning (multi-armed bandit) learner library.

Reference surface being re-expressed (citations into /root/reference):
- abstract base ``org.avenir.reinforce.ReinforcementLearner`` — actions,
  batch selection, reward stats, min-trial bootstrapping
  (reinforce/ReinforcementLearner.java:35-167).
- the 10 concrete learners created by the string-keyed factory
  ``ReinforcementLearnerFactory`` (reinforce/ReinforcementLearnerFactory.java:35-63):
  intervalEstimator, sampsonSampler, optimisticSampsonSampler, randomGreedy,
  upperConfidenceBoundOne, upperConfidenceBoundTwo, softMax, actionPursuit,
  rewardComparison, exponentialWeight.
- ``Action`` value object (trial count + total reward;
  reinforce/Action.java:24-59).

These are tiny scalar state machines driven one event at a time by the
streaming loop (models.streaming, the Storm-topology replacement) — per-event
device dispatch would be pure overhead, so state lives in plain Python/NumPy,
vectorized over actions where the math allows.  The fleet-scale batch
selection path (thousands of independent learners advanced per step) is the
batch bandit jobs in models.bandit, which vectorize over groups.

Deliberate divergences from reference behavior (each a reference defect that
prevents convergence; the user-facing config surface is unchanged):
- ``randomGreedy``: the reference selects the BEST action with the decaying
  probability and random with its complement (`if (curProb < Math.random())
  select random` — RandomGreedyLearner.java:83-96), inverting the ε-greedy
  schedule so late rounds become fully random.  We explore (random) with the
  decaying ``curProb`` and exploit otherwise.
- ``findBestAction`` never updates its running max
  (ReinforcementLearner.java:157-166), returning an arbitrary action; we
  return the true argmax of average reward (used by ``actionPursuit``).

Randomness: every learner takes a seeded ``numpy.random.Generator``
(``random.seed`` config key) instead of global ``Math.random()`` — tests
assert statistical equivalence (SURVEY §7.3 item 5).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.stats import (AverageValue, CategoricalSampler, HistogramStat,
                          SimpleStat)


def _cfg(config: Dict, key: str, default=None, required: bool = False):
    """Dict/JobConfig-agnostic lookup with dotted keys (chombo
    ConfigUtility.getX equivalents; both dict and JobConfig expose .get)."""
    val = config.get(key)
    if val is None:
        if required and default is None:
            raise ValueError(f"missing required learner config: {key}")
        return default
    return val


def _cfg_int(config, key, default=None, required=False):
    v = _cfg(config, key, default, required)
    return v if v is None else int(v)


def _cfg_float(config, key, default=None, required=False):
    v = _cfg(config, key, default, required)
    return v if v is None else float(v)


class Action:
    """Bandit arm with trial/reward counters (reinforce/Action.java:24-59)."""

    def __init__(self, action_id: str):
        self.id = action_id
        self.trial_count = 0
        self.total_reward = 0

    def select(self) -> None:
        self.trial_count += 1

    def reward(self, reward: int) -> None:
        self.total_reward += reward

    def get_average_reward(self) -> float:
        return self.total_reward / self.trial_count if self.trial_count else 0

    def __repr__(self):
        return (f"Action({self.id!r}, trials={self.trial_count}, "
                f"reward={self.total_reward})")


class ReinforcementLearner:
    """Abstract base (reinforce/ReinforcementLearner.java:35-167)."""

    def __init__(self):
        self.actions: List[Action] = []
        self.batch_size = 1
        self.total_trial_count = 0
        self.min_trial = -1
        self.reward_stats: Dict[str, AverageValue] = {}
        self.rewarded = False
        self.reward_scale = 1
        self.rng: np.random.Generator = np.random.default_rng()

    def with_actions(self, action_ids: Sequence[str]) -> "ReinforcementLearner":
        self.actions = [Action(a) for a in action_ids]
        return self

    def with_batch_size(self, batch_size: int) -> "ReinforcementLearner":
        self.batch_size = batch_size
        return self

    def initialize(self, config: Dict) -> None:
        self.min_trial = _cfg_int(config, "min.trial", -1)
        self.batch_size = _cfg_int(config, "batch.size", 1)
        self.reward_scale = _cfg_int(config, "reward.scale", 1)
        seed = _cfg_int(config, "random.seed", None)
        self.rng = np.random.default_rng(seed)

    def next_actions(self) -> List[Action]:
        return [self.next_action() for _ in range(self.batch_size)]

    def next_action(self) -> Action:
        raise NotImplementedError

    def set_reward(self, action_id: str, reward: int) -> None:
        raise NotImplementedError

    def get_stat(self) -> str:
        return ""

    # -- helpers ------------------------------------------------------------
    def find_action(self, action_id: str) -> Optional[Action]:
        for a in self.actions:
            if a.id == action_id:
                return a
        return None

    def find_action_with_min_trial(self) -> Action:
        return min(self.actions, key=lambda a: a.trial_count)

    def select_action_based_on_min_trial(self) -> Optional[Action]:
        """Bootstrap: force the least-tried action until every arm has
        ``min.trial`` trials (ReinforcementLearner.java:142-152)."""
        if self.min_trial > 0:
            action = self.find_action_with_min_trial()
            if action.trial_count <= self.min_trial:
                return action
        return None

    def find_best_action(self) -> Action:
        """True argmax of average reward (the reference's loop never updates
        its max — ReinforcementLearner.java:157-166; see module docstring)."""
        best_id = max(self.reward_stats,
                      key=lambda a: self.reward_stats[a].get_avg_value())
        return self.find_action(best_id)

    def _select_random(self) -> Action:
        return self.actions[int(self.rng.integers(len(self.actions)))]


class RandomGreedyLearner(ReinforcementLearner):
    """ε-greedy with linear/log-linear ε decay and non-stationary floor
    (reinforce/RandomGreedyLearner.java:31-108)."""

    PROB_RED_NONE = "none"
    PROB_RED_LINEAR = "linear"
    PROB_RED_LOG_LINEAR = "logLinear"

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.random_selection_prob = _cfg_float(config, "random.selection.prob", 0.5)
        self.prob_red_algorithm = _cfg(config, "prob.reduction.algorithm",
                                       self.PROB_RED_LINEAR)
        self.prob_reduction_constant = _cfg_float(config, "prob.reduction.constant", 1.0)
        self.min_prob = _cfg_float(config, "min.prob", -1.0)
        for a in self.actions:
            self.reward_stats[a.id] = SimpleStat()

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            t = self.total_trial_count
            if self.prob_red_algorithm == self.PROB_RED_NONE:
                cur_prob = self.random_selection_prob
            elif self.prob_red_algorithm == self.PROB_RED_LINEAR:
                cur_prob = (self.random_selection_prob
                            * self.prob_reduction_constant / t)
            elif self.prob_red_algorithm == self.PROB_RED_LOG_LINEAR:
                cur_prob = (self.random_selection_prob
                            * self.prob_reduction_constant * math.log(t) / t)
            else:
                raise ValueError("Invalid probability reduction algorithm")
            cur_prob = min(cur_prob, self.random_selection_prob)
            if 0 < self.min_prob and cur_prob < self.min_prob:
                cur_prob = self.min_prob  # non-stationary reward floor
            if self.rng.random() < cur_prob:
                action = self._select_random()   # explore with decaying prob
            else:
                action = self.find_best_action() # exploit otherwise
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward)
        self.find_action(action_id).reward(reward)


class UpperConfidenceBoundOneLearner(ReinforcementLearner):
    """UCB1: ``avgReward + sqrt(2 ln n / n_a)``; untried arms score +inf
    (Java divides by zero trial count — UpperConfidenceBoundOneLearner.java:58)."""

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.reward_scale = _cfg_int(config, "reward.scale", 100)
        for a in self.actions:
            self.reward_stats[a.id] = SimpleStat()

    def _ucb_score(self, action: Action) -> float:
        if action.trial_count == 0:
            return float("inf")
        return (self.reward_stats[action.id].get_avg_value()
                + math.sqrt(2.0 * math.log(self.total_trial_count)
                            / action.trial_count))

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            action = max(self.actions, key=self._ucb_score)
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward / self.reward_scale)
        self.find_action(action_id).reward(reward)


class UpperConfidenceBoundTwoLearner(ReinforcementLearner):
    """UCB2: epoch-based, ``a(t, tau) = (1+α) ln(e·t/τ) / (2τ)`` with
    τ = (1+α)^epochs (reinforce/UpperConfidenceBoundTwoLearner.java:54-96)."""

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.reward_scale = _cfg_int(config, "reward.scale", 100)
        self.alpha = _cfg_float(config, "ucb2.alpha", 0.1)
        self.num_epochs: Dict[str, int] = {a.id: 0 for a in self.actions}
        self.current_action: Optional[Action] = None
        self.epoch_size = 0
        self.epoch_trial_count = 0
        for a in self.actions:
            self.reward_stats[a.id] = SimpleStat()

    def _score(self, action: Action) -> float:
        reward = self.reward_stats[action.id].get_avg_value()
        epochs = self.num_epochs[action.id]
        tau = 1.0 if epochs == 0 else (1.0 + self.alpha) ** epochs
        a = ((1 + self.alpha)
             * math.log(math.e * self.total_trial_count / tau) / (2 * tau))
        return reward + math.sqrt(max(a, 0.0))

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            if (self.current_action is not None
                    and self.epoch_trial_count < self.epoch_size):
                action = self.current_action
                self.epoch_trial_count += 1
            else:
                if self.current_action is not None:
                    self.num_epochs[self.current_action.id] += 1
                action = max(self.actions, key=self._score)
                self.current_action = action
                epochs = self.num_epochs[action.id]
                size = round((1.0 + self.alpha) ** (epochs + 1)
                             - (1.0 + self.alpha) ** epochs)
                self.epoch_size = max(int(size), 1)
                self.epoch_trial_count = 0
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward / self.reward_scale)
        self.find_action(action_id).reward(reward)


class SampsonSamplerLearner(ReinforcementLearner):
    """Thompson-style sampling from each arm's empirical reward list
    (reinforce/SampsonSamplerLearner.java:33-100)."""

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.min_sample_size = _cfg_int(config, "min.sample.size", required=True)
        self.max_reward = _cfg_int(config, "max.reward", required=True)
        self.reward_distr: Dict[str, List[int]] = {a.id: [] for a in self.actions}

    def enforce(self, action_id: str, reward: int) -> int:
        return reward

    def next_action(self) -> Action:
        self.total_trial_count += 1
        best_id, best_reward = None, -1
        for action_id, rewards in self.reward_distr.items():
            if len(rewards) > self.min_sample_size:
                reward = rewards[int(self.rng.integers(len(rewards)))]
                reward = self.enforce(action_id, reward)
            else:
                reward = self.rng.random() * self.max_reward
            if reward > best_reward:
                best_id, best_reward = action_id, reward
        action = self.find_action(best_id)
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_distr[action_id].append(reward)
        self.find_action(action_id).reward(reward)


class OptimisticSampsonSamplerLearner(SampsonSamplerLearner):
    """Sampled reward floored at the arm's mean
    (reinforce/OptimisticSampsonSamplerLearner.java:30-54)."""

    def enforce(self, action_id: str, reward: int) -> int:
        rewards = self.reward_distr.get(action_id)
        if rewards:
            mean = sum(rewards) // len(rewards)
            return max(reward, mean)
        return reward


class IntervalEstimatorLearner(ReinforcementLearner):
    """Interval estimation on binned reward histograms with a shrinking
    confidence limit (reinforce/IntervalEstimatorLearner.java:35-172)."""

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.bin_width = _cfg_int(config, "bin.width", required=True)
        self.confidence_limit = _cfg_int(config, "confidence.limit", required=True)
        self.min_confidence_limit = _cfg_int(config, "min.confidence.limit",
                                             required=True)
        self.cur_confidence_limit = self.confidence_limit
        self.reduction_step = _cfg_int(config, "confidence.limit.reduction.step",
                                       required=True)
        self.reduction_round_interval = _cfg_int(
            config, "confidence.limit.reduction.round.interval", required=True)
        self.min_distr_sample = _cfg_int(config, "min.reward.distr.sample",
                                         required=True)
        self.reward_distr: Dict[str, HistogramStat] = {
            a.id: HistogramStat(self.bin_width) for a in self.actions}
        self.last_round_num = 1
        self.random_select_count = 0
        self.intv_est_select_count = 0
        self.low_sample = True

    def _adjust_conf_limit(self) -> None:
        if self.cur_confidence_limit > self.min_confidence_limit:
            red_step = ((self.total_trial_count - self.last_round_num)
                        // self.reduction_round_interval)
            if red_step > 0:
                self.cur_confidence_limit = max(
                    self.cur_confidence_limit - red_step * self.reduction_step,
                    self.min_confidence_limit)
                self.last_round_num = self.total_trial_count

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.low_sample:
            self.low_sample = any(
                s.get_count() < self.min_distr_sample
                for s in self.reward_distr.values())
            if not self.low_sample:
                self.last_round_num = self.total_trial_count
        if self.low_sample:
            action = self._select_random()
            self.random_select_count += 1
        else:
            self._adjust_conf_limit()
            best_id, best_ub = None, 0
            for action_id, stat in self.reward_distr.items():
                _, upper = stat.get_confidence_bounds(self.cur_confidence_limit)
                if upper > best_ub:
                    best_id, best_ub = action_id, upper
            action = self.find_action(best_id)
            self.intv_est_select_count += 1
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        stat = self.reward_distr.get(action_id)
        if stat is None:
            raise ValueError(f"invalid action:{action_id}")
        stat.add(reward)
        self.find_action(action_id).reward(reward)

    def get_stat(self) -> str:
        return (f"randomSelectCount:{self.random_select_count} "
                f"intvEstSelectCount:{self.intv_est_select_count}")


class SoftMaxLearner(ReinforcementLearner):
    """Boltzmann exploration with temperature decay
    (reinforce/SoftMaxLearner.java:32-123)."""

    TEMP_RED_LINEAR = "linear"
    TEMP_RED_LOG_LINEAR = "logLinear"

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.temp_constant = _cfg_float(config, "temp.constant", 100.0)
        self.min_temp_constant = _cfg_float(config, "min.temp.constant", -1.0)
        self.temp_red_algorithm = _cfg(config, "temp.reduction.algorithm",
                                       self.TEMP_RED_LINEAR)
        self.sampler = CategoricalSampler()
        for a in self.actions:
            self.reward_stats[a.id] = SimpleStat()
            self.sampler.add(a.id, 1.0 / len(self.actions))

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            if self.rewarded:
                self.sampler.initialize()
                # max-subtracted softmax: same distribution as the reference's
                # raw exp (SoftMaxLearner.java:79-85) without overflow once
                # the temperature has decayed
                max_avg = max(self.reward_stats[a.id].get_avg_value()
                              for a in self.actions)
                exp_distr = {
                    a.id: math.exp((self.reward_stats[a.id].get_avg_value()
                                    - max_avg) / self.temp_constant)
                    for a in self.actions}
                total = sum(exp_distr.values())
                for a in self.actions:
                    self.sampler.add(a.id, exp_distr[a.id] / total)
                self.rewarded = False
            action = self.find_action(self.sampler.sample(self.rng))
            # temperature decay (SoftMaxLearner.java:96-109); min_trial is
            # subtracted raw — it defaults to -1, so with min.trial unset the
            # divisor is totalTrialCount+1, exactly as in the reference
            soft_max_round = self.total_trial_count - self.min_trial
            if soft_max_round > 1:
                if self.temp_red_algorithm == self.TEMP_RED_LINEAR:
                    self.temp_constant /= soft_max_round
                elif self.temp_red_algorithm == self.TEMP_RED_LOG_LINEAR:
                    self.temp_constant *= (math.log(soft_max_round)
                                           / soft_max_round)
                if (self.min_temp_constant > 0
                        and self.temp_constant < self.min_temp_constant):
                    self.temp_constant = self.min_temp_constant
                # the cumulative decay underflows to 0.0 within ~170 rounds
                # when no floor is configured; clamp to a tiny positive
                # temperature (= argmax sampling) instead of dividing by zero
                if self.temp_constant <= 0.0:
                    self.temp_constant = 1e-12
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward)
        self.find_action(action_id).reward(reward)
        self.rewarded = True


class ActionPursuitLearner(ReinforcementLearner):
    """Pursuit: push sampling probability toward the best arm
    (reinforce/ActionPursuitLearner.java:32-84)."""

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.learning_rate = _cfg_float(config, "pursuit.learning.rate", 0.05)
        self.sampler = CategoricalSampler()
        for a in self.actions:
            self.sampler.add(a.id, 1.0 / len(self.actions))
            self.reward_stats[a.id] = SimpleStat()

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.rewarded:
            best = self.find_best_action()
            for a in self.actions:
                distr = self.sampler.get(a.id)
                if a is best:
                    distr += self.learning_rate * (1.0 - distr)
                else:
                    distr -= self.learning_rate * distr
                self.sampler.set(a.id, distr)
            self.rewarded = False
        action = self.find_action(self.sampler.sample(self.rng))
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward)
        self.rewarded = True
        self.find_action(action_id).reward(reward)


class RewardComparisonLearner(ReinforcementLearner):
    """Preference learning against a moving reference reward
    (reinforce/RewardComparisonLearner.java:32-105)."""

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.preference_change_rate = _cfg_float(config, "preference.change.rate", 0.01)
        self.ref_reward_change_rate = _cfg_float(config,
                                                 "reference.reward.change.rate", 0.01)
        self.ref_reward = _cfg_float(config, "intial.reference.reward", 100.0)
        self.sampler = CategoricalSampler()
        self.action_prefs: Dict[str, float] = {}
        for a in self.actions:
            self.sampler.add(a.id, 1.0 / len(self.actions))
            self.reward_stats[a.id] = SimpleStat()
            self.action_prefs[a.id] = 0.0

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.rewarded:
            self.sampler.initialize()
            max_pref = max(self.action_prefs.values())
            exp_distr = {a.id: math.exp(self.action_prefs[a.id] - max_pref)
                         for a in self.actions}
            total = sum(exp_distr.values())
            for a in self.actions:
                self.sampler.add(a.id, exp_distr[a.id] / total)
            self.rewarded = False
        action = self.find_action(self.sampler.sample(self.rng))
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward)
        self.rewarded = True
        self.find_action(action_id).reward(reward)
        mean_reward = self.reward_stats[action_id].get_avg_value()
        self.action_prefs[action_id] += (self.preference_change_rate
                                         * (mean_reward - self.ref_reward))
        self.ref_reward += (self.ref_reward_change_rate
                            * (mean_reward - self.ref_reward))


class ExponentialWeightLearner(ReinforcementLearner):
    """EXP3: importance-weighted exponential weights
    (reinforce/ExponentialWeightLearner.java:32-86).  ``distr.constant`` is
    EXP3's γ ∈ (0, 1]; the reference defaults it to 100.0, which is outside
    the valid range — configure it explicitly."""

    def initialize(self, config: Dict) -> None:
        super().initialize(config)
        self.distr_constant = _cfg_float(config, "distr.constant", 0.1)
        self.weight_distr: Dict[str, float] = {a.id: 1.0 for a in self.actions}
        self.sampler = CategoricalSampler()
        for a in self.actions:
            self.sampler.add(a.id, 1.0 / len(self.actions))

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.rewarded:
            sum_wt = sum(self.weight_distr.values())
            self.sampler.initialize()
            k = len(self.actions)
            for a in self.actions:
                prob = ((1.0 - self.distr_constant)
                        * self.weight_distr[a.id] / sum_wt
                        + self.distr_constant / k)
                self.sampler.add(a.id, prob)
            self.rewarded = False
        action = self.find_action(self.sampler.sample(self.rng))
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.find_action(action_id).reward(reward)
        scaled = reward / self.reward_scale
        exponent = (self.distr_constant * (scaled / self.sampler.get(action_id))
                    / len(self.actions))
        self.weight_distr[action_id] *= math.exp(min(exponent, 700.0))
        # renormalize: the sampling probabilities only see weight ratios, so
        # dividing by the sum is behavior-neutral and prevents the unbounded
        # growth that overflows the reference (ExponentialWeightLearner.java:81)
        total = sum(self.weight_distr.values())
        for k in self.weight_distr:
            self.weight_distr[k] /= total
        self.rewarded = True


_LEARNERS = {
    "intervalEstimator": IntervalEstimatorLearner,
    "sampsonSampler": SampsonSamplerLearner,
    "optimisticSampsonSampler": OptimisticSampsonSamplerLearner,
    "randomGreedy": RandomGreedyLearner,
    "upperConfidenceBoundOne": UpperConfidenceBoundOneLearner,
    "upperConfidenceBoundTwo": UpperConfidenceBoundTwoLearner,
    "softMax": SoftMaxLearner,
    "actionPursuit": ActionPursuitLearner,
    "rewardComparison": RewardComparisonLearner,
    "exponentialWeight": ExponentialWeightLearner,
}


def create_learner(learner_type: str, actions: Sequence[str],
                   config: Dict) -> ReinforcementLearner:
    """String-keyed factory preserving the reference's learner-type names
    (reinforce/ReinforcementLearnerFactory.java:35-63)."""
    cls = _LEARNERS.get(learner_type)
    if cls is None:
        raise ValueError(f"invalid learner type:{learner_type}")
    learner = cls().with_actions(actions)
    learner.initialize(config)
    return learner


class ReinforcementLearnerFactory:
    """Class-shaped alias mirroring the reference entry point."""

    @staticmethod
    def create(learner_type: str, actions: Sequence[str],
               config: Dict) -> ReinforcementLearner:
        return create_learner(learner_type, actions, config)


class ReinforcementLearnerGroup:
    """Per-entity learner map (reinforce/ReinforcementLearnerGroup.java:30-70):
    one independent learner per entity id (user, product, campaign ...), all
    built by the factory from shared config.  Config keys match the
    reference: ``learner.type`` (default ``randomGreedy``) and the required
    ``action.list`` comma list.
    """

    def __init__(self, config: Dict):
        self.config = config
        self.learner_type = _cfg(config, "learner.type", "randomGreedy")
        actions = _cfg(config, "action.list", required=True)
        self.actions = (actions.split(",")
                        if isinstance(actions, str) else list(actions))
        self.learners: Dict[str, ReinforcementLearner] = {}

    def add_learner(self, learner_id: str) -> ReinforcementLearner:
        learner = create_learner(self.learner_type, self.actions, self.config)
        self.learners[learner_id] = learner
        return learner

    def get_learner(self, learner_id: str) -> Optional[ReinforcementLearner]:
        return self.learners.get(learner_id)

    def _require(self, learner_id: str) -> ReinforcementLearner:
        learner = self.learners.get(learner_id)
        if learner is None:
            raise ValueError(
                f"unknown learner id {learner_id!r}; call add_learner first "
                f"(known: {sorted(self.learners)[:10]})")
        return learner

    def next_actions(self, learner_id: str) -> List[Action]:
        return self._require(learner_id).next_actions()

    def set_reward(self, learner_id: str, action_id: str, reward: int) -> None:
        self._require(learner_id).set_reward(action_id, reward)

"""k-nearest-neighbor pipeline: distance job, probability joiner, classifier
(TPU-native).

Reference surface re-expressed (citations into /root/reference):
- the external sifarish ``SameTypeSimilarity`` distance MR the pipeline
  calls first (resource/knn.sh:46-59) — here ``SameTypeSimilarity``, an
  in-framework sharded MXU matmul kernel (ops.distance) emitting the same
  pair lines: ``trainId, testId, distance, [trainClass, testClass]`` with
  int distances scaled by ``distance.scale`` (resource/knn.properties:12).
- ``org.avenir.knn.FeatureCondProbJoiner`` — joins distance pairs with the
  Naive Bayes feature-posterior output for class-conditional weighting
  (FeatureCondProbJoiner.java:50-80; prob files identified by the
  ``feature.cond.prob.split.prefix`` file-name prefix, distance files
  otherwise, exactly like the reference's input-split dispatch).
- ``org.avenir.knn.NearestNeighbor`` — secondary-sorted top-K per test
  entity + ``Neighborhood`` kernel-weighted voting
  (NearestNeighbor.java:95-190, Neighborhood.java:59-340): kernels none /
  linearMultiplicative / linearAdditive / gaussian, inverse-distance and
  class-conditional-probability weighting, decision threshold, cost-based
  arbitration, classification and regression (average / median / single-
  variable linear regression) modes, confusion-matrix validation counters.

TPU re-design: the shuffle + grouping-comparator top-K becomes ``lax.top_k``
over sharded distance blocks (inside SameTypeSimilarity when
``output.top.matches`` is set); Neighborhood scoring is vectorized over all
(test, neighbor) pairs at once instead of per-reducer-group loops.

Parity notes:
- Neighborhood's integer kernel scores (KERNEL_SCALE=100, int division in
  ``linearMultiplicative`` 100/d and int truncation of the gaussian) are
  reproduced exactly (Neighborhood.java:126-160).
- The reference's non-weighted class-distribution output drops the leading
  field delimiter (NearestNeighbor.java:370 appends ``classVal`` without a
  separator, corrupting the line); we emit it with the separator.
- The ``sigmoid`` kernel is an empty branch in the reference
  (Neighborhood.java:161) that would leave every neighborhood unscored;
  we raise instead of silently classifying null.
"""

from __future__ import annotations

import math
import os
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.config import JobConfig
from ..core.obs import get_tracer, traced_run
from ..core.io import _input_files, read_lines, split_line, write_output
from ..core.metrics import ConfusionMatrix, CostBasedArbitrator, Counters
from ..core.schema import FeatureSchema
from ..ops.distance import pairwise_distances

KERNEL_SCALE = 100
PROB_SCALE = 100


# ---------------------------------------------------------------------------
# distance job (sifarish SameTypeSimilarity equivalent)
# ---------------------------------------------------------------------------

class SameTypeSimilarity:
    """Pairwise entity distances between a training and a test set (or a
    self-join), schema-driven.

    Config surface (resource/knn.properties:9-17): ``distance.scale``,
    ``inter.set.matching``, ``base.set.split.prefix`` (file-name prefix
    marking training-set files), plus ours: ``distance.algorithm``
    (euclidean|manhattan), ``include.class.attributes``,
    ``output.top.matches`` (emit only the k nearest per test entity via
    device top_k instead of all pairs)."""

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        self.schema = schema or FeatureSchema.from_file(
            config.must("feature.schema.file.path"))

    def _encode(self, records: List[List[str]],
                vocabs: Dict[int, Dict[str, int]]):
        """Numeric columns range-normalized to [0,1] when min/max are
        declared; categorical columns to vocab codes.  ``vocabs`` is SHARED
        between the train and test encode calls so undeclared values get one
        consistent code across both sets."""
        num_cols, cat_cols = [], []
        num_w, cat_w = [], []
        for f in self.schema.feature_fields():
            w = float(f.extra.get("weight", 1.0))
            if f.is_categorical():
                vocab = vocabs.setdefault(
                    f.ordinal, {v: i for i, v in enumerate(f.cardinality or [])})
                col = np.asarray(
                    [vocab.setdefault(r[f.ordinal], len(vocab))
                     for r in records], dtype=np.int32)
                cat_cols.append(col)
                cat_w.append(w)
            else:
                col = np.asarray([float(r[f.ordinal]) for r in records])
                if f.min is not None and f.max is not None and f.max > f.min:
                    col = (col - f.min) / (f.max - f.min)
                num_cols.append(col)
                num_w.append(w)
        num = (np.stack(num_cols, axis=1) if num_cols
               else np.zeros((len(records), 0)))
        cat = (np.stack(cat_cols, axis=1) if cat_cols
               else np.zeros((len(records), 0), dtype=np.int32))
        return num, cat, np.asarray(num_w), np.asarray(cat_w)

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        inter_set = self.config.get_boolean("inter.set.matching", True)
        prefix = self.config.get("base.set.split.prefix", "tr")
        scale = self.config.get_int("distance.scale", 1000)
        algorithm = self.config.get("distance.algorithm", "euclidean")
        include_class = self.config.get_boolean("include.class.attributes",
                                                True)
        top_k = self.config.get_int("output.top.matches", None)
        # 'exact' (default) reproduces the secondary-sort ordering and
        # auto-selects the fused Pallas engine on TPU, where the two
        # exact engines may differ by +/-1 int unit on ~1e-3 of rows
        # (MXU rounding at the int-scale boundary; see
        # ops.distance.pairwise_distances); 'fused'/'sorted' force one
        # engine, 'approx' opts into lax.approx_min_k (recall ~0.98);
        # validated here so a typo fails loudly even on dense-output
        # runs where no selection runs
        topk_method = self.config.get("topk.method", "exact")
        if topk_method not in ("exact", "fused", "sorted", "approx"):
            raise ValueError(f"unknown top-k method {topk_method!r}; "
                             "use 'exact', 'fused', 'sorted' or 'approx'")

        train_recs: List[List[str]] = []
        test_recs: List[List[str]] = []
        for fp in _input_files(in_path):
            is_base = os.path.basename(fp).startswith(prefix)
            for line in read_lines(fp):
                rec = split_line(line, delim_regex)
                (train_recs if is_base or not inter_set else test_recs
                 ).append(rec)
        if not inter_set:
            test_recs = train_recs
        counters.set("Basic", "Training records", len(train_recs))
        counters.set("Basic", "Test records", len(test_recs))

        vocabs: Dict[int, Dict[str, int]] = {}
        tnum, tcat, num_w, cat_w = self._encode(train_recs, vocabs)
        qnum, qcat, _, _ = self._encode(test_recs, vocabs)

        id_field = self.schema.id_field()
        cls_field = None
        try:
            cls_field = self.schema.class_attr_field()
        except ValueError:
            include_class = False
        train_ids = [r[id_field.ordinal] for r in train_recs]
        test_ids = [r[id_field.ordinal] for r in test_recs]

        # self-join: request one extra neighbor so the zero-distance
        # diagonal entry does not consume a top-k slot
        effective_k = (top_k + 1 if top_k and not inter_set else top_k)
        dist, idx = pairwise_distances(
            qnum, qcat, tnum, tcat, num_w, cat_w, algorithm=algorithm,
            scale=scale, top_k=effective_k, mesh=mesh,
            topk_method=topk_method)

        lines: List[str] = []
        for qi in range(len(test_recs)):
            cols = (idx[qi] if idx is not None
                    else range(len(train_recs)))
            emitted = 0
            for rank, ti in enumerate(cols):
                ti = int(ti)
                if not inter_set and ti == qi:
                    continue   # self-join skips the diagonal
                if top_k and emitted == top_k:
                    break
                d = int(dist[qi, rank] if idx is not None else dist[qi, ti])
                parts = [train_ids[ti], test_ids[qi], str(d)]
                if include_class and cls_field is not None:
                    parts.append(train_recs[ti][cls_field.ordinal])
                    parts.append(test_recs[qi][cls_field.ordinal])
                lines.append(delim.join(parts))
                emitted += 1
        counters.set("Basic", "Pairs emitted", len(lines))
        write_output(out_path, lines)
        return counters


# ---------------------------------------------------------------------------
# FeatureCondProbJoiner
# ---------------------------------------------------------------------------

class FeatureCondProbJoiner:
    """Joins distance pairs with NB feature-posterior lines
    (knn/FeatureCondProbJoiner.java).

    Prob lines are the BayesianPredictor's ``output.feature.prob.only``
    format: ``id, featPrior, class1, post1, class2, post2, actualClass``
    (BayesianPredictor.java output path); the joiner keeps, per training
    item, the posterior of its OWN class value
    (FeatureCondProbJoiner.java reducer first-tuple scan).  Output:
    ``testId, testClass, trainId, distance, trainClass, postProb`` — the
    exact column order NearestNeighbor's class-condition-weighted mapper
    expects (NearestNeighbor.java:137-149)."""

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        prefix = self.config.get("feature.cond.prob.split.prefix", "condProb")

        prob: Dict[str, Tuple[str, str]] = {}
        pair_lines: List[List[str]] = []
        for root in in_path.split(","):
            for fp in _input_files(root):
                is_prob = os.path.basename(fp).startswith(prefix)
                for line in read_lines(fp):
                    items = split_line(line, delim_regex)
                    if is_prob:
                        # id, featPrior, (class, post)*, actualClass
                        actual = items[-1]
                        post = ""
                        for i in range(2, len(items) - 1, 2):
                            if items[i] == actual:
                                post = items[i + 1]
                                break
                        prob[items[0]] = (actual, post)
                    else:
                        pair_lines.append(items)

        out: List[str] = []
        for items in pair_lines:
            train_id, test_id, dist = items[0], items[1], items[2]
            test_class = items[4] if len(items) > 4 else ""
            cls, post = prob.get(train_id, ("", ""))
            out.append(delim.join(
                [test_id, test_class, train_id, dist, cls, post]))
            counters.incr("Join", "Joined pairs")
        write_output(out_path, out)
        return counters


# ---------------------------------------------------------------------------
# Neighborhood (voting / kernel library)
# ---------------------------------------------------------------------------

class Neighborhood:
    """Vectorized Neighborhood (knn/Neighborhood.java): kernel scores for a
    whole [n_test, k] neighbor block at once; per-neighborhood reductions
    follow the reference's integer arithmetic."""

    CLASSIFICATION = "classification"
    REGRESSION = "regression"

    def __init__(self, kernel_function: str = "none", kernel_param: int = -1,
                 class_cond_weighted: bool = False,
                 inverse_distance_weighted: bool = False):
        self.kernel_function = kernel_function
        self.kernel_param = kernel_param
        self.class_cond_weighted = class_cond_weighted
        self.inverse_distance_weighted = inverse_distance_weighted

    def scores(self, distances: np.ndarray) -> np.ndarray:
        """Integer kernel score per neighbor (Neighborhood.java:126-160)."""
        d = distances.astype(np.int64)
        if self.kernel_function == "none":
            return np.ones_like(d)
        if self.kernel_function == "linearMultiplicative":
            return np.where(d == 0, 2 * KERNEL_SCALE,
                            KERNEL_SCALE // np.maximum(d, 1))
        if self.kernel_function == "linearAdditive":
            return KERNEL_SCALE - d
        if self.kernel_function == "gaussian":
            t = d.astype(np.float64) / self.kernel_param
            return (KERNEL_SCALE * np.exp(-0.5 * t * t)).astype(np.int64)
        raise ValueError(
            f"unsupported kernel function {self.kernel_function}")

    def weighted_scores(self, scores: np.ndarray, distances: np.ndarray,
                        post_probs: np.ndarray) -> np.ndarray:
        """Class-conditional weighting (Neighborhood.Neighbor.setScore,
        Neighborhood.java:52-66 of the inner class): score * postProb,
        optionally * 1/distance."""
        w = np.where(post_probs > 0, scores * post_probs,
                     scores.astype(np.float64))
        if self.inverse_distance_weighted:
            w = w / np.maximum(distances, 1e-12)
        return w


# ---------------------------------------------------------------------------
# NearestNeighbor classifier/regressor job
# ---------------------------------------------------------------------------

class NearestNeighbor:
    """Top-K voting job (knn/NearestNeighbor.java)."""

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        c = config
        self.top_match_count = c.get_int("top.match.count", 10)
        self.validation = c.get_boolean("validation.mode", True)
        # the reference reads BOTH spellings: the mapper uses
        # "class.condition.weighted" (NearestNeighbor.java:121), the reducer
        # "class.condtion.weighted" (:239, matching knn.properties:37)
        ccw = c.get("class.condition.weighted", c.get("class.condtion.weighted"))
        self.class_cond_weighted = str(ccw).lower() == "true"
        self.prediction_mode = c.get("prediction.mode", "classification")
        self.regression_method = c.get("regression.method", "average")
        self.neighborhood = Neighborhood(
            c.get("kernel.function", "none"), c.get_int("kernel.param", -1),
            self.class_cond_weighted,
            c.get_boolean("inverse.distance.weighted", False))
        self.output_class_distr = c.get_boolean("output.class.distr", False)
        self.decision_threshold = c.get_float("decision.threshold", -1.0)
        self.use_cost_based = c.get_boolean("use.cost.based.classifier", False)
        self.pos_class = self.neg_class = None
        self.arbitrator = None
        if (self.decision_threshold > 0 or self.use_cost_based) \
                and self.prediction_mode == "classification":
            vals = c.must("class.attribute.values").split(",")
            self.pos_class, self.neg_class = vals[0], vals[1]
            if self.use_cost_based:
                cost = [int(v) for v in
                        c.must("misclassification.cost").split(",")]
                self.arbitrator = CostBasedArbitrator(
                    self.neg_class, self.pos_class, cost[1], cost[0])
        self.conf_matrix = None
        if self.validation and self.prediction_mode == "classification":
            schema = schema or FeatureSchema.from_file(
                c.must("feature.schema.file.path"))
            card = schema.class_attr_field().cardinality
            self.conf_matrix = ConfusionMatrix(card[0], card[1])

    # -- per-neighborhood decisions (Neighborhood.java:224-320) ----------
    @staticmethod
    def _distribution(class_vals: List[str],
                      scores: np.ndarray) -> Dict[str, float]:
        distr: Dict[str, float] = defaultdict(float)
        for cv, s in zip(class_vals, scores):
            distr[cv] += s
        return distr

    def _classify(self, distr: Dict[str, float]) -> str:
        if self.decision_threshold > 0 and not self.class_cond_weighted:
            pos = distr.get(self.pos_class, 0)
            neg = max((v for k, v in distr.items() if k != self.pos_class),
                      default=0)
            # neg == 0 -> pos/neg = Infinity in the reference
            # (Neighborhood.java:300), i.e. unanimous positive wins
            ratio = pos / neg if neg > 0 else float("inf")
            return (self.pos_class if ratio > self.decision_threshold
                    else self.neg_class)
        best, best_score = None, 0
        for cv, s in distr.items():
            if s > best_score:
                best, best_score = cv, s
        return best if best is not None else ""

    def _class_prob(self, distr: Dict[str, float], class_val: str) -> int:
        total = sum(distr.values())
        if total <= 0:
            return 0
        return int(distr.get(class_val, 0) * PROB_SCALE / total)

    def _regress(self, class_vals: List[str], regr_in: List[float],
                 test_regr_in: float) -> int:
        vals = [int(float(v)) for v in class_vals]
        if self.regression_method == "average":
            return int(sum(vals) / len(vals))   # int division parity
        if self.regression_method == "median":
            vals.sort()
            mid = len(vals) // 2
            return (vals[mid] if len(vals) % 2 == 1
                    else (vals[mid - 1] + vals[mid]) // 2)
        if self.regression_method == "linearRegression":
            x = np.asarray(regr_in, dtype=np.float64)
            yv = np.asarray([float(v) for v in class_vals])
            xm, ym = x.mean(), yv.mean()
            sxx = ((x - xm) ** 2).sum()
            slope = ((x - xm) * (yv - ym)).sum() / sxx if sxx > 0 else 0.0
            return int(ym + slope * (test_regr_in - xm))
        raise ValueError(
            f"unsupported regression method {self.regression_method}")

    def classify_group(self, neighbors: List[Tuple], test_id: str,
                       test_class_val: str = "",
                       test_regr_val: float = 0.0) -> Tuple[str, str]:
        """One neighborhood decision: ``neighbors`` are (dist, trainId,
        trainClass, postProb, regrIn) tuples in arrival order.  Returns
        (output line, predicted) — the per-reducer-group body of ``run``,
        shared verbatim with the serving engine's kNN adapter so online
        responses are byte-identical to the batch job's lines."""
        delim = self.config.field_delim_out()
        ccw = self.class_cond_weighted
        neighbors = sorted(neighbors, key=lambda t: t[0])
        top = neighbors[:self.top_match_count]
        dists = np.asarray([t[0] for t in top])
        cvals = [t[2] for t in top]
        posts = np.asarray([t[3] for t in top])
        scores = self.neighborhood.scores(dists)
        if ccw:
            scores = self.neighborhood.weighted_scores(scores, dists, posts)

        distr = self._distribution(cvals, scores)
        parts = [test_id]
        if self.output_class_distr \
                and self.prediction_mode == "classification":
            for cv, s in distr.items():
                parts += [cv, str(s if ccw else int(s))]
        if self.validation:
            parts.append(test_class_val)

        if self.prediction_mode == "classification":
            if self.use_cost_based:
                pos_prob = self._class_prob(distr, self.pos_class)
                predicted = self.arbitrator.classify(pos_prob)
            else:
                predicted = self._classify(distr)
        else:
            predicted = str(self._regress(
                cvals, [t[4] for t in top], test_regr_val))
        parts.append(predicted)
        return delim.join(parts), predicted

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        delim_regex = self.config.field_delim_regex()
        ccw = self.class_cond_weighted
        is_linreg = (self.prediction_mode == "regression"
                     and self.regression_method == "linearRegression")

        # mapper parse (NearestNeighbor.java:130-180)
        tracer = get_tracer()
        groups: Dict[str, List[Tuple]] = defaultdict(list)
        test_class: Dict[str, str] = {}
        test_regr: Dict[str, float] = {}
        with tracer.span("phase:load"):
            for line in read_lines(in_path):
                items = split_line(line, delim_regex)
                if ccw:
                    test_id, t_class, train_id = items[0], items[1], items[2]
                    dist = int(items[3])
                    train_class = items[4]
                    post = float(items[5]) if items[5] else -1.0
                    groups[test_id].append(
                        (dist, train_id, train_class, post, 0.0))
                    test_class[test_id] = t_class
                else:
                    train_id, test_id = items[0], items[1]
                    dist = int(items[2])
                    train_class = items[3]
                    i = 4
                    if self.validation:
                        test_class[test_id] = items[i]
                        i += 1
                    r_in = 0.0
                    if is_linreg:
                        r_in = float(items[i])
                        test_regr[test_id] = float(items[i + 1])
                    groups[test_id].append(
                        (dist, train_id, train_class, -1.0, r_in))

        out: List[str] = []
        with tracer.span("phase:score"):
            for test_id, neighbors in groups.items():
                line, predicted = self.classify_group(
                    neighbors, test_id, test_class.get(test_id, ""),
                    test_regr.get(test_id, 0.0))
                out.append(line)
                if self.conf_matrix is not None:
                    self.conf_matrix.report(predicted,
                                            test_class.get(test_id, ""))

        with tracer.span("phase:emit"):
            if self.conf_matrix is not None:
                self.conf_matrix.to_counters(counters)
            write_output(out_path, out)
        return counters

"""Probabilistic suffix tree: n-gram count generator + in-memory tree.

Reference surface:
- ``markov.ProbabilisticSuffixTreeGenerator`` — per record emits every
  sliding window of length 2..max.seq.length (optionally per partition-id
  fields and per class label), plus a root-symbol line whose count is the
  number of windows the record produced
  (ProbabilisticSuffixTreeGenerator.java:150-211); reducer sums and writes
  ``[partIds,][classLabel,]sym1,..,symk,count`` lines (:294-304).  A
  one-event-per-row input mode maintains a rolling window per partition
  (:219-243).
- ``markov.SuffixTreeBuilder`` / ``SuffixTreeNode`` — in-memory suffix tree
  built from those lines (SuffixTreeBuilder.java:45-70), used downstream for
  sequence probability queries.

TPU re-design: symbols are vocab-encoded; for each window length w the
(partition, class, sym_1..sym_w) counts are ONE dense ``count_table`` scatter
over all sliding windows (the mapper's triple loop vanishes into indexing).
When the dense key space V^w would blow past a size cap the job falls back to
an exact host Counter — same output, still one pass.
"""

from __future__ import annotations

from collections import Counter as PyCounter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters
from ..ops.counting import (count_table, sharded_ngram_counts,
                            sharded_reduce)

_DENSE_CAP = 1 << 22  # max dense count-tensor cells before host fallback


def _pst_local(windows, part_cls, mask, sizes):
    """windows int32 [n, w]; part_cls int32 [n] combined partition/class id."""
    idx = tuple(part_cls[:, None] if d == 0 else windows[:, d - 1:d]
                for d in range(len(sizes)))
    m = mask[:, None]
    return count_table(sizes, idx, mask=m)


class ProbabilisticSuffixTreeGenerator:
    """The PST counting job."""

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        delim = cfg.field_delim_out()
        skip = cfg.get_int("skip.field.count", 0)
        class_ord = cfg.get_int("class.label.field.ord", -1)
        if class_ord >= 0:
            skip += 1
        root_symbol = cfg.get("tree.root.symbol", "$")
        max_len = cfg.get_int("max.seq.length", 5)
        id_ords = cfg.get_list("id.field.ordinals")
        id_ords = [int(v) for v in id_ords] if id_ords else None
        sequential = cfg.get_boolean("input.format.sequential", True)

        records = [split_line(l, delim_regex) for l in read_lines(in_path)]
        if not sequential:
            data_ord = cfg.must_int(
                "data.field.ordinal",
                "for non sequential data data field ordinal must be specified")
            records = self._sessionize(records, id_ords, class_ord, data_ord,
                                       max_len)
            skip_eff = (len(id_ords) if id_ords else 0) + (1 if class_ord >= 0 else 0)
        else:
            skip_eff = skip

        # prefix = partition ids + class label (both optional)
        prefixes: List[Tuple[str, ...]] = []
        seqs: List[List[str]] = []
        vocab: Dict[str, int] = {}
        for r in records:
            if sequential:
                pre = tuple(r[o] for o in id_ords) if id_ords else ()
                if class_ord >= 0:
                    pre = pre + (r[class_ord],)
            else:
                pre = tuple(r[:skip_eff])
            body = r[skip_eff:]
            prefixes.append(pre)
            seqs.append(body)
            for s in body:
                if s not in vocab:
                    vocab[s] = len(vocab)

        pre_vocab: Dict[Tuple[str, ...], int] = {}
        for p in prefixes:
            if p not in pre_vocab:
                pre_vocab[p] = len(pre_vocab)

        V = max(1, len(vocab))
        P = max(1, len(pre_vocab))
        ngram_counts: Dict[Tuple, int] = {}
        root_counts: Dict[Tuple[str, ...], int] = PyCounter()

        inv = list(vocab.keys())
        inv_pre = list(pre_vocab.keys())

        def extract(c: np.ndarray) -> None:
            for key in np.argwhere(c > 0):
                toks_k = tuple(inv[k] for k in key[1:])
                ngram_counts[(inv_pre[key[0]],) + toks_k] = int(c[tuple(key)])

        # sequential mode: concatenate every row into ONE segmented stream
        # (-1 separators, per-token fused prefix id) so all sliding windows
        # of every length come from the sequence-parallel halo-exchange
        # counter — no host window materialization
        # (ProbabilisticSuffixTreeGenerator.java:153-173); skipped when even
        # the w=2 table exceeds the dense cap (every w would fall back)
        stream = seg_ids = None
        if sequential and max_len >= 2 and P * V * V <= _DENSE_CAP:
            toks, sgs = [], []
            for r_i, body in enumerate(seqs):
                if len(body) < 2:
                    continue
                toks.extend(vocab[t] for t in body)
                toks.append(-1)
                sgs.extend([pre_vocab[prefixes[r_i]]] * len(body))
                sgs.append(-1)
            stream = np.asarray(toks, dtype=np.int32)
            seg_ids = np.asarray(sgs, dtype=np.int32)

        for w in range(2, max_len + 1):
            sizes = (P,) + (V,) * w
            if (stream is not None and stream.size
                    and int(np.prod(sizes)) <= _DENSE_CAP):
                c = np.asarray(sharded_ngram_counts(
                    stream, V, w, seg=seg_ids, n_seg=P, mesh=mesh))
                extract(c)
                for p_i in range(P):
                    n_win = int(c[p_i].sum())
                    if n_win:
                        root_counts[inv_pre[p_i]] += n_win
                continue
            # sessionized rows emit ONLY the length-w prefix of each full
            # rolling window — the reference emits window[0:w] once per
            # event (:225-241), so sliding inside overlapping windows would
            # over-count interior n-grams; also the host fallback for
            # over-cap dense tables
            rows, pcs = [], []
            for r_i, body in enumerate(seqs):
                if len(body) < 2:
                    continue
                if sequential:
                    starts = range(0, len(body) - w + 1)
                else:
                    starts = range(0, 1) if len(body) >= w else range(0)
                for s in starts:
                    rows.append([vocab[t] for t in body[s:s + w]])
                    pcs.append(pre_vocab[prefixes[r_i]])
                    root_counts[prefixes[r_i]] += 1
            if not rows:
                continue
            windows = np.asarray(rows, dtype=np.int32)
            part_cls = np.asarray(pcs, dtype=np.int32)
            if int(np.prod(sizes)) <= _DENSE_CAP:
                c = np.asarray(sharded_reduce(
                    _pst_local, windows, part_cls, mesh=mesh,
                    static_args=(sizes,)))
                extract(c)
            else:
                host = PyCounter()
                for row, pc in zip(rows, pcs):
                    host[(inv_pre[pc],) + tuple(inv[k] for k in row)] += 1
                for k, v in host.items():
                    ngram_counts[k] = ngram_counts.get(k, 0) + v
                counters.incr("PST", "HostFallbackWindows", len(rows))

        lines: List[str] = []
        for key in sorted(ngram_counts):
            pre, toks = key[0], key[1:]
            parts = list(pre) + list(toks) + [str(ngram_counts[key])]
            lines.append(delim.join(parts))
        for pre in sorted(root_counts):
            lines.append(delim.join(list(pre) + [root_symbol,
                                                 str(root_counts[pre])]))
        write_output(out_path, lines)
        counters.set("PST", "Ngrams", len(ngram_counts))
        return counters

    @staticmethod
    def _sessionize(records, id_ords, class_ord, data_ord, max_len):
        """One-event-per-row input: maintain a rolling window per partition
        and materialize one pseudo-record per full window
        (ProbabilisticSuffixTreeGenerator.java:219-243)."""
        windows: Dict[Tuple[str, ...], List[str]] = {}
        out = []
        for r in records:
            pid = tuple(r[o] for o in id_ords) if id_ords else ()
            key = pid + ((r[class_ord],) if class_ord >= 0 else ())
            win = windows.setdefault(key, [])
            win.append(r[data_ord])
            if len(win) > max_len:
                win.pop(0)
            if len(win) == max_len:
                out.append(list(key) + list(win))
        return out


class SuffixTreeNode:
    """In-memory PST node (markov/SuffixTreeNode.java:28-158)."""

    def __init__(self, token: Optional[str] = None):
        self.token = token
        self.count = 0
        self.children: Dict[str, "SuffixTreeNode"] = {}

    def add(self, tokens: Sequence[str], count: int = 1) -> None:
        node = self
        for t in tokens[:-1]:
            node = node.children.setdefault(t, SuffixTreeNode(t))
        # last token carries the count (lines are full paths with counts)
        leaf = node.children.setdefault(tokens[-1], SuffixTreeNode(tokens[-1]))
        leaf.count += count

    def find(self, tokens: Sequence[str]) -> Optional["SuffixTreeNode"]:
        node = self
        for t in tokens:
            node = node.children.get(t)
            if node is None:
                return None
        return node

    def is_leaf(self) -> bool:
        return not self.children


class SuffixTreeBuilder:
    """Builds (optionally partitioned) trees from generator output lines
    (markov/SuffixTreeBuilder.java:45-70)."""

    def __init__(self, path: str, delim: str = ",",
                 num_id_fields: int = 0):
        self.tree = SuffixTreeNode()
        self.partitioned: Dict[Tuple[str, ...], SuffixTreeNode] = {}
        for line in read_lines(path):
            items = line.split(delim)
            count = int(items[-1])
            toks = items[:-1]
            if num_id_fields:
                pid = tuple(toks[:num_id_fields])
                tree = self.partitioned.setdefault(pid, SuffixTreeNode())
                tree.add(toks[num_id_fields:], count)
            else:
                self.tree.add(toks, count)

    def get_tree(self, part_id: Optional[Tuple[str, ...]] = None) -> SuffixTreeNode:
        return self.tree if part_id is None else self.partitioned[part_id]

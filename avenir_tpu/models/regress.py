"""Iterative batch logistic regression (TPU-native).

Reference surface being re-expressed (citations into /root/reference):
- ``org.avenir.regress.LogisticRegressionJob`` — one MR pass per iteration:
  mapper loads the LAST line of the coefficient-history file
  (``coeff.file.path``, one line per iteration; LogisticRegressionJob.java:154-160),
  parses the feature columns as ints with a constant-1 bias prepended
  (:182-191), and aggregates per-record gradient contributions; the reducer
  sums partial aggregates, writes the new coefficient line to the job output,
  and APPENDS it to the history file (:220-255).  The driver then checks
  convergence and returns CONVERGED(100)/NOT_CONVERGED(101) so an outer loop
  can re-run (:95-119, main :279-289).
- ``org.avenir.regress.LogisticRegressor`` — the gradient:
  ``agg += x * (y - sigmoid(w.x))`` (LogisticRegressor.java:61-73), and the
  convergence measures over the percent relative change between consecutive
  coefficient lines: all-below-threshold and average-below-threshold
  (:105-163).

Reference-parity note: the reference's "new coefficients" ARE the raw
gradient aggregates — the reducer saves ``regressor.getAggregates()``
verbatim with no learning-rate step (LogisticRegressionJob.java:220-230), a
fixed-point iteration rather than gradient ascent.  We reproduce that by
default so history files and convergence behavior match.  Setting
``learning.rate`` (no reference equivalent) switches to the standard ascent
update ``w' = w + lr * agg / n`` — the numerically sane mode for new users.

TPU re-design: mapper+shuffle+reducer collapse into one jitted
``shard_map`` pass — each device computes ``X_shard^T (y - sigmoid(X w))``
on its row shard (an MXU matvec pair) and ``psum`` over the data axis plays
the reducer's aggregate sum.  The row batch is padded/sharded once and stays
device-resident across iterations; only the 1-D coefficient vector moves
per step.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from ..parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import (atomic_write_text, read_lines, split_line,
                       write_output)
from ..core.metrics import Counters
from ..core.schema import FeatureSchema
from ..parallel.mesh import get_mesh, pad_rows
from ..utils.caches import bounded_cache_get, bounded_cache_put

CONVERGED = 100
NOT_CONVERGED = 101

ITER_LIMIT = "iterLimit"
ALL_BELOW_THRESHOLD = "allBelowThreshold"
AVERAGE_BELOW_THRESHOLD = "averageBelowThreshold"


class LogisticRegressor:
    """Host-side convergence math (LogisticRegressor.java:105-163)."""

    def __init__(self, coefficients: np.ndarray, aggregates: np.ndarray):
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.aggregates = np.asarray(aggregates, dtype=np.float64)

    def coeff_diff(self) -> np.ndarray:
        """|(new - old) * 100 / old| per coefficient.

        A coefficient that is exactly 0 in the previous line (the natural
        all-zero starting point) would make the reference formula divide by
        zero and never converge; treat 0 -> 0 as 0% change and 0 -> nonzero
        as infinite change so thresholds behave sensibly.
        """
        with np.errstate(divide="ignore", invalid="ignore"):
            diff = np.abs((self.aggregates - self.coefficients) * 100.0
                          / self.coefficients)
        both_zero = (self.coefficients == 0.0) & (self.aggregates == 0.0)
        return np.where(both_zero, 0.0, diff)

    def is_all_converged(self, threshold: float) -> bool:
        return bool(np.all(self.coeff_diff() <= threshold))

    def is_average_converged(self, threshold: float) -> bool:
        return bool(self.coeff_diff().mean() < threshold)


_grad_cache = {}


def _gradient_fn(mesh, shape_key):
    fn = bounded_cache_get(_grad_cache, (mesh, shape_key))
    if fn is None:
        def local(x, y, mask, w):
            # mapper hot loop: sigmoid scores + gradient outer-sum, one
            # matvec pair on the MXU per shard; psum = reducer sum.
            # HIGHEST precision: the TPU default rounds f32 operands to
            # bf16 (8 mantissa bits), which would quantize scores and
            # gradients ~0.4% — the reference's mapper computes in
            # doubles (LogisticRegressionJob gradient math)
            hi = jax.lax.Precision.HIGHEST
            z = jnp.matmul(x, w, precision=hi)
            p = 1.0 / (1.0 + jnp.exp(-z))
            g = jnp.matmul(x.T, jnp.where(mask, y - p, 0.0), precision=hi)
            return jax.lax.psum(g, "data")

        fn = jax.jit(shard_map(
            local, mesh=mesh,
            in_specs=(P("data"), P("data"), P("data"), P()),
            out_specs=P()))
        bounded_cache_put(_grad_cache, (mesh, shape_key), fn)
    return fn


class LogisticRegressionJob:
    """One logistic-regression iteration + convergence check; ``run_loop``
    mirrors the reference driver's do-while (LogisticRegressionJob.java:279-289)."""

    def __init__(self, config: JobConfig):
        self.config = config
        self.schema = FeatureSchema.from_file(config.must("feature.schema.file.path"))
        self.counters = Counters()
        # device-resident batch, loaded lazily and reused across iterations
        self._resident = None
        self._resident_path = None

    # -- history file -------------------------------------------------------
    def _read_history(self) -> List[str]:
        path = self.config.must("coeff.file.path")
        return [l for l in read_lines(path)]

    def _write_history(self, lines: List[str]) -> None:
        # the coefficient history drives iterative restart (README
        # "Failure recovery"): atomic replace, so a crash mid-iteration
        # leaves the previous complete history, never a torn file
        atomic_write_text(self.config.must("coeff.file.path"),
                          "".join(line + "\n" for line in lines))

    # -- data ---------------------------------------------------------------
    def _load(self, in_path: str, mesh=None):
        if self._resident is not None and self._resident_path == in_path:
            return self._resident
        delim = self.config.field_delim_regex()
        ords = [f.ordinal for f in self.schema.feature_fields()]
        class_ord = self.schema.class_attr_field().ordinal
        pos_val = self.config.must("positive.class.value")

        xs, ys = [], []
        for line in read_lines(in_path):
            items = split_line(line, delim)
            # bias term first, features parsed as ints
            # (LogisticRegressionJob.java:184-191)
            xs.append([1] + [int(items[o]) for o in ords])
            ys.append(1.0 if items[class_ord] == pos_val else 0.0)
        x = np.asarray(xs, dtype=np.float64)
        y = np.asarray(ys, dtype=np.float64)

        mesh = mesh or get_mesh()
        d = mesh.shape["data"]
        x, mask = pad_rows(x, d)
        y, _ = pad_rows(y, d)
        self._resident = (jnp.asarray(x), jnp.asarray(y),
                          jnp.asarray(mask), mesh)
        self._resident_path = in_path
        return self._resident

    # -- one iteration ------------------------------------------------------
    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> int:
        cfg = self.config
        delim = cfg.field_delim_out()
        history = self._read_history()
        if not history:
            raise ValueError("coeff.file.path must hold the initial "
                             "coefficient line (bias first, one per feature)")
        coeff = np.asarray(
            [float(v) for v in split_line(history[-1], cfg.field_delim_regex())])

        x, y, mask, mesh = self._load(in_path, mesh)
        if coeff.shape[0] != x.shape[1]:
            raise ValueError(
                f"coefficient line has {coeff.shape[0]} values; expected "
                f"{x.shape[1]} (bias + feature fields)")
        grad = np.asarray(
            _gradient_fn(mesh, x.shape)(x, y, mask, jnp.asarray(coeff)))

        lr = cfg.get_float("learning.rate", None)
        if lr is None:
            # reference parity: the aggregates ARE the next line
            new_coeff = grad
        else:
            n = int(np.asarray(mask).sum())
            new_coeff = coeff + lr * grad / n

        line = delim.join(repr(float(v)) for v in new_coeff)
        history.append(line)
        self._write_history(history)
        write_output(out_path, [line])
        self.counters.incr("Regression", "Iterations")
        return self._check_convergence(history)

    def _check_convergence(self, history: List[str]) -> int:
        cfg = self.config
        criteria = cfg.get("convergence.criteria", ITER_LIMIT)
        if criteria == ITER_LIMIT:
            limit = cfg.get_int("iteration.limit", 10)
            return NOT_CONVERGED if len(history) < limit else CONVERGED
        prev = np.asarray([float(v) for v in
                           split_line(history[-2], cfg.field_delim_regex())])
        cur = np.asarray([float(v) for v in
                          split_line(history[-1], cfg.field_delim_regex())])
        reg = LogisticRegressor(prev, cur)
        threshold = cfg.get_float("convergence.threshold", 5.0)
        if criteria == ALL_BELOW_THRESHOLD:
            return CONVERGED if reg.is_all_converged(threshold) else NOT_CONVERGED
        if criteria == AVERAGE_BELOW_THRESHOLD:
            return (CONVERGED if reg.is_average_converged(threshold)
                    else NOT_CONVERGED)
        raise ValueError(f"Invalid convergence criteria:{criteria}")

    # -- the outer do-while (reference main) --------------------------------
    def run_loop(self, in_path: str, out_path: str,
                 max_iterations: Optional[int] = None) -> int:
        # finite default bound: a threshold criterion that never fires (e.g.
        # a coefficient stuck at +/-inf percent change) must not spin forever;
        # an iterLimit run keeps its full configured budget even past the cap
        if max_iterations is None:
            max_iterations = self.config.get_int("max.iterations", 1000)
            criteria = self.config.get("convergence.criteria", ITER_LIMIT)
            if criteria == ITER_LIMIT:
                max_iterations = max(max_iterations,
                                     self.config.get_int("iteration.limit", 10))
        status = NOT_CONVERGED
        it = 0
        while status == NOT_CONVERGED:
            status = self.run(in_path, out_path)
            it += 1
            if it >= max_iterations:
                break
        return status

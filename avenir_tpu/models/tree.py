"""Decision tree family: split-gain generation, level-synchronous tree
growth, and physical data partitioning (TPU-native).

Reference surface re-expressed (citations into /root/reference):
- ``org.avenir.explore.ClassPartitionGenerator`` — candidate-split quality
  job: mapper enumerates splits and emits (attr, splitKey, segmentIndex,
  classVal)->1 (ClassPartitionGenerator.java:200-230), combiner sums, reducer
  accumulates AttributeSplitStat and emits gain-ratio per candidate in
  cleanup (:483-566); ``at.root`` mode emits the dataset's own info content
  (:161-163, 516-519).
- ``org.avenir.tree.SplitGenerator`` — thin wrapper deriving in/out paths
  from ``project.base.path``/``split.path`` (SplitGenerator.java:31-53).
- ``org.avenir.tree.DecisionTreeBuilder`` — one MR pass per tree level;
  mapper routes records down decision paths and emits once per satisfied
  candidate predicate (DecisionTreeBuilder.java:245-321); reducer accumulates
  per-(parentPath, childPredicate) class histograms, picks the min
  weighted-entropy/gini attribute per parent in cleanup, and writes the new
  DecisionPathList JSON (:423-538).
- ``org.avenir.tree.DataPartitioner`` — picks the best candidate split and
  physically partitions records into ``split=…/segment=…/data/`` directories
  (DataPartitioner.java:60-131, 155-201).

TPU re-design: the mapper's per-record x per-predicate emit loop (the data
explosion identified in SURVEY §3.3) becomes a vectorized boolean predicate
matrix ``B[n, preds]`` plus ONE dense (path, predicate, class) scatter-add on
device, psum'd over the row-sharded data axis — mapper+combiner+shuffle+
reducer collapse into ``ops.counting.sharded_reduce``.  Split selection and
the DecisionPathList JSON checkpoint stay host-side (tiny), preserving the
reference's iteration-granularity resume model (SURVEY §5 checkpoint/resume).

Documented deviations from the reference (which is unexercised and carries
several blocking defects in this package):
- DecisionTreeBuilder.BuilderMapper indexes schema ordinals into the
  path-prefixed record without shifting (DecisionTreeBuilder.java:255-257:
  ``items[classField.getOrdinal()]`` while ``items[0]`` is the decision
  path), which reads the wrong columns from the second level on.  We strip
  the path prefix first so ordinals always address the original fields.
- BuilderReducer reads the class value from ``values.toString()`` — the
  Iterable's identity string — instead of each value
  (DecisionTreeBuilder.java:610), so every reference histogram collapses to
  one garbage key.  We count each record's actual class value.
- DecisionPathStoppingStrategy compares the strategy STRING to the int depth
  limit (DecisionPathStoppingStrategy.java:61 ``stoppingStrategy.equals(
  maxDepthLimit)``), making maxDepth unusable.  Implemented as intended:
  stop when ``depth >= maxDepthLimit``.
- generateRoot drops the root predicate it builds
  (DecisionTreeBuilder.java:529-537), leaving ``predicates`` null and
  breaking every later ``findDecisionPath``.  We persist the ``$root``
  predicate so iteration 2 can match it.
- Records on ``stopped`` paths pass through unchanged instead of being
  re-split forever (the reference ignores its own stopped flag,
  DecisionTreeBuilder.java:261-267 checks existence only); this is what lets
  ``run_loop`` terminate.
"""

from __future__ import annotations

import json
import os
import random
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.binning import Vocab
from ..core.obs import traced_run
from ..core.config import JobConfig
from ..core.io import (OutputWriter, atomic_write_text, read_lines,
                       split_line, write_output)
from ..core.metrics import Counters
from ..core.schema import FeatureField, FeatureSchema
from ..ops.counting import (count_on_mxu, count_table, masked_onehot,
                            onehot_dtype, sharded_reduce)
from .split import (ALG_ENTROPY, ALG_GINI_INDEX, AttributePredicate, Split,
                    class_probabilities, enumerate_attr_splits, info_content,
                    predicate_matrix, segment_predicates, split_info_content,
                    split_stat)

ROOT_PATH = "$root"
CHILD_PATH = "$child"


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _class_vocab(records: List[List[str]], class_field: FeatureField) -> Vocab:
    """Stable class-value vocabulary: declared cardinality order first, then
    first-seen discovery (core.binning.Vocab policy)."""
    vocab = Vocab(class_field.cardinality or ())
    for items in records:
        vocab.add(items[class_field.ordinal])
    return vocab


def _column(records: List[List[str]], field: FeatureField) -> np.ndarray:
    col = [items[field.ordinal] for items in records]
    if field.is_categorical():
        return np.asarray(col, dtype=object)
    return np.asarray([float(v) for v in col], dtype=np.float64)


# Module-level local_fns so sharded_reduce's compiled-function cache hits
# across iterations (tree levels / partition rounds).

def _seg_class_count_local(seg, y, mask, n_splits, max_seg, n_class,
                           force_mxu=None):
    """C[split, segment, class] += 1; seg is the [n, n_splits] segment-index
    matrix (the vectorized AttributeSplitHandler.getSegmentIndex)."""
    n = seg.shape[0]
    if count_on_mxu(n, force_mxu, onehot_elems=n * n_splits * max_seg):
        oy = masked_onehot(y, n_class, mask=mask)
        og = masked_onehot(seg, max_seg)
        c = jnp.einsum("nsg,nc->sgc", og, oy,
                       preferred_element_type=jnp.float32)
        return c.astype(jnp.int32)
    ids = jnp.arange(n_splits, dtype=jnp.int32)[None, :]
    return count_table((n_splits, max_seg, n_class),
                       (ids, seg, y[:, None]), mask=mask[:, None])


def _path_pred_class_count_local(path_id, y, bmat, mask, n_paths, n_preds,
                                 n_class, force_mxu=None):
    """C[path, predicate, class] += 1 where bmat[n, preds] marks satisfied
    predicates — the whole BuilderMapper emit loop + shuffle + BuilderReducer
    histogram (DecisionTreeBuilder.java:245-321,350-423) as one pass.

    TPU path: C[(path, class), pred] is a single MXU matmul between the
    one-hot of the fused (path, class) cell and the predicate matrix —
    the per-record emit loop becomes the contraction over n."""
    n = path_id.shape[0]
    if count_on_mxu(n, force_mxu, onehot_elems=n * n_paths * n_class):
        # the fused (path, class) cell can alias a neighboring cell when a
        # component is out of range, so validity is checked per component
        # (the scatter path's count_table does the same range drop)
        valid = (mask & (y >= 0) & (y < n_class)
                 & (path_id >= 0) & (path_id < n_paths))
        cell = path_id * n_class + y
        oc = masked_onehot(cell, n_paths * n_class, mask=valid)
        bm = (bmat & mask[:, None]).astype(onehot_dtype())
        c = jnp.einsum("nz,nk->zk", oc, bm,
                       preferred_element_type=jnp.float32)
        return (c.reshape(n_paths, n_class, n_preds)
                .transpose(0, 2, 1).astype(jnp.int32))
    ids = jnp.arange(n_preds, dtype=jnp.int32)[None, :]
    return count_table((n_paths, n_preds, n_class),
                       (path_id[:, None], ids, y[:, None]),
                       mask=bmat & mask[:, None])


def _class_count_local(y, mask, n_class):
    return count_table((n_class,), (y,), mask=mask)


# ---------------------------------------------------------------------------
# ClassPartitionGenerator
# ---------------------------------------------------------------------------

class ClassPartitionGenerator:
    """Candidate-split gain job (explore/ClassPartitionGenerator.java)."""

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        self.schema = schema or FeatureSchema.from_file(
            config.must("feature.schema.file.path"))
        self.rng = random.Random(config.get_int("seed", None))

    def _split_attributes(self) -> List[int]:
        """Attribute selection (ClassPartitionGenerator.java:159-196)."""
        strategy = self.config.get("split.attribute.selection.strategy",
                                   "userSpecified")
        ordinals = [f.ordinal for f in self.schema.feature_fields()]
        if strategy == "userSpecified":
            attrs = self.config.must("split.attributes")
            return [int(a) for a in attrs.split(",")]
        if strategy in ("all", "notUsedYet"):
            # notUsedYet's used-attribute tracking is a TODO in the reference
            # (ClassPartitionGenerator.java:173) and degrades to all
            return ordinals
        if strategy == "random":
            k = self.config.get_int("random.split.set.size", 3)
            picked: set = set()
            while len(picked) != min(k, len(ordinals)):
                picked.add(self.rng.choice(ordinals))
            return sorted(picked)
        raise ValueError(
            f"invalid splitting attribute selection strategy {strategy}")

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        algorithm = self.config.get("split.algorithm", ALG_GINI_INDEX)
        at_root = self.config.get_boolean("at.root", False)
        output_split_prob = self.config.get_boolean("output.split.prob", False)

        records = [split_line(l, delim_regex) for l in read_lines(in_path)]
        counters.set("Basic", "Records", len(records))
        class_field = self.schema.class_attr_field()
        class_vocab = _class_vocab(records, class_field)
        class_values = class_vocab.values
        n_class = len(class_values)
        y = np.asarray([class_vocab[r[class_field.ordinal]] for r in records],
                       dtype=np.int32)

        if at_root:
            # dataset-level info content (ClassPartitionGenerator.java:161-163,
            # 516-519)
            counts = np.asarray(sharded_reduce(
                _class_count_local, y, mesh=mesh, static_args=(n_class,)))
            stat = float(info_content(counts, algorithm))
            write_output(out_path, [str(stat)])
            return counters

        parent_info = self.config.get_float("parent.info", None)
        if parent_info is None and algorithm in (ALG_ENTROPY, ALG_GINI_INDEX):
            raise ValueError("parent.info must be set (output of the at.root "
                             "run) for entropy/gini gain computation")
        max_cat_groups = self.config.get_int("max.cat.attr.split.groups", 3)

        # enumerate all candidate splits for the selected attributes and
        # compute the [n, n_splits] segment-index matrix (host, vectorized)
        attrs = self._split_attributes()
        splits: List[Split] = []
        seg_cols: List[np.ndarray] = []
        for attr in attrs:
            field = self.schema.field_by_ordinal(attr)
            col = _column(records, field)
            for sp in enumerate_attr_splits(field, use_bucket_grid=True,
                                            max_cat_groups=max_cat_groups):
                splits.append(sp)
                seg = sp.segment_index(col)
                if (seg < 0).any():
                    # CategoricalSplit.getSegmentIndex throws for values
                    # outside every group (AttributeSplitHandler.java:196-199)
                    bad = col[int(np.nonzero(seg < 0)[0][0])]
                    raise ValueError(f"split segment not found for {bad}")
                seg_cols.append(seg)
        if not splits:
            write_output(out_path, [])
            return counters

        seg = np.stack(seg_cols, axis=1).astype(np.int32)
        max_seg = max(sp.segment_count for sp in splits)
        counters.set("Stats", "mapper output count", len(records) * len(splits))

        counts = np.asarray(sharded_reduce(
            _seg_class_count_local, seg, y, mesh=mesh,
            static_args=(len(splits), max_seg, n_class)))

        # reducer cleanup: per-split stats -> gain ratio lines
        # (ClassPartitionGenerator.java:513-553)
        lines: List[str] = []
        for si, sp in enumerate(splits):
            seg_counts = counts[si, :sp.segment_count, :]
            stat = split_stat(seg_counts, algorithm)
            if algorithm in (ALG_ENTROPY, ALG_GINI_INDEX):
                gain = parent_info - stat
                denom = split_info_content(seg_counts)
                gain_ratio = gain / denom if denom else 0.0
                line = f"{sp.attr}{delim}{sp.key}{delim}{gain_ratio}"
                if output_split_prob:
                    pr = class_probabilities(seg_counts, class_values)
                    ser = delim.join(
                        f"{si2}{delim}{cv}{delim}{p}"
                        for si2, cps in pr.items() for cv, p in cps.items())
                    line += delim + ser
            else:
                line = f"{sp.attr}{delim}{sp.key}{delim}{stat}"
            lines.append(line)
        counters.set("Stats", "reducer input count",
                     int((counts.sum(axis=-1) > 0).sum()))
        write_output(out_path, lines)
        return counters


class SplitGenerator(ClassPartitionGenerator):
    """Derives in/out from project.base.path / split.path
    (tree/SplitGenerator.java:36-53): in = base/split=root/data[/<split
    path>], out = sibling 'splits' directory."""

    def node_paths(self) -> Tuple[str, str]:
        base = self.config.must("project.base.path")
        split_path = self.config.get("split.path")
        in_path = os.path.join(base, "split=root", "data")
        if split_path:
            in_path = os.path.join(in_path, split_path)
        return in_path, os.path.join(os.path.dirname(in_path), "splits")

    @traced_run
    def run(self, in_path: Optional[str] = None,
            out_path: Optional[str] = None, mesh=None) -> Counters:
        if self.config.get("project.base.path"):
            in_path, out_path = self.node_paths()
        return super().run(in_path, out_path, mesh=mesh)


# ---------------------------------------------------------------------------
# DecisionPathList (JSON model checkpoint)
# ---------------------------------------------------------------------------

@dataclass
class DecisionPath:
    """tree/DecisionPathList.java DecisionPath bean."""
    predicate_strs: List[str]
    population: int = 0
    info_content: float = 0.0
    stopped: bool = False

    @property
    def path_str(self) -> str:
        return ";".join(self.predicate_strs)

    def depth(self) -> int:
        return len(self.predicate_strs)


class DecisionPathList:
    """JSON (de)serialization compatible with the reference's Jackson bean
    layout (predicates carry attribute/operator/values plus predicateStr;
    matching is by predicateStr, DecisionPathList.java:120-131)."""

    def __init__(self, paths: Optional[List[DecisionPath]] = None):
        self.paths: List[DecisionPath] = paths or []

    def add(self, path: DecisionPath) -> None:
        self.paths.append(path)

    def find(self, predicate_strs: Sequence[str]) -> Optional[DecisionPath]:
        want = list(predicate_strs)
        for p in self.paths:
            if p.predicate_strs == want:
                return p
        return None

    def find_str(self, path_str: str, delim: str = ";") -> Optional[DecisionPath]:
        return self.find(path_str.split(delim))

    def all_stopped(self) -> bool:
        return all(p.stopped for p in self.paths)

    def to_json(self, schema: FeatureSchema) -> str:
        out = []
        for p in self.paths:
            preds = []
            for ps in p.predicate_strs:
                bean: Dict = {"predicateStr": ps}
                if ps != ROOT_PATH:
                    attr = int(ps.split()[0])
                    field = schema.field_by_ordinal(attr)
                    pred = AttributePredicate.parse(ps, field)
                    bean.update({
                        "attribute": pred.attr,
                        "operator": pred.operator,
                        "valueInt": int(pred.value)
                        if pred.value is not None and pred.integer else 0,
                        "valueDbl": float(pred.value)
                        if pred.value is not None else 0.0,
                        "categoricalValues": pred.values or None,
                        "otherBoundInt": int(pred.other_bound)
                        if pred.other_bound is not None and pred.integer else None,
                        "otherBoundDbl": float(pred.other_bound)
                        if pred.other_bound is not None else None,
                    })
                preds.append(bean)
            out.append({"predicates": preds, "population": p.population,
                        "infoContent": p.info_content, "stopped": p.stopped})
        return json.dumps({"decisionPaths": out}, indent=1)

    @classmethod
    def from_json(cls, text: str) -> "DecisionPathList":
        d = json.loads(text)
        paths = []
        for pd in d.get("decisionPaths", []):
            preds = [b["predicateStr"] for b in (pd.get("predicates") or [])]
            paths.append(DecisionPath(preds, pd.get("population", 0),
                                      pd.get("infoContent", 0.0),
                                      pd.get("stopped", False)))
        return cls(paths)

    @classmethod
    def from_file(cls, path: str) -> "DecisionPathList":
        with open(path) as fh:
            return cls.from_json(fh.read())


class DecisionPathStoppingStrategy:
    """tree/DecisionPathStoppingStrategy.java:43-71 (maxDepth implemented as
    intended — see module docstring)."""

    STOP_MAX_DEPTH = "maxDepth"
    STOP_MIN_POPULATION = "minPopulation"
    STOP_MIN_INFO_GAIN = "minInfoGain"

    def __init__(self, strategy: str, max_depth_limit: int = -1,
                 min_info_gain_limit: float = -1.0,
                 min_population_limit: int = -1):
        self.strategy = strategy
        self.max_depth_limit = max_depth_limit
        self.min_info_gain_limit = min_info_gain_limit
        self.min_population_limit = min_population_limit

    @classmethod
    def from_config(cls, config: JobConfig) -> "DecisionPathStoppingStrategy":
        strategy = config.get("path.stopping.strategy", cls.STOP_MIN_INFO_GAIN)
        max_depth = -1
        min_gain = -1.0
        min_pop = -1
        if strategy == cls.STOP_MAX_DEPTH:
            max_depth = config.must_int("max.depth.limit",
                                        "missing max depth limit for tree")
        elif strategy == cls.STOP_MIN_INFO_GAIN:
            min_gain = config.must_float("min.info.gain.limit",
                                         "missing min info gain limit")
        elif strategy == cls.STOP_MIN_POPULATION:
            min_pop = config.must_int("min.population.limit",
                                      "missing min population limit")
        else:
            raise ValueError(f"invalid stopping strategy {strategy}")
        return cls(strategy, max_depth, min_gain, min_pop)

    def should_stop(self, total_count: int, stat: float, parent_stat: float,
                    depth: int) -> bool:
        if self.strategy == self.STOP_MIN_POPULATION:
            return total_count < self.min_population_limit
        if self.strategy == self.STOP_MIN_INFO_GAIN:
            return (parent_stat - stat) < self.min_info_gain_limit
        if self.strategy == self.STOP_MAX_DEPTH:
            return depth >= self.max_depth_limit
        raise ValueError(f"invalid stopping strategy {self.strategy}")


# ---------------------------------------------------------------------------
# DecisionTreeBuilder
# ---------------------------------------------------------------------------

class DecisionTreeBuilder:
    """Level-synchronous tree/random-forest growth; one call = one reference
    job run = one tree level (tree/DecisionTreeBuilder.java)."""

    ATTR_SEL_ALL = "all"
    ATTR_SEL_NOT_USED_YET = "notUsedYet"
    ATTR_SEL_RANDOM_ALL = "randomAll"
    ATTR_SEL_RANDOM_NOT_USED_YET = "randomNotUsedYet"

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        self.schema = schema or FeatureSchema.from_file(
            config.must("feature.schema.file.path"))
        self.decision_file = config.must("decision.file.path")
        self.dec_path_delim = config.get("dec.path.delim", ";")
        self.algorithm = config.get("split.algorithm", ALG_GINI_INDEX)
        self.attr_select_strategy = config.get(
            "split.attribute.selection.strategy", self.ATTR_SEL_NOT_USED_YET)
        self.random_split_set_size = config.get_int("random.split.set.size", 3)
        self.rng = random.Random(config.get_int("seed", None))

    # -- attribute selection (DecisionTreeBuilder.java:327-343) -----------
    def _candidate_attrs(self, used: Sequence[int]) -> List[int]:
        ordinals = [f.ordinal for f in self.schema.feature_fields()]
        strategy = self.attr_select_strategy
        if strategy == self.ATTR_SEL_ALL:
            return ordinals
        if strategy == self.ATTR_SEL_NOT_USED_YET:
            return [o for o in ordinals if o not in set(used)]
        if strategy == self.ATTR_SEL_RANDOM_ALL:
            k = min(self.random_split_set_size, len(ordinals))
            return sorted(self.rng.sample(ordinals, k))
        if strategy == self.ATTR_SEL_RANDOM_NOT_USED_YET:
            remaining = [o for o in ordinals if o not in set(used)]
            k = min(self.random_split_set_size, len(remaining))
            return sorted(self.rng.sample(remaining, k))
        raise ValueError(
            f"invalid splitting attribute selection strategy {strategy}")

    # -- sub-sampling (DecisionTreeBuilder.java:164-223; random-forest hook)
    def _subsample(self, lines: List[str]) -> List[str]:
        strategy = self.config.get("sub.sampling.strategy", "withReplace")
        if strategy == "none":
            return lines
        if strategy == "withoutReplace":
            rate = self.config.must_int(
                "sub.sampling.rate",
                "samling rate should be provided for sampling without replacement")
            return [l for l in lines if self.rng.random() * 100 < rate]
        if strategy == "withReplace":
            # chunked bootstrap: the reference buffers batches and emits
            # |batch| uniform draws with replacement per batch
            size = self.config.get_int("sub.sampling.buffer.size", 10000)
            out: List[str] = []
            for start in range(0, len(lines), size):
                chunk = lines[start:start + size]
                out.extend(self.rng.choice(chunk) for _ in range(len(chunk)))
            return out
        raise ValueError(f"invalid sub sampling strategy {strategy}")

    def tree_available(self) -> bool:
        return (os.path.exists(self.decision_file)
                and os.path.getsize(self.decision_file) > 0)

    # rough per-record device bytes (pid + class + predicate booleans) for
    # pipeline.device.budget.bytes chunk sizing
    _BUDGET_ROW_BYTES = 128

    # -- one level ---------------------------------------------------------
    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        if not self.tree_available():
            return self._run_root(in_path, out_path, counters, mesh=mesh)
        chunk_rows = self.config.pipeline_chunk_rows(
            row_bytes=self._BUDGET_ROW_BYTES)
        if chunk_rows is not None:
            res = self._run_level_streamed(
                in_path, out_path, counters, mesh, chunk_rows,
                self.config.pipeline_prefetch_depth())
            if res is not None:
                return res
            counters = Counters()     # fallback re-runs from scratch
        return self._run_level(in_path, out_path, counters, mesh=mesh)

    def _enum_preds(self, all_attrs: Sequence[int]
                    ) -> Tuple[List[AttributePredicate], List[int]]:
        """Schema-only candidate predicate enumeration for a level pass
        (data-independent, so the streamed pass can fix its count extents
        before any record is read)."""
        preds: List[AttributePredicate] = []
        pred_attr: List[int] = []
        for attr in all_attrs:
            field = self.schema.field_by_ordinal(attr)
            for sp in enumerate_attr_splits(field, use_bucket_grid=False):
                for pred in segment_predicates(sp, field):
                    preds.append(pred)
                    pred_attr.append(attr)
        return preds, pred_attr

    def _level_cleanup(self, path_objs, active, passthrough, cand_attrs,
                       preds, pred_attr, counts, stopping
                       ) -> Tuple[DecisionPathList, Dict[int, int]]:
        """Reducer cleanup (generateTree, DecisionTreeBuilder.java:423-538):
        per parent, group predicate stats by attribute, min weighted stat —
        shared verbatim by the monolithic and streamed level passes."""
        new_dpl = DecisionPathList()
        selected_attr: Dict[int, int] = {}
        n_paths = len(path_objs)
        for pid in range(n_paths):
            parent = path_objs[pid]
            if parent is None or not active[pid]:
                if parent is not None and passthrough[pid]:
                    new_dpl.add(parent)
                continue
            pred_tot = counts[pid].sum(axis=1)            # [K]
            pred_stat = info_content(counts[pid], self.algorithm)
            best_attr = None
            min_info = 1000.0
            for attr in cand_attrs[pid]:
                sel = np.asarray([a == attr for a in pred_attr]) & (pred_tot > 0)
                tot = pred_tot[sel].sum()
                if tot == 0:
                    continue
                av = float((pred_stat[sel] * pred_tot[sel]).sum() / tot)
                if av < min_info:
                    min_info = av
                    best_attr = attr
            if best_attr is None:
                parent.stopped = True
                new_dpl.add(parent)
                continue
            selected_attr[pid] = best_attr
            parent_preds = [p for p in path_objs[pid].predicate_strs
                            if p != ROOT_PATH]
            parent_stat = path_objs[pid].info_content
            for k, pred in enumerate(preds):
                if pred_attr[k] != best_attr or pred_tot[k] == 0:
                    continue
                stat_k = float(pred_stat[k])
                # depth = the child path's own predicate count (the "$root"
                # sentinel never counts — DecisionPath.depth() parity)
                stop = stopping.should_stop(int(pred_tot[k]), stat_k,
                                            parent_stat,
                                            len(parent_preds) + 1)
                new_dpl.add(DecisionPath(
                    parent_preds + [pred.to_string()],
                    int(pred_tot[k]), stat_k, stop))
        return new_dpl, selected_attr

    def _run_root(self, in_path: str, out_path: str, counters: Counters,
                  mesh=None) -> Counters:
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        lines = self._subsample(list(read_lines(in_path)))
        records = [split_line(l, delim_regex) for l in lines]
        counters.set("Basic", "Records", len(records))

        class_field = self.schema.class_attr_field()
        class_vocab = _class_vocab(records, class_field)
        y = np.asarray([class_vocab[r[class_field.ordinal]] for r in records],
                       dtype=np.int32)
        counts = np.asarray(sharded_reduce(
            _class_count_local, y, mesh=mesh, static_args=(len(class_vocab),)))
        stat = float(info_content(counts, self.algorithm))

        dpl = DecisionPathList(
            [DecisionPath([ROOT_PATH], int(counts.sum()), stat, False)])
        atomic_write_text(self.decision_file, dpl.to_json(self.schema))
        write_output(out_path, (f"{ROOT_PATH}{delim}{l}" for l in lines))
        return counters

    def _run_level(self, in_path: str, out_path: str, counters: Counters,
                   mesh=None) -> Counters:
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        dpl = DecisionPathList.from_file(self.decision_file)
        stopping = DecisionPathStoppingStrategy.from_config(self.config)

        # split the path prefix off each record (see deviation note: ordinals
        # address the original fields)
        raw = list(read_lines(in_path))
        counters.set("Basic", "Records", len(raw))
        path_strs: List[str] = []
        records: List[List[str]] = []
        rests: List[str] = []
        for line in raw:
            pos = line.find(delim)
            path_strs.append(line[:pos])
            rest = line[pos + len(delim):]
            rests.append(rest)
            records.append(split_line(rest, delim_regex))

        # path vocabulary + per-path status
        path_vocab: Dict[str, int] = {}
        for ps in path_strs:
            path_vocab.setdefault(ps, len(path_vocab))
        n_paths = len(path_vocab)
        path_objs: List[Optional[DecisionPath]] = [None] * n_paths
        for ps, pid in path_vocab.items():
            path_objs[pid] = dpl.find_str(ps, self.dec_path_delim)

        path_id = np.asarray([path_vocab[ps] for ps in path_strs],
                             dtype=np.int32)
        active = np.asarray(
            [p is not None and not p.stopped for p in path_objs], dtype=bool)
        passthrough = np.asarray(
            [p is not None and p.stopped for p in path_objs], dtype=bool)
        record_active = active[path_id]

        # per-path candidate attributes -> union predicate list
        used_by_path: List[List[int]] = []
        for p in path_objs:
            used: List[int] = []
            if p is not None:
                for ps in p.predicate_strs:
                    if ps != ROOT_PATH:
                        used.append(int(ps.split()[0]))
            used_by_path.append(used)
        cand_attrs = [self._candidate_attrs(used_by_path[pid])
                      if active[pid] else []
                      for pid in range(n_paths)]
        all_attrs = sorted({a for attrs in cand_attrs for a in attrs})

        preds, pred_attr = self._enum_preds(all_attrs)
        if not preds:
            # nothing left to split on: mark all active paths stopped
            for p in path_objs:
                if p is not None:
                    p.stopped = True
            atomic_write_text(self.decision_file, DecisionPathList(
                [p for p in path_objs if p is not None]
            ).to_json(self.schema))
            write_output(out_path, (raw[i] for i in range(len(raw))
                                    if path_objs[path_id[i]] is not None))
            return counters

        col_by_attr = {attr: _column(records, self.schema.field_by_ordinal(attr))
                       for attr in all_attrs}
        bmat = predicate_matrix(preds, col_by_attr)
        allowed = np.zeros((n_paths, len(preds)), dtype=bool)
        for pid in range(n_paths):
            cset = set(cand_attrs[pid])
            allowed[pid] = np.asarray([a in cset for a in pred_attr])

        class_field = self.schema.class_attr_field()
        class_vocab = _class_vocab(records, class_field)
        n_class = len(class_vocab)
        y = np.asarray([class_vocab[r[class_field.ordinal]] for r in records],
                       dtype=np.int32)

        counts = np.asarray(sharded_reduce(
            _path_pred_class_count_local, path_id, y,
            bmat & record_active[:, None], mesh=mesh,
            static_args=(n_paths, len(preds), n_class)))
        counts = counts * allowed[:, :, None]

        new_dpl, selected_attr = self._level_cleanup(
            path_objs, active, passthrough, cand_attrs, preds, pred_attr,
            counts, stopping)

        atomic_write_text(self.decision_file,
                          new_dpl.to_json(self.schema))

        # output: every record once per satisfied predicate OF THE SELECTED
        # attribute, path extended; stopped paths pass through.  (The
        # reference's reducer passes through every candidate predicate's
        # records, DecisionTreeBuilder.java:608-612, but the next level drops
        # all non-selected paths at the dpl lookup — emitting them is pure
        # inflation, so we emit only lines the next level can consume.)
        out_lines: List[str] = []
        pred_strs = [p.to_string() for p in preds]
        sel_mask = np.zeros((n_paths, len(preds)), dtype=bool)
        for pid, attr in selected_attr.items():
            sel_mask[pid] = np.asarray([a == attr for a in pred_attr])
        for i in range(len(records)):
            pid = path_id[i]
            if passthrough[pid]:
                out_lines.append(raw[i])
                continue
            if not active[pid] or pid not in selected_attr:
                continue
            base = path_strs[i]
            if base == ROOT_PATH:
                base = ""
            for k in np.nonzero(bmat[i] & sel_mask[pid])[0]:
                prefix = (base + self.dec_path_delim if base else "") + pred_strs[k]
                out_lines.append(f"{prefix}{delim}{rests[i]}")
        counters.set("Stats", "output records", len(out_lines))
        write_output(out_path, out_lines)
        return counters

    def _run_level_streamed(self, in_path: str, out_path: str,
                            counters: Counters, mesh, chunk_rows: int,
                            depth: int) -> Optional[Counters]:
        """Out-of-core level pass: two streaming passes over row chunks.

        Pass 1 folds the C[path, predicate, class] histogram through
        ``core.pipeline`` (double-buffered, donated accumulator) while
        discovering the path/class vocabularies in input order; pass 2
        re-streams the input and emits the routed records chunk by chunk,
        so peak memory is O(chunk) regardless of input size.  The count
        extents are fixed BEFORE reading any data: active paths and their
        candidate attributes come from the decision file, predicates from
        the schema (``_enum_preds``).  Output — decision-file JSON and
        routed records — is bit-identical to ``_run_level``; cases whose
        parity cannot be guaranteed return None and the caller falls back:
        random attribute-selection strategies (the RNG draw order follows
        path DISCOVERY order, unknowable before reading the data) and
        class values first appearing after the first chunk beyond the
        declared cardinality + headroom."""
        from ..core import pipeline
        from ..core.binning import ChunkedEncodeUnsupported

        if self.attr_select_strategy in (self.ATTR_SEL_RANDOM_ALL,
                                         self.ATTR_SEL_RANDOM_NOT_USED_YET):
            return None
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        dpl = DecisionPathList.from_file(self.decision_file)
        stopping = DecisionPathStoppingStrategy.from_config(self.config)
        class_field = self.schema.class_attr_field()

        # static extents from the decision file + schema (data-free)
        active_dpl = [p for p in dpl.paths if not p.stopped]
        akey = {tuple(p.predicate_strs): i for i, p in enumerate(active_dpl)}
        cand_by_aid = []
        for p in active_dpl:
            used = [int(ps.split()[0]) for ps in p.predicate_strs
                    if ps != ROOT_PATH]
            cand_by_aid.append(self._candidate_attrs(used))
        sup_attrs = sorted({a for attrs in cand_by_aid for a in attrs})
        preds_sup, pred_attr_sup = self._enum_preds(sup_attrs)
        K = len(preds_sup)
        a_cap = max(len(active_dpl), 1)

        # streaming discovery state (chunks are consumed sequentially, so
        # discovery order == the monolithic pass's record order)
        path_vocab: Dict[str, int] = {}
        aid_of_ps: Dict[str, int] = {}
        class_vocab = Vocab(class_field.cardinality or ())
        cap = [None]
        n_records = [0]

        def parse_chunk(lines):
            path_c: List[str] = []
            rests: List[str] = []
            recs: List[List[str]] = []
            for line in lines:
                pos = line.find(delim)
                path_c.append(line[:pos])
                rest = line[pos + len(delim):]
                rests.append(rest)
                recs.append(split_line(rest, delim_regex))
            return path_c, rests, recs

        def encode_chunk(lines):
            path_c, _, recs = parse_chunk(lines)
            apid = np.empty(len(lines), dtype=np.int32)
            for i, ps in enumerate(path_c):
                aid = aid_of_ps.get(ps)
                if aid is None:
                    path_vocab.setdefault(ps, len(path_vocab))
                    aid = akey.get(tuple(ps.split(self.dec_path_delim)), -1)
                    aid_of_ps[ps] = aid
                apid[i] = aid
            y = np.asarray([class_vocab.add(r[class_field.ordinal])
                            for r in recs], dtype=np.int32)
            if cap[0] is not None and len(class_vocab) > cap[0]:
                raise ChunkedEncodeUnsupported("late class value")
            col_by_attr = {a: _column(recs, self.schema.field_by_ordinal(a))
                           for a in sup_attrs}
            return apid, y, predicate_matrix(preds_sup, col_by_attr)

        def chunks():
            for lines in pipeline.iter_line_chunks(in_path, chunk_rows):
                n_records[0] += len(lines)
                yield encode_chunk(lines)

        try:
            first, stream = pipeline.peek(chunks())
            cap[0] = n_class_cap = max(len(class_vocab), 1) + 2
            if K:
                counts_sup = pipeline.streaming_fold(
                    stream, _path_pred_class_count_local,
                    static_args=(a_cap, K, n_class_cap), mesh=mesh,
                    prefetch_depth=depth, capacity=chunk_rows)
            else:
                for _ in stream:      # discovery only; nothing to count
                    pass
                counts_sup = None
        except ChunkedEncodeUnsupported:
            return None
        counters.set("Basic", "Records", n_records[0])

        # reconstruct the monolithic pass's discovery-order state
        n_paths = len(path_vocab)
        path_objs: List[Optional[DecisionPath]] = [None] * n_paths
        for ps, pid in path_vocab.items():
            path_objs[pid] = dpl.find_str(ps, self.dec_path_delim)
        active = np.asarray(
            [p is not None and not p.stopped for p in path_objs], dtype=bool)
        passthrough = np.asarray(
            [p is not None and p.stopped for p in path_objs], dtype=bool)
        used_by_path = []
        for p in path_objs:
            used = []
            if p is not None:
                for ps in p.predicate_strs:
                    if ps != ROOT_PATH:
                        used.append(int(ps.split()[0]))
            used_by_path.append(used)
        cand_attrs = [self._candidate_attrs(used_by_path[pid])
                      if active[pid] else [] for pid in range(n_paths)]
        all_attrs = sorted({a for attrs in cand_attrs for a in attrs})
        # the predicate list the monolithic pass would have built (the
        # superset pass counted extra attributes of non-appearing paths;
        # selecting the appearing-attr columns restores exact parity,
        # including the all-paths-exhausted early branch below)
        attr_set = set(all_attrs)
        sel_cols = [k for k in range(K) if pred_attr_sup[k] in attr_set]
        preds = [preds_sup[k] for k in sel_cols]
        pred_attr = [pred_attr_sup[k] for k in sel_cols]

        if not preds:
            for p in path_objs:
                if p is not None:
                    p.stopped = True
            atomic_write_text(self.decision_file, DecisionPathList(
                [p for p in path_objs if p is not None]
            ).to_json(self.schema))
            with OutputWriter(out_path) as w:
                for lines in pipeline.iter_line_chunks(in_path, chunk_rows):
                    path_c, _, _ = parse_chunk(lines)
                    for i, line in enumerate(lines):
                        if path_objs[path_vocab[path_c[i]]] is not None:
                            w.write(line)
            return counters

        n_class = len(class_vocab)
        counts = np.zeros((n_paths, len(preds), n_class), dtype=np.int32)
        if counts_sup is not None:
            for ps, pid in path_vocab.items():
                aid = aid_of_ps[ps]
                if aid >= 0 and active[pid]:
                    counts[pid] = counts_sup[aid][sel_cols][:, :n_class]
        allowed = np.zeros((n_paths, len(preds)), dtype=bool)
        for pid in range(n_paths):
            cset = set(cand_attrs[pid])
            allowed[pid] = np.asarray([a in cset for a in pred_attr])
        counts = counts * allowed[:, :, None]

        new_dpl, selected_attr = self._level_cleanup(
            path_objs, active, passthrough, cand_attrs, preds, pred_attr,
            counts, stopping)
        atomic_write_text(self.decision_file,
                          new_dpl.to_json(self.schema))

        # pass 2: re-stream the input and emit routed records per chunk.
        # Only predicates of SELECTED attributes are ever consulted here
        # (sel_mask), so the per-chunk evaluation is restricted to them —
        # the emission order over the reduced list matches the monolithic
        # full-list scan because both ascend in preds order.
        sel_attr_set = set(selected_attr.values())
        emit_cols = [k for k in range(len(preds))
                     if pred_attr[k] in sel_attr_set]
        emit_preds = [preds[k] for k in emit_cols]
        emit_strs = [preds[k].to_string() for k in emit_cols]
        sel_mask = np.zeros((n_paths, len(emit_cols)), dtype=bool)
        for pid, attr in selected_attr.items():
            sel_mask[pid] = np.asarray([pred_attr[k] == attr
                                        for k in emit_cols])
        n_out = 0
        with OutputWriter(out_path) as w:
            for lines in pipeline.iter_line_chunks(in_path, chunk_rows):
                path_c, rests, recs = parse_chunk(lines)
                col_by_attr = {
                    a: _column(recs, self.schema.field_by_ordinal(a))
                    for a in sorted(sel_attr_set)}
                bmat = predicate_matrix(emit_preds, col_by_attr) \
                    if emit_cols else np.zeros((len(lines), 0), bool)
                for i, line in enumerate(lines):
                    pid = path_vocab[path_c[i]]
                    if passthrough[pid]:
                        w.write(line)
                        n_out += 1
                        continue
                    if not active[pid] or pid not in selected_attr:
                        continue
                    base = path_c[i]
                    if base == ROOT_PATH:
                        base = ""
                    for k in np.nonzero(bmat[i] & sel_mask[pid])[0]:
                        prefix = ((base + self.dec_path_delim if base else "")
                                  + emit_strs[k])
                        w.write(f"{prefix}{delim}{rests[i]}")
                        n_out += 1
        counters.set("Stats", "output records", n_out)
        return counters

    # -- host-side multi-level loop (TPU-native convenience; the reference
    # re-runs the job manually per level, SURVEY §3.3 outer loop) ----------
    def run_loop(self, in_path: str, work_dir: str, max_levels: int = 10,
                 mesh=None) -> DecisionPathList:
        os.makedirs(work_dir, exist_ok=True)
        cur = in_path
        for level in range(max_levels):
            out = os.path.join(work_dir, f"level_{level}")
            self.run(cur, out, mesh=mesh)
            cur = out
            dpl = DecisionPathList.from_file(self.decision_file)
            if level > 0 and dpl.all_stopped():
                break
        return DecisionPathList.from_file(self.decision_file)


# ---------------------------------------------------------------------------
# DataPartitioner
# ---------------------------------------------------------------------------

class DataPartitioner:
    """Physically partitions records by the best candidate split
    (tree/DataPartitioner.java).  Candidate-split lines are ';'-delimited
    ``attr;splitKey;stat`` (see split.py module docstring on the reference's
    delimiter inconsistency); selection is 'best' (max stat) or
    'randomFromTop' (DataPartitioner.java:160-186); output goes to
    ``<node>/split=<idx>/segment=<i>/data/partition.txt``
    (DataPartitioner.java:115-131)."""

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        self.schema = schema or FeatureSchema.from_file(
            config.must("feature.schema.file.path"))
        self.rng = random.Random(config.get_int("seed", None))

    def node_path(self) -> str:
        base = self.config.must("project.base.path", "base path not defined")
        split_path = self.config.get("split.path")
        node = os.path.join(base, "split=root", "data")
        if split_path:
            node = os.path.join(node, split_path)
        return node

    def _find_best_split(self, candidates_path: str) -> Tuple[int, Split, int]:
        lines = list(read_lines(candidates_path))
        parsed = []
        for i, line in enumerate(lines):
            items = line.split(";")
            parsed.append((float(items[2]), i, int(items[0]), items[1]))
        parsed.sort(key=lambda t: -t[0])
        strategy = self.config.get("split.selection.strategy", "best")
        idx = 0
        if strategy == "randomFromTop":
            n_top = self.config.get_int("num.top.splits", 5)
            idx = int(self.rng.random() * min(n_top, len(parsed)))
        _, orig_index, attr, key = parsed[idx]
        field = self.schema.field_by_ordinal(attr)
        return attr, Split.from_key(attr, key, field), orig_index

    @traced_run
    def run(self, in_path: Optional[str] = None,
            out_path: Optional[str] = None, mesh=None) -> Counters:
        counters = Counters()
        delim_regex = self.config.field_delim_regex()
        # the reference derives both paths strictly from config
        # (DataPartitioner.java:135-149); positional args only apply when no
        # base path is configured (so the generic CLI arg shape still works)
        node = self.node_path() if self.config.get("project.base.path") \
            else in_path
        candidates = (self.config.get("candidate.splits.path")
                      or os.path.join(os.path.dirname(node.rstrip("/")),
                                      "splits", "part-r-00000"))
        attr, split, index = self._find_best_split(candidates)

        out_base = (os.path.join(node, f"split={index}")
                    if self.config.get("project.base.path") else out_path)
        lines = list(read_lines(node))
        records = [split_line(l, delim_regex) for l in lines]
        field = self.schema.field_by_ordinal(attr)
        seg = split.segment_index(_column(records, field))
        if (seg < 0).any():
            bad = records[int(np.nonzero(seg < 0)[0][0])][field.ordinal]
            raise ValueError(f"split segment not found for {bad}")

        for si in range(split.segment_count):
            seg_dir = os.path.join(out_base, f"segment={si}", "data")
            os.makedirs(seg_dir, exist_ok=True)
            atomic_write_text(
                os.path.join(seg_dir, "partition.txt"),
                "".join(lines[i] + "\n" for i in np.nonzero(seg == si)[0]))
            counters.set("Partition", f"segment {si}",
                         int((seg == si).sum()))
        return counters

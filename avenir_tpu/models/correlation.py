"""Correlation jobs: Cramer index, heterogeneity reduction, numerical Pearson.

Reference surface:
- ``explore.CramerCorrelation`` / ``explore.CategoricalCorrelation`` —
  contingency matrices per (source, dest) categorical attribute pair
  (CramerCorrelation.java:105-182), reduced to the Cramer index
  (util/ContingencyMatrix.java:86-123: pearson = sum t^2/(rowSum*colSum) - 1,
  cramer = pearson/(minDim-1)); output ``srcName,dstName,value``.
- ``explore.HeterogeneityReductionCorrelation`` — same matrices, reduced to
  the concentration (gini) or uncertainty coefficient
  (ContingencyMatrix.java:141-185), selected by ``heterogeneity.algorithm``.
- ``explore.NumericalCorrelation`` — Pearson over configured ``attr.pairs``
  using external mean/stddev (NumericalCorrelation.java:115-218); output
  ``ord1,ord2,corr``.

TPU re-design: all contingency matrices for all pairs come from one
``count_table`` scatter over (pair, srcIdx, dstIdx); the coefficient math is
tiny host NumPy mirroring the reference formulas (including its
guard of clamping zero row/col sums to 1).  Numerical cross-moments are one
masked einsum over the centered value matrix.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.config import JobConfig
from ..core.multiscan import FoldSpec as MultiScanFoldSpec
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters
from ..core.schema import FeatureSchema
from ..ops.counting import count_table, sharded_reduce


# ---------------------------------------------------------------------------
# ContingencyMatrix math (util/ContingencyMatrix.java)
# ---------------------------------------------------------------------------

def cramer_index(table: np.ndarray) -> float:
    t = np.asarray(table, dtype=np.float64)
    row = t.sum(axis=1)
    col = t.sum(axis=0)
    row[row == 0] = 1
    col[col == 0] = 1
    pearson = float((t * t / (row[:, None] * col[None, :])).sum()) - 1.0
    return pearson / (min(t.shape) - 1)


def concentration_coeff(table: np.ndarray) -> float:
    t = np.asarray(table, dtype=np.float64)
    total = t.sum()
    row = t.sum(axis=1); col = t.sum(axis=0)
    row[row == 0] = 1; col[col == 0] = 1
    rown = row / total; coln = col / total
    e = t / total
    sum_one = float(((e * e).sum(axis=1) / rown).sum())
    sum_two = float((coln * coln).sum())
    return (sum_one - sum_two) / (1.0 - sum_two)


def uncertainty_coeff(table: np.ndarray) -> float:
    t = np.asarray(table, dtype=np.float64)
    total = t.sum()
    row = t.sum(axis=1); col = t.sum(axis=0)
    row[row == 0] = 1; col[col == 0] = 1
    rown = row / total; coln = col / total
    e = t / total
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = e * np.log10(e * coln[None, :] / rown[:, None])
    # DELIBERATE deviation: the reference's dense int[][] table hits
    # 0 * log10(0) = NaN on any never-co-occurring value pair and outputs
    # NaN (ContingencyMatrix.java:165-185); we skip zero cells (the standard
    # convention, and what its own MI job does for unobserved cells) so the
    # coefficient stays finite
    sum_one = float(np.nansum(np.where(e > 0, terms, 0.0)))
    sum_two = float((coln * np.log10(coln)).sum())
    return sum_one / sum_two


def _cat_corr_local(src, dst, mask, sizes):
    n, P = src.shape
    p_idx = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :], (n, P))
    m = mask[:, None]
    return count_table(sizes, (p_idx, src, dst), mask=m)


def _encode_pairs_from_cols(cols, n, pairs, card):
    """(src_idx, dst_idx) int32 [n, n_pairs] cardinality indices from
    per-ordinal value columns (str or bytes arrays) — one ``np.unique``
    + LUT per distinct ordinal.  An attribute value outside the declared
    cardinality raises KeyError exactly like a per-record lookup."""
    idx = {}
    for o, col in cols.items():
        uniq, inv = np.unique(col, return_inverse=True)
        lut = np.asarray(
            [card[o][u.decode() if isinstance(u, bytes) else str(u)]
             for u in uniq.tolist()], dtype=np.int32)
        idx[o] = lut[inv.reshape(-1)]
    if not pairs:
        return (np.zeros((n, 0), np.int32), np.zeros((n, 0), np.int32))
    src_idx = np.stack([idx[s] for s, _ in pairs], axis=1)
    dst_idx = np.stack([idx[d] for _, d in pairs], axis=1)
    return src_idx, dst_idx


def _encode_pair_columns(records, pairs, card):
    """``_encode_pairs_from_cols`` over parsed records (field matrix or
    list of field lists)."""
    ords = sorted({o for p in pairs for o in p})
    if isinstance(records, np.ndarray) and records.ndim == 2:
        cols = {o: records[:, o] for o in ords}
        n = records.shape[0]
    else:
        cols = {o: np.asarray([r[o] for r in records], dtype=str)
                for o in ords}
        n = len(records)
    return _encode_pairs_from_cols(cols, n, pairs, card)


class CategoricalCorrelation:
    """Shared contingency-matrix job; subclasses choose the statistic."""

    stat_name = "cramer"

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        self.schema = schema or FeatureSchema.from_file(
            config.must("feature.schema.file.path"))

    def statistic(self, table: np.ndarray) -> float:
        return cramer_index(table)

    def _pair_setup(self):
        """(pairs, fields, card, sizes) from the configured source/dest
        attribute lists — shared by ``run`` and the multi-scan FoldSpec."""
        cfg = self.config
        src_attrs = [int(v) for v in cfg.must_list("source.attributes")]
        dst_attrs = [int(v) for v in cfg.must_list("dest.attributes")]
        pairs: List[Tuple[int, int]] = [
            (s, d) for s in src_attrs for d in dst_attrs if s != d]
        fields = {o: self.schema.field_by_ordinal(o)
                  for o in set(src_attrs) | set(dst_attrs)}
        card = {o: {v: i for i, v in enumerate(fields[o].cardinality)}
                for o in fields}
        max_card = max(len(c) for c in card.values())
        sizes = (len(pairs), max_card, max_card)
        return pairs, fields, card, sizes

    def _emit_lines(self, counts, pairs, fields, card, delim) -> List[str]:
        out = []
        for p, (s, d) in enumerate(pairs):
            tbl = counts[p, :len(card[s]), :len(card[d])]
            out.append(f"{fields[s].name}{delim}{fields[d].name}{delim}"
                       f"{self.statistic(tbl)}")
        return out

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim = cfg.field_delim_out()
        pairs, fields, card, sizes = self._pair_setup()

        records = [split_line(l, cfg.field_delim_regex())
                   for l in read_lines(in_path)]
        src_idx, dst_idx = _encode_pair_columns(records, pairs, card)

        counts = np.asarray(sharded_reduce(
            _cat_corr_local, src_idx, dst_idx, mesh=mesh,
            static_args=(sizes,)))

        write_output(out_path,
                     self._emit_lines(counts, pairs, fields, card, delim))
        counters.set("Correlation", "Pairs", len(pairs))
        return counters

    def fold_spec(self, out_path: str):
        """Export this job's shared-scan ``core.multiscan.FoldSpec``."""
        return _CatCorrFoldSpec(self, out_path)

    # -- artifact import (core.dag consumers) ------------------------------
    @staticmethod
    def parse_output(lines, delim: str = ","
                     ) -> List[Tuple[str, str, float]]:
        """``(src_name, dst_name, statistic)`` triples out of this job
        family's output lines — the artifact-import hook a DAG stage
        uses to consume correlation results in memory (e.g. to audit a
        feature selection against plan/churn correlation).  Malformed
        lines raise naming the line (a truncated artifact must not
        silently yield a shorter result)."""
        out = []
        for line in lines:
            parts = line.split(delim)
            try:
                if len(parts) != 3:
                    raise ValueError
                out.append((parts[0], parts[1], float(parts[2])))
            except ValueError:
                raise ValueError(
                    f"malformed correlation output line (want "
                    f"src{delim}dst{delim}statistic): {line!r}") from None
        return out


class CramerCorrelation(CategoricalCorrelation):
    pass


class HeterogeneityReductionCorrelation(CategoricalCorrelation):
    """gini -> concentration coefficient, else uncertainty coefficient
    (HeterogeneityReductionCorrelation.java:71-90)."""

    def statistic(self, table: np.ndarray) -> float:
        alg = self.config.get("heterogeneity.algorithm", "gini")
        if alg == "gini":
            return concentration_coeff(table)
        return uncertainty_coeff(table)


class _CatCorrFoldSpec(MultiScanFoldSpec):
    """Shared-scan FoldSpec for the contingency-matrix correlation
    family (Cramer/heterogeneity — the statistic stays the driver's):
    per chunk the configured attribute pairs encode to cardinality
    indices and fold one ``count_table`` scatter; finalize reduces each
    pair's matrix with the job's statistic.  An attribute value outside
    the declared cardinality withdraws the spec (the standalone re-run
    then raises the same KeyError a standalone workflow would).

  Split invariance (fold(A ++ B) == merge_carries(fold(A),
    fold(B)), any chunk boundaries/order) is property-tested at
    mesh=1 and 8-way by the fold-algebra verifier
    (core.algebra, tests/test_algebra.py) — the ROADMAP-1
    multi-host psum contract this spec must keep.
    """

    def __init__(self, job: CategoricalCorrelation, out_path: str):
        self.job = job
        self.out_path = out_path
        self.name = type(job).__name__
        self.local_fn = _cat_corr_local
        self.delim = job.config.field_delim_out()
        self.pairs, self.fields, self.card, sizes = job._pair_setup()
        self.static_args = (sizes,)

    def encode(self, ctx):
        from ..core.binning import ChunkedEncodeUnsupported

        ords = tuple(sorted({o for p in self.pairs for o in p}))
        cols = ctx.columns(ords)
        try:
            if cols is not None:
                n = len(next(iter(cols.values()))) if cols else 0
                if n == 0:
                    return None
                return _encode_pairs_from_cols(cols, n, self.pairs,
                                               self.card)
            chunk = ctx.fields()
            n = (chunk.shape[0] if isinstance(chunk, np.ndarray)
                 else len(chunk))
            if n == 0:
                return None
            return _encode_pair_columns(chunk, self.pairs, self.card)
        except KeyError as exc:
            raise ChunkedEncodeUnsupported(
                f"undeclared attribute value {exc}")

    def finalize(self, carry) -> Counters:
        counters = Counters()
        write_output(self.out_path, self.job._emit_lines(
            np.asarray(carry), self.pairs, self.fields, self.card,
            self.delim))
        counters.set("Correlation", "Pairs", len(self.pairs))
        return counters


class NumericalCorrelation:
    """Pearson over configured ordinal pairs; config prefix ``nco``.

    The reference pulls means/stddevs from a chombo stats file
    (``stats.file.path``); when absent we compute them from the data in the
    same pass (exact host moments, as in models.bayesian).
    """

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("nco") if not config.prefix else config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim = cfg.field_delim_out()
        # "0:1,2:3" style pair list
        pair_spec = cfg.must("attr.pairs")
        pairs = []
        for item in pair_spec.replace(";", ",").split(","):
            a, b = item.split(":")
            pairs.append((int(a), int(b)))

        records = [split_line(l, cfg.field_delim_regex())
                   for l in read_lines(in_path)]
        ords = sorted({o for p in pairs for o in p})
        vals = np.asarray([[float(r[o]) for o in ords] for r in records])
        col = {o: i for i, o in enumerate(ords)}

        stats_path = cfg.get("stats.file.path")
        if stats_path:
            mgr = NumericalAttrStatsManager(stats_path, delim)
            mean = {o: mgr.mean(o) for o in ords}
            std = {o: mgr.std_dev(o) for o in ords}
        else:
            mean = {o: float(vals[:, col[o]].mean()) for o in ords}
            std = {o: float(vals[:, col[o]].std()) for o in ords}

        out = []
        for a, b in pairs:
            ca = vals[:, col[a]] - mean[a]
            cb = vals[:, col[b]] - mean[b]
            corr = float((ca * cb).mean()) / (std[a] * std[b])
            out.append(f"{a}{delim}{b}{delim}{corr}")
        write_output(out_path, out)
        counters.set("Correlation", "Pairs", len(pairs))
        return counters


class NumericalAttrStatsManager:
    """Reader for the stats file written by models.discriminant.
    NumericalAttrStats (chombo ``NumericalAttrStatsManager`` equivalent)."""

    def __init__(self, path: str, delim: str = ","):
        self.stats = {}
        for line in read_lines(path):
            items = line.split(delim)
            # attr, condVal, sum, sumSq, count, mean, variance, stdDev
            self.stats[(int(items[0]), items[1])] = [float(v) for v in items[2:]]

    def _row(self, attr: int, cond: str = "0"):
        return self.stats[(attr, cond)]

    def mean(self, attr: int, cond: str = "0") -> float:
        return self._row(attr, cond)[3]

    def variance(self, attr: int, cond: str = "0") -> float:
        return self._row(attr, cond)[4]

    def std_dev(self, attr: int, cond: str = "0") -> float:
        return self._row(attr, cond)[5]

    def count(self, attr: int, cond: str = "0") -> int:
        return int(self._row(attr, cond)[2])

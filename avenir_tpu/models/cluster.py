"""Greedy graph clustering (cluster/AgglomerativeGraphical.java,
cluster/EdgeWeightedCluster.java) plus the entity-distance random-access
store (util/EntityDistanceMapFileAccessor.java equivalent).

The reference keeps pairwise distances in a Hadoop ``MapFile`` for O(log n)
row lookups (EntityDistanceMapFileAccessor.java:70-127); here the store is a
host dict built from either row-format lines (``entity, other1, d1, other2,
d2, ...``) or the SameTypeSimilarity pair lines produced in-framework — the
distance matrix itself comes off the sharded MXU kernel (ops.distance), so
the O(n^2) work that sifarish did upstream stays on device.

Greedy membership (AgglomerativeGraphical.GraphMapper.map,
AgglomerativeGraphical.java:96-117): for each entity in arrival order, try
every existing cluster, computing the average edge weight if the entity
joined (EdgeWeightedCluster.tryMembership, EdgeWeightedCluster.java:47-81:
``(avgWeight * numEdges + weightSum) / (numEdges + clusterSize)``, with
distances flipped to weights as ``distScale - d`` when the store holds
distances); join the best cluster if above ``min.av.edge.weight.threshold``,
else found a new cluster.

Parity notes (reference defects fixed as intended):
- the reference founds new clusters EMPTY (``clusters.add(new
  EdgeWeightedCluster())``, AgglomerativeGraphical.java:113 — the entity is
  dropped); we seed the new cluster with the entity.
- EntityDistanceMapFileAccessor.read splits the row by the delimiter and
  then splits each single token by the same delimiter again
  (EntityDistanceMapFileAccessor.java:115-121), which can never produce the
  (entity, distance) pairs it indexes; we parse alternating tokens.
- initReader assigns its MapFile.Reader to a local, leaving the field null
  (EntityDistanceMapFileAccessor.java:106-110); nothing to reproduce.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters


class EntityDistanceStore:
    """entity -> {other: distance} random-access map."""

    def __init__(self):
        self.rows: Dict[str, Dict[str, float]] = {}

    @classmethod
    def from_row_file(cls, path: str, delim_regex: str = ",") -> "EntityDistanceStore":
        """Row format: ``entity, other1, d1, other2, d2, ...`` (the MapFile
        value layout the reference writes,
        EntityDistanceMapFileAccessor.java:70-93)."""
        store = cls()
        for line in read_lines(path):
            items = split_line(line, delim_regex)
            row = store.rows.setdefault(items[0], {})
            for i in range(1, len(items) - 1, 2):
                row[items[i]] = float(items[i + 1])
        return store

    @classmethod
    def from_pair_file(cls, path: str, delim_regex: str = ",") -> "EntityDistanceStore":
        """Pair format: ``id1, id2, distance, ...`` (SameTypeSimilarity
        output); symmetrized."""
        store = cls()
        for line in read_lines(path):
            items = split_line(line, delim_regex)
            d = float(items[2])
            store.rows.setdefault(items[0], {})[items[1]] = d
            store.rows.setdefault(items[1], {})[items[0]] = d
        return store

    def read(self, entity: str) -> Dict[str, float]:
        return self.rows.get(entity, {})


class EdgeWeightedCluster:
    """cluster/EdgeWeightedCluster.java semantics."""

    def __init__(self, cluster_id: str, dist_scale: Optional[float] = None):
        self.id = cluster_id
        self.members: List[str] = []
        self.av_edge_weight = 0.0
        self.dist_scale = dist_scale   # set -> store holds distances

    def add(self, entity: str, av_edge_weight: float) -> None:
        self.members.append(entity)
        self.av_edge_weight = av_edge_weight

    def try_membership(self, entity: str, store: EntityDistanceStore) -> float:
        weight_sum = 0.0
        for member in self.members:
            d = store.read(member).get(entity)
            if d is not None:
                weight_sum += (self.dist_scale - d
                               if self.dist_scale is not None else d)
        n = len(self.members)
        num_edges = (n * (n - 1)) // 2
        return (self.av_edge_weight * num_edges + weight_sum) / (num_edges + n)

    def to_line(self, delim: str = ",") -> str:
        return delim.join([self.id] + self.members
                          + [str(self.av_edge_weight)])


class AgglomerativeGraphical:
    """Map-only greedy clustering job (cluster/AgglomerativeGraphical.java).

    Config: ``min.av.edge.weight.threshold`` (required),
    ``distance.file.path`` (row- or pair-format distance store; pair format
    auto-detected when ``distance.file.format=pair``), ``distance.scale``
    (set when the store holds distances rather than similarities)."""

    def __init__(self, config: JobConfig):
        self.config = config
        self.threshold = config.must_float(
            "min.av.edge.weight.threshold", "missing min average edge weight")
        self.rng = random.Random(config.get_int("seed", None))

    def _load_store(self) -> EntityDistanceStore:
        path = self.config.must("distance.file.path",
                                "missing distance map file directory")
        fmt = self.config.get("distance.file.format", "row")
        regex = self.config.field_delim_regex()
        if fmt == "pair":
            return EntityDistanceStore.from_pair_file(path, regex)
        return EntityDistanceStore.from_row_file(path, regex)

    def _new_id(self) -> str:
        return "%032x" % self.rng.getrandbits(128)

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        store = self._load_store()
        dist_scale = self.config.get_float("distance.scale", None)

        clusters: List[EdgeWeightedCluster] = []
        for line in read_lines(in_path):
            entity = split_line(line, delim_regex)[0]
            best = None
            best_weight = -float("inf")
            for cluster in clusters:
                w = cluster.try_membership(entity, store)
                if w > best_weight:
                    best_weight = w
                    best = cluster
            if best is not None and best_weight > self.threshold:
                best.add(entity, best_weight)
            else:
                fresh = EdgeWeightedCluster(self._new_id(), dist_scale)
                fresh.add(entity, 0.0)
                clusters.append(fresh)

        counters.set("Cluster", "clusters", len(clusters))
        write_output(out_path, (c.to_line(delim) for c in clusters))
        return counters

"""Split enumeration, predicates, and split-quality statistics for the tree
family (reference: tree/SplitManager.java, util/AttributeSplitHandler.java,
util/AttributeSplitStat.java, util/InfoContentStat.java).

Everything here is host-side model logic: candidate-split lists are tiny
(bounded by maxSplit <= 3 and the scan interval), so enumeration stays in
Python exactly as the reference keeps it in task-local JVM memory
(SURVEY §7.3 hard part: the combinatorial categorical set-partition
enumeration stays host-side).  The per-record/per-predicate evaluation that
the reference does in mapper hot loops (DecisionTreeBuilder.java:275-320) is
vectorized in ``predicate_matrix`` / ``segment_index`` over whole columns;
the (path, predicate, class) counting those feed runs on device
(models/tree.py).

Reference-parity notes (deliberate reproductions / documented deviations):
- SplitManager.createIntAttrPredicates (SplitManager.java:551-578) gives the
  LAST split point an *unbounded* ``le`` predicate (the ``i == len-1`` branch
  skips the lower bound), so multi-point splits have overlapping predicates.
  ``segment_predicates`` reproduces this faithfully — DecisionTreeBuilder
  counts per predicate, so the overlap is observable in its output.
- DoublePredicate's two-bound constructor never assigns ``otherBound``
  (SplitManager.java:749-752), so double predicates evaluate AND print
  unbounded.  Reproduced.
- The reference joins integer split keys with ";" when emitting
  (AttributeSplitHandler.java:44) but parses them with ":"
  (AttributeSplitHandler.java:160, DataPartitioner's getSegmentCount).  We
  standardize on ":" — the only self-consistent choice — and note it here.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.schema import FeatureField, FeatureSchema

OPERATOR_LE = "le"
OPERATOR_GT = "gt"
OPERATOR_GE = "ge"
OPERATOR_LT = "lt"
OPERATOR_IN = "in"

ALG_ENTROPY = "entropy"
ALG_GINI_INDEX = "giniIndex"
ALG_HELLINGER_DIST = "hellingerDistance"
ALG_CLASS_CONF = "classConfidenceRatio"

SPLIT_ELEMENT_SEPARATOR = ":"


# ---------------------------------------------------------------------------
# split-point / set-partition enumeration
# ---------------------------------------------------------------------------

def point_partitions(min_val: float, max_val: float, interval: float,
                     max_split: int, integer: bool) -> List[Tuple]:
    """All ordered split-point tuples within ``max_split`` segments, scanning
    by ``interval`` (SplitManager.createIntPartitions /
    createDoublePartitions, SplitManager.java:230-268,295-333).

    The recursion only extends the LAST segment, producing every ascending
    tuple of 1..max_split-1 points on the scan grid.  For int fields the
    reference's ``int`` loop variable truncates after each ``+= interval``;
    replicated via ``int()`` per step.
    """
    num_splits = int((max_val - min_val) / interval)
    if num_splits == 0:
        interval = (max_val - min_val) / 2
    out: List[Tuple] = []

    def step(cur: float) -> float:
        nxt = cur + interval
        return int(nxt) if integer else nxt

    def first() -> float:
        v = min_val + interval
        return int(v) if integer else v

    def rec(splits: Tuple) -> None:
        if len(splits) < max_split - 1:
            s = step(splits[-1])
            while s < max_val:
                ns = splits + (s,)
                out.append(ns)
                rec(ns)
                s = step(s)

    s = first()
    while s < max_val:
        ns = (s,)
        out.append(ns)
        rec(ns)
        s = step(s)
    return out


def bucket_point_partitions(field: FeatureField, max_split: int) -> List[Tuple]:
    """ClassPartitionGenerator's variant: integer grid stepping by
    ``bucketWidth`` from ``(int)(min+0.01)`` to ``(int)(max+0.01)``
    (ClassPartitionGenerator.java:279-311)."""
    min_v = int(field.min + 0.01)
    max_v = int(field.max + 0.01)
    width = int(field.bucketWidth)
    out: List[Tuple] = []

    def rec(splits: Tuple) -> None:
        if len(splits) < max_split - 1:
            for s in range(splits[-1] + width, max_v, width):
                ns = splits + (s,)
                out.append(ns)
                rec(ns)

    for s in range(min_v + width, max_v, width):
        ns = (s,)
        out.append(ns)
        rec(ns)
    return out


def categorical_partitions(cardinality: Sequence[str],
                           num_groups: int) -> List[List[List[str]]]:
    """All partitions of ``cardinality`` into exactly ``num_groups`` ordered
    groups, in the reference's construction order
    (ClassPartitionGenerator.createCatPartitions /
    SplitManager.createCategoricalPartitions, SplitManager.java:339-486):
    seed with the first ``num_groups`` elements one-per-group (plus "partial"
    prefixes one group short), then each further element either joins each
    group of a full split or forms the new last group of a partial split."""
    cardinality = list(cardinality)
    if num_groups < 2 or num_groups > len(cardinality):
        return []
    splits: List[List[List[str]]] = []
    _cat_partitions(splits, cardinality, 0, num_groups)
    return splits


def _cat_partitions(splits: List[List[List[str]]], cardinality: List[str],
                    idx: int, num_groups: int) -> None:
    if idx == 0:
        splits.append([[cardinality[i]] for i in range(num_groups)])
        splits.extend(_partial_split(cardinality, num_groups - 1, num_groups))
        _cat_partitions(splits, cardinality, num_groups, num_groups)
    elif idx < len(cardinality):
        new_splits: List[List[List[str]]] = []
        elem = cardinality[idx]
        for sp in splits:
            if len(sp) == num_groups:
                for i in range(num_groups):
                    new_splits.append(
                        [list(g) + ([elem] if j == i else [])
                         for j, g in enumerate(sp)])
            else:
                new_splits.append([list(g) for g in sp] + [[elem]])
        if idx < len(cardinality) - 1:
            new_splits.extend(_partial_split(cardinality, idx, num_groups))
        splits[:] = new_splits
        _cat_partitions(splits, cardinality, idx + 1, num_groups)


def _partial_split(cardinality: List[str], idx: int,
                   num_groups: int) -> List[List[List[str]]]:
    if num_groups == 2:
        return [[[cardinality[i] for i in range(idx + 1)]]]
    out: List[List[List[str]]] = []
    _cat_partitions(out, cardinality[:idx + 1], 0, num_groups - 1)
    return out


# ---------------------------------------------------------------------------
# splits and predicates
# ---------------------------------------------------------------------------

def int_split_key(points: Sequence) -> str:
    return SPLIT_ELEMENT_SEPARATOR.join(str(p) for p in points)


def cat_split_key(groups: Sequence[Sequence[str]]) -> str:
    """CategoricalSplit.toString: Java List.toString per group, ":"-joined
    (AttributeSplitHandler.java:205-212) -> ``[a, b]:[c]``."""
    return SPLIT_ELEMENT_SEPARATOR.join(
        "[" + ", ".join(g) + "]" for g in groups)


@dataclass
class Split:
    """One candidate split of one attribute: numeric split points or
    categorical groups; knows its reference-format key and computes segment
    indices for whole columns at once."""
    attr: int
    points: Optional[Tuple] = None             # numeric
    groups: Optional[List[List[str]]] = None   # categorical
    key: str = ""

    def __post_init__(self):
        if not self.key:
            self.key = (int_split_key(self.points) if self.points is not None
                        else cat_split_key(self.groups))

    @property
    def segment_count(self) -> int:
        if self.points is not None:
            return len(self.points) + 1
        return len(self.groups)

    def segment_index(self, column: np.ndarray) -> np.ndarray:
        """Vectorized AttributeSplitHandler.getSegmentIndex
        (AttributeSplitHandler.java:146-153: first i with value <= point;
        side='left' reproduces the strict ``>`` loop guard)."""
        if self.points is not None:
            vals = column.astype(np.float64)
            return np.searchsorted(np.asarray(self.points, dtype=np.float64),
                                   vals, side="left").astype(np.int32)
        seg = np.full(column.shape[0], -1, dtype=np.int32)
        for gi, group in enumerate(self.groups):
            seg[np.isin(column, group) & (seg < 0)] = gi
        return seg

    @classmethod
    def from_key(cls, attr: int, key: str, field: FeatureField) -> "Split":
        """IntegerSplit.fromString / CategoricalSplit.fromString
        (AttributeSplitHandler.java:158-165, 217-231)."""
        if field.is_categorical():
            groups = []
            for part in key.split(SPLIT_ELEMENT_SEPARATOR):
                part = part.strip()
                if part.startswith("["):
                    part = part[1:-1]
                groups.append([it.strip() for it in part.split(",")])
            return cls(attr, groups=groups, key=key)
        points = tuple(int(p) for p in key.split(SPLIT_ELEMENT_SEPARATOR))
        return cls(attr, points=points, key=key)


@dataclass
class AttributePredicate:
    """SplitManager.AttributePredicate and its Int/Double/Categorical
    subclasses collapsed into one record with vectorized evaluation.

    String form matches the reference: ``attr op value[ otherBound]`` for
    numerics (IntPredicate.toString), ``attr in a:b:c`` for categoricals
    (CategoricalPredicate.toString, ':'-joined values)."""
    attr: int
    operator: str
    value: Optional[float] = None
    other_bound: Optional[float] = None
    values: List[str] = dc_field(default_factory=list)
    integer: bool = True

    def to_string(self) -> str:
        if self.operator == OPERATOR_IN:
            return f"{self.attr} {OPERATOR_IN} " + ":".join(self.values)
        v = int(self.value) if self.integer else self.value
        s = f"{self.attr} {self.operator} {v}"
        if self.other_bound is not None:
            ob = int(self.other_bound) if self.integer else self.other_bound
            s += f" {ob}"
        return s

    def evaluate(self, column: np.ndarray) -> np.ndarray:
        """Vectorized SplitManager.IntPredicate/DoublePredicate/
        CategoricalPredicate.evaluate (SplitManager.java:686-721,758-787,
        824-833)."""
        if self.operator == OPERATOR_IN:
            return np.isin(column, self.values)
        col = column.astype(np.float64)
        if self.operator == OPERATOR_GE:
            r = col >= self.value
            if self.other_bound is not None:
                r &= col < self.other_bound
        elif self.operator == OPERATOR_GT:
            r = col > self.value
            if self.other_bound is not None:
                r &= col <= self.other_bound
        elif self.operator == OPERATOR_LE:
            r = col <= self.value
            if self.other_bound is not None:
                r &= col > self.other_bound
        elif self.operator == OPERATOR_LT:
            r = col < self.value
            if self.other_bound is not None:
                r &= col >= self.other_bound
        else:
            raise ValueError(f"illegal operator {self.operator}")
        return r

    @classmethod
    def parse(cls, text: str, field: FeatureField) -> "AttributePredicate":
        """Inverse of to_string (DecisionPathList.createIntPredicate etc.,
        DecisionPathList.java:196-243)."""
        items = text.split()
        attr = int(items[0])
        op = items[1]
        if field.is_categorical():
            return cls(attr, op, values=items[2].split(":"), integer=False)
        if field.is_integer():
            return cls(attr, op, value=int(items[2]),
                       other_bound=int(items[3]) if len(items) == 4 else None,
                       integer=True)
        return cls(attr, op, value=float(items[2]),
                   other_bound=float(items[3]) if len(items) == 4 else None,
                   integer=False)


def predicate_matrix(preds: Sequence[AttributePredicate],
                     col_by_attr: Dict[int, np.ndarray]) -> np.ndarray:
    """Vectorized evaluation of a predicate list over one record batch:
    bool ``B[n, len(preds)]`` with one column extraction per distinct
    attribute (``col_by_attr[attr]`` is the attribute's value column).
    This is the whole BuilderMapper predicate loop
    (DecisionTreeBuilder.java:275-320) for a batch — shared by the
    monolithic level pass and the chunked streaming pass, which calls it
    once per row chunk."""
    n = len(next(iter(col_by_attr.values()))) if col_by_attr else 0
    if not preds:
        return np.zeros((n, 0), dtype=bool)
    return np.stack([p.evaluate(col_by_attr[p.attr]) for p in preds],
                    axis=1)


def segment_predicates(split: Split, field: FeatureField) -> List[AttributePredicate]:
    """Predicates for each split segment, replicating
    SplitManager.createIntAttrPredicates / createDoubleAttrPredicates /
    createCategoricalAttrSplitPredicates (SplitManager.java:551-620,436-465)
    including the reference's overlapping last-segment ``le`` (see module
    docstring) and DoublePredicate's dropped other bound."""
    if field.is_categorical():
        return [AttributePredicate(split.attr, OPERATOR_IN, values=list(g),
                                   integer=False)
                for g in split.groups]
    integer = field.is_integer()
    pts = split.points
    preds: List[AttributePredicate] = []
    if len(pts) == 1:
        preds.append(AttributePredicate(split.attr, OPERATOR_LE, value=pts[0],
                                        integer=integer))
        preds.append(AttributePredicate(split.attr, OPERATOR_GT, value=pts[0],
                                        integer=integer))
    else:
        for i, p in enumerate(pts):
            if i == len(pts) - 1:
                preds.append(AttributePredicate(split.attr, OPERATOR_LE,
                                                value=p, integer=integer))
                preds.append(AttributePredicate(split.attr, OPERATOR_GT,
                                                value=p, integer=integer))
            elif i == 0:
                preds.append(AttributePredicate(split.attr, OPERATOR_LE,
                                                value=p, integer=integer))
            else:
                ob = pts[i - 1] if integer else None   # double drops bound
                preds.append(AttributePredicate(split.attr, OPERATOR_LE,
                                                value=p, other_bound=ob,
                                                integer=integer))
    return preds


def enumerate_attr_splits(field: FeatureField, use_bucket_grid: bool,
                          max_cat_groups: int = 3) -> List[Split]:
    """All candidate splits for one attribute.

    ``use_bucket_grid`` selects ClassPartitionGenerator's bucketWidth grid
    (ClassPartitionGenerator.java:283-286) over SplitManager's
    splitScanInterval grid (SplitManager.java:231-238)."""
    attr = field.ordinal
    max_split = int(field.maxSplit or 2)
    if field.is_categorical():
        if max_split > max_cat_groups:
            raise ValueError(
                f"more than {max_cat_groups} split groups not allowed for "
                f"categorical attr {attr}")
        splits = []
        for gr in range(2, max_split + 1):
            for groups in categorical_partitions(field.cardinality, gr):
                splits.append(Split(attr, groups=groups))
        return splits
    if use_bucket_grid:
        parts = bucket_point_partitions(field, max_split)
    else:
        parts = point_partitions(field.min, field.max,
                                 float(field.splitScanInterval),
                                 max_split, field.is_integer())
    return [Split(attr, points=p) for p in parts]


# ---------------------------------------------------------------------------
# split-quality statistics (util/AttributeSplitStat.java, InfoContentStat.java)
# ---------------------------------------------------------------------------

def info_content(counts: np.ndarray, algorithm: str) -> np.ndarray:
    """Entropy or gini over the LAST axis of a class-count tensor
    (InfoContentStat.processStat, util/InfoContentStat.java:71-101).  Zero
    counts contribute nothing (the reference never creates zero entries in
    its hash maps)."""
    counts = np.asarray(counts, dtype=np.float64)
    total = counts.sum(axis=-1, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        pr = np.where(total > 0, counts / total, 0.0)
        if algorithm == ALG_ENTROPY:
            term = np.where(pr > 0, -pr * np.log2(pr), 0.0)
            return term.sum(axis=-1)
        if algorithm == ALG_GINI_INDEX:
            return 1.0 - (pr * pr).sum(axis=-1)
    raise ValueError(f"unknown info algorithm {algorithm}")


def weighted_split_stat(seg_class_counts: np.ndarray, algorithm: str) -> float:
    """Population-weighted average of per-segment entropy/gini
    (AttributeSplitStat.SplitInfoContent.processStat,
    util/AttributeSplitStat.java:186-212). ``seg_class_counts``: [S, C]."""
    seg_tot = seg_class_counts.sum(axis=1)
    stats = info_content(seg_class_counts, algorithm)
    total = seg_tot.sum()
    return float((stats * seg_tot).sum() / total) if total > 0 else 0.0


def hellinger_split_stat(seg_class_counts: np.ndarray) -> float:
    """Hellinger distance over a binary-class split
    (util/AttributeSplitStat.java:240-283).  Segments with zero total count
    are skipped (the reference only materializes observed segments)."""
    if seg_class_counts.shape[1] != 2:
        raise ValueError("Hellinger distance algorithm is only valid for "
                         "binary valued class attributes")
    counts = seg_class_counts[seg_class_counts.sum(axis=1) > 0].astype(np.float64)
    class_tot = counts.sum(axis=0)
    frac = counts / np.maximum(class_tot, 1)[None, :]
    diff = np.sqrt(frac[:, 0]) - np.sqrt(frac[:, 1])
    return float(math.sqrt((diff * diff).sum()))


def class_confidence_split_stat(seg_class_counts: np.ndarray) -> float:
    """Class-confidence-ratio entropy, population-weighted across segments
    (util/AttributeSplitStat.java:289-336, 433-459)."""
    counts = seg_class_counts.astype(np.float64)
    observed = counts.sum(axis=1) > 0
    class_tot = counts.sum(axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        conf = np.where(class_tot[None, :] > 0, counts / class_tot[None, :], 0.0)
        conf_tot = conf.sum(axis=1, keepdims=True)
        ccr = np.where(conf_tot > 0, conf / conf_tot, 0.0)
        ent = np.where(ccr > 0, -ccr * np.log2(ccr), 0.0).sum(axis=1)
    seg_tot = counts.sum(axis=1)
    total = seg_tot[observed].sum()
    return float((ent * seg_tot)[observed].sum() / total) if total > 0 else 0.0


def split_stat(seg_class_counts: np.ndarray, algorithm: str) -> float:
    """AttributeSplitStat.processStat dispatch
    (util/AttributeSplitStat.java:84-93)."""
    if algorithm in (ALG_ENTROPY, ALG_GINI_INDEX):
        return weighted_split_stat(seg_class_counts, algorithm)
    if algorithm == ALG_HELLINGER_DIST:
        return hellinger_split_stat(seg_class_counts)
    if algorithm == ALG_CLASS_CONF:
        return class_confidence_split_stat(seg_class_counts)
    raise ValueError(f"unknown split algorithm {algorithm}")


def split_info_content(seg_class_counts: np.ndarray) -> float:
    """Entropy of the SEGMENT populations — the gain-ratio denominator
    (AttributeSplitStat.SplitStat.getInfoContent,
    util/AttributeSplitStat.java:151-170)."""
    seg_tot = seg_class_counts.sum(axis=1).astype(np.float64)
    seg_tot = seg_tot[seg_tot > 0]
    total = seg_tot.sum()
    if total <= 0:
        return 0.0
    pr = seg_tot / total
    return float(-(pr * np.log2(pr)).sum())


def class_probabilities(seg_class_counts: np.ndarray,
                        class_values: List[str]) -> Dict[int, Dict[str, float]]:
    """Per-segment class probabilities for output.split.prob
    (AttributeSplitStat.getClassProbab)."""
    out: Dict[int, Dict[str, float]] = {}
    for si in range(seg_class_counts.shape[0]):
        tot = seg_class_counts[si].sum()
        if tot <= 0:
            continue
        out[si] = {cv: float(seg_class_counts[si, ci] / tot)
                   for ci, cv in enumerate(class_values)
                   if seg_class_counts[si, ci] > 0}
    return out

"""Streaming reinforcement learning — the Storm/Redis topology replacement.

Reference surface being re-expressed (citations into /root/reference):
- ``org.avenir.reinforce.ReinforcementLearnerTopology`` — properties file ->
  Storm Config; RedisSpout(xN) shuffle-grouped to
  ReinforcementLearnerBolt(xM); StormSubmitter
  (reinforce/ReinforcementLearnerTopology.java:42-85).
- ``RedisSpout`` — ``rpop`` of ``redis.event.queue``, events are
  ``eventID,roundNum`` (reinforce/RedisSpout.java:86-100).
- ``ReinforcementLearnerBolt`` — on an event: drain the reward queue into
  ``learner.setReward``, select ``learner.nextActions()``, write
  ``eventID,action[,action...]`` to the action queue; on a reward tuple:
  apply it (reinforce/ReinforcementLearnerBolt.java:92-125); learner built
  from config keys ``reinforcement.learner.type`` /
  ``reinforcement.learrner.actions`` [sic — the reference's typo'd key is
  accepted too] (:66-71).
- ``RedisActionWriter`` / ``RedisRewardReader`` — queue adapters; rewards
  are ``actionID,reward`` lines (reinforce/RedisActionWriter.java:45-58,
  RedisRewardReader.java:53-88).

Re-design: Storm's spout/bolt thread graph existed to scale trivial per-event
math across JVM workers; a single host loop keeps up with any realistic event
rate here, so the topology becomes ``StreamingLearnerLoop`` — a pull loop
over a ``Transport`` with the same three queues and wire formats.
``InMemoryTransport`` serves tests/embedding; ``RedisTransport`` is a
drop-in for the reference's deployment (requires the optional ``redis``
package; the queue names/keys match, so the reference's producers/consumers
interoperate unchanged).
"""

from __future__ import annotations

import re
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .reinforce import Action, ReinforcementLearner, create_learner
from ..core import sanitizer
from ..core.obs import traced_run
from ..core.resilience import with_retries

_INT_RE = re.compile(r"-?\d+", re.ASCII)


class FakeRedisError(Exception):
    """The fakeredis-style stand-in for ``redis.exceptions.ResponseError``
    (``BUSYGROUP`` / ``NOGROUP`` messages match the server's, so callers
    classifying by message work against either client)."""


class Transport:
    """Queue transport: event source, reward source, action sink."""

    def next_event(self) -> Optional[str]:
        """Pop one ``eventID,roundNum`` message, or None when idle."""
        raise NotImplementedError

    def read_rewards(self) -> List[str]:
        """Drain pending ``actionID,reward`` messages."""
        raise NotImplementedError

    def write_action(self, message: str) -> None:
        """Push one ``eventID,action[,action...]`` message."""
        raise NotImplementedError


class InMemoryTransport(Transport):
    """Process-local queues (tests / embedded use).  Events live in a
    deque: the fleet loop pops tens of thousands per wave and a list's
    ``pop(0)`` is O(queue) per pop — the r4 bench spent more time
    shifting list elements than stepping learners."""

    def __init__(self):
        self.events: deque = deque()
        self.rewards: List[str] = []
        self.actions: List[str] = []

    def push_event(self, event_id: str, round_num: int) -> None:
        self.events.append(f"{event_id},{round_num}")

    def push_reward(self, action_id: str, reward: int) -> None:
        self.rewards.append(f"{action_id},{reward}")

    def next_event(self) -> Optional[str]:
        return self.events.popleft() if self.events else None

    def read_rewards(self) -> List[str]:
        out, self.rewards = self.rewards, []
        return out

    def write_action(self, message: str) -> None:
        self.actions.append(message)


def _sid(entry_id: str) -> Tuple[int, int]:
    """A stream entry id's sort key (``<ms>-<seq>`` -> (ms, seq))."""
    ms, _, seq = str(entry_id).partition("-")
    return int(ms), int(seq or 0)


#: below every real entry id (trimming to here would be a no-op)
ZERO_TRIM_ID = "0-0"


class FakeRedis:
    """fakeredis-style in-process double of the redis-py commands the
    transports use — the list commands (:class:`RedisTransport`: same
    lpush/rpop semantics and decoded-string returns) PLUS the stream
    commands (:class:`RedisStreamTransport`: XADD / XLEN / XRANGE /
    XGROUP CREATE / XREADGROUP / XACK / XPENDING with consumer groups,
    per-consumer pending-entry redelivery, and blocking reads), no
    server.  Producers/consumers standing in for the reference's Redis
    peers (and the round-trip tests in ``tests/test_reinforce.py`` /
    ``tests/test_stream.py``) drive the REAL transports against this
    client, so both wire protocols are covered without the optional
    ``redis`` dependency.

    Entry ids are deterministic (``1-0``, ``2-0``, ... per stream — the
    server's ``<ms>-<seq>`` shape with a counter clock), so tests and
    the byte-equivalence gates reproduce exactly.  Thread-safe: one
    condition guards every structure, and blocking ``xreadgroup`` reads
    wait on it."""

    def __init__(self):
        self._cond = sanitizer.make_condition("models.fakeredis")
        self._lists: Dict[str, deque] = {}
        #: stream key -> ordered [(id, fields dict)]
        self._streams: Dict[str, List[Tuple[str, Dict[str, str]]]] = {}
        self._next_id: Dict[str, int] = {}
        #: (stream, group) -> {"last": id, "pending": {id: consumer}}
        self._groups: Dict[Tuple[str, str], dict] = {}

    # -- list commands (the reference queue protocol) ----------------------
    def lpush(self, key: str, *values) -> int:
        with self._cond:
            q = self._lists.setdefault(key, deque())
            for v in values:
                q.appendleft(str(v))
            return len(q)

    def rpop(self, key: str) -> Optional[str]:
        with self._cond:
            q = self._lists.get(key)
            return q.pop() if q else None

    def llen(self, key: str) -> int:
        with self._cond:
            return len(self._lists.get(key) or ())

    def lrange(self, key: str, start: int, stop: int) -> List[str]:
        with self._cond:
            items = list(self._lists.get(key) or ())
            return items[start:None if stop == -1 else stop + 1]

    # -- stream commands (XADD / consumer groups) --------------------------
    def xadd(self, key: str, fields: Dict[str, str], id: str = "*") -> str:
        with self._cond:
            entries = self._streams.setdefault(key, [])
            if id == "*":
                n = self._next_id.get(key, 0) + 1
                self._next_id[key] = n
                eid = f"{n}-0"
            else:
                eid = str(id)
                if entries and _sid(eid) <= _sid(entries[-1][0]):
                    raise FakeRedisError(
                        "ERR The ID specified in XADD is equal or smaller "
                        "than the target stream top item")
                self._next_id[key] = max(self._next_id.get(key, 0),
                                         _sid(eid)[0])
            entries.append((eid, {str(k): str(v)
                                  for k, v in fields.items()}))
            self._cond.notify_all()
            return eid

    def xlen(self, key: str) -> int:
        with self._cond:
            return len(self._streams.get(key) or ())

    def xrange(self, key: str, min: str = "-", max: str = "+",
               count: Optional[int] = None):
        with self._cond:
            entries = list(self._streams.get(key) or ())
        lo = None if min == "-" else _sid(min)
        hi = None if max == "+" else _sid(max)
        out = [(eid, dict(f)) for eid, f in entries
               if (lo is None or _sid(eid) >= lo)
               and (hi is None or _sid(eid) <= hi)]
        return out[:count] if count is not None else out

    def xgroup_create(self, key: str, group: str, id: str = "$",
                      mkstream: bool = False) -> bool:
        with self._cond:
            if key not in self._streams:
                if not mkstream:
                    raise FakeRedisError(
                        "ERR The XGROUP subcommand requires the key to "
                        "exist (consider MKSTREAM)")
                self._streams[key] = []
            if (key, group) in self._groups:
                raise FakeRedisError(
                    "BUSYGROUP Consumer Group name already exists")
            entries = self._streams[key]
            last = (entries[-1][0] if id == "$" and entries else "0-0")
            if id not in ("$", "0"):
                last = str(id)
            self._groups[(key, group)] = {"last": last, "pending": {}}
            return True

    def _group(self, key: str, group: str) -> dict:
        g = self._groups.get((key, group))
        if g is None:
            raise FakeRedisError(
                f"NOGROUP No such consumer group '{group}' for key name "
                f"'{key}'")
        return g

    def xreadgroup(self, groupname: str, consumername: str,
                   streams: Dict[str, str], count: Optional[int] = None,
                   block: Optional[int] = None):
        """One stream per call (all this double's users read one); id
        ``>`` delivers NEW entries (recorded pending under this
        consumer, blocking up to ``block`` ms when none), any other id
        replays THIS consumer's pending entries above it (the
        crash-redelivery path) without blocking."""
        (key, from_id), = streams.items()
        deadline = (time.monotonic() + block / 1000.0
                    if block is not None else None)
        while True:
            with self._cond:
                g = self._group(key, groupname)
                entries = self._streams.get(key) or []
                if from_id == ">":
                    lo = _sid(g["last"])
                    fresh = [(eid, dict(f)) for eid, f in entries
                             if _sid(eid) > lo]
                    if count is not None:
                        fresh = fresh[:count]
                    if fresh:
                        for eid, _ in fresh:
                            g["pending"][eid] = consumername
                        g["last"] = fresh[-1][0]
                        return [[key, fresh]]
                else:
                    lo = _sid(from_id)
                    mine = sorted(
                        (eid for eid, owner in g["pending"].items()
                         if owner == consumername and _sid(eid) > lo),
                        key=_sid)
                    if count is not None:
                        mine = mine[:count]
                    by_id = dict(entries)
                    return ([[key, [(eid, dict(by_id[eid]))
                                    for eid in mine if eid in by_id]]]
                            if mine else [])
                if deadline is None:
                    return []
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)

    def xack(self, key: str, group: str, *ids) -> int:
        with self._cond:
            g = self._group(key, group)
            n = 0
            for eid in ids:
                if g["pending"].pop(str(eid), None) is not None:
                    n += 1
            return n

    def xpending(self, key: str, group: str) -> dict:
        with self._cond:
            g = self._group(key, group)
            pend = sorted(g["pending"], key=_sid)
            return {"pending": len(pend),
                    "min": pend[0] if pend else None,
                    "max": pend[-1] if pend else None}

    def xtrim(self, key: str, maxlen: Optional[int] = None,
              minid: Optional[str] = None) -> int:
        """XTRIM: drop entries below ``minid`` (exclusive, the server's
        MINID strategy) or beyond ``maxlen`` newest; returns entries
        removed.  Deliberately DUMB, like the server command — computing
        a safe horizon across consumer groups is the caller's job
        (``RedisStreamTransport.trim_acked``)."""
        if (maxlen is None) == (minid is None):
            raise FakeRedisError(
                "ERR XTRIM requires exactly one of maxlen / minid")
        with self._cond:
            entries = self._streams.get(key)
            if not entries:
                return 0
            if minid is not None:
                cut = _sid(str(minid))
                keep = [e for e in entries if _sid(e[0]) >= cut]
            else:
                keep = entries[len(entries) - min(maxlen, len(entries)):]
            removed = len(entries) - len(keep)
            self._streams[key] = keep
            return removed

    def xinfo_groups(self, key: str) -> List[dict]:
        """XINFO GROUPS: per-group name / last-delivered-id / pending
        count (the subset the trim-horizon computation reads)."""
        with self._cond:
            if key not in self._streams:
                raise FakeRedisError("ERR no such key")
            out = []
            for (k, group), g in sorted(self._groups.items()):
                if k != key:
                    continue
                out.append({"name": group,
                            "last-delivered-id": g["last"],
                            "pending": len(g["pending"])})
            return out

    def advance_id_clock(self, key: str, ms: int) -> None:
        """Advance the stream's id counter to at least ``ms``.  A real
        server's entry ids are millisecond-clock based and therefore
        monotonic across process restarts; this double's counter clock
        restarts at 1, so a consumer resuming an offset checkpoint
        against a FRESH in-process broker calls this with its watermark
        — otherwise every new entry would sort below the watermark and
        be deduplicated away."""
        with self._cond:
            self._next_id[key] = max(self._next_id.get(key, 0), int(ms))


def _redis_client(host: str, port: int, client=None):
    """The injected client (e.g. :class:`FakeRedis`) or a real redis-py
    connection.  Construction itself is lazy on the redis side (redis-py
    connects per command), so the transient-failure surface is the
    commands — each wrapped in ``with_retries`` at its call site."""
    if client is not None:
        return client
    import redis  # optional dependency; gate at construction
    return redis.Redis(host=host, port=port, decode_responses=True)


class RedisTransport(Transport):
    """Redis-list transport matching the reference's queue protocol
    (``rpop`` events, reward list, ``lpush`` actions).  ``client``
    injects a ready client (e.g. :class:`FakeRedis`); otherwise the
    optional ``redis`` package connects to ``host:port``.  Every network
    command runs under ``core.resilience.with_retries`` (transient
    ``OSError``-family failures back off and reattempt; the io-retry
    analysis rule patrols these call sites)."""

    def __init__(self, host: str, port: int, event_queue: str,
                 reward_queue: str, action_queue: str, client=None):
        self._r = _redis_client(host, port, client)
        self.event_queue = event_queue
        self.reward_queue = reward_queue
        self.action_queue = action_queue

    def next_event(self) -> Optional[str]:
        return with_retries(lambda: self._r.rpop(self.event_queue),
                            op="redis")

    def read_rewards(self) -> List[str]:
        out = []
        while True:
            msg = with_retries(lambda: self._r.rpop(self.reward_queue),
                               op="redis")
            if msg is None:
                return out
            out.append(msg)

    def write_action(self, message: str) -> None:
        with_retries(lambda: self._r.lpush(self.action_queue, message),
                     op="redis")


class RedisStreamTransport:
    """Redis-STREAM transport for the ``avenir_tpu/stream`` feedback
    subsystem: reward events are stream entries consumed through a
    consumer group (XREADGROUP), so at-least-once delivery with
    per-consumer pending-entry redelivery is the substrate the
    exactly-once checkpoint layer rides on.  ``client`` injects a ready
    client (:class:`FakeRedis` in tests and server-less deployments);
    otherwise the optional ``redis`` package connects to ``host:port``.
    Every network command runs under ``core.resilience.with_retries``."""

    def __init__(self, host: str, port: int, stream: str, group: str,
                 consumer: str, client=None):
        self._r = _redis_client(host, port, client)
        self.stream = stream
        self.group = group
        self.consumer = consumer

    def ensure_group(self) -> None:
        """Create the consumer group from the stream head (idempotent:
        an existing group is fine — BUSYGROUP is the already-exists
        signal, not an error)."""
        try:
            with_retries(
                lambda: self._r.xgroup_create(self.stream, self.group,
                                              id="0", mkstream=True),
                op="redis")
        except Exception as e:                      # noqa: BLE001
            if "BUSYGROUP" not in str(e):
                raise

    def publish(self, fields: Dict[str, str]) -> str:
        """XADD one reward event; returns the assigned entry id."""
        return with_retries(lambda: self._r.xadd(self.stream, fields),
                            op="redis")

    def read_new(self, count: int,
                 block_ms: Optional[int] = None) -> List[tuple]:
        """XREADGROUP ``>``: up to ``count`` new entries (recorded in
        this consumer's pending list), blocking up to ``block_ms``."""
        res = with_retries(
            lambda: self._r.xreadgroup(self.group, self.consumer,
                                       {self.stream: ">"}, count=count,
                                       block=block_ms),
            op="redis")
        return list(res[0][1]) if res else []

    def read_pending(self, count: int,
                     after: str = "0-0") -> List[tuple]:
        """XREADGROUP from an explicit id: THIS consumer's still-pending
        (delivered but unacknowledged) entries above ``after`` — the
        crash-redelivery read a resumed consumer drains, cursor-style,
        before any new entries (applied-but-unacked entries stay in the
        PEL until their covering checkpoint, so the cursor is what keeps
        the drain a single pass)."""
        res = with_retries(
            lambda: self._r.xreadgroup(self.group, self.consumer,
                                       {self.stream: after}, count=count),
            op="redis")
        return list(res[0][1]) if res else []

    def ack(self, ids: Sequence[str]) -> int:
        if not ids:
            return 0
        return with_retries(
            lambda: self._r.xack(self.stream, self.group, *ids),
            op="redis")

    def pending_count(self) -> int:
        return int(with_retries(
            lambda: self._r.xpending(self.stream, self.group),
            op="redis")["pending"])

    def length(self) -> int:
        return int(with_retries(lambda: self._r.xlen(self.stream),
                                op="redis"))

    # -- trimming (ROADMAP: streams grow forever without it) ---------------
    @staticmethod
    def _next_id(eid: str) -> str:
        ms, seq = _sid(eid)
        return f"{ms}-{seq + 1}"

    def all_groups_ack_floor(self) -> Optional[str]:
        """The smallest entry id ANY consumer group of this stream still
        needs: per group, the oldest pending (delivered, unacked) entry
        when one exists, else the first id past its last-delivered
        cursor (undelivered entries must survive).  Entries BELOW the
        minimum across groups are acked by every consumer — safe to
        trim.  None when the stream has no groups (nothing is provably
        consumed, so nothing trims)."""
        groups = with_retries(lambda: self._r.xinfo_groups(self.stream),
                              op="redis")
        floors = []
        for g in groups:
            name = g.get("name")
            oldest = None
            if int(g.get("pending", 0)) > 0:
                p = with_retries(
                    lambda n=name: self._r.xpending(self.stream, n),
                    op="redis")
                # the group may have acked its last pending entry
                # between the xinfo read and this call: min comes back
                # None and the last-delivered fallback below applies
                oldest = p.get("min")
            if oldest is not None:
                floors.append(str(oldest))
            else:
                floors.append(self._next_id(
                    str(g.get("last-delivered-id", "0-0"))))
        if not floors:
            return None
        return min(floors, key=_sid)

    def trim_acked(self, horizon: str) -> int:
        """XTRIM entries at or below ``horizon`` (a checkpoint-covered
        ack horizon), clamped to the ALL-consumers ack floor so no
        group's undelivered or still-pending entries are ever dropped;
        returns entries removed."""
        floor = self.all_groups_ack_floor()
        if floor is None:
            return 0
        cut = min(self._next_id(horizon), floor, key=_sid)
        if _sid(cut) <= _sid(ZERO_TRIM_ID):
            return 0
        return int(with_retries(
            lambda: self._r.xtrim(self.stream, minid=cut), op="redis"))


def _get(config: Dict, *keys, default=None, required=False):
    """First non-None value among alternate key spellings (both dict and
    JobConfig expose .get)."""
    for k in keys:
        v = config.get(k)
        if v is not None:
            return v
    if required:
        raise ValueError(f"missing required config: {keys[0]}")
    return default


def _parse_learner_spec(config: Dict):
    """(learner type, action-id list) from the topology config keys,
    accepting the reference's typo'd actions key
    (ReinforcementLearnerBolt.java:66-71)."""
    learner_type = _get(config, "reinforcement.learner.type", required=True)
    actions = _get(config, "reinforcement.learner.actions",
                   "reinforcement.learrner.actions", required=True)
    if isinstance(actions, str):
        actions = actions.split(",")
    return learner_type, actions


def _pull_loop(step_fn, max_events: Optional[int],
               idle_timeout: Optional[float],
               poll_interval: float) -> int:
    """Shared pull-loop skeleton: ``step_fn(room)`` does up to ``room``
    events (None = unbounded) and returns how many it processed; the loop
    stops after ``max_events`` or ``idle_timeout`` idle seconds."""
    processed = 0
    idle_since = None
    while max_events is None or processed < max_events:
        room = None if max_events is None else max_events - processed
        n = step_fn(room)
        if n:
            processed += n
            idle_since = None
        else:
            if idle_timeout is None:
                time.sleep(poll_interval)
                continue
            if idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since > idle_timeout:
                break
            time.sleep(poll_interval)
    return processed


class StreamingLearnerLoop:
    """The topology+bolt equivalent: one learner, three queues, a pull loop.

    ``step()`` processes at most one event (plus any pending rewards) and
    returns whether it did work; ``run()`` loops until ``max_events`` or an
    idle timeout — the Storm topology ran forever, so both bounds are
    optional.
    """

    def __init__(self, config: Dict, transport: Optional[Transport] = None):
        self.config = config
        learner_type, actions = _parse_learner_spec(config)
        self.learner: ReinforcementLearner = create_learner(
            learner_type, actions, config)
        if transport is not None:
            self.transport = transport
        else:
            writer = _get(config, "reinforcement.learner.action.writer",
                          "reinforcement.learrner.action.writer",
                          default="redis")
            if writer != "redis":
                raise ValueError(f"unknown action writer: {writer}")
            self.transport = RedisTransport(
                host=_get(config, "redis.server.host", required=True),
                port=int(_get(config, "redis.server.port", required=True)),
                event_queue=_get(config, "redis.event.queue", required=True),
                reward_queue=_get(config, "redis.reward.queue", required=True),
                action_queue=_get(config, "redis.action.queue", required=True))
        self.event_count = 0
        self.reward_count = 0

    def apply_rewards(self) -> int:
        """Drain the reward queue into the learner
        (ReinforcementLearnerBolt.java:96-99)."""
        n = 0
        for msg in self.transport.read_rewards():
            action_id, reward = msg.split(",")[:2]
            self.learner.set_reward(action_id, int(reward))
            n += 1
        self.reward_count += n
        return n

    def step(self) -> bool:
        """One spout+bolt cycle: rewards first, then one event -> actions."""
        self.apply_rewards()
        msg = self.transport.next_event()
        if msg is None:
            return False
        event_id = msg.split(",")[0]
        actions = self.learner.next_actions()
        action_list = ",".join(a.id for a in actions)
        self.transport.write_action(f"{event_id},{action_list}")
        self.event_count += 1
        return True

    @traced_run
    def run(self, max_events: Optional[int] = None,
            idle_timeout: Optional[float] = 1.0,
            poll_interval: float = 0.01) -> int:
        """Pull loop; returns events processed.  Stops after ``max_events``
        or after ``idle_timeout`` seconds with an empty event queue."""
        return _pull_loop(lambda room: int(self.step()), max_events,
                          idle_timeout, poll_interval)


class GroupedStreamingLearnerLoop:
    """Fleet-scale streaming RL: one learner PER ENTITY, batched on device.

    The reference pairs its Storm bolt with a ``ReinforcementLearnerGroup``
    (one learner per entity id, ReinforcementLearnerGroup.java:30-70); with
    thousands of entities the per-event Python map is the bottleneck SURVEY
    §7.2 stage 7 flags.  This loop drains the event queue in waves and
    advances every touched entity's learner in ONE jitted masked step of a
    ``VectorizedLearnerGroup``, applying drained rewards as one bulk
    scatter.  Unknown entities auto-enroll with fresh learner state.

    Wire formats extend the single-learner loop's with the entity key:
    events ``entityID,roundNum`` (the entity IS the learner id), rewards
    ``entityID,actionID,reward``, actions out ``entityID,action``.
    """

    def __init__(self, config: Dict, transport: Transport,
                 entities: Sequence[str] = ()):
        from .reinforce_vec import VectorizedLearnerGroup

        learner_type, actions = _parse_learner_spec(config)
        self.group = VectorizedLearnerGroup(learner_type, list(entities),
                                            actions, config)
        self._actions = set(actions)
        self.transport = transport
        self.event_count = 0
        self.reward_count = 0
        self.malformed_count = 0
        # action-latency knob: how many dispatched waves may backlog
        # before their selections are read back and emitted.  1 restores
        # the reference bolt's immediate per-wave emit
        # (ReinforcementLearnerBolt.java:103-117) for latency-sensitive
        # transports; the default keeps the throughput pipelining.
        pending = _get(config, "streaming.max.pending.batches")
        if pending is not None:
            pending = int(pending)
            if pending < 1:
                raise ValueError(
                    f"streaming.max.pending.batches must be >= 1: {pending}")
            self.max_pending_batches = pending

    def _parse_rewards(self):
        """Drain and validate ``entityID,actionID,reward`` messages;
        malformed or unknown-action messages are counted and skipped so
        one bad queue entry cannot take down the fleet loop."""
        gids, aids, rs = [], [], []
        for msg in self.transport.read_rewards():
            parts = msg.split(",")
            # strict integer syntax: int() alone would admit '1_0'/' 10'/+
            if (len(parts) < 3 or parts[1] not in self._actions
                    or not _INT_RE.fullmatch(parts[2])):
                self.malformed_count += 1
                continue
            gids.append(parts[0])
            aids.append(parts[1])
            rs.append(int(parts[2]))
        self.reward_count += len(gids)
        return gids, aids, rs

    def apply_rewards(self) -> int:
        """Drain the reward queue into the fleet as one bulk scatter."""
        gids, aids, rs = self._parse_rewards()
        if gids:
            self.group.add_groups(gids)
            self.group.set_rewards(gids, aids, rs)
        return len(gids)

    def _dispatch_batch(self, max_events: int):
        """Drain up to ``max_events`` events, apply pending rewards, and
        dispatch the masked device step(s) WITHOUT materializing the
        selections: returns ``(n_events, pending)`` where pending holds
        ``(wave_entities, rows, sels_device)`` records for ``_emit``.
        The async dispatch is what lets ``run()`` overlap the next
        wave's transport drain/parse with this wave's device step (the
        ``models/bayesian._train_streamed`` double-buffer pattern)."""
        entities: List[str] = []
        for _ in range(max_events):
            msg = self.transport.next_event()
            if msg is None:
                break
            # validate symmetrically with apply_rewards: a malformed or
            # empty event must not auto-enroll a bogus entity (e.g. "")
            ent = msg.split(",")[0]
            if not ent:
                self.malformed_count += 1
                continue
            entities.append(ent)
        # rewards AFTER the event drain (a transport refilled mid-drain
        # delivers this wave's rewards in time) but BEFORE the step
        # dispatch — the bolt's rewards-before-selection order
        # (ReinforcementLearnerBolt.java:92-99)
        gids, aids, rs = self._parse_rewards()
        if not entities:
            if gids:
                self.group.add_groups(gids)
                self.group.set_rewards(gids, aids, rs)
            return 0, []
        self.group.add_groups(entities)
        if gids:
            self.group.add_groups(gids)
        out = []
        todo = entities
        first = True
        while todo:
            wave: List[str] = []
            seen = set()
            rest: List[str] = []
            for e in todo:
                (rest if e in seen else wave).append(e)
                seen.add(e)
            rows = self.group.rows_for(wave)
            # batch.size selections per event in ONE jitted scan, matching
            # the scalar loop's learner.next_actions() / the bolt's
            # eventID,action[,action...] format.  Wave inputs (reward
            # triples + active rows) ship as ONE packed int32 array —
            # through a tunneled device each device_put / eager op is a
            # serial ~100 ms round trip, so the RPC count per wave IS
            # the throughput; buckets are powers of two so recompiles
            # are O(log max-wave).  The first sub-wave carries the
            # drained rewards; duplicate-entity sub-waves go reward-free.
            nr = len(gids) if first else 0
            rb = 8
            while rb < nr:
                rb *= 2
            wb = 8
            while wb < len(wave):
                wb *= 2
            packed = np.full(2 + 3 * rb + wb, self.group.capacity,
                             np.int32)     # pad rows = capacity (dropped)
            packed[0], packed[1] = nr, len(wave)
            packed[2:2 + 3 * rb] = 0
            if nr:
                packed[2:2 + nr] = self.group.rows_for(gids)
                packed[2 + rb:2 + rb + nr] = [
                    self.group._aindex[x] for x in aids]
                packed[2 + 2 * rb:2 + 2 * rb + nr] = rs
            packed[2 + 3 * rb:2 + 3 * rb + len(wave)] = rows
            sels = self.group.step_waved_async(packed, rb,
                                               self.group.batch_size)
            first = False
            out.append((wave, rows, sels))
            todo = rest
        self.event_count += len(entities)
        return len(entities), out

    def _emit(self, pending) -> None:
        """Materialize the device selections and write the
        ``entityID,action[,action...]`` messages.  All pending waves'
        selections concatenate ON DEVICE first so the whole batch costs
        ONE blocking transfer (each read is a full tunnel round trip)."""
        if not pending:
            return
        names = np.asarray(self.group.action_ids, dtype=object)
        # concatenate per CAPACITY group: an auto-enroll between
        # pipelined waves grows the fleet's state arrays, so backlogged
        # selections may have different widths — one transfer per
        # distinct shape (growth is O(log fleet), so still amortized)
        mats: List = [None] * len(pending)
        by_shape: Dict[tuple, List[int]] = {}
        for i, (_, _, s) in enumerate(pending):
            by_shape.setdefault(tuple(s.shape), []).append(i)
        import jax.numpy as jnp
        for shape, idxs in by_shape.items():
            if len(idxs) == 1:
                mats[idxs[0]] = np.asarray(pending[idxs[0]][2])
                continue
            flat = np.asarray(jnp.concatenate(
                [pending[i][2] for i in idxs], axis=0))
            ns = shape[0]
            for j, i in enumerate(idxs):
                mats[i] = flat[j * ns:(j + 1) * ns]
        for (wave, rows, _), sels in zip(pending, mats):
            acts = names[sels[:, rows]]                   # [n_steps, W]
            if acts.shape[0] == 1:
                for e, a in zip(wave, acts[0]):
                    self.transport.write_action(f"{e},{a}")
            else:
                for i, e in enumerate(wave):
                    self.transport.write_action(
                        e + "," + ",".join(acts[:, i]))

    def step_batch(self, max_events: int = 1024) -> int:
        """Drain rewards + up to ``max_events`` events and write their
        actions before returning (the synchronous surface; ``run()``
        pipelines batches instead).  Entities repeating within a batch
        step their learner once per event, preserving per-event
        semantics."""
        n, pending = self._dispatch_batch(max_events)
        self._emit(pending)
        return n

    # dispatched batches whose selections are still device futures;
    # bounding the backlog bounds action latency while amortizing the
    # blocking device read (a full tunnel round trip) across waves.
    # Class default; ``streaming.max.pending.batches`` overrides per
    # instance (1 = the reference bolt's immediate per-wave emit).
    MAX_PENDING_BATCHES = 4

    @property
    def max_pending_batches(self) -> int:
        return getattr(self, "_max_pending_batches", self.MAX_PENDING_BATCHES)

    @max_pending_batches.setter
    def max_pending_batches(self, value: int) -> None:
        self._max_pending_batches = value

    @traced_run
    def run(self, max_events: Optional[int] = None,
            idle_timeout: Optional[float] = 1.0,
            poll_interval: float = 0.01, batch: int = 1024) -> int:
        """Pipelined pull loop: subsequent waves' drain/parse/dispatch
        run while earlier device steps are still in flight; actions are
        emitted (the blocking device read) once ``max_pending_batches``
        waves are queued (``streaming.max.pending.batches``; 1 = the
        reference bolt's immediate per-wave emit), on idle, and before
        returning — so the queue drains at dispatch speed and every
        action is flushed by exit."""
        processed = 0
        idle_since = None
        prev: List = []
        try:
            while max_events is None or processed < max_events:
                room = (batch if max_events is None
                        else min(batch, max_events - processed))
                n, pending = self._dispatch_batch(room)
                if n:
                    processed += n
                    prev.extend(pending)
                    if len(prev) >= self.max_pending_batches:
                        self._emit(prev)
                        prev = []
                    idle_since = None
                    continue
                if prev:                   # idle: flush before sleeping
                    self._emit(prev)
                    prev = []
                if idle_timeout is None:
                    time.sleep(poll_interval)
                    continue
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since > idle_timeout:
                    break
                time.sleep(poll_interval)
        finally:
            self._emit(prev)
        return processed


class ReinforcementLearnerTopology:
    """CLI-shaped alias mirroring the reference entry point
    (``java -jar uber-avenir.jar <topologyName> <configFile>``,
    ReinforcementLearnerTopology.java:42-85).

    Registered in the CLI job table so
    ``python -m avenir_tpu ReinforcementLearnerTopology <topologyName>
    <configFile>`` submits the streaming loop the way ``StormSubmitter``
    submitted the topology.  The two positional args keep the reference's
    order; properties may equivalently come via ``-Dconf.path``.  The loop
    runs until the event queue stays idle for ``topology.idle.timeout.sec``
    (default 1.0; the Storm topology ran forever — pass ``none`` to match)
    or ``topology.max.events`` is reached.
    """

    def __init__(self, config: Optional[Dict] = None):
        self.config = dict(getattr(config, "props", config) or {})

    @staticmethod
    def build(config: Dict,
              transport: Optional[Transport] = None) -> StreamingLearnerLoop:
        return StreamingLearnerLoop(config, transport)

    @traced_run
    def run(self, topology_name: str, config_file: str,
            transport: Optional[Transport] = None):
        """Job-driver surface: args mirror the reference main()'s
        ``(topologyName, configFile)``; returns event/reward Counters."""
        from ..core.config import parse_properties
        from ..core.metrics import Counters

        props: Dict[str, str] = {}
        if config_file:
            with open(config_file, "r") as fh:
                props.update(parse_properties(fh.read()))
        # -D defines (and -Dconf.path contents) overlay the positional file,
        # matching load_job_config precedence (core/config.py:154-165)
        props.update(self.config)
        loop = StreamingLearnerLoop(props, transport)

        max_events = _get(props, "topology.max.events")
        idle = _get(props, "topology.idle.timeout.sec", default="1.0")
        idle_timeout = None if str(idle).lower() == "none" else float(idle)
        loop.run(max_events=int(max_events) if max_events else None,
                 idle_timeout=idle_timeout)

        counters = Counters()
        counters.incr("Topology", "EventsProcessed", loop.event_count)
        counters.incr("Topology", "RewardsApplied", loop.reward_count)
        return counters

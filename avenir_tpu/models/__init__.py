"""Algorithm layer: each model family is a thin parameterization of the
``ops`` counting/distance/scan engines plus reference-format text I/O.

Job classes expose ``run(config, in_path, out_path) -> Counters`` and are
registered in ``avenir_tpu.cli`` under the reference's driver class names so
existing pipeline scripts translate 1:1.
"""

"""Batch (round-based) multi-armed bandit jobs.

Reference surface being re-expressed (citations into /root/reference):
- ``org.avenir.reinforce.GreedyRandomBandit`` — per-group ε-greedy batch
  selection with linear/logLinear ε decay or the AuerGreedy schedule
  (GreedyRandomBandit.java:76-302); input rows ``group,item,count,reward``
  grouped by group id, batch sizes from a ``group.item.count.path`` side file
  (:117-124), output ``group,item`` lines.
- ``org.avenir.reinforce.AuerDeterministic`` — UCB1 over normalized rewards
  ``reward/maxReward + sqrt(2 ln n / n_item)``, untried items first
  (AuerDeterministic.java:182-231).
- ``org.avenir.reinforce.SoftMaxBandit`` — Boltzmann sampling over
  ``exp((reward/maxReward)/T)`` scaled by 1000, untried items first
  (SoftMaxBandit.java:170-206).
- ``org.avenir.reinforce.RandomFirstGreedyBandit`` — pure exploration for the
  first ``explorationCount`` selections (position-cycling ranges via
  ``ExplorationCounter``), then pure exploitation of the top-reward items
  through a rank secondary sort (RandomFirstGreedyBandit.java:83-245,
  ExplorationCounter.java:27-118).

The reward feedback loop is EXTERNAL, exactly as in the reference: outputs
are scored by a simulator/real system, re-aggregated (chombo
RunningAggregator's role — see ``aggregate_rewards`` below), the round
counter ``current.round.num`` is bumped, and the job re-runs
(resource/price_optimize_tutorial.txt:29-63).

Deliberate divergence (same defect as RandomGreedyLearner — see
models.reinforce): the reference's ``if (curProb < Math.random()) select
random`` (GreedyRandomBandit.java:263,285) inverts the ε schedule so later
rounds get MORE random; we explore with the decaying probability.
Randomness is seeded via the ``random.seed`` config key.

TPU note: these jobs are pure per-group selection logic over tiny per-group
item lists (100 products in the price-optimization tutorial) driven from
text files between externally-scored rounds; the math is argmax/sampling over
a handful of scalars, so the idiomatic implementation is vectorized NumPy per
group, not a device kernel.  The device-scale bandit path is the online
learner library (models.reinforce) driven by the streaming loop.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters


class GroupedItems:
    """Per-group (item, count, reward) list with selection helpers
    (reinforce/GroupedItems.java:31-145)."""

    def __init__(self):
        self.items: List[dict] = []

    def create_item(self, item_id: str, count: int, reward: int) -> None:
        self.items.append({"itemID": item_id, "count": count, "reward": reward})

    def size(self) -> int:
        return len(self.items)

    def collect_items_not_tried(self, batch_size: int) -> List[dict]:
        """Remove and return up to batch_size items with count==0
        (GroupedItems.java:94-113)."""
        collected = []
        remaining = []
        for it in self.items:
            if it["count"] == 0 and len(collected) < batch_size:
                collected.append(it)
            else:
                remaining.append(it)
        self.items = remaining
        return collected

    def select_random(self, rng: np.random.Generator) -> dict:
        return self.items[int(rng.integers(len(self.items)))]

    def get_max_reward_item(self) -> Optional[dict]:
        """Max strictly-positive reward; None when nothing has been rewarded
        (GroupedItems.java:130-143 starts its max at 0)."""
        best, best_reward = None, 0
        for it in self.items:
            if it["reward"] > best_reward:
                best, best_reward = it, it["reward"]
        return best

    def remove(self, item: dict) -> None:
        self.items.remove(item)

    def add(self, item: dict) -> None:
        self.items.append(item)


def _read_grouped(in_path: str, delim_regex: str, count_ord: int,
                  reward_ord: int) -> "OrderedDict[str, GroupedItems]":
    """Rows ``group,item,...`` -> per-group item lists, preserving first-seen
    group order (the reference streams grouped input through one mapper)."""
    groups: "OrderedDict[str, GroupedItems]" = OrderedDict()
    for line in read_lines(in_path):
        items = split_line(line, delim_regex)
        g = groups.setdefault(items[0], GroupedItems())
        g.create_item(items[1], int(items[count_ord]), int(items[reward_ord]))
    return groups


def _read_batch_sizes(path: Optional[str]) -> Dict[str, Tuple[int, ...]]:
    """group.item.count.path side file: ``group,batchSize`` (2 cols) or
    ``group,count,batchSize`` (3 cols, RandomFirstGreedyBandit)."""
    out: Dict[str, Tuple[int, ...]] = {}
    if not path:
        return out
    for line in read_lines(path):
        parts = split_line(line, ",")
        out[parts[0]] = tuple(int(v) for v in parts[1:])
    return out


class _BanditJobBase:
    def __init__(self, config: JobConfig):
        self.config = config
        seed = config.get_int("random.seed", None)
        self.rng = np.random.default_rng(seed)

    def _common(self):
        cfg = self.config
        return (cfg.field_delim_regex(), cfg.get("field.delim", ","),
                cfg.get_int("current.round.num", -1),
                cfg.must_int("count.ordinal"),
                cfg.must_int("reward.ordinal"),
                _read_batch_sizes(cfg.get("group.item.count.path")))

    @staticmethod
    def _batch_size(batch_sizes, group_id) -> int:
        if not batch_sizes:
            return 1
        try:
            return batch_sizes[group_id][-1]
        except KeyError:
            raise ValueError(
                f"group {group_id!r} present in the input but missing from "
                f"the group.item.count.path side file") from None
        except IndexError:
            raise ValueError(
                f"group {group_id!r} line in the group.item.count.path side "
                f"file has no batch-size column") from None


class GreedyRandomBandit(_BanditJobBase):
    """ε-greedy batch bandit (GreedyRandomBandit.java:76-302)."""

    PROB_RED_LINEAR = "linear"
    PROB_RED_LOG_LINEAR = "logLinear"
    AUER_GREEDY = "AuerGreedy"

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        (delim_regex, delim, round_num, count_ord, reward_ord,
         batch_sizes) = self._common()
        algo = cfg.get("prob.reduction.algorithm", self.PROB_RED_LINEAR)
        rand_prob = cfg.get_float("random.selection.prob", 0.5)
        red_const = cfg.get_float("prob.reduction.constant", 1.0)
        auer_const = cfg.get_int("auer.greedy.constant", 5)

        groups = _read_grouped(in_path, delim_regex, count_ord, reward_ord)
        out = []
        for group_id, grouped in groups.items():
            batch = self._batch_size(batch_sizes, group_id)
            if algo in (self.PROB_RED_LINEAR, self.PROB_RED_LOG_LINEAR):
                selected = self._linear_select(
                    grouped, batch, round_num, rand_prob, red_const,
                    log_linear=(algo == self.PROB_RED_LOG_LINEAR))
            elif algo == self.AUER_GREEDY:
                selected = self._auer_greedy_select(
                    grouped, batch, round_num, auer_const)
            else:
                raise ValueError(f"invalid prob.reduction.algorithm:{algo}")
            for item in selected:
                out.append(f"{group_id}{delim}{item}")
                counters.incr("Bandit", "Selections")
        write_output(out_path, out)
        return counters

    def _linear_select(self, grouped: GroupedItems, batch_size: int,
                       round_num: int, rand_prob: float, red_const: float,
                       log_linear: bool) -> List[str]:
        selected: List[str] = []
        count = (round_num - 1) * batch_size
        n_avail = grouped.size()
        for _ in range(min(batch_size, n_avail)):
            count += 1
            # early rounds (count <= 1, incl. the unset round default -1)
            # explore at the full base probability instead of dividing by
            # zero / going negative
            t = max(count, 1)
            if log_linear:
                cur_prob = rand_prob * red_const * math.log(t) / t
            else:
                cur_prob = rand_prob * red_const / t
            cur_prob = min(cur_prob, rand_prob)
            # explore with the decaying prob, exploit otherwise (see module
            # docstring re the reference's flipped comparison); the picked
            # item leaves the pool so batch selections are distinct without
            # the reference's unbounded rejection loop
            # (GreedyRandomBandit.java:214-216)
            item = self._pick(grouped, cur_prob)
            selected.append(item["itemID"])
            grouped.remove(item)
        return selected

    def _pick(self, grouped: GroupedItems, cur_prob: float) -> dict:
        if self.rng.random() < cur_prob:
            return grouped.select_random(self.rng)
        best = grouped.get_max_reward_item()
        if best is None:  # nothing rewarded yet -> random
            return grouped.select_random(self.rng)
        return best

    def _auer_greedy_select(self, grouped: GroupedItems, batch_size: int,
                            round_num: int, auer_const: int) -> List[str]:
        """ε_t = cK/(d²t) schedule (GreedyRandomBandit.java:233-275)."""
        selected: List[str] = []
        count = (round_num - 1) * batch_size
        group_count = grouped.size()

        for it in grouped.collect_items_not_tried(batch_size):
            selected.append(it["itemID"])
        count += len(selected)

        if len(selected) < batch_size and grouped.size() > 0:
            max_item = grouped.get_max_reward_item()
            reward_diff = 1.0
            if max_item is not None and grouped.size() > 1:
                max_reward = max_item["reward"]
                grouped.remove(max_item)
                next_item = grouped.get_max_reward_item()
                next_reward = next_item["reward"] if next_item else 0
                grouped.add(max_item)
                if max_reward > 0:
                    reward_diff = (max_reward - next_reward) / max_reward
            reward_diff = max(reward_diff, 1e-9)
            while len(selected) < batch_size and grouped.size() > 0:
                prob = (auer_const * group_count
                        / (reward_diff * reward_diff * max(count, 1)))
                prob = min(prob, 1.0)
                if self.rng.random() < prob:
                    item = grouped.select_random(self.rng)
                else:
                    item = grouped.get_max_reward_item()
                    if item is None:
                        item = grouped.select_random(self.rng)
                selected.append(item["itemID"])
                grouped.remove(item)
                count += 1
        return selected


class AuerDeterministic(_BanditJobBase):
    """Deterministic UCB1 batch bandit (AuerDeterministic.java:74-233)."""

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        (delim_regex, delim, round_num, count_ord, reward_ord,
         batch_sizes) = self._common()
        algo = cfg.get("det.algorithm", "AuerUBC1")
        if algo != "AuerUBC1":
            raise ValueError(f"invalid det.algorithm:{algo}")

        groups = _read_grouped(in_path, delim_regex, count_ord, reward_ord)
        out = []
        for group_id, grouped in groups.items():
            batch = self._batch_size(batch_sizes, group_id)
            selected: List[str] = []
            count = (round_num - 1) * batch
            for it in grouped.collect_items_not_tried(batch):
                selected.append(it["itemID"])
            count += len(selected)

            while len(selected) < batch and grouped.size() > 0:
                max_item = grouped.get_max_reward_item()
                max_reward = max_item["reward"] if max_item else 1
                # UCB over the remaining items, vectorized
                rewards = np.asarray([it["reward"] for it in grouped.items],
                                     dtype=float)
                trials = np.asarray([it["count"] for it in grouped.items],
                                    dtype=float)
                with np.errstate(divide="ignore"):
                    bonus = np.sqrt(2.0 * math.log(max(count, 2)) /
                                    np.maximum(trials, 1e-12))
                value = rewards / max(max_reward, 1) + bonus
                pick = grouped.items[int(np.argmax(value))]
                selected.append(pick["itemID"])
                grouped.remove(pick)
                count += 1

            for item in selected:
                out.append(f"{group_id}{delim}{item}")
                counters.incr("Bandit", "Selections")
        write_output(out_path, out)
        return counters


class SoftMaxBandit(_BanditJobBase):
    """Boltzmann batch bandit (SoftMaxBandit.java:76-208); distribution
    values scaled by 1000 as in the reference (DISTR_SCALE)."""

    DISTR_SCALE = 1000

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        (delim_regex, delim, round_num, count_ord, reward_ord,
         batch_sizes) = self._common()
        temp = cfg.get_float("temp.constant", 1.0)

        groups = _read_grouped(in_path, delim_regex, count_ord, reward_ord)
        out = []
        for group_id, grouped in groups.items():
            batch = self._batch_size(batch_sizes, group_id)
            selected: List[str] = []
            for it in grouped.collect_items_not_tried(batch):
                selected.append(it["itemID"])

            if grouped.size() > 0 and len(selected) < batch:
                max_item = grouped.get_max_reward_item()
                max_reward = max_item["reward"] if max_item else 1
                ids = [it["itemID"] for it in grouped.items]
                distr = np.asarray([it["reward"] / max(max_reward, 1)
                                    for it in grouped.items])
                # max-subtracted exponent keeps the int scaling in range at
                # cold temperatures (the reference's raw (int) cast saturates
                # at Integer.MAX_VALUE — SoftMaxBandit.java:187); shifting
                # leaves the softmax distribution unchanged
                scaled = (np.exp((distr - distr.max()) / temp)
                          * self.DISTR_SCALE).astype(np.int64)
                # floor at 1 so cold temperatures cannot zero an arm out of
                # the replace=False draw entirely
                scaled = np.maximum(scaled, 1)
                probs = scaled / scaled.sum()
                take = min(batch - len(selected), len(ids))
                picks = self.rng.choice(len(ids), size=take, replace=False,
                                        p=probs)
                selected.extend(ids[i] for i in picks)

            for item in selected:
                out.append(f"{group_id}{delim}{item}")
                counters.incr("Bandit", "Selections")
        write_output(out_path, out)
        return counters


class ExplorationCounter:
    """Position-cycling exploration schedule
    (reinforce/ExplorationCounter.java:27-118)."""

    def __init__(self, group_id: str, count: int, exploration_count: int,
                 batch_size: int):
        self.group_id = group_id
        self.count = count
        self.exploration_count = exploration_count
        self.batch_size = batch_size
        self.selections: List[Tuple[int, int]] = []

    def select_next_round(self, round_num: int) -> None:
        remaining = self.exploration_count - (round_num - 1) * self.batch_size
        self.selections = []
        if remaining > 0:
            beg = remaining % self.count
            end = beg + self.batch_size - 1
            if end >= self.count:
                self.selections = [(beg, self.count - 1), (0, end - self.count)]
            else:
                self.selections = [(beg, end)]

    def is_in_exploration(self) -> bool:
        return bool(self.selections)

    def should_explore(self, item_index: int) -> bool:
        return any(lo <= item_index <= hi for lo, hi in self.selections)


class RandomFirstGreedyBandit(_BanditJobBase):
    """Explore-first-then-exploit batch bandit
    (RandomFirstGreedyBandit.java:83-245).  Input rows ``group,item[,reward]``;
    the side file carries ``group,count,batchSize``.  During exploration,
    items are chosen by cycling positions; afterwards the top-reward items
    win (the reference's rank secondary sort becomes an argsort)."""

    RANK_MAX = 1000

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        delim = cfg.get("field.delim", ",")
        round_num = cfg.get_int("current.round.num", 2)
        strategy = cfg.get("exploration.count.strategy", "simple")
        if strategy == "simple":
            expl_factor = cfg.get_int("exploration.count.factor", 2)
        else:
            reward_diff = cfg.get_float("pac.reward.diff", 0.2)
            prob_diff = cfg.get_float("pac.prob.diff", 0.2)

        expl_counters: Dict[str, ExplorationCounter] = {}
        for line in read_lines(cfg.must("group.item.count.path")):
            parts = split_line(line, ",")
            group_id, count, batch = parts[0], int(parts[1]), int(parts[2])
            if strategy == "simple":
                expl_count = expl_factor * count
            else:  # PAC bound (RandomFirstGreedyBandit.java:143)
                expl_count = int(4.0 / (reward_diff * reward_diff)
                                 + math.log(2.0 * count / prob_diff))
            expl_counters[group_id] = ExplorationCounter(
                group_id, count, expl_count, batch)

        # group rows preserving in-group position (the mapper's curItemIndex)
        rows: "OrderedDict[str, List[List[str]]]" = OrderedDict()
        for line in read_lines(in_path):
            items = split_line(line, delim_regex)
            rows.setdefault(items[0], []).append(items)

        out = []
        for group_id, group_rows in rows.items():
            ec = expl_counters[group_id]
            ec.select_next_round(round_num)
            ranked: List[Tuple[int, str]] = []
            for idx, items in enumerate(group_rows):
                if ec.is_in_exploration():
                    rank = 1 if ec.should_explore(idx) else -1
                else:
                    rank = (self.RANK_MAX - int(items[2])
                            if len(items) > 2 else -1)
                if rank > 0:
                    ranked.append((rank, items[1]))
            # rank ascending = highest reward first (secondary sort order)
            ranked.sort(key=lambda t: t[0])
            for _, item in ranked[:ec.batch_size]:
                out.append(f"{group_id}{delim}{item}")
                counters.incr("Bandit", "Selections")
        write_output(out_path, out)
        return counters


class BanditFeedbackAggregator:
    """Batch replay of a reward-event log into per-arm posterior state —
    the offline twin of the streaming feedback consumer
    (``avenir_tpu/stream``), and the byte-equivalence reference its
    exactly-once gate compares against: replaying the same event log
    through this job and through the Redis-stream consumer must emit
    byte-identical ``tenant,arm,pulls,rewardSum`` posterior lines.

    Input rows are CSV reward events; the ``stream.tenant.ordinal`` /
    ``stream.arm.ordinal`` / ``stream.reward.ordinal`` keys (defaults
    0/1/2 — the consumer's ``tenant,arm,reward`` wire format) map
    arbitrary logs.  Tenants/arms come from the declared
    ``stream.tenants`` / ``stream.arms`` manifest; malformed events
    (unknown tenant/arm, non-integer reward) are skipped and counted,
    identically to the online consumer.  Exports the shared-scan
    :class:`~avenir_tpu.stream.posterior.FeedbackFoldSpec`, so the
    fold-algebra verifier certifies the posterior fold's split/merge
    invariance like every other registered fold (``analyze --dynamic``
    jid ``bandit_fb``)."""

    def __init__(self, config: JobConfig):
        self.config = config

    def fold_spec(self, out_path: str):
        """Export the shared-scan ``core.multiscan.FoldSpec``."""
        from ..stream.posterior import FeedbackFoldSpec

        return FeedbackFoldSpec(self.config, out_path)

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        """Drive the FoldSpec over the input exactly the way the shared
        scan would (chunked encode -> H2D -> donated-carry fold), so the
        standalone run IS the certified fold path."""
        from ..core import pipeline
        from ..parallel.mesh import get_mesh
        from ..core.multiscan import ChunkContext

        mesh = mesh or get_mesh()
        cfg = self.config
        spec = self.fold_spec(out_path)
        delim = cfg.field_delim_regex()
        chunk_rows = cfg.pipeline_chunk_rows(
            default=pipeline.DEFAULT_CHUNK_ROWS)
        xfer = pipeline.ChunkTransfer(mesh, capacity=None)
        fold = None
        for raw, _idx, _end in pipeline.iter_byte_chunks_meta(
                in_path, chunk_rows):
            arrs = spec.encode(ChunkContext(raw, delim))
            if arrs is None:
                continue
            if fold is None:
                fold = pipeline.ChunkFold(
                    spec.local_fn, static_args=spec.static_args,
                    mesh=mesh)
            fold.fold(xfer(tuple(arrs)))
        return spec.finalize(fold.result() if fold is not None else None)


def aggregate_rewards(selection_reward_lines: List[str],
                      prev_state_lines: List[str],
                      delim: str = ",") -> List[str]:
    """Inter-round reward aggregation — the chombo ``RunningAggregator`` role
    in the bandit loop (price_optimize_tutorial.txt:44-56): merge this
    round's scored selections ``group,item,reward`` into the running
    ``group,item,count,rewardAvg`` state consumed by the next round."""
    state: Dict[Tuple[str, str], List[int]] = {}
    for line in prev_state_lines:
        g, item, count, avg = line.split(delim)[:4]
        state[(g, item)] = [int(count), int(avg)]
    for line in selection_reward_lines:
        g, item, reward = line.split(delim)[:3]
        cur = state.setdefault((g, item), [0, 0])
        total = cur[0] * cur[1] + int(reward)
        cur[0] += 1
        cur[1] = total // cur[0]
    return [f"{g}{delim}{item}{delim}{c}{delim}{r}"
            for (g, item), (c, r) in state.items()]

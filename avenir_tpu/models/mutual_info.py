"""Mutual information distributions + feature-selection scores.

Reference surface:
- ``explore.MutualInformation`` — one pass emits 7 distribution families
  (class; feature; feature-pair; feature-class; feature-pair-class;
  feature-class-conditional; feature-pair-class-conditional — constants at
  MutualInformation.java:61-67, map at :136-214); the reducer materializes
  them, prints each section under a ``distribution:<name>`` header, computes
  feature/pair/pair-class/pair-class-conditional MI under
  ``mutualInformation:<name>`` headers (:479-784), then ranked feature
  scores per configured algorithm (:792-840).
- ``explore.MutualInformationScore`` — MIM (sort by MI desc), MIFS
  (redundancy-penalized greedy, MutualInformationScore.java:116-153), JMI
  (:177-241), DISR (pair MI / pair entropy), mRMR (:265-300).

TPU re-design: the 7 families all project from two dense device tables —
``FC[class, feature, bin]`` (one ``feature_class_counts`` einsum/scatter) and
``PC[pair, b1, b2, class]`` (one ``count_table`` over all i<j column pairs).
The mapper's quadratic per-record pair loop disappears into indexing; the MI
arithmetic runs on the host over the tiny tables, preserving the reference's
"only observed cells" summation (dense zero cells are skipped, which is the
same set).  Binning requires every numeric feature to declare bucketWidth
(MutualInformation.java:220-227 has no unbinned path).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.binning import DatasetEncoder, EncodedDataset
from ..core.multiscan import FoldSpec as MultiScanFoldSpec
from ..core.obs import traced_run
from ..core.config import JobConfig
from ..core.io import write_output
from ..core.metrics import Counters
from ..core.schema import FeatureSchema
from ..ops.counting import count_table, feature_class_counts, sharded_reduce


def _mi_local(x, y, mask, n_class, max_bins, pair_i, pair_j):
    fc = feature_class_counts(x, y, n_class, max_bins, mask=mask)
    n_pairs = len(pair_i)
    pi = np.asarray(pair_i, dtype=np.int32)
    pj = np.asarray(pair_j, dtype=np.int32)
    import jax.numpy as jnp
    xi = x[:, pi]                                  # [n, n_pairs]
    xj = x[:, pj]
    p_idx = jnp.broadcast_to(jnp.arange(n_pairs, dtype=jnp.int32)[None, :],
                             xi.shape)
    yb = jnp.broadcast_to(y[:, None], xi.shape)
    m = mask[:, None]
    pc = count_table((n_pairs, max_bins, max_bins, n_class),
                     (p_idx, xi, xj, yb), mask=m)
    return {"fc": fc, "pc": pc}


class MutualInformationScore:
    """Feature-ranking algorithms (MutualInformationScore.java)."""

    def __init__(self):
        self.feature_mi: List[Tuple[int, float]] = []
        self.pair_mi: List[Tuple[int, int, float]] = []
        self.pair_class_mi: List[Tuple[int, int, float]] = []
        self.pair_class_entropy: List[Tuple[int, int, float]] = []

    # -- MIM ----------------------------------------------------------------
    def mim(self) -> List[Tuple[int, float]]:
        return sorted(self.feature_mi, key=lambda t: -t[1])

    # -- MIFS ---------------------------------------------------------------
    def mifs(self, redundancy_factor: float) -> List[Tuple[int, float]]:
        out, selected = [], set()
        while len(selected) < len(self.feature_mi):
            best, best_f = -math.inf, 0
            for f, mi in self.feature_mi:
                if f in selected:
                    continue
                red = sum(v for a, b, v in self.pair_mi
                          if (a == f and b in selected)
                          or (b == f and a in selected))
                score = mi - redundancy_factor * red
                if score > best:
                    best, best_f = score, f
            out.append((best_f, best))
            selected.add(best_f)
        return out

    # -- JMI / DISR ---------------------------------------------------------
    def _jmi_helper(self, joint: bool) -> List[Tuple[int, float]]:
        out, selected = [], set()
        first = self.mim()[0]
        out.append(first)
        selected.add(first[0])
        while len(selected) < len(self.feature_mi):
            best, best_f = -math.inf, 0
            for f, _ in self.feature_mi:
                if f in selected:
                    continue
                s = 0.0
                for a, b, v in self.pair_class_mi:
                    if (a == f and b in selected) or (b == f and a in selected):
                        if joint:
                            s += v
                        else:
                            ent = self._pair_entropy(a, b)
                            s += v / ent
                if s > best:
                    best, best_f = s, f
            out.append((best_f, best))
            selected.add(best_f)
        return out

    def jmi(self) -> List[Tuple[int, float]]:
        return self._jmi_helper(True)

    def disr(self) -> List[Tuple[int, float]]:
        return self._jmi_helper(False)

    def _pair_entropy(self, a: int, b: int) -> float:
        for x, y, v in self.pair_class_entropy:
            if (x == a and y == b) or (x == b and y == a):
                return v
        raise KeyError((a, b))

    # -- mRMR ---------------------------------------------------------------
    def mrmr(self) -> List[Tuple[int, float]]:
        out, selected = [], set()
        while len(selected) < len(self.feature_mi):
            best, best_f = -math.inf, 0
            for f, mi in self.feature_mi:
                if f in selected:
                    continue
                red = sum(v for a, b, v in self.pair_mi
                          if (a == f and b in selected)
                          or (b == f and a in selected))
                score = (mi - red / len(selected)) if selected else mi
                if score > best:
                    best, best_f = score, f
            out.append((best_f, best))
            selected.add(best_f)
        return out


_ALGOS = {
    "mutual.info.maximization": lambda s, rf: s.mim(),
    "mutual.info.selection": lambda s, rf: s.mifs(rf),
    "joint.mutual.info": lambda s, rf: s.jmi(),
    "double.input.symmetric.relevance": lambda s, rf: s.disr(),
    "min.redundancy.max.relevance": lambda s, rf: s.mrmr(),
}


class _MIStreamState:
    """Per-chunk guards, cap sizing, and bin/row accounting shared by the
    standalone streamed MI path and the shared-scan FoldSpec."""

    def __init__(self, enc: DatasetEncoder):
        self.enc = enc
        ffields = enc.feature_fields
        self.F = len(ffields)
        self.num_bins_seen = np.zeros(self.F, dtype=np.int64)
        self.n_rows = 0
        self.caps: Dict[str, int] = {}
        self.declared = [f.num_bins() if (f.is_bucket_width_defined()
                                          and f.max is not None) else 0
                         for f in ffields]
        self.pair_i: Tuple[int, ...] = ()
        self.pair_j: Tuple[int, ...] = ()

    def size_caps(self) -> None:
        """Bin/class extents from the declared schema + the first
        accepted chunk (+headroom); call after the first ``accept``."""
        cat_card = [len(self.enc.vocabs[f.ordinal])
                    for f in self.enc.feature_fields if f.is_categorical()]
        self.caps["B"] = int(max([1] + self.declared + cat_card
                                 + list(self.num_bins_seen))) + 4
        self.caps["C"] = max(len(self.enc.class_vocab), 1) + 2
        self.pair_i, self.pair_j = map(tuple, np.triu_indices(self.F, k=1))

    def accept(self, x, y, n: int):
        """Guard one encoded chunk; returns the (x, y) fold arrays or
        None for an empty chunk.  ``x`` carries raw (unshifted) bins —
        callers on the shifting Python encode guard ``bin_offset``
        themselves; the negative check here covers the native path."""
        from ..core.binning import ChunkedEncodeUnsupported

        if n == 0:
            return None
        if (x < 0).any():
            raise ChunkedEncodeUnsupported("negative bin")
        mx = x.max(axis=0) + 1
        np.maximum(self.num_bins_seen, mx, out=self.num_bins_seen)
        if self.caps and (int(mx.max()) > self.caps["B"]
                          or int(y.max()) >= self.caps["C"]):
            raise ChunkedEncodeUnsupported("cap overflow")
        self.n_rows += n
        return x, y


def pair_table_bytes(F: int, B: int, C: int) -> int:
    """Estimated device bytes of the MI count tables: the dominant
    ``PC[pair, b1, b2, class]`` int32 over all i<j feature pairs plus
    the ``FC[class, feature, bin]`` table — the quadratic-in-features,
    quadratic-in-bins residency this job materializes per device."""
    n_pairs = F * (F - 1) // 2
    return 4 * (n_pairs * B * B * C + C * F * B)


def check_pair_table_budget(cfg, F: int, B: int, C: int) -> None:
    """Fail fast — BEFORE any device allocation — when the estimated MI
    pair-table residency exceeds the configured
    ``pipeline.device.budget.bytes``.  The PC table grows as
    F^2/2 * B^2 * C int32 cells, so a wide or finely-binned schema turns
    into an opaque device OOM mid-fold; this guard turns it into an
    actionable error naming the estimate and the knobs (no guard when no
    budget is declared)."""
    from ..core import pipeline

    budget = cfg.get_int(pipeline.KEY_DEVICE_BUDGET, None)
    if budget is None:
        return
    est = pair_table_bytes(F, B, C)
    if est > budget:
        n_pairs = F * (F - 1) // 2
        raise ValueError(
            f"MutualInformation pair tables need ~{est} bytes per device "
            f"({n_pairs} feature pairs x {B}x{B} bins x {C} classes, "
            f"int32) which exceeds {pipeline.KEY_DEVICE_BUDGET}={budget}. "
            f"Raise the budget, coarsen bucketWidth (fewer bins), or "
            f"reduce the feature set (e.g. a prior feature-select stage).")


class MutualInformation:
    """The MI job."""

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        self.schema = schema or FeatureSchema.from_file(
            config.must("feature.schema.file.path"))
        for f in self.schema.feature_fields():
            if not f.is_categorical() and not f.is_bucket_width_defined():
                raise ValueError(
                    f"MutualInformation requires bucketWidth on numeric "
                    f"feature {f.name!r} (reference has no unbinned path)")
        # early ceiling check from DECLARED extents alone (discovered
        # extents re-check at cap sizing): constructing the job against
        # an over-budget schema fails before any input is read
        ffields = self.schema.feature_fields()
        decl_bins = [f.num_bins() for f in ffields
                     if f.is_categorical() or f.max is not None]
        cls = self.schema.class_attr_field()
        check_pair_table_budget(
            config, len(ffields), max(decl_bins, default=1),
            max(len(cls.cardinality), 1))

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim = cfg.field_delim_out()
        enc = DatasetEncoder(self.schema)
        chunk_rows = cfg.pipeline_chunk_rows(
            row_bytes=4 * (len(enc.feature_fields) + 1))
        if chunk_rows is not None:
            res = self._run_streamed(
                enc, in_path, out_path, cfg, delim, counters, mesh,
                chunk_rows, cfg.pipeline_prefetch_depth())
            if res is not None:
                return res
            enc = DatasetEncoder(self.schema)   # fresh vocabs for fallback
            counters = Counters()
        ds = enc.encode_path(in_path, cfg.field_delim_regex())
        counters.set("Basic", "Records", ds.n_rows)

        F = ds.n_features
        C = len(ds.class_vocab)
        B = max(ds.num_bins)
        check_pair_table_budget(cfg, F, B, C)
        pair_i, pair_j = map(tuple, np.triu_indices(F, k=1))
        res = sharded_reduce(_mi_local, ds.x, ds.y, mesh=mesh,
                             static_args=(C, B, pair_i, pair_j))
        fc = np.asarray(res["fc"], dtype=np.int64)       # [C, F, B]
        pc = np.asarray(res["pc"], dtype=np.int64)       # [P, B, B, C]

        lines = self._emit(ds, fc, pc, pair_i, pair_j, delim, cfg)
        write_output(out_path, lines)
        return counters

    def _run_streamed(self, enc: DatasetEncoder, in_path, out_path, cfg,
                      delim, counters: Counters, mesh, chunk_rows: int,
                      depth: int) -> Optional[Counters]:
        """Chunked streaming MI: row chunks bulk-parse + encode on the
        prefetch worker (vocabularies grow in input order, identical to
        the one-shot encode) and both distribution tables fold on device
        through ``core.pipeline`` with a donated accumulator.  Bin/class
        extents cap from the declared schema + first chunk (+headroom);
        an overflow — late class value, beyond-cap bin, or a
        negative-bin column (whose shift is global) — returns None and
        the caller re-runs the monolithic path for identical output."""
        from ..core import ingestcache, pipeline
        from ..core.binning import ChunkedEncodeUnsupported

        delim_regex = cfg.field_delim_regex()
        st = _MIStreamState(enc)

        # parse-once cache (core.ingestcache): a validated artifact for
        # this (input, schema, delim, chunk_rows) replays mmapped encoded
        # chunks — MI's all-features-binned x is exactly the artifact's
        # raw-bin matrix (the bin_offset==0 guard below is what makes the
        # native and Python encodes agree).  A miss tees this scan into a
        # new artifact; the per-chunk guards in ``st.accept`` run on warm
        # replay too, so cap overflows still fall back identically.
        cache = ingestcache.IngestCache.from_config(cfg, in_path, enc,
                                                    delim_regex)
        builder = None
        scan = cache.load(chunk_rows) if cache is not None else None
        if scan is not None:
            scan.seed_encoder(enc)

            def encoded():
                for x, values, y, n, _ in scan.chunks():
                    out = st.accept(np.asarray(x), np.asarray(y), n)
                    if out is not None:
                        yield out
        else:
            if cache is not None:
                builder = cache.builder(chunk_rows)

            def encoded():
                for arr in pipeline.iter_field_chunks(in_path, delim_regex,
                                                      chunk_rows):
                    dsc = enc.encode(arr)
                    if (dsc.bin_offset != 0).any():
                        raise ChunkedEncodeUnsupported("negative bin")
                    out = st.accept(dsc.x, dsc.y, dsc.n_rows)
                    if out is not None:
                        if builder is not None:
                            builder.add(dsc.x, dsc.values, dsc.y,
                                        dsc.n_rows)
                        yield out

        try:
            first, stream = pipeline.peek(encoded())
            if first is None:
                if builder is not None:
                    builder.abort()
                return None
            st.size_caps()
            check_pair_table_budget(cfg, st.F, st.caps["B"], st.caps["C"])
            res = pipeline.streaming_fold(
                stream, _mi_local,
                static_args=(st.caps["C"], st.caps["B"],
                             st.pair_i, st.pair_j),
                mesh=mesh, prefetch_depth=depth, capacity=chunk_rows)
        except ChunkedEncodeUnsupported:
            if builder is not None:
                builder.abort()
            return None
        if res is None:
            if builder is not None:
                builder.abort()
            return None
        if builder is not None:
            builder.finish()
        counters.set("Basic", "Records", st.n_rows)
        lines = self._streamed_lines(enc, st, res, delim, cfg)
        write_output(out_path, lines)
        return counters

    def _streamed_lines(self, enc: DatasetEncoder, st: _MIStreamState,
                        res, delim, cfg) -> List[str]:
        """Output lines from a streamed fold result (shared tail of
        ``_run_streamed`` and the multi-scan FoldSpec)."""
        ffields = enc.feature_fields
        F = len(ffields)
        num_bins = []
        for j, f in enumerate(ffields):
            if f.is_categorical():
                num_bins.append(len(enc.vocabs[f.ordinal]))
            else:
                num_bins.append(max(st.declared[j], int(st.num_bins_seen[j])))
        C = len(enc.class_vocab)
        B = max(num_bins)
        fc = np.asarray(res["fc"], dtype=np.int64)[:C, :, :B]
        pc = np.asarray(res["pc"], dtype=np.int64)[:, :B, :B, :C]
        ds_meta = EncodedDataset(
            schema=enc.schema, feature_fields=ffields,
            x=np.zeros((0, F), np.int32), values=np.zeros((0, F)),
            y=np.zeros(0, np.int32), num_bins=num_bins,
            bin_offset=np.zeros(F, np.int32),
            binned_mask=np.ones(F, dtype=bool),
            vocabs=enc.vocabs, class_vocab=enc.class_vocab)
        return self._emit(ds_meta, fc, pc, st.pair_i, st.pair_j, delim, cfg)

    def fold_spec(self, out_path: str):
        """Export this job's shared-scan ``core.multiscan.FoldSpec``."""
        return _MIFoldSpec(self, out_path)

    # -- artifact import (core.dag feature-select stage) -------------------
    @staticmethod
    def parse_scores(lines, algorithm: Optional[str] = None,
                     delim: str = ",") -> List[Tuple[int, float]]:
        """The ranked ``(ordinal, score)`` list out of this job's output
        lines — the artifact-import hook a DAG feature-select stage uses
        to consume the ranking in memory.  ``algorithm`` picks one
        ``mutualInformationScoreAlgorithm:`` section (default: the
        first); unknown algorithm -> KeyError naming what the artifact
        does contain."""
        sections: Dict[str, List[Tuple[int, float]]] = {}
        current: Optional[str] = None
        for line in lines:
            if line.startswith("mutualInformationScoreAlgorithm:"):
                current = line.split(":", 1)[1].strip()
                sections[current] = []
                continue
            if current is None:
                continue
            if ":" in line and delim not in line:
                current = None          # a following non-score header
                continue
            parts = line.split(delim)
            if len(parts) == 2:
                try:
                    parsed = (int(parts[0]), float(parts[1]))
                except ValueError:
                    # score sections are the LAST sections of the
                    # artifact, so a non-`ordinal,score` line here is
                    # corruption (partial write, hand edit) — fail
                    # loudly instead of silently truncating the
                    # ranking a feature-select stage will consume
                    raise ValueError(
                        f"malformed score line in MI artifact section "
                        f"{current!r}: {line!r}") from None
                sections[current].append(parsed)
        if not sections:
            raise ValueError(
                "no mutualInformationScoreAlgorithm section in the MI "
                "artifact (was the job run with "
                "mutual.info.score.algorithms set?)")
        if algorithm is None:
            return next(iter(sections.values()))
        if algorithm not in sections:
            raise KeyError(
                f"MI artifact has no score section {algorithm!r}; "
                f"present: {sorted(sections)}")
        return sections[algorithm]

    # -- host post-processing ----------------------------------------------
    def _emit(self, ds: EncodedDataset, fc, pc, pair_i, pair_j, delim,
              cfg) -> List[str]:
        out: List[str] = []
        F = ds.n_features
        C, B = fc.shape[0], fc.shape[2]
        ords = [f.ordinal for f in ds.feature_fields]
        class_vals = ds.class_vocab.values
        class_counts = fc[:, 0, :].sum(axis=1)           # every row binned
        total = int(class_counts.sum())
        feat = fc.sum(axis=0)                            # [F, B]
        pair = pc.sum(axis=3)                            # [P, B, B]

        def bl(j, b):
            return ds.bin_label(j, b)

        # ---- distributions ----
        out.append("distribution:class")
        for c in range(C):
            out.append(f"{class_vals[c]}{delim}{class_counts[c] / total}")

        out.append("distribution:feature")
        for j in range(F):
            for b in range(B):
                if feat[j, b]:
                    out.append(f"{ords[j]}{delim}{bl(j, b)}{delim}"
                               f"{feat[j, b] / total}")

        out.append("distribution:featurePair")
        for p, (i, j) in enumerate(zip(pair_i, pair_j)):
            for b1 in range(B):
                for b2 in range(B):
                    v = pair[p, b1, b2]
                    if v:
                        out.append(
                            f"{ords[i]}{delim}{ords[j]}{delim}{bl(i, b1)}"
                            f"{delim}{bl(j, b2)}{delim}{v / total}")

        out.append("distribution:featureClass")
        for j in range(F):
            for b in range(B):
                for c in range(C):
                    v = fc[c, j, b]
                    if v:
                        out.append(f"{ords[j]}{delim}{bl(j, b)}{delim}"
                                   f"{class_vals[c]}{delim}{v / total}")

        out.append("distribution:featurePairClass")
        for p, (i, j) in enumerate(zip(pair_i, pair_j)):
            for b1 in range(B):
                for b2 in range(B):
                    for c in range(C):
                        v = pc[p, b1, b2, c]
                        if v:
                            out.append(
                                f"{ords[i]}{delim}{ords[j]}{delim}{bl(i, b1)}"
                                f"{delim}{bl(j, b2)}{delim}{class_vals[c]}"
                                f"{delim}{v / total}")

        out.append("distribution:featureClassConditional")
        for j in range(F):
            for c in range(C):
                for b in range(B):
                    v = fc[c, j, b]
                    if v:
                        out.append(f"{ords[j]}{delim}{class_vals[c]}{delim}"
                                   f"{bl(j, b)}{delim}{v / class_counts[c]}")

        out.append("distribution:featurePairClassConditional")
        for p, (i, j) in enumerate(zip(pair_i, pair_j)):
            for c in range(C):
                for b1 in range(B):
                    for b2 in range(B):
                        v = pc[p, b1, b2, c]
                        if v:
                            out.append(
                                f"{ords[i]}{delim}{ords[j]}{delim}"
                                f"{class_vals[c]}{delim}{bl(i, b1)}{delim}"
                                f"{bl(j, b2)}{delim}{v / class_counts[c]}")

        # ---- mutual information ----
        score = MutualInformationScore()

        out.append("mutualInformation:feature")
        for j in range(F):
            s = 0.0
            for b in range(B):
                if not feat[j, b]:
                    continue
                fp = feat[j, b] / total
                for c in range(C):
                    v = fc[c, j, b]
                    if v:
                        jp = v / total
                        s += jp * math.log(jp / (fp * class_counts[c] / total))
            out.append(f"{ords[j]}{delim}{s}")
            score.feature_mi.append((ords[j], s))

        out.append("mutualInformation:featurePair")
        for p, (i, j) in enumerate(zip(pair_i, pair_j)):
            s = 0.0
            for b1 in range(B):
                if not feat[i, b1]:
                    continue
                p1 = feat[i, b1] / total
                for b2 in range(B):
                    if not feat[j, b2]:
                        continue
                    p2 = feat[j, b2] / total
                    v = pair[p, b1, b2]
                    if v:
                        jp = v / total
                        s += jp * math.log(jp / (p1 * p2))
            out.append(f"{ords[i]}{delim}{ords[j]}{delim}{s}")
            score.pair_mi.append((ords[i], ords[j], s))

        out.append("mutualInformation:featurePairClass")
        for p, (i, j) in enumerate(zip(pair_i, pair_j)):
            s = 0.0
            ent = 0.0
            for b1 in range(B):
                for b2 in range(B):
                    jf = pair[p, b1, b2]
                    if not jf:
                        continue
                    jfp = jf / total
                    for c in range(C):
                        v = pc[p, b1, b2, c]
                        if v:
                            jp = v / total
                            s += jp * math.log(
                                jp / (jfp * class_counts[c] / total))
                            ent -= jp * math.log(jp)
            out.append(f"{ords[i]}{delim}{ords[j]}{delim}{s}")
            score.pair_class_mi.append((ords[i], ords[j], s))
            score.pair_class_entropy.append((ords[i], ords[j], ent))

        out.append("mutualInformation:featurePairClassConditional")
        for p, (i, j) in enumerate(zip(pair_i, pair_j)):
            total_s = 0.0
            for c in range(C):
                cp = class_counts[c] / total
                s = 0.0
                for b1 in range(B):
                    v1 = fc[c, i, b1]
                    if not v1:
                        continue
                    # reference normalizes class-conditional marginals by
                    # TOTAL count here (MutualInformation.java:759-762)
                    p1 = v1 / total
                    for b2 in range(B):
                        v2 = fc[c, j, b2]
                        if not v2:
                            continue
                        p2 = v2 / total
                        v = pc[p, b1, b2, c]
                        if v:
                            jp = v / total
                            s += cp * (jp * math.log(jp / (p1 * p2)))
                total_s += s
            out.append(f"{ords[i]}{delim}{ords[j]}{delim}{total_s}")

        # ---- scores ----
        algos = cfg.get("mutual.info.score.algorithms",
                        "mutual.info.maximization").split(",")
        rf = cfg.get_float("mutual.info.redundancy.factor", 1.0)
        for alg in algos:
            out.append(f"mutualInformationScoreAlgorithm: {alg}")
            fn = _ALGOS.get(alg)
            if fn is None:
                continue
            for f, v in fn(score, rf):
                out.append(f"{f}{delim}{v}")
        return out


class _MIFoldSpec(MultiScanFoldSpec):
    """Shared-scan FoldSpec for MutualInformation: shares the schema
    encode (and H2D copy) with co-registered jobs on the same schema
    file, folds both distribution tables on device, finalizes to the
    normal distributions/MI/scores output file.

  Split invariance (fold(A ++ B) == merge_carries(fold(A),
    fold(B)), any chunk boundaries/order) is property-tested at
    mesh=1 and 8-way by the fold-algebra verifier
    (core.algebra, tests/test_algebra.py) — the ROADMAP-1
    multi-host psum contract this spec must keep.
    """

    def __init__(self, job: "MutualInformation", out_path: str):
        self.job = job
        self.out_path = out_path
        self.name = type(job).__name__
        self.local_fn = _mi_local
        self.static_args: tuple = ()
        self.enc = DatasetEncoder(job.schema)
        self.delim = job.config.field_delim_out()
        self.st: Optional[_MIStreamState] = None

    def bind(self, engine) -> None:
        import os
        sp = self.job.config.get("feature.schema.file.path")
        if sp:
            self.enc = engine.shared_encoder(
                ("schema-encoder", os.path.abspath(sp)), self.enc)

    def encode(self, ctx):
        x, _, y, n = ctx.encoded(self.enc)
        if self.st is None:
            self.st = _MIStreamState(self.enc)
        out = self.st.accept(x, y, n)
        if out is not None and not self.st.caps:
            self.st.size_caps()
            check_pair_table_budget(self.job.config, self.st.F,
                                    self.st.caps["B"], self.st.caps["C"])
            self.static_args = (self.st.caps["C"], self.st.caps["B"],
                                self.st.pair_i, self.st.pair_j)
        return out

    def finalize(self, carry) -> Counters:
        counters = Counters()
        counters.set("Basic", "Records", self.st.n_rows)
        lines = self.job._streamed_lines(self.enc, self.st, carry,
                                         self.delim, self.job.config)
        write_output(self.out_path, lines)
        return counters

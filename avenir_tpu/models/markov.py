"""Markov-chain models: transition trainer, classifier, HMM builder, Viterbi.

Reference surface (citations into /root/reference/src/main/java/org/avenir/):
- ``markov.MarkovStateTransitionModel`` — counts (class?, from, to) state
  transitions over each row's trailing state sequence
  (MarkovStateTransitionModel.java:116-133), row-normalizes to scaled ints
  with whole-row Laplace correction and writes one row per line with an
  optional state-list header (:202-242).
- ``markov.MarkovModelClassifier`` — map-only: per sequence accumulates
  ``log(P_c0[from,to] / P_c1[from,to])`` and thresholds
  (MarkovModelClassifier.java:127-150).
- ``markov.HiddenMarkovModelBuilder`` — counts STATE_TRANS / STATE_OBS /
  INITIAL_STATE families from fully-tagged ``obs:state`` items
  (HiddenMarkovModelBuilder.java:136-166) or partially-tagged rows with a
  distance-decay window function (:174-260); serialized model = states line,
  observations line, A rows, B rows, pi row (:309-343).  NOTE: the initial
  state vector keeps the default scale 100 (the reference never calls
  setScale on it — :304-306) while A and B use ``trans.prob.scale``.
- ``markov.ViterbiStatePredictor`` + ``ViterbiDecoder`` — map-only Viterbi
  max-product forward pass + backtrack per record (ViterbiDecoder.java:66-143).

TPU re-design: sequences are vocab-encoded and padded into an int32
``[n, Lmax]`` matrix; transition counting is one ``count_table`` scatter over
all adjacent pairs under the sharded-reduce skeleton; Viterbi runs as a
``lax.scan`` over time on the whole row batch at once (the reference's
O(T·S^2) per-record loop becomes a batched [n, S] dynamic program).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import JobConfig
from ..core.multiscan import FoldSpec as MultiScanFoldSpec
from ..core.obs import get_tracer, traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters
from ..core.tabular import deserialize_matrix, normalize_rows, serialize_matrix
from ..ops.counting import count_table, sharded_reduce


# ---------------------------------------------------------------------------
# sequence ingest
# ---------------------------------------------------------------------------

def encode_sequences(records: Sequence[Sequence[str]], skip: int,
                     vocab: Dict[str, int],
                     strict: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Encode each record's trailing items as vocab ids, padded with -1.

    Returns (seq int32 [n, Lmax], lengths int32 [n]).  Unknown symbols raise
    (strict) or map to -1.
    """
    n = len(records)
    lengths = np.asarray([max(0, len(r) - skip) for r in records], dtype=np.int32)
    lmax = int(lengths.max()) if n else 0
    seq = np.full((n, lmax), -1, dtype=np.int32)
    for i, r in enumerate(records):
        for t, sym in enumerate(r[skip:]):
            if strict and sym not in vocab:
                raise KeyError(f"unknown state/observation symbol: {sym!r}")
            seq[i, t] = vocab.get(sym, -1)
    return seq, lengths


def _transition_pairs(seq: np.ndarray):
    """(from, to) index arrays for every adjacent pair; -1-padded cells
    self-mask in count_table."""
    return seq[:, :-1], seq[:, 1:]


# Module-level local_fns (cache-friendly; see ops.counting._compiled_reduce).
def _markov_local(frm, to, cls, mask, n_class, n_states):
    m = mask[:, None]
    if n_class > 0:
        c = jnp.broadcast_to(cls[:, None], frm.shape)
        return count_table((n_class, n_states, n_states), (c, frm, to), mask=m)
    return count_table((n_states, n_states), (frm, to), mask=m)


def _markov_pair_local(frm, to, cls, mask, n_class, n_states):
    """Streaming-fold twin of ``_markov_local`` over FLATTENED 1-D
    transition-pair streams (row-major, so chunk shapes bucket by pair
    count instead of recompiling per ragged sequence length); -1 padding
    cells self-mask via the count_table range drop."""
    if n_class > 0:
        return count_table((n_class, n_states, n_states), (cls, frm, to),
                           mask=mask)
    return count_table((n_states, n_states), (frm, to), mask=mask)


def _mmc_pair_log_odds(frm, to, valid, t0, t1):
    """Per-row log-odds ``sum log(P_c0[from,to] / P_c1[from,to])`` over a
    sequence batch, with invalid (-1 padded) cells contributing exact 0 —
    module-level so the jitted scorer is shared (and compile-cached)
    between the batch classifier job and the serving engine's bucketed
    scorer.

    The row sum runs as an ORDERED left-to-right ``lax.scan`` rather than
    an axis reduction: a reduction's association (and therefore its
    rounding) may change with the padded extent, while the scan's
    sequential order means appended padding terms — exact +0.0 — can
    never perturb a score.  That padding invariance is what lets the
    serving batcher pad rows/lengths to power-of-two buckets and still
    return byte-identical lines to the batch job (tests/test_serve.py),
    while only n floats (not the [n, L] pair matrix) leave the device."""
    f = jnp.where(valid, frm, 0)
    t = jnp.where(valid, to, 0)
    lo = jnp.where(valid, jnp.log(t0[f, t] / t1[f, t]), 0.0)

    def step(acc, col):
        return acc + col, None

    total, _ = jax.lax.scan(
        step, jnp.zeros(lo.shape[0], lo.dtype), lo.T)
    return total


def _hmm_local(frm, to, obs_s, obs_o, init_s, mask, S, O):
    m = mask[:, None]
    return {
        "trans": count_table((S, S), (frm, to), mask=m),
        "obs": count_table((S, O), (obs_s, obs_o), mask=m),
        "init": count_table((S,), (init_s,), mask=mask),
    }


# ---------------------------------------------------------------------------
# Markov transition model trainer
# ---------------------------------------------------------------------------

class MarkovStateTransitionModel:
    """Trainer job; config prefix ``mst`` with un-prefixed fallback
    (MarkovStateTransitionModel.java:73-75)."""

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("mst") if not config.prefix else config

    # rough pair-stream bytes per input row for device-budget chunk sizing
    # (3 int32 streams x ~8 transitions)
    _BUDGET_ROW_BYTES = 96

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        states = cfg.must("model.states").split(",")
        vocab = {s: i for i, s in enumerate(states)}
        S = len(states)
        skip = cfg.get_int("skip.field.count", 0)
        class_ord = cfg.get_int("class.label.field.ord", -1)
        scale = cfg.get_int("trans.prob.scale", 1000)
        output_states = cfg.get_boolean("output.states", True)
        # class label occupies one leading field when present (:107-109)
        eff_skip = skip + (1 if class_ord >= 0 else 0)

        tracer = get_tracer()
        chunk_rows = cfg.pipeline_chunk_rows(row_bytes=self._BUDGET_ROW_BYTES)
        counted = None
        if chunk_rows is not None:
            with tracer.span("phase:train"):
                counted = self._count_streamed(
                    in_path, delim_regex, vocab, S, eff_skip, class_ord,
                    chunk_rows, cfg.pipeline_prefetch_depth(), mesh)
        if counted is not None:
            counts, class_labels = counted
        else:
            with tracer.span("phase:train"):
                records = [split_line(l, delim_regex)
                           for l in read_lines(in_path)]
                # reference mapper skips rows too short to hold a
                # transition (:119)
                records = [r for r in records if len(r) >= eff_skip + 2]
                class_labels = []
                cls_idx = np.zeros(len(records), dtype=np.int32)
                if class_ord >= 0:
                    seen: Dict[str, int] = {}
                    for i, r in enumerate(records):
                        lbl = r[class_ord]
                        if lbl not in seen:
                            seen[lbl] = len(seen)
                            class_labels.append(lbl)
                        cls_idx[i] = seen[lbl]
                seq, _ = encode_sequences(records, eff_skip, vocab)
                if seq.shape[1] < 2:
                    counts = (np.zeros((len(class_labels), S, S),
                                       dtype=np.int64)
                              if class_ord >= 0
                              else np.zeros((S, S), dtype=np.int64))
                else:
                    frm, to = _transition_pairs(seq)
                    counts = np.asarray(sharded_reduce(
                        _markov_local, frm, to, cls_idx, mesh=mesh,
                        static_args=(len(class_labels)
                                     if class_ord >= 0 else 0, S)))

        with tracer.span("phase:emit"):
            write_output(out_path, self._model_lines(
                counts, class_labels, states, scale, output_states,
                class_ord))
        counters.set("Markov", "Transitions", int(counts.sum()))
        return counters

    @staticmethod
    def _model_lines(counts, class_labels, states, scale, output_states,
                     class_ord) -> List[str]:
        """Reference-format model lines (shared by ``run`` and the
        multi-scan FoldSpec)."""
        lines: List[str] = []
        if output_states:
            lines.append(",".join(states))
        if class_ord >= 0:
            for ci, lbl in enumerate(class_labels):
                lines.append(f"classLabel:{lbl}")
                lines.extend(
                    serialize_matrix(normalize_rows(counts[ci], scale)))
        else:
            lines.extend(serialize_matrix(normalize_rows(counts, scale)))
        return lines

    def fold_spec(self, out_path: str):
        """Export this trainer's shared-scan ``core.multiscan.FoldSpec``."""
        return _MarkovFoldSpec(self, out_path)

    def _count_streamed(self, in_path, delim_regex, vocab, S, eff_skip,
                        class_ord, chunk_rows, depth, mesh):
        """One streaming pass over row chunks: per chunk the trailing
        state sequences encode and flatten to 1-D (from, to, class) pair
        streams, folded through ``core.pipeline`` with a donated
        accumulator.  Class labels are discovered in input order exactly
        like the monolithic path (chunks are consumed sequentially); the
        class extent is capped after the first chunk — a label first
        appearing later overflows the cap and returns None, and the
        caller re-runs the monolithic path for identical output."""
        from ..core import ingestcache, pipeline
        from ..core.binning import ChunkedEncodeUnsupported

        # parse-once cache (core.ingestcache): the flattened (from, to,
        # class) pair streams are this job's entire parse product, so a
        # validated artifact replays them off mmap chunk-for-chunk with
        # the recorded class labels; any cap works as long as it covers
        # n_class — counts truncate to n_class either way, so warm output
        # is byte-identical to cold.  A miss tees this scan.
        pcache = ingestcache.PairStreamCache.from_config(
            self.config, in_path, list(vocab), eff_skip, class_ord,
            delim_regex)
        cached = pcache.load(chunk_rows) if pcache is not None else None
        if cached is not None:
            class_labels = list(cached.class_labels)
            n_class_cap = (max(len(class_labels), 1) + 2
                           if class_ord >= 0 else 0)
            counts = pipeline.streaming_fold(
                (tuple(np.asarray(a) for a in ch)
                 for ch in cached.chunks()),
                _markov_pair_local, static_args=(n_class_cap, S),
                mesh=mesh, prefetch_depth=depth)
            n_class = len(class_labels)
            if counts is None:
                counts = (np.zeros((n_class, S, S), dtype=np.int64)
                          if class_ord >= 0 else np.zeros((S, S), np.int64))
            elif class_ord >= 0:
                counts = counts[:n_class]
            return counts, class_labels
        builder = pcache.builder(chunk_rows) if pcache is not None else None

        class_labels: List[str] = []
        seen: Dict[str, int] = {}
        cap = [None]          # set after the first chunk is parsed

        def parsed():
            for lines in pipeline.iter_line_chunks(in_path, chunk_rows):
                records = [split_line(l, delim_regex) for l in lines]
                records = [r for r in records if len(r) >= eff_skip + 2]
                if not records:
                    continue
                cls_idx = np.zeros(len(records), dtype=np.int32)
                if class_ord >= 0:
                    for i, r in enumerate(records):
                        lbl = r[class_ord]
                        if lbl not in seen:
                            seen[lbl] = len(seen)
                            class_labels.append(lbl)
                        cls_idx[i] = seen[lbl]
                    if cap[0] is not None and len(class_labels) > cap[0]:
                        raise ChunkedEncodeUnsupported("late class label")
                seq, _ = encode_sequences(records, eff_skip, vocab)
                if seq.shape[1] < 2:
                    continue
                frm, to = _transition_pairs(seq)
                cls = np.repeat(cls_idx, frm.shape[1])
                out = (frm.ravel(), to.ravel(), cls)
                if builder is not None:
                    builder.add(*out)
                yield out

        try:
            first, stream = pipeline.peek(parsed())
            n_class_cap = 0
            if class_ord >= 0:
                # headroom covers stragglers; a genuinely late-appearing
                # label beyond it falls back
                cap[0] = n_class_cap = max(len(class_labels), 1) + 2
            counts = pipeline.streaming_fold(
                stream, _markov_pair_local, static_args=(n_class_cap, S),
                mesh=mesh, prefetch_depth=depth)
        except ChunkedEncodeUnsupported:
            if builder is not None:
                builder.abort()
            return None
        if builder is not None:
            builder.finish(class_labels)
        n_class = len(class_labels)
        if counts is None:
            counts = (np.zeros((n_class, S, S), dtype=np.int64)
                      if class_ord >= 0 else np.zeros((S, S), np.int64))
        elif class_ord >= 0:
            counts = counts[:n_class]
        return counts, class_labels


class _MarkovFoldSpec(MultiScanFoldSpec):
    """Shared-scan FoldSpec for the Markov transition trainer: each
    parsed chunk's trailing state sequences flatten to 1-D (from, to,
    class) pair streams (variable length -> power-of-two buckets, so
    ``fixed_capacity`` is False) folded by ``_markov_pair_local``; class
    labels are discovered in input order exactly like the standalone
    paths, with the same first-chunk class cap + fallback contract.

  Split invariance (fold(A ++ B) == merge_carries(fold(A),
    fold(B)), any chunk boundaries/order) is property-tested at
    mesh=1 and 8-way by the fold-algebra verifier
    (core.algebra, tests/test_algebra.py) — the ROADMAP-1
    multi-host psum contract this spec must keep.
    """

    fixed_capacity = False

    def __init__(self, job: "MarkovStateTransitionModel", out_path: str):
        cfg = job.config
        self.job = job
        self.out_path = out_path
        self.name = type(job).__name__
        self.local_fn = _markov_pair_local
        self.static_args: tuple = ()
        self.states = cfg.must("model.states").split(",")
        self.vocab = {s: i for i, s in enumerate(self.states)}
        self.S = len(self.states)
        skip = cfg.get_int("skip.field.count", 0)
        self.class_ord = cfg.get_int("class.label.field.ord", -1)
        self.eff_skip = skip + (1 if self.class_ord >= 0 else 0)
        self.scale = cfg.get_int("trans.prob.scale", 1000)
        self.output_states = cfg.get_boolean("output.states", True)
        self.class_labels: List[str] = []
        self._seen: Dict[str, int] = {}
        self._cap: Optional[int] = None

    def encode(self, ctx):
        from ..core.binning import ChunkedEncodeUnsupported

        records = [r for r in ctx.fields() if len(r) >= self.eff_skip + 2]
        if not records:
            return None
        cls_idx = np.zeros(len(records), dtype=np.int32)
        if self.class_ord >= 0:
            for i, r in enumerate(records):
                lbl = str(r[self.class_ord])
                if lbl not in self._seen:
                    self._seen[lbl] = len(self._seen)
                    self.class_labels.append(lbl)
                cls_idx[i] = self._seen[lbl]
            if self._cap is not None and len(self.class_labels) > self._cap:
                raise ChunkedEncodeUnsupported("late class label")
        seq, _ = encode_sequences(records, self.eff_skip, self.vocab)
        if seq.shape[1] < 2:
            return None
        if self._cap is None:
            # headroom covers stragglers; a genuinely late-appearing
            # label beyond it falls back (standalone re-run)
            n_class_cap = 0
            if self.class_ord >= 0:
                self._cap = n_class_cap = max(len(self.class_labels), 1) + 2
            self.static_args = (n_class_cap, self.S)
        frm, to = _transition_pairs(seq)
        cls = np.repeat(cls_idx, frm.shape[1])
        return frm.ravel(), to.ravel(), cls

    def finalize(self, carry) -> Counters:
        counters = Counters()
        counts = np.asarray(carry)
        if self.class_ord >= 0:
            counts = counts[:len(self.class_labels)]
        write_output(self.out_path, self.job._model_lines(
            counts, self.class_labels, self.states, self.scale,
            self.output_states, self.class_ord))
        counters.set("Markov", "Transitions", int(counts.sum()))
        return counters


# ---------------------------------------------------------------------------
# model + classifier
# ---------------------------------------------------------------------------

class MarkovModel:
    """Text-format model loader (markov/MarkovModel.java:38-65)."""

    def __init__(self, lines: List[str], class_label_based: bool):
        self.states = lines[0].split(",")
        S = len(self.states)
        self.index = {s: i for i, s in enumerate(self.states)}
        self.class_trans: Dict[str, np.ndarray] = {}
        self.trans: Optional[np.ndarray] = None
        i = 1
        if class_label_based:
            while i < len(lines):
                if lines[i].startswith("classLabel"):
                    label = lines[i].split(":")[1]
                    i += 1
                    self.class_trans[label] = deserialize_matrix(lines[i:i + S], S)
                    i += S
                else:  # pragma: no cover - malformed files mirror Java behavior
                    raise ValueError(f"unexpected model line: {lines[i]}")
        else:
            self.trans = deserialize_matrix(lines[1:1 + S], S)

    @classmethod
    def load(cls, path: str, class_label_based: bool) -> "MarkovModel":
        return cls(list(read_lines(path)), class_label_based)


# ---------------------------------------------------------------------------
# transaction -> state conversion + marketing plan (L0 resource scripts)
# ---------------------------------------------------------------------------

MARKETING_STATES = ["SL", "SE", "SG", "ML", "ME", "MG", "LL", "LE", "LG"]


def _pair_state(pr_date, pr_amt: int, date, amt: int) -> str:
    """One (prev, cur) transaction pair -> 2-letter state: days-gap letter
    S/M/L x amount-trend letter L/E/G (resource/xaction_state.rb:24-39)."""
    days = (date - pr_date).days
    dd = "S" if days < 30 else ("M" if days < 60 else "L")
    ad = "L" if pr_amt < 0.9 * amt else ("E" if pr_amt < 1.1 * amt else "G")
    return dd + ad


def _group_xactions(rows):
    """Group custID,xid,date,amount rows into per-customer (date, amount)
    histories preserving input order (resource/xaction_seq.rb:9-19)."""
    import datetime

    hist: Dict[str, list] = {}
    for items in rows:
        hist.setdefault(items[0], []).append(
            (datetime.date.fromisoformat(items[2]), int(items[3])))
    return hist


def xactions_to_state_seqs(rows) -> List[List[str]]:
    """resource/xaction_seq.rb equivalent: raw transactions -> one
    ``custID,state,state,...`` row per customer with >= 2 transactions —
    the Markov trainer's input format."""
    out = []
    for cid, hist in _group_xactions(rows).items():
        seq = [_pair_state(*hist[i - 1], *hist[i])
               for i in range(1, len(hist))]
        if seq:
            out.append([cid] + seq)
    return out


def projected_to_histories(rows) -> Dict[str, list]:
    """Parse compact chombo-Projection output rows
    ``custID,date1,amt1,date2,amt2,...`` (projection.field=2,3 +
    format.compact=true per resource/buyhist.properties:6-11, already
    time-ordered by the projection) into per-customer (date, amount)
    histories — the same shape ``_group_xactions`` builds from raw rows."""
    import datetime

    return {items[0]: [(datetime.date.fromisoformat(items[i]),
                        int(items[i + 1]))
                       for i in range(1, len(items) - 1, 2)]
            for items in rows}


def projected_to_state_seqs(rows) -> List[List[str]]:
    """resource/xaction_seq.rb equivalent for the chombo Projection leg
    (cust_churn_markov_chain tutorial:26-45): compact projected rows ->
    one ``custID,state,state,...`` row per customer with >= 2
    transactions."""
    out = []
    for cid, hist in projected_to_histories(rows).items():
        seq = [_pair_state(*hist[i - 1], *hist[i])
               for i in range(1, len(hist))]
        if seq:
            out.append([cid] + seq)
    return out


def marketing_next_dates(rows, model: "MarkovModel") -> List[str]:
    """resource/mark_plan.rb:39-92 equivalent over raw transaction rows."""
    return marketing_next_dates_from_histories(_group_xactions(rows), model)


def marketing_next_dates_from_histories(histories: Dict[str, list],
                                        model: "MarkovModel") -> List[str]:
    """resource/mark_plan.rb:39-92 equivalent: per customer, map the last
    observed transaction state through the trained (non-class) transition
    matrix, take the most likely next state, and schedule the next
    marketing contact 15/45/90 days after the last transaction depending on
    the predicted gap letter.  Emits ``custID,ISO-date`` lines.  Histories
    are per-customer time-ordered (date, amount) lists — from
    ``_group_xactions`` (raw rows) or ``projected_to_histories``
    (Projection-job output)."""
    import datetime

    trans = model.trans
    assert trans is not None, "marketing plan needs a non-class-based model"
    out = []
    for cid, hist in histories.items():
        if len(hist) < 2:
            continue
        last_state = _pair_state(*hist[-2], *hist[-1])
        row = trans[model.index[last_state]]
        next_state = model.states[int(np.argmax(row))]
        gap = {"S": 15, "M": 45}.get(next_state[0], 90)
        next_date = hist[-1][0] + datetime.timedelta(days=gap)
        out.append(f"{cid},{next_date.isoformat()}")
    return out


class MarkovModelClassifier:
    """Map-only log-odds classifier, vectorized over the sequence batch.

    The scoring core is exposed as :meth:`classify_records` so the online
    serving engine (``avenir_tpu.serve``) runs the IDENTICAL code path the
    batch job does: the jitted scorer is the module-level
    ``_mmc_pair_log_odds`` (one compile per padded shape, shareable through
    a caller-supplied compiled function), whose ordered row sum makes
    scores invariant to the serving engine's bucket padding."""

    def __init__(self, config: JobConfig):
        self.config = config
        self._prepared = False

    def _prepare(self) -> None:
        """Parse config + load the model once (idempotent) — the serving
        registry constructs the classifier at model-load time and calls
        ``classify_records`` per micro-batch."""
        if self._prepared:
            return
        cfg = self.config
        self.skip = cfg.get_int("skip.field.count", 1)
        self.id_ord = cfg.get_int("id.field.ord", 0)
        class_based = cfg.get_boolean("class.label.based.model", False)
        self.validation = cfg.get_boolean("validation.mode", False)
        self.class_ord = -1
        if self.validation:
            self.skip += 1
            self.class_ord = cfg.get_int("class.label.field.ord", -1)
            if self.class_ord < 0:
                raise ValueError(
                    "In validation mode actual class labels must be provided")
        self.model = MarkovModel.load(cfg.must("mm.model.path"), class_based)
        self.class_labels = cfg.must("class.labels").split(",")
        self.threshold = cfg.get_float("log.odds.threshold", 0.0)
        # mmc.score.precision=float32 casts the transition tables (and so
        # the whole log-odds sum) to f32 — the fast serving VARIANT of
        # this classifier.  Batch and serve share this code path, so a
        # batch run with the same key is byte-identical to the variant's
        # online responses (asserted in tests/test_pool.py).
        self.score_precision = cfg.get("mmc.score.precision", "float64")
        if self.score_precision not in ("float64", "float32"):
            raise ValueError(
                f"invalid mmc.score.precision: {self.score_precision}")
        dt = (jnp.float32 if self.score_precision == "float32"
              else jnp.float64)
        self._t0 = jnp.asarray(
            self.model.class_trans[self.class_labels[0]], dtype=dt)
        self._t1 = jnp.asarray(
            self.model.class_trans[self.class_labels[1]], dtype=dt)
        self._prepared = True

    def min_fields(self) -> int:
        """Shortest record the classifier can score (shorter rows are
        dropped by the batch job / rejected per-request by serving)."""
        self._prepare()
        return self.skip + 2

    def log_odds_scores(self, usable: List[List[str]], score_fn=None,
                        pad_rows_to: Optional[int] = None,
                        pad_len_to: Optional[int] = None) -> List[float]:
        """Log-odds per usable record.  ``pad_rows_to``/``pad_len_to`` pad
        the encoded [n, Lmax] sequence matrix with -1 (self-masking) up to
        a serving bucket so the jitted scorer hits a fixed set of compiled
        shapes; padding is score-invariant (masked cells contribute exact
        0.0 to the ordered scan sum — see ``_mmc_pair_log_odds``)."""
        self._prepare()
        if not usable:
            return []
        seq, _ = encode_sequences(usable, self.skip, self.model.index)
        n, L = seq.shape
        if pad_len_to is not None and pad_len_to > L:
            seq = np.concatenate(
                [seq, np.full((n, pad_len_to - L), -1, np.int32)], axis=1)
        if pad_rows_to is not None and pad_rows_to > n:
            seq = np.concatenate(
                [seq, np.full((pad_rows_to - n, seq.shape[1]), -1, np.int32)],
                axis=0)
        frm, to = _transition_pairs(seq)
        valid = (frm >= 0) & (to >= 0)
        fn = score_fn if score_fn is not None else jax.jit(_mmc_pair_log_odds)
        total = np.asarray(fn(frm, to, valid, self._t0, self._t1))
        return [float(v) for v in total[:n]]

    def classify_records(self, records: List[List[str]], counters: Counters,
                         score_fn=None, pad_rows_to: Optional[int] = None,
                         pad_len_to: Optional[int] = None) -> List[str]:
        """Classify pre-split records; returns output lines (records too
        short to hold a transition are dropped, as the reference mapper
        does)."""
        self._prepare()
        delim = self.config.field_delim_out()
        usable = [r for r in records if len(r) >= self.skip + 2]
        log_odds = self.log_odds_scores(usable, score_fn=score_fn,
                                        pad_rows_to=pad_rows_to,
                                        pad_len_to=pad_len_to)
        out: List[str] = []
        for i, r in enumerate(usable):
            pred = (self.class_labels[0] if log_odds[i] > self.threshold
                    else self.class_labels[1])
            parts = [r[self.id_ord]]
            if self.validation:
                parts.append(r[self.class_ord])
                if r[self.class_ord] == pred:
                    counters.incr("Validation", "Correct")
                else:
                    counters.incr("Validation", "Incorrect")
            parts += [pred, repr(float(log_odds[i]))]
            out.append(delim.join(parts))
        return out

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        records = [split_line(l, self.config.field_delim_regex())
                   for l in read_lines(in_path)]
        out = self.classify_records(records, counters)
        write_output(out_path, out)
        return counters


# ---------------------------------------------------------------------------
# HMM builder
# ---------------------------------------------------------------------------

class HiddenMarkovModelBuilder:
    """Builds A/B/pi from tagged sequences; model text format per
    HiddenMarkovModelBuilder.java:309-343."""

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        sub_delim = cfg.get("sub.field.delim", ":")
        skip = cfg.get_int("skip.field.count", 0)
        states = cfg.must("model.states").split(",")
        observations = cfg.must("model.observations").split(",")
        scale = cfg.get_int("trans.prob.scale", 1000)
        partially = cfg.get_boolean("partially.tagged", False)
        s_vocab = {s: i for i, s in enumerate(states)}
        o_vocab = {o: i for i, o in enumerate(observations)}
        S, O = len(states), len(observations)

        records = [split_line(l, delim_regex) for l in read_lines(in_path)]
        if partially:
            trans_c, obs_c, init_c = self._count_partially_tagged(
                records, states, s_vocab, o_vocab, cfg)
        else:
            trans_c, obs_c, init_c = self._count_fully_tagged(
                records, skip, sub_delim, s_vocab, o_vocab, S, O, mesh)

        lines: List[str] = [",".join(states), ",".join(observations)]
        lines.extend(serialize_matrix(normalize_rows(trans_c, scale)))
        lines.extend(serialize_matrix(normalize_rows(obs_c, scale)))
        # initial vector keeps the reference's default scale of 100
        lines.extend(serialize_matrix(normalize_rows(init_c[None, :], 100)))
        write_output(out_path, lines)
        counters.set("HMM", "Transitions", int(trans_c.sum()))
        counters.set("HMM", "Emissions", int(obs_c.sum()))
        return counters

    def _count_fully_tagged(self, records, skip, sub_delim, s_vocab, o_vocab,
                            S, O, mesh):
        """Device path: encode (state, obs) streams, count three families."""
        st_rows, ob_rows = [], []
        for r in records:
            if len(r) < skip + 2:
                st_rows.append([]); ob_rows.append([])
                continue
            st, ob = [], []
            for item in r[skip:]:
                o, s = item.split(sub_delim)
                st.append(s); ob.append(o)
            st_rows.append(st); ob_rows.append(ob)
        st_seq, _ = encode_sequences(st_rows, 0, s_vocab)
        ob_seq, _ = encode_sequences(ob_rows, 0, o_vocab)
        frm, to = st_seq[:, :-1], st_seq[:, 1:]
        init = st_seq[:, 0] if st_seq.shape[1] else np.zeros(0, np.int32)
        res = sharded_reduce(_hmm_local, frm, to, st_seq, ob_seq, init,
                             mesh=mesh, static_args=(S, O))
        return (np.asarray(res["trans"], dtype=np.int64),
                np.asarray(res["obs"], dtype=np.int64),
                np.asarray(res["init"], dtype=np.int64))

    def _count_partially_tagged(self, records, states, s_vocab, o_vocab, cfg):
        """Host path: the distance-decay window logic of
        HiddenMarkovModelBuilder.java:174-260 (including its asymmetric
        window arithmetic) is inherently per-row sequential; rows are few in
        this mode and counting stays exact on host."""
        window = [int(v) for v in cfg.must("window.function").split(",")]
        S, O = len(s_vocab), len(o_vocab)
        trans_c = np.zeros((S, S), dtype=np.int64)
        obs_c = np.zeros((S, O), dtype=np.int64)
        init_c = np.zeros(S, dtype=np.int64)
        state_set = set(states)
        for items in records:
            sidx = [i for i, it in enumerate(items) if it in state_set]
            if not sidx:
                continue
            init_c[s_vocab[items[sidx[0]]]] += 1
            for i, si in enumerate(sidx):
                # reference operator-precedence quirks preserved:
                # left = s[i] - s[i-1]/2 ; right = s[i+1] - s[i]/2
                if i > 0:
                    lw = sidx[i] - sidx[i - 1] // 2
                    lb = sidx[i] - lw
                else:
                    lb = -1
                if i < len(sidx) - 1:
                    rw = sidx[i + 1] - sidx[i] // 2
                    rb = sidx[i] + rw
                else:
                    rb = -1
                if lb == -1 and rb != -1:
                    lb = max(sidx[i] - rw, 0)
                elif rb == -1 and lb != -1:
                    rb = min(sidx[i] + lw, len(items) - 1)
                elif lb == -1 and rb == -1:
                    lb = sidx[i] // 2
                    rb = sidx[i] + (len(items) - 1 - sidx[i]) // 2
                s = s_vocab[items[si]]
                for j, k in zip(range(si - 1, lb - 1, -1), range(10 ** 9)):
                    if items[j] in o_vocab:
                        w = window[k] if k < len(window) else window[-1]
                        obs_c[s, o_vocab[items[j]]] += w
                for j, k in zip(range(si + 1, rb + 1), range(10 ** 9)):
                    if items[j] in o_vocab:
                        w = window[k] if k < len(window) else window[-1]
                        obs_c[s, o_vocab[items[j]]] += w
            for a, b in zip(sidx[:-1], sidx[1:]):
                trans_c[s_vocab[items[a]], s_vocab[items[b]]] += 1
        return trans_c, obs_c, init_c


# ---------------------------------------------------------------------------
# HMM model + Viterbi
# ---------------------------------------------------------------------------

class HiddenMarkovModel:
    """Text-format HMM loader (markov/HiddenMarkovModel.java:46-70)."""

    def __init__(self, lines: List[str]):
        self.states = lines[0].split(",")
        self.observations = lines[1].split(",")
        S, O = len(self.states), len(self.observations)
        self.trans = deserialize_matrix(lines[2:2 + S], S)
        self.obs = deserialize_matrix(lines[2 + S:2 + 2 * S], S)
        self.initial = np.asarray([float(v) for v in lines[2 + 2 * S].split(",")])
        self.obs_index = {o: i for i, o in enumerate(self.observations)}

    @classmethod
    def load(cls, path: str) -> "HiddenMarkovModel":
        return cls(list(read_lines(path)))


def viterbi_batch(obs_idx: jnp.ndarray, lengths: jnp.ndarray,
                  trans: jnp.ndarray, emit: jnp.ndarray,
                  initial: jnp.ndarray) -> jnp.ndarray:
    """Batched max-product Viterbi: ``lax.scan`` over time on [n, S] path
    scores (the reference's per-record O(T*S^2) loop,
    ViterbiDecoder.java:66-105, over the whole row batch at once).

    Padded steps (obs == -1 at t >= length) freeze the path scores and write
    backpointers that keep the argmax stable.  Returns decoded state ids
    [n, T] (forward order), -1 on padding.

    Scores accumulate in LOG space: the reference multiplies raw scaled-int
    probabilities (ViterbiDecoder.java:91 — a product that overflows even
    double for the tutorial's 210-day sequences); log-sum decoding picks the
    identical argmax path at any length.
    """
    n, T = obs_idx.shape
    S = trans.shape[0]
    obs_safe = jnp.where(obs_idx >= 0, obs_idx, 0)
    ltrans = jnp.log(trans)
    lemit = jnp.log(emit)
    linit = jnp.log(initial)

    def step(carry, t):
        path = carry                                  # [n, S] log scores
        o = obs_safe[:, t]
        active = (t < lengths) & (t > 0)
        # candidate[n, s] = max_p path[n, p] + ltrans[p, s]
        cand = path[:, :, None] + ltrans[None, :, :]  # [n, S, S]
        best_p = jnp.argmax(cand, axis=1)             # first max, as in Java
        best = jnp.max(cand, axis=1)
        new_path = best + lemit[:, o].T               # [n, S]
        path = jnp.where(active[:, None], new_path, path)
        ptr = jnp.where(active[:, None], best_p, -1)
        return path, ptr

    init_path = linit[None, :] + lemit[:, obs_safe[:, 0]].T
    path, ptrs = jax.lax.scan(step, init_path, jnp.arange(T))
    ptrs = jnp.moveaxis(ptrs, 0, 1)                   # [n, T, S]

    last = jnp.argmax(path, axis=1)                   # [n]

    def back(carry, t):
        nxt = carry                                   # [n]
        tt = T - 1 - t
        use = tt < lengths
        prev = jnp.take_along_axis(ptrs[:, tt, :], nxt[:, None], axis=1)[:, 0]
        state_here = jnp.where(use, nxt, -1)
        nxt = jnp.where(use & (tt > 0), prev, nxt)
        return nxt, state_here

    # rev column t holds the state at position T-1-t (padding already -1),
    # so a flip yields forward order with -1 exactly at t >= length
    _, rev = jax.lax.scan(back, last, jnp.arange(T))
    return jnp.flip(jnp.moveaxis(rev, 0, 1), axis=1)


class ViterbiStatePredictor:
    """Map-only decoding job (ViterbiStatePredictor.java:77-152)."""

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        delim = cfg.field_delim_out()
        skip = cfg.get_int("skip.field.count", 1)
        id_ord = cfg.get_int("id.field.ordinal", 0)
        state_only = cfg.get_boolean("output.state.only", True)
        sub_delim = cfg.get("sub.field.delim", ":")
        model = HiddenMarkovModel.load(cfg.must("hmm.model.path"))

        records = [split_line(l, delim_regex) for l in read_lines(in_path)]
        obs_idx, lengths = encode_sequences(records, skip, model.obs_index)
        decoded = np.asarray(jax.jit(viterbi_batch)(
            jnp.asarray(obs_idx), jnp.asarray(lengths),
            jnp.asarray(model.trans), jnp.asarray(model.obs),
            jnp.asarray(model.initial)))

        out: List[str] = []
        for i, r in enumerate(records):
            L = int(lengths[i])
            parts = [r[id_ord]]
            for t in range(L):
                s = model.states[int(decoded[i, t])]
                if state_only:
                    parts.append(s)
                else:
                    parts.append(f"{r[skip + t]}{sub_delim}{s}")
            out.append(delim.join(parts))
            counters.incr("Viterbi", "Decoded")
        write_output(out_path, out)
        return counters

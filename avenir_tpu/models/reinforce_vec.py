"""Vectorized multi-learner engine: a fleet of per-entity learners stepped
as ONE jitted call.

The scalar library (models.reinforce) mirrors the reference's per-object
learners (reinforce/ReinforcementLearner.java:35-167 and subclasses), and
``ReinforcementLearnerGroup`` holds one per entity
(reinforce/ReinforcementLearnerGroup.java:30-70).  With thousands of
entities that map is a host Python loop per event — the bottleneck SURVEY
§7.2 stage 7 commits to removing with "vectorized pure-JAX state + grouped
vmap selections".  This module keeps the SAME learner math as dense
``[group, action]`` arrays:

- state: per-arm trial counts, reward (count, sum) running stats
  (SimpleStat's consumed surface), per-group total trial counts — all JAX
  arrays advanced inside one ``lax.scan`` per ``next_actions`` call;
- ``upperConfidenceBoundOne`` is bit-faithful to the scalar learner
  (deterministic: same scores, same first-max/first-min tie order, same
  min-trial bootstrap) — the parity test locks it step-for-step;
- ``randomGreedy`` matches the exploit path exactly; exploration draws come
  from ``jax.random`` instead of each learner's NumPy generator, so
  per-entity random streams differ from the scalar library while remaining
  distributionally identical (same ε schedule, same uniform arm choice);
- ``softMax`` reproduces the per-group temperature-decay state machine
  (probabilities recomputed only after a reward arrives, decay divisor
  ``total - min_trial`` with the raw -1 default — SoftMaxLearner.java:79-109)
  with ``jax.random.categorical`` sampling.

Rewards are applied in bulk (``set_rewards`` takes index arrays), so a full
streaming round over G entities is two device dispatches total.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .reinforce import _cfg, _cfg_float, _cfg_int

_SUPPORTED = ("upperConfidenceBoundOne", "randomGreedy", "softMax")


class VectorizedLearnerGroup:
    """Dense [group, action] replacement for a ``ReinforcementLearnerGroup``
    whose learners all share one type + config."""

    def __init__(self, learner_type: str, group_ids: Sequence[str],
                 action_ids: Sequence[str], config: Optional[Dict] = None):
        if learner_type not in _SUPPORTED:
            raise ValueError(
                f"unsupported vectorized learner type {learner_type!r}; "
                f"supported: {', '.join(_SUPPORTED)} (use the scalar "
                "ReinforcementLearnerGroup for the others)")
        config = config or {}
        self.learner_type = learner_type
        self.group_ids = list(group_ids)
        self.action_ids = list(action_ids)
        self._gindex = {g: i for i, g in enumerate(self.group_ids)}
        self._aindex = {a: i for i, a in enumerate(self.action_ids)}
        G, A = len(self.group_ids), len(self.action_ids)

        self.min_trial = _cfg_int(config, "min.trial", -1)
        self.batch_size = _cfg_int(config, "batch.size", 1)
        seed = _cfg_int(config, "random.seed", None)
        self._key = jax.random.PRNGKey(0 if seed is None else seed)

        # shared state (all types)
        self.trials = jnp.zeros((G, A), jnp.int32)       # Action.trial_count
        self.rcnt = jnp.zeros((G, A), jnp.int32)         # SimpleStat.count
        self.rsum = jnp.zeros((G, A), jnp.float32)       # SimpleStat.sum
        self.total = jnp.zeros((G,), jnp.int32)          # total_trial_count

        if learner_type == "upperConfidenceBoundOne":
            self.reward_scale = _cfg_int(config, "reward.scale", 100)
        else:
            self.reward_scale = _cfg_int(config, "reward.scale", 1)
        if learner_type == "randomGreedy":
            self.random_selection_prob = _cfg_float(
                config, "random.selection.prob", 0.5)
            self.prob_red_algorithm = _cfg(
                config, "prob.reduction.algorithm", "linear")
            if self.prob_red_algorithm not in ("none", "linear", "logLinear"):
                raise ValueError("Invalid probability reduction algorithm")
            self.prob_reduction_constant = _cfg_float(
                config, "prob.reduction.constant", 1.0)
            self.min_prob = _cfg_float(config, "min.prob", -1.0)
        if learner_type == "softMax":
            temp0 = self._temp0 = _cfg_float(config, "temp.constant", 100.0)
            self.min_temp_constant = _cfg_float(
                config, "min.temp.constant", -1.0)
            self.temp_red_algorithm = _cfg(
                config, "temp.reduction.algorithm", "linear")
            if self.temp_red_algorithm not in ("linear", "logLinear"):
                raise ValueError("Invalid temperature reduction algorithm")
            self.temp = jnp.full((G,), temp0, jnp.float32)
            self.probs = jnp.full((G, A), 1.0 / A, jnp.float32)
            self.rewarded = jnp.zeros((G,), bool)

        (self._step_fn, self._masked_fn,
         self._waved_fn) = self._build_step()

    # -- per-type step bodies (state advanced inside lax.scan) --------------

    def _build_step(self):
        A = len(self.action_ids)
        min_trial = self.min_trial
        ltype = self.learner_type

        def bootstrap(trials):
            """Least-tried arm while below min.trial
            (ReinforcementLearner.java:142-152); first-min tie order."""
            amin = jnp.argmin(trials, axis=1)
            take = (min_trial > 0) & (
                jnp.take_along_axis(trials, amin[:, None], 1)[:, 0]
                <= min_trial)
            return amin, take

        # Each body advances only the groups where ``active`` is True (the
        # streaming case: an entity's learner steps only when its event
        # arrives); the full-fleet scan passes active=ones.

        def ucb1_step(state, key, active):
            trials, rcnt, rsum, total = state
            total = total + active
            avg = jnp.where(rcnt > 0, rsum / jnp.maximum(rcnt, 1), 0.0)
            score = jnp.where(
                trials == 0, jnp.inf,
                avg + jnp.sqrt(2.0 * jnp.log(
                    jnp.maximum(total, 1).astype(jnp.float32))
                    [:, None] / jnp.maximum(trials, 1)))
            sel = jnp.argmax(score, axis=1)
            amin, take = bootstrap(trials)
            sel = jnp.where(take, amin, sel)
            trials = trials.at[jnp.arange(trials.shape[0]), sel].add(
                active.astype(jnp.int32))
            return (trials, rcnt, rsum, total), sel

        def random_greedy_step(state, key, active):
            trials, rcnt, rsum, total = state
            total = total + active
            t = jnp.maximum(total, 1).astype(jnp.float32)
            p0 = self.random_selection_prob
            if self.prob_red_algorithm == "none":
                cur = jnp.full_like(t, p0)
            elif self.prob_red_algorithm == "linear":
                cur = p0 * self.prob_reduction_constant / t
            else:   # logLinear
                cur = p0 * self.prob_reduction_constant * jnp.log(t) / t
            cur = jnp.minimum(cur, p0)
            if self.min_prob > 0:
                cur = jnp.maximum(cur, self.min_prob)
            ku, kr = jax.random.split(key)
            explore = jax.random.uniform(ku, t.shape) < cur
            rand_sel = jax.random.randint(kr, t.shape, 0, A)
            avg = jnp.where(rcnt > 0, rsum / jnp.maximum(rcnt, 1), 0.0)
            best = jnp.argmax(avg, axis=1)
            sel = jnp.where(explore, rand_sel, best)
            amin, take = bootstrap(trials)
            sel = jnp.where(take, amin, sel)
            trials = trials.at[jnp.arange(trials.shape[0]), sel].add(
                active.astype(jnp.int32))
            return (trials, rcnt, rsum, total), sel

        def softmax_step(state, key, active):
            trials, rcnt, rsum, total, temp, probs, rewarded = state
            total = total + active
            # a bootstrap step skips the whole sampler path — recompute,
            # rewarded-latch reset, AND temperature decay all live inside
            # the scalar learner's `if action is None` branch
            # (models.reinforce SoftMaxLearner.next_action)
            amin, take = bootstrap(trials)
            avg = jnp.where(rcnt > 0, rsum / jnp.maximum(rcnt, 1), 0.0)
            # recompute the sampler only where a reward arrived since the
            # last sampler-path step (SoftMaxLearner.java:74-89 latch)
            shifted = (avg - avg.max(axis=1, keepdims=True)) \
                / temp[:, None]
            fresh = jax.nn.softmax(shifted, axis=1)
            recompute = rewarded & ~take & active
            probs = jnp.where(recompute[:, None], fresh, probs)
            rewarded = rewarded & ~recompute
            sel = jax.random.categorical(key, jnp.log(probs), axis=1)
            sel = jnp.where(take, amin, sel)
            # temperature decay (SoftMaxLearner.java:96-109): divisor is
            # total - min_trial with min_trial's raw -1 default
            rnd = (total - self.min_trial).astype(jnp.float32)
            decay_on = (rnd > 1) & ~take & active
            if self.temp_red_algorithm == "linear":
                newt = temp / rnd
            else:   # logLinear
                newt = temp * jnp.log(jnp.maximum(rnd, 1.0)) / rnd
            if self.min_temp_constant > 0:
                newt = jnp.maximum(newt, self.min_temp_constant)
            newt = jnp.maximum(newt, 1e-12)   # underflow clamp (scalar lib)
            temp = jnp.where(decay_on, newt, temp)
            trials = trials.at[jnp.arange(trials.shape[0]), sel].add(
                active.astype(jnp.int32))
            return (trials, rcnt, rsum, total, temp, probs, rewarded), sel

        body = {"upperConfidenceBoundOne": ucb1_step,
                "randomGreedy": random_greedy_step,
                "softMax": softmax_step}[ltype]

        from functools import partial

        @partial(jax.jit, static_argnums=2)
        def steps(state, key, n_steps):
            def scan_body(st, k):
                ones = jnp.ones(st[0].shape[0], dtype=bool)
                return body(st, k, ones)
            keys = jax.random.split(key, n_steps)
            return jax.lax.scan(scan_body, state, keys)

        @partial(jax.jit, static_argnums=2)
        def masked_steps(state, key, n_steps, active):
            def scan_body(st, k):
                return body(st, k, active)
            keys = jax.random.split(key, n_steps)
            return jax.lax.scan(scan_body, state, keys)

        rscale = float(getattr(self, "reward_scale", 1)
                       if ltype == "upperConfidenceBoundOne" else 1)

        @partial(jax.jit, static_argnums=(2, 3))
        def waved_steps(state, key, n_steps, rb, packed):
            # ONE device call AND one host->device transfer per
            # streaming wave: ``packed`` is a single int32 array
            # [nr, nw, g[rb], a[rb], r[rb], rows[wb]] (through a
            # tunneled device every eager op / device_put is a serial
            # ~100 ms round trip, so the wave cost is the RPC count,
            # not bytes — the r4 loop spent ~0.7 s/wave on exactly
            # that).  It applies the bulk reward scatter (entries past
            # nr are weight-zero padding) THEN runs the masked steps —
            # the bolt's rewards-before-selection order — with the key
            # advancing inside the jit.
            nr, nw = packed[0], packed[1]
            g = packed[2:2 + rb]
            a = packed[2 + rb:2 + 2 * rb]
            r = packed[2 + 2 * rb:2 + 3 * rb].astype(jnp.float32) / rscale
            rows = packed[2 + 3 * rb:]
            w = (jnp.arange(rb) < nr).astype(jnp.float32)
            trials, rcnt, rsum, total = state[:4]
            rsum = rsum.at[g, a].add(r * w)
            rcnt = rcnt.at[g, a].add(w.astype(jnp.int32))
            state = (trials, rcnt, rsum, total) + tuple(state[4:])
            if ltype == "softMax":
                rewarded = state[6].at[g].max(w > 0)
                state = state[:6] + (rewarded,)
            # padding rows carry G (out of bounds) and drop
            active = jnp.zeros(trials.shape[0], bool).at[rows].set(
                True, mode="drop")
            del nw
            keys = jax.random.split(key, n_steps + 1)

            def scan_body(st, k):
                return body(st, k, active)
            state, sels = jax.lax.scan(scan_body, state, keys[1:])
            return keys[0], state, sels

        return steps, masked_steps, waved_steps

    def _state(self):
        if self.learner_type == "softMax":
            return (self.trials, self.rcnt, self.rsum, self.total,
                    self.temp, self.probs, self.rewarded)
        return (self.trials, self.rcnt, self.rsum, self.total)

    def _set_state(self, state):
        if self.learner_type == "softMax":
            (self.trials, self.rcnt, self.rsum, self.total,
             self.temp, self.probs, self.rewarded) = state
        else:
            (self.trials, self.rcnt, self.rsum, self.total) = state

    @property
    def capacity(self) -> int:
        """Row count of the state arrays (>= len(group_ids); the surplus
        rows are unenrolled capacity so growth doesn't recompile per id)."""
        return int(self.trials.shape[0])

    def rows_for(self, ids: Sequence[str]) -> List[int]:
        """State-array row indices for the given group ids."""
        return [self._gindex[g] for g in ids]

    def add_groups(self, new_ids: Sequence[str]) -> None:
        """Grow the fleet with fresh learners (zeroed state — identical to a
        newly constructed scalar learner).  Capacity grows in powers of two
        so steady enrollment recompiles the jitted step O(log N) times, not
        once per wave; unenrolled rows are inert (never active, never
        emitted)."""
        fresh = list(dict.fromkeys(
            g for g in new_ids if g not in self._gindex))
        if not fresh:
            return
        first_row = len(self.group_ids)
        for g in fresh:
            self._gindex[g] = len(self.group_ids)
            self.group_ids.append(g)
        if len(self.group_ids) > self.capacity:
            cap = max(8, self.capacity)
            while cap < len(self.group_ids):
                cap *= 2
            add = cap - self.capacity

            def pad(a, fill=0):
                return jnp.concatenate(
                    [a, jnp.full((add,) + a.shape[1:], fill, a.dtype)],
                    axis=0)

            self.trials = pad(self.trials)
            self.rcnt = pad(self.rcnt)
            self.rsum = pad(self.rsum)
            self.total = pad(self.total)
            if self.learner_type == "softMax":
                self.temp = pad(self.temp, self._temp0)
                self.probs = pad(self.probs, 1.0 / len(self.action_ids))
                self.rewarded = pad(self.rewarded, False)
        # explicitly zero the enrolled rows: surplus capacity rows are
        # advanced by full-fleet step() calls, so a recycled row must be
        # reset to honor the fresh-learner contract
        rows = jnp.arange(first_row, len(self.group_ids))
        self.trials = self.trials.at[rows].set(0)
        self.rcnt = self.rcnt.at[rows].set(0)
        self.rsum = self.rsum.at[rows].set(0.0)
        self.total = self.total.at[rows].set(0)
        if self.learner_type == "softMax":
            self.temp = self.temp.at[rows].set(self._temp0)
            self.probs = self.probs.at[rows].set(1.0 / len(self.action_ids))
            self.rewarded = self.rewarded.at[rows].set(False)

    # -- public surface ------------------------------------------------------

    def step(self, n_steps: Optional[int] = None) -> np.ndarray:
        """Advance every learner ``n_steps`` times (default ``batch.size``)
        in one jitted scan; returns selected action indices [n_steps, G]."""
        n = self.batch_size if n_steps is None else n_steps
        self._key, sub = jax.random.split(self._key)
        state, sels = self._step_fn(self._state(), sub, n)
        self._set_state(state)
        return np.asarray(sels)

    def step_masked(self, active: np.ndarray,
                    n_steps: int = 1) -> np.ndarray:
        """Advance ONLY the groups where ``active`` is True (the streaming
        case: an entity's learner steps when its event arrives), ``n_steps``
        times inside one jitted scan.  Returns selected action indices
        [n_steps, capacity]; entries for inactive groups are meaningless and
        their state is untouched."""
        return np.asarray(self.step_masked_async(active, n_steps))

    def step_masked_async(self, active: np.ndarray, n_steps: int = 1):
        """``step_masked`` without the blocking host transfer: the state
        update is dispatched and the selections return as a DEVICE array
        future.  The streaming loop uses this to overlap the next wave's
        transport drain/parse with this wave's device step; callers
        materialize with ``np.asarray`` when they emit."""
        self._key, sub = jax.random.split(self._key)
        state, sels = self._masked_fn(self._state(), sub, n_steps,
                                      jnp.asarray(active, bool))
        self._set_state(state)
        return sels

    def step_waved_async(self, packed: np.ndarray, reward_bucket: int,
                         n_steps: int = 1):
        """One fused device call for a streaming wave: ``packed`` int32
        ``[nr, nw, g[rb], a[rb], r[rb], rows[wb]]`` (see
        ``_build_step.waved_steps``) applies the bulk reward scatter
        then runs ``n_steps`` steps masked to the wave's rows; returns
        the selections as a device future.  The key advances inside the
        jit, so a wave costs exactly one transfer + one dispatch + one
        (deferrable) read."""
        self._key, state, sels = self._waved_fn(
            self._state(), self._key, n_steps, reward_bucket,
            jnp.asarray(packed, jnp.int32))
        self._set_state(state)
        return sels

    def next_actions(self) -> List[List[str]]:
        """``batch.size`` action ids per group: [G][batch] of action_id —
        the grouped equivalent of ``ReinforcementLearner.next_actions``."""
        sels = self.step()
        return [[self.action_ids[a] for a in sels[:, g]]
                for g in range(len(self.group_ids))]

    def set_rewards(self, group_ids: Sequence[str],
                    action_ids: Sequence[str],
                    rewards: Sequence[float]) -> None:
        """Bulk reward application: one scatter per round."""
        g = np.asarray([self._gindex[x] for x in group_ids], np.int32)
        a = np.asarray([self._aindex[x] for x in action_ids], np.int32)
        r = np.asarray(rewards, np.float32)
        if self.learner_type == "upperConfidenceBoundOne":
            # only UCB1 scales its reward stats (reinforce.py set_reward);
            # randomGreedy/softMax add the raw reward
            r = r / self.reward_scale
        self.rsum = self.rsum.at[g, a].add(r)
        self.rcnt = self.rcnt.at[g, a].add(1)
        if self.learner_type == "softMax":
            self.rewarded = self.rewarded.at[g].set(True)

"""Text analytics: analyzed word count.

Reference surface being re-expressed (citations into /root/reference):
- ``org.avenir.text.WordCounter`` — mapper tokenizes the configured text
  column (``text.field.ordinal``; ordinal <= 0 means the whole line —
  text/WordCounter.java:98-103) with Lucene's ``StandardAnalyzer``
  (lowercasing + English stop-word removal, no stemming;
  text/WordCounter.java:94,117-128), emits ``(token, 1)``; reducer counts and
  writes ``word,count`` lines (:139-151).  The same analyzer backs
  BayesianDistribution's text mode.

TPU re-design: tokenization and vocab assignment are host passes (strings
never go on device — SURVEY §7.3 item 1); the count itself runs through the
framework's sharded counting engine (``count_table`` under ``sharded_reduce``,
the same mapper+shuffle+reducer collapse every trainer uses), which is where
the scale lives when the corpus is large.
"""

from __future__ import annotations

import re
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import JobConfig
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters
from ..ops.counting import count_table, sharded_reduce

# Lucene StandardAnalyzer's default English stop set (StopAnalyzer
# ENGLISH_STOP_WORDS_SET, the list StandardAnalyzer(Version.LUCENE_35) uses)
LUCENE_STOP_WORDS = frozenset("""
a an and are as at be but by for if in into is it no not of on or such that
the their then there these they this to was will with
""".split())

# apostrophes only BETWEEN letters (UAX#29, as StandardTokenizer does:
# don't -> don't, 'hello' -> hello)
_TOKEN = re.compile(r"[0-9A-Za-z]+(?:'[0-9A-Za-z]+)*")


def standard_tokenize(text: str) -> List[str]:
    """StandardAnalyzer-equivalent: lowercase alphanumeric tokens minus
    English stop words (no stemming — the reference's ``tokenize`` comment
    says stemming but StandardAnalyzer does none)."""
    return [t for t in (m.group(0).lower() for m in _TOKEN.finditer(text))
            if t not in LUCENE_STOP_WORDS]


class WordCounter:
    """Analyzed word-count job."""

    def __init__(self, config: JobConfig):
        self.config = config

    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim = cfg.field_delim_out()
        text_ord = cfg.must_int("text.field.ordinal")
        delim_regex = cfg.field_delim_regex()

        vocab: dict = {}
        ids: List[int] = []
        for line in read_lines(in_path):
            if text_ord > 0:
                text = split_line(line, delim_regex)[text_ord]
            else:
                text = line
            for token in standard_tokenize(text):
                ids.append(vocab.setdefault(token, len(vocab)))
        words = list(vocab)
        counters.set("Words", "Distinct", len(words))
        counters.set("Words", "Total", len(ids))

        if not words:
            write_output(out_path, [])
            return counters

        # the count runs through the sharded engine: per-shard bincount
        # (mapper+combiner) + psum over the data axis (shuffle+reducer)
        id_arr = np.asarray(ids, dtype=np.int32)
        counts = np.asarray(sharded_reduce(
            _wc_local, id_arr, mesh=mesh, static_args=(len(words),)))

        out = [f"{w}{delim}{int(counts[i])}" for i, w in enumerate(words)]
        write_output(out_path, out)
        return counters


def _wc_local(ids, mask, n_words):
    # int64 counts when x64 is on (the CLI enables it): a token can exceed
    # 2^31 occurrences in a large corpus and must not silently overflow
    dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return count_table((n_words,), (ids,), weights=None, mask=mask,
                       dtype=dtype)

"""Text analytics: analyzed word count.

Reference surface being re-expressed (citations into /root/reference):
- ``org.avenir.text.WordCounter`` — mapper tokenizes the configured text
  column (``text.field.ordinal``; ordinal <= 0 means the whole line —
  text/WordCounter.java:98-103) with Lucene's ``StandardAnalyzer``
  (lowercasing + English stop-word removal, no stemming;
  text/WordCounter.java:94,117-128), emits ``(token, 1)``; reducer counts and
  writes ``word,count`` lines (:139-151).  The same analyzer backs
  BayesianDistribution's text mode.

TPU re-design: tokenization and vocab assignment are host passes (strings
never go on device — SURVEY §7.3 item 1); the count itself runs through the
framework's sharded counting engine (``count_table`` under ``sharded_reduce``,
the same mapper+shuffle+reducer collapse every trainer uses), which is where
the scale lives when the corpus is large.
"""

from __future__ import annotations

import unicodedata
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters
from ..ops.counting import count_table, sharded_reduce

# Lucene StandardAnalyzer's default English stop set (StopAnalyzer
# ENGLISH_STOP_WORDS_SET, the list StandardAnalyzer(Version.LUCENE_35) uses)
LUCENE_STOP_WORDS = frozenset("""
a an and are as at be but by for if in into is it no not of on or such that
the their then there these they this to was will with
""".split())

# ---------------------------------------------------------------------------
# UAX#29 word-break scanner, matching Lucene 3.5's StandardTokenizer
# (a JFlex grammar generated from the Unicode 6.0 word-break property
# data — text/WordCounter.java:117-128 builds StandardAnalyzer
# (Version.LUCENE_35)).  Rules implemented, with the Unicode-6.0 class
# memberships of that era:
#   WB5   ALetter x ALetter                     ("foo" + "bar")
#   WB6/7 ALetter x (MidLetter|MidNumLet) ALetter   ("don't", "a:b",
#         "john.smith" — colon was MidLetter in Unicode 6.0)
#   WB8   Numeric x Numeric
#   WB9/10 ALetter <-> Numeric                  ("x86", "3rd")
#   WB11/12 Numeric x (MidNum|MidNumLet) Numeric    ("3.14", "1,000")
#   WB13a/b ExtendNumLet ("_") joins words/numbers  ("foo_bar")
# plus Lucene's maxTokenLength (255): an over-long token is DISCARDED,
# not truncated (StandardTokenizer.incrementToken skips it and bumps
# the position increment).  Han/Hiragana ideographs emit one token per
# character and Katakana as runs, as the UAX29 grammar's IDEOGRAPHIC /
# HIRAGANA / KATAKANA productions do.

MAX_TOKEN_LENGTH = 255

# Unicode 6.0 Word_Break memberships (WordBreakProperty-6.0.0), the
# era Lucene 3.5's JFlex grammar was generated from (colon/semicolon
# were reclassified out of MidLetter/MidNum only in Unicode 6.3)
_MIDLETTER = frozenset("\u003A\u00B7\u0387\u05F4\u2027\uFE13\uFE55\uFF1A")
_MIDNUMLET = frozenset("\u0027\u002E\u2018\u2019\u2024\uFE52\uFF07\uFF0E")
_MIDNUM = frozenset("\u002C\u003B\u037E\u0589\u060C\u060D\u066C\u07F8\u2044\uFE10\uFE14\uFE50\uFE54\uFF0C\uFF1B")
_EXTEND = frozenset("\u005F\u203F\u2040\u2054\uFE33\uFE34\uFE4D\uFE4E\uFE4F\uFF3F")

# Katakana / Hiragana Word_Break memberships (WordBreakProperty-6.0.0);
# U+30FB KATAKANA MIDDLE DOT is Word_Break=Other — it SEPARATES
# katakana words — and the voiced-sound marks U+309B/309C are Katakana
_KATAKANA_RANGES = ((0x3031, 0x3035), (0x309B, 0x309C), (0x30A0, 0x30FA),
                    (0x30FC, 0x30FF), (0x31F0, 0x31FF), (0xFF66, 0xFF9F))
_HIRAGANA_RANGES = ((0x3041, 0x3096), (0x309D, 0x309F))


def _char_class(ch: str) -> str:
    """UAX#29 word-break class of one char (the subset the grammar
    distinguishes): A(Letter) N(umeric) ML MN MNL E(xtendNumLet)
    K(atakana) I(deographic incl. hiragana) or '' (break)."""
    if "a" <= ch <= "z" or "A" <= ch <= "Z":
        return "A"
    if "0" <= ch <= "9":
        return "N"
    if ch in _EXTEND:
        return "E"
    if ch in _MIDNUMLET:
        return "MNL"
    if ch in _MIDLETTER:
        return "ML"
    if ch in _MIDNUM:
        return "MN"
    o = ord(ch)
    if o < 128:
        return ""
    if any(lo <= o <= hi for lo, hi in _KATAKANA_RANGES):
        return "K"
    if any(lo <= o <= hi for lo, hi in _HIRAGANA_RANGES):
        return "I"
    cat = unicodedata.category(ch)
    if cat == "Nd":
        return "N"
    if cat.startswith("L"):
        # Han (and other ideographic letters) break per character
        if "CJK" in unicodedata.name(ch, ""):
            return "I"
        return "A"
    return ""


def _scan_word(cls, i: int, n: int) -> int:
    """End index of the word starting at alnum position ``i``: WB5/8/9/10
    runs, WB6/7 and WB11/12 single-mid joins, WB13a ExtendNumLet."""
    last_alnum = cls[i]
    i += 1
    while i < n:
        c = cls[i]
        if c in ("A", "N"):
            last_alnum = c
            i += 1
        elif c == "E":
            i += 1                             # WB13a: ExtendNumLet joins
        elif (last_alnum == "A" and c in ("ML", "MNL")
              and i + 1 < n and cls[i + 1] == "A"):
            last_alnum = "A"
            i += 2                             # WB6/7
        elif (last_alnum == "N" and c in ("MN", "MNL")
              and i + 1 < n and cls[i + 1] == "N"):
            last_alnum = "N"
            i += 2                             # WB11/12
        else:
            break
    return i


def _uax29_words(text: str) -> List[str]:
    """Maximal word tokens per the rules above (untruncated; the caller
    applies the maxTokenLength discard)."""
    out = []
    n = len(text)
    cls = [_char_class(c) for c in text]
    i = 0
    while i < n:
        c = cls[i]
        if c in ("A", "N"):
            end = _scan_word(cls, i, n)
            out.append(text[i:end])
            i = end
        elif c == "E":
            # leading underscores attach to a following word (WB13b);
            # bare underscores with no adjacent alnum are not words
            start = i
            while i < n and cls[i] == "E":
                i += 1
            if i < n and cls[i] in ("A", "N"):
                end = _scan_word(cls, i, n)
                out.append(text[start:end])
                i = end
        elif c == "K":
            start = i
            while i < n and cls[i] == "K":
                i += 1                         # WB13: Katakana runs
            out.append(text[start:i])
        elif c == "I":
            out.append(text[i])                # one token per ideograph
            i += 1
        else:
            i += 1
    return out


def standard_tokenize(text: str) -> List[str]:
    """StandardAnalyzer(Version.LUCENE_35)-equivalent: UAX#29 word
    tokens (Unicode-6.0 class memberships), tokens longer than 255
    chars discarded, lowercased, minus the English stop words (no
    stemming — the reference's ``tokenize`` comment says stemming but
    StandardAnalyzer does none).  Pinned by the golden fixture in
    tests/test_text.py::test_standard_tokenize_lucene_golden."""
    return [t for t in (w.lower() for w in _uax29_words(text)
                        if len(w) <= MAX_TOKEN_LENGTH)
            if t not in LUCENE_STOP_WORDS]


class WordCounter:
    """Analyzed word-count job."""

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim = cfg.field_delim_out()
        text_ord = cfg.must_int("text.field.ordinal")
        delim_regex = cfg.field_delim_regex()

        vocab: dict = {}
        ids: List[int] = []
        for line in read_lines(in_path):
            if text_ord > 0:
                text = split_line(line, delim_regex)[text_ord]
            else:
                text = line
            for token in standard_tokenize(text):
                ids.append(vocab.setdefault(token, len(vocab)))
        words = list(vocab)
        counters.set("Words", "Distinct", len(words))
        counters.set("Words", "Total", len(ids))

        if not words:
            write_output(out_path, [])
            return counters

        # the count runs through the sharded engine: per-shard bincount
        # (mapper+combiner) + psum over the data axis (shuffle+reducer)
        id_arr = np.asarray(ids, dtype=np.int32)
        counts = np.asarray(sharded_reduce(
            _wc_local, id_arr, mesh=mesh, static_args=(len(words),)))

        out = [f"{w}{delim}{int(counts[i])}" for i, w in enumerate(words)]
        write_output(out_path, out)
        return counters


def _wc_local(ids, mask, n_words):
    # int64 counts when x64 is on (the CLI enables it): a token can exceed
    # 2^31 occurrences in a large corpus and must not silently overflow
    dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return count_table((n_words,), (ids,), weights=None, mask=mask,
                       dtype=dtype)

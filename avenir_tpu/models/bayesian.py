"""Naive Bayes: distribution trainer + predictor (TPU-native).

Reference surface being re-expressed (citations into /root/reference):
- trainer ``org.avenir.bayesian.BayesianDistribution`` — mapper bins features
  and emits (class, ord, bin)->1 or (class, ord)->(1, v, v^2)
  (BayesianDistribution.java:137-179); reducer sums and writes the model as
  delimited text with empty-column type tags (:264-328) plus Gaussian feature
  priors in cleanup (:241-259).
- predictor ``org.avenir.bayesian.BayesianPredictor`` — map-only; loads the
  model text (BayesianPredictor.java:186-224), computes per-class
  ``P(C|x) ∝ P(x|C)P(C)/P(x)`` scaled to int percent (:396-421), arbitrates
  max-prob / cost-based (:342-391), emits prediction + confusion counters.
- text mode (``tabular.input=false``) — the trainer alternatively consumes
  ``text<delim>classVal`` lines, Lucene-tokenizes the text, and counts token
  presence per class at the fixed feature ordinal 1
  (BayesianDistribution.java:126-131 analyzer setup, :187-196 mapText);
  the model file uses the same format with tokens as bin labels.  The
  matching predictor text mode here is net-new (the reference ships no text
  predictor): it tokenizes, scores ``P(C)·Π P(tok|C) / Π P(tok)`` through
  the loaded model, and arbitrates exactly like the tabular path.

TPU re-design: binning happens once in ingest (core.binning); the whole
mapper+shuffle+reducer collapses into one ``feature_class_counts`` /
``moment_table`` scatter under ``shard_map`` with ``psum`` over the data axis
(ops.counting); prediction is a vectorized gather + log-free product over
per-class probability tables, jitted over the row-sharded batch.  The model
TEXT FORMAT is preserved verbatim so reference model files and consumers
(e.g. the kNN pipeline's FeatureCondProbJoiner) interoperate.

Normalization parity note: the reference emits one class-prior line per
reduce key and the loader SUMS them (BayesianModel.addClassPrior), making the
stored class count = N_c x F (records of class c times feature fields); every
per-feature normalizer carries the same F factor, which cancels in the final
posterior/prior ratio.  We reproduce that accumulation exactly so the
"output.feature.prob.only" numbers match the reference's, not just the final
predictions.
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import telemetry
from ..core.binning import DatasetEncoder, EncodedDataset
from ..core.multiscan import FoldSpec as MultiScanFoldSpec
from ..core.obs import get_tracer, traced_run
from ..core.config import JobConfig
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import ConfusionMatrix, CostBasedArbitrator, Counters
from ..core.schema import FeatureSchema
from ..ops.counting import (feature_class_counts, feature_class_counts_rawbin,
                            sharded_reduce)


def _java_int32(x):
    """Java ``(int)`` cast semantics for a float array (JLS §5.1.3,
    BayesianPredictor.java:416 ``(int)(ratio * 100)``): NaN maps to 0,
    out-of-range values saturate at Integer.MIN/MAX_VALUE, in-range
    values truncate toward zero.  NumPy/XLA casts of non-finite or
    out-of-range floats are undefined (and emit RuntimeWarning on
    host), so extreme records — zero priors, huge Gaussian density
    ratios — would otherwise produce arbitrary scores where the
    reference produces defined ones (VERDICT r2 item 3)."""
    x = jnp.asarray(x)
    # clip to the largest dtype-representable value <= 2^31-1 (f32 rounds
    # 2147483647 up to 2^31, which overflows the cast), then pin clipped
    # values to Java's exact Integer.MAX_VALUE
    hi = 2147483520.0 if x.dtype == jnp.float32 else 2147483647.0
    x = jnp.where(jnp.isnan(x), 0.0, x)
    out = jnp.clip(x, -2147483648.0, hi).astype(jnp.int32)
    # strictly-above-hi pins to MAX_VALUE; x == hi is itself a
    # representable in-range value whose clip+cast is already exact
    # (Java (int)2147483520.0f == 2147483520, not MAX_VALUE)
    return jnp.where(x > hi, np.int32(2**31 - 1), out)


def _java_int32_np(x):
    """NumPy twin of ``_java_int32`` for host oracles (f64 only)."""
    x = np.where(np.isnan(x), 0.0, x)
    return np.clip(x, -2147483648.0, 2147483647.0).astype(np.int32)


def _jdiv(a: int, b: int) -> int:
    """Java long division: truncates toward zero (floor division does not,
    for negative operands — BayesianDistribution.java:249 does ``valSum / count``
    on longs)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def _jstd(vsq: int, cnt: int, mean: int) -> int:
    """Reference stddev: ``(long)Math.sqrt((valSqSum - count*mean*mean)/(count-1))``
    (BayesianDistribution.java:250-251); Java's sqrt(negative) is NaN and
    ``(long)NaN == 0``."""
    if cnt <= 1:
        return 0
    t = (vsq - cnt * mean * mean) / (cnt - 1)
    return int(math.sqrt(t)) if t > 0 else 0


# Module-level local_fn so sharded_reduce's compiled-function cache hits on
# repeated training runs (a per-call closure would key a fresh cache entry
# every time).  Static shape params ride static_args.
#
# Moments for unbinned numerics are deliberately NOT computed here: exact
# (count, sum, sum-of-squares) needs 64-bit arithmetic, which TPUs emulate at
# ~6x the cost of the entire counting pass; the moments are C x F_cont
# scalars, so each host computes them exactly in NumPy over its shard (and
# they would psum trivially across hosts).  The device does what it is good
# at -- the massively parallel binned counting.
def _nb_local(x, y, mask, n_class, max_bins):
    return feature_class_counts(x, y, n_class, max_bins, mask=mask)


def _nb_local_rawbin(x, y, mask, n_class, max_bins, widths):
    """Warm-cache fold: ``x`` holds PRE-BIN raw integers straight off the
    mmapped artifact; binning fuses into the count kernel on device
    (ops.pallas_count rawbin variant on TPU, an XLA-fused div elsewhere)."""
    return feature_class_counts_rawbin(x, y, n_class, max_bins, widths,
                                       mask=mask)


def _aborting_salvage(builder, inner):
    """Wrap a salvage callable so a salvaged (quarantined) chunk kills the
    cache build: the artifact must equal a clean re-encode of the input
    bytes, and salvage means this scan's output does not."""
    def salvage(chunk):
        builder.abort()
        return inner(chunk)
    return salvage


# Scratch buffers for _host_moments, thread-local so concurrent trainings
# cannot interleave writes; one live (n, n_class) size per thread (training
# passes repeat the same shape — the buffers are overwritten every call and
# stay allocated between passes by design: first-touch faults on fresh 16MB
# temporaries were ~2x the arithmetic).
_moment_tls = threading.local()


def _moment_scratch(n: int, n_class: int):
    cached = getattr(_moment_tls, "scratch", None)
    if cached is not None and cached[0] == (n, n_class):
        return cached[1]
    bufs = (np.empty(n, dtype=bool),
            np.empty((n_class, n), dtype=np.float64),
            np.empty(n, dtype=np.float64),
            np.empty(n, dtype=np.float64))
    _moment_tls.scratch = ((n, n_class), bufs)
    return bufs


def _host_moments(values: np.ndarray, y: np.ndarray, n_class: int,
                  cont_cols) -> Dict[int, np.ndarray]:
    """Exact per-class (count, sum, sumsq) for each unbinned column.

    Per-class sums run as BLAS matrix-vector products against a reused
    class-indicator matrix instead of weighted ``np.bincount`` per column
    — measured 53 ms -> ~16 ms at 2M rows (the bincount path was 84% of
    the whole NB training step).  Every class is computed by its own
    direct dot (no complement subtraction, so float-valued columns see no
    cancellation); only the summation order differs from a sequential
    loop, which for the reference's integer-valued moment tuples (long
    (1, v, v^2) accumulators, BayesianDistribution.java:156-171) is
    exact under any order."""
    out = {}
    if not cont_cols:
        return out
    cont_cols = tuple(cont_cols)
    if n_class == 0:
        return {j: np.zeros((3, 0)) for j in cont_cols}
    n = len(y)
    # the indicator matrix costs O(n * n_class) memory and GEMV flops; for
    # many-class problems (or a matrix past ~256MB) the one-pass bincount
    # is the better trade and nothing is pinned
    if n_class > 16 or n * n_class * 8 > (1 << 28):
        cnt = np.bincount(y, minlength=n_class)[:n_class]
        for j in cont_cols:
            v = np.ascontiguousarray(values[:, j])
            s = np.bincount(y, weights=v, minlength=n_class)[:n_class]
            s2 = np.bincount(y, weights=v * v,
                             minlength=n_class)[:n_class]
            out[j] = np.stack([cnt, s, s2])
        return out
    maskb, M, vbuf, v2buf = _moment_scratch(n, n_class)
    cnt = np.empty(n_class, dtype=np.int64)
    for c in range(n_class):
        np.equal(y, c, out=maskb)
        np.copyto(M[c], maskb)
        cnt[c] = maskb.sum()
    for j in cont_cols:
        np.copyto(vbuf, values[:, j])
        np.multiply(vbuf, vbuf, out=v2buf)
        out[j] = np.stack([cnt.astype(np.float64), M @ vbuf, M @ v2buf])
    return out


# ---------------------------------------------------------------------------
# trainer
# ---------------------------------------------------------------------------

class _NBStreamState:
    """Cap sizing, per-chunk guards, and host-moment accumulation shared
    by the standalone streamed trainer (``_train_streamed``) and the
    shared-scan FoldSpec (``fold_spec``) — one definition of the stream
    contract so the two paths cannot drift."""

    def __init__(self, enc: DatasetEncoder):
        ffields = enc.feature_fields
        self.enc = enc
        self.F = len(ffields)
        self.binned = [j for j, f in enumerate(ffields)
                       if f.is_categorical() or f.is_bucket_width_defined()]
        self.cont_cols = [j for j in range(self.F) if j not in self.binned]
        self.bucket_cols = [j for j, f in enumerate(ffields)
                            if f.is_bucket_width_defined()]
        self.declared = [f.num_bins() if (f.is_bucket_width_defined()
                                          and f.max is not None) else 0
                         for f in ffields]
        self.mom_acc: Dict[int, np.ndarray] = {}
        self.num_bins_seen = np.zeros(self.F, dtype=np.int64)
        self.n_chunks = 0
        self.bins_cap: Optional[int] = None
        self.n_class_cap: Optional[int] = None

    def size_caps(self, x0: np.ndarray) -> None:
        """Bin/class extents from the declared schema + first chunk
        (+headroom); see ``_train_streamed`` for the sizing rationale."""
        obs0 = [int(x0[:, j].max()) + 1 if len(x0) else 0
                for j in self.binned]
        cat_card = [len(self.enc.vocabs[f.ordinal])
                    for f in self.enc.feature_fields if f.is_categorical()]
        self.bins_cap = max([1] + [self.declared[j] for j in self.bucket_cols]
                            + obs0 + cat_card) + 4
        # no class headroom: the class vocabulary is complete after
        # chunk 0 in practice (declared in the schema, or every class
        # present early); a late new class fails the cap guard and
        # falls back — cheaper than paying a wider moments GEMV and
        # count table on every run
        self.n_class_cap = max(len(self.enc.class_vocab), 1)

    def accept(self, x, values, y, n, narrow: bool = True):
        """Guard + accumulate one encoded chunk; returns the (x, y) fold
        arrays (int8-narrowed when ``narrow``), None for an empty chunk.
        Raises ``ChunkedEncodeUnsupported`` on any cap overflow."""
        from ..core.binning import ChunkedEncodeUnsupported

        if n == 0:
            return None
        for j in self.bucket_cols:
            if int(x[:, j].min()) < 0:
                raise ChunkedEncodeUnsupported("negative bin")
        mx = [int(x[:, j].max()) + 1 for j in self.binned]
        for j, m in zip(self.binned, mx):
            self.num_bins_seen[j] = max(self.num_bins_seen[j], m)
        if (max(mx, default=0) > self.bins_cap
                or int(y.max(initial=-1)) >= self.n_class_cap):
            raise ChunkedEncodeUnsupported("cap overflow")
        xs, ys = x, y
        if narrow:
            if self.bins_cap <= 127 and self.F <= 127:
                xs = xs.astype(np.int8)
            if self.n_class_cap <= 127:
                ys = ys.astype(np.int8)
        mom = _host_moments(values, y, self.n_class_cap, self.cont_cols)
        for j, m in mom.items():
            acc = self.mom_acc.get(j)
            self.mom_acc[j] = m.copy() if acc is None else acc + m
        self.n_chunks += 1
        return xs, ys


def load_model_feature_counts(path: str, delim: str = ","
                              ) -> Dict[int, Dict[str, int]]:
    """Per-feature bin-count tables out of a written NB model file:
    ``{ordinal: {bin_label: count}}`` summed across the per-class
    feature-prior-binned lines (``<empty><delim>ord<delim>bin<delim>n``,
    the empty-column tag dispatch the reference loader uses).  The
    stored baseline side of the drift gauges — and the shape
    :func:`core.telemetry.count_drift` consumes directly."""
    out: Dict[int, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for line in read_lines(path):
        parts = line.split(delim)
        # feature prior binned: ["", ordinal, bin_label, count]; class
        # priors have parts[1] == "", posteriors have parts[0] != "",
        # continuous priors have 5 parts
        if (len(parts) == 4 and parts[0] == ""
                and parts[1] != "" and parts[2] != ""):
            try:
                out[int(parts[1])][parts[2]] += int(parts[3])
            except ValueError:
                continue
    return {k: dict(v) for k, v in out.items()}


class BayesianDistribution:
    """The Naive Bayes distribution trainer job."""

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None):
        self.config = config
        self.tabular = config.get_boolean("tabular.input", True)
        if self.tabular:
            self.schema = schema or FeatureSchema.from_file(
                config.must("feature.schema.file.path"))
        else:
            self.schema = schema      # text mode needs no feature schema

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        delim_in = self.config.field_delim_regex()
        delim = self.config.field_delim_out()
        if not self.tabular:
            return self._run_text(in_path, out_path, counters, delim_in,
                                  delim, mesh)

        tracer = get_tracer()
        with tracer.span("phase:train"):
            lines = self._train_streamed(in_path, delim_in, delim, counters,
                                         mesh, out_path=out_path)
            if lines is None:
                with tracer.span("phase:load"):
                    ds = self._encode_monolithic(in_path, out_path, delim_in,
                                                 counters)
                lines = self.train_lines(ds, delim, counters, mesh=mesh)
        with tracer.span("phase:emit"):
            write_output(out_path, lines)
        return counters

    def _encode_monolithic(self, in_path: str, out_path: str,
                           delim_in: str, counters: Counters):
        """One-shot fallback encode; with an ``ingest.error.budget``
        configured it pre-filters malformed rows into the quarantine
        sidecar (the streamed path quarantines per chunk — this keeps
        the fallback's contract identical)."""
        from ..core.io import read_lines, split_line
        from ..core.resilience import RowQuarantine, row_guard

        enc = DatasetEncoder(self.schema)
        quarantine = RowQuarantine.from_config(
            self.config, out_path + ".quarantine")
        if quarantine is None:
            return enc.encode_path(in_path, delim_in)
        guard = row_guard(enc)
        good, bad = [], []
        for line in read_lines(in_path):
            fields = split_line(line, delim_in)
            if guard(fields):
                good.append(fields)
            else:
                bad.append(line)
        if bad:
            quarantine.record(bad, "rows rejected by schema guard")
        quarantine.admit(len(good))
        quarantine.finish(counters)
        return enc.encode(good)

    def _train_streamed(self, in_path: str, delim_in: str, delim: str,
                        counters: Counters, mesh=None,
                        out_path: Optional[str] = None
                        ) -> Optional[List[str]]:
        """Chunked streaming training through ``core.pipeline``: the C
        encode + host-moment pass of chunk c+1 runs on the prefetch
        worker while chunk c's H2D copy and jitted, donated count fold
        are in flight on device (``pipeline.prefetch.depth`` deep;
        depth 0 = strict serial).  Chunks are ``pipeline.chunk.rows``
        rows (or derived from ``pipeline.device.budget.bytes``, or the
        legacy ``ingest.chunk.bytes``), so inputs larger than device
        memory stream through with bounded residency.  Count/class
        extents are capped from the declared schema + the first chunk
        (+headroom); data that overflows a cap — late-appearing
        categories, negative or beyond-declared bins — returns None and
        the caller re-runs the one-shot ``encode_path`` path, so results
        are always identical to the serial encode.

        Resilience surface (core.checkpoint / core.resilience): with
        ``checkpoint.interval.chunks`` set, a sidecar checkpoint (carry
        + encoder vocabularies + stream state + byte offset) is written
        every N folded chunks and ``--resume`` restarts mid-file with
        byte-identical output; with ``ingest.error.budget`` set,
        malformed rows quarantine to a sidecar instead of failing the
        chunk."""
        from ..core import ingestcache, pipeline
        from ..core.binning import ChunkedEncodeUnsupported
        from ..core.checkpoint import StreamCheckpointer
        from ..core.parparse import parse_threads_from_config
        from ..core.resilience import RowQuarantine, salvage_chunk

        enc = DatasetEncoder(self.schema)
        F = len(enc.feature_fields)
        chunk_bytes = self.config.get_int("ingest.chunk.bytes", 48 << 20)
        # budget row estimate: un-narrowed int32 x row + y (conservative —
        # int8 narrowing only shrinks the live set under the budget)
        chunk_rows = self.config.pipeline_chunk_rows(row_bytes=4 * (F + 1))
        depth = self.config.pipeline_prefetch_depth()
        sidecar_base = out_path if out_path is not None else in_path
        ck = StreamCheckpointer.from_config(
            self.config, kind="nb-train", in_path=in_path,
            default_path=sidecar_base + ".ckpt",
            params={"chunk_bytes": chunk_bytes, "chunk_rows": chunk_rows,
                    "delim": delim_in})
        quarantine = RowQuarantine.from_config(
            self.config, sidecar_base + ".quarantine")

        st = _NBStreamState(enc)
        start_offset = 0
        initial_carry = None
        resumed = False
        if ck is not None and ck.resume:
            payload = ck.load()
            if payload is not None:
                # the checkpointed encoder/stream state REPLACES the
                # fresh one: vocabularies, caps, moment accumulators and
                # budget counts continue exactly where the killed run's
                # last checkpoint left them
                enc = payload["state"]["enc"]
                st = payload["state"]["st"]
                if quarantine is not None and payload["state"].get("q"):
                    quarantine.restore(payload["state"]["q"])
                initial_carry = payload["carry"]
                start_offset = payload["offset"]
                resumed = True

        # parse-once cache: a validated artifact for (input bytes, encoder
        # schema, delim, chunk_rows) replays mmapped chunks instead of
        # parsing; a miss tees this cold scan into a new artifact.  Resumed
        # runs keep the checkpointed cold path (the sidecar's byte offset
        # anchors to the raw file, not the cache).
        cache = ingestcache.IngestCache.from_config(self.config, in_path,
                                                    enc, delim_in)
        builder = None
        if cache is not None and not resumed and start_offset == 0:
            scan = cache.load(chunk_rows)
            if scan is not None:
                lines = self._train_warm(scan, enc, st, counters, mesh,
                                         delim, quarantine, chunk_rows)
                if lines is not None and ck is not None:
                    ck.complete()
                return lines
            builder = cache.builder(chunk_rows)

        salvage = (salvage_chunk(enc, quarantine, delim_in)
                   if quarantine is not None else None)
        if builder is not None and salvage is not None:
            salvage = _aborting_salvage(builder, salvage)
        try:
            gen = enc.encode_path_chunks(
                in_path, delim_in,
                chunk_bytes=chunk_bytes,
                chunk_rows=chunk_rows,
                start_offset=start_offset,
                with_offsets=True,
                salvage=salvage,
                parse_threads=parse_threads_from_config(self.config))
            if not resumed:
                first, gen = pipeline.peek(gen)
                if first is None:
                    return None
                # declared categorical cardinalities are pre-seeded into
                # the vocab, so the emit loop walks len(vocab) bins even
                # when the data uses fewer — the count tensor must cover
                # them
                st.size_caps(first[0])

            def chunks():
                # guards + dtype narrowing + host moments run HERE — on
                # the prefetch worker when depth >= 1, overlapping the
                # device fold of the previous chunk.  Checkpoint tokens
                # capture (pickle) the host state at produce time, so a
                # prefetch worker running ahead cannot leak later-chunk
                # state into an earlier checkpoint.
                for x, values, y, n, idx, end in gen:
                    if quarantine is not None:
                        quarantine.admit(n)
                    out = st.accept(x, values, y, n)
                    if out is None:
                        continue
                    if builder is not None:
                        builder.add(x, values, y, n)
                    if ck is not None and ck.due(idx):
                        token = ck.token(idx, end, {
                            "enc": enc, "st": st,
                            "q": (quarantine.state()
                                  if quarantine is not None else None)})
                        yield pipeline.Checkpointed(out, token)
                    else:
                        yield out

            total = pipeline.streaming_fold(
                chunks(), _nb_local,
                static_args=(st.n_class_cap, st.bins_cap),
                mesh=mesh, prefetch_depth=depth, capacity=chunk_rows,
                checkpointer=ck, initial_carry=initial_carry)
        except ChunkedEncodeUnsupported:
            if builder is not None:
                builder.abort()
            if ck is not None:
                # the fallback run supersedes any sidecar this attempt
                # wrote — a stale checkpoint must not shadow it
                ck.complete()
            return None
        if total is None:
            if builder is not None:
                builder.abort()
            return None
        if builder is not None:
            builder.finish()
        if quarantine is not None:
            quarantine.finish(counters)
        lines = self._streamed_model_lines(enc, st, total, counters, delim)
        if ck is not None:
            ck.complete()
        return lines

    def _train_warm(self, scan, enc: DatasetEncoder, st: "_NBStreamState",
                    counters: Counters, mesh, delim: str, quarantine,
                    chunk_rows: int) -> Optional[List[str]]:
        """The warm half of ``_train_streamed``: replay the validated
        cache artifact's recorded chunks off mmap — no parse, no encode —
        through the SAME stream state (caps, guards, host moments,
        quarantine accounting), so every downstream byte matches the cold
        run.  With the raw matrix present and ``ingest.cache.fused`` on,
        the fold ships pre-bin integers and bins INSIDE the count kernel
        (``_nb_local_rawbin``); otherwise it folds the stored binned
        matrix through the standard ``_nb_local``."""
        from ..core import ingestcache, pipeline
        from ..core.binning import ChunkedEncodeUnsupported

        tracer = get_tracer()
        scan.seed_encoder(enc)
        depth = self.config.pipeline_prefetch_depth()
        use_raw = (scan.xraw is not None and self.config.get_boolean(
            ingestcache.KEY_CACHE_FUSED, True))
        sl0 = scan.chunk_slice(0)
        if sl0 is None:
            return None
        st.size_caps(np.asarray(sl0[0]))

        def chunks():
            for item in scan.chunks(with_raw=use_raw):
                if use_raw:
                    xraw, x, values, y, n, _ = item
                else:
                    x, values, y, n, _ = item
                    xraw = None
                with tracer.span("ingest.cache.read", rows=n):
                    if quarantine is not None:
                        quarantine.admit(n)
                    out = st.accept(x, values, y, n)
                if out is None:
                    continue
                xs, ys = out
                yield (np.asarray(xraw), ys) if use_raw else (xs, ys)

        try:
            if use_raw:
                widths = tuple(
                    int(f.bucketWidth) if f.is_bucket_width_defined() else 1
                    for f in enc.feature_fields)
                total = pipeline.streaming_fold(
                    chunks(), _nb_local_rawbin,
                    static_args=(st.n_class_cap, st.bins_cap, widths),
                    mesh=mesh, prefetch_depth=depth, capacity=chunk_rows)
            else:
                total = pipeline.streaming_fold(
                    chunks(), _nb_local,
                    static_args=(st.n_class_cap, st.bins_cap),
                    mesh=mesh, prefetch_depth=depth, capacity=chunk_rows)
        except ChunkedEncodeUnsupported:
            return None
        if total is None:
            return None
        if quarantine is not None:
            quarantine.finish(counters)
        return self._streamed_model_lines(enc, st, total, counters, delim)

    def _streamed_model_lines(self, enc: DatasetEncoder,
                              st: _NBStreamState, total, counters: Counters,
                              delim: str) -> List[str]:
        """Model lines from a streamed count fold (shared tail of
        ``_train_streamed`` and the multi-scan FoldSpec)."""
        counters.set("Ingest", "Chunks", st.n_chunks)
        ffields = enc.feature_fields
        F = len(ffields)
        n_class = len(enc.class_vocab)
        counts = np.asarray(total)[:n_class]
        moments = {j: m[:, :n_class] for j, m in st.mom_acc.items()}

        num_bins = []
        for j, f in enumerate(ffields):
            if f.is_categorical():
                num_bins.append(len(enc.vocabs[f.ordinal]))
            elif f.is_bucket_width_defined():
                num_bins.append(max(st.declared[j], int(st.num_bins_seen[j])))
            else:
                num_bins.append(0)
        ds_meta = EncodedDataset(
            schema=enc.schema, feature_fields=ffields,
            x=np.zeros((0, F), np.int32), values=np.zeros((0, F)),
            y=np.zeros(0, np.int32), num_bins=num_bins,
            bin_offset=np.zeros(F, np.int32),
            binned_mask=np.array([f.is_categorical()
                                  or f.is_bucket_width_defined()
                                  for f in ffields], dtype=bool),
            vocabs=enc.vocabs, class_vocab=enc.class_vocab)
        return self._emit_model_lines(ds_meta, counts, moments, delim,
                                      counters)

    def fold_spec(self, out_path: str):
        """Export this trainer's shared-scan ``core.multiscan.FoldSpec``
        (None in text mode — token streams cannot ride the tabular
        scan)."""
        if not self.tabular:
            return None
        return _NBFoldSpec(self, out_path)

    def train_lines(self, ds: EncodedDataset, delim: str,
                    counters: Counters, mesh=None) -> List[str]:
        """Compute all distributions on device; emit reference-format lines."""
        n_class = len(ds.class_vocab)
        F = ds.n_features
        max_bins = max([b for b in ds.num_bins] + [1])
        cont_cols = [j for j in range(F) if not ds.binned_mask[j]]

        # transfer-narrow: the binned matrix is bin indices, so when every
        # extent fits int8 send 1/4 the bytes over PCIe/tunnel and let the
        # one-hot compare on device widen it (host->device transfer is the
        # end-to-end bottleneck; the count table itself stays int32)
        xs, ys = ds.x, ds.y
        if max_bins <= 127 and F <= 127:
            xs = xs.astype(np.int8)
        if n_class <= 127:
            ys = ys.astype(np.int8)
        counts = np.asarray(sharded_reduce(
            _nb_local, xs, ys, mesh=mesh,
            static_args=(n_class, max_bins)))       # [n_class, F, max_bins]
        moments = _host_moments(ds.values, ds.y, n_class, cont_cols)
        return self._emit_model_lines(ds, counts, moments, delim, counters)

    def _emit_model_lines(self, ds: EncodedDataset, counts, moments,
                          delim: str, counters: Counters) -> List[str]:
        n_class = len(ds.class_vocab)
        F = ds.n_features
        lines: List[str] = []
        # feature-prior continuous accumulators: ord -> [count, sum, sumsq]
        prior_mom: Dict[int, List[float]] = defaultdict(lambda: [0, 0.0, 0.0])

        # reducer key order: Tuple sorts by its string form; we emit grouped
        # by (class, ordinal, bin) in encoding order, which downstream
        # loaders are insensitive to (they dispatch on the empty-column tags)
        for c in range(n_class):
            class_val = ds.class_vocab.values[c]
            for j in range(F):
                f = ds.feature_fields[j]
                ordinal = f.ordinal
                if ds.binned_mask[j]:
                    for b in range(ds.num_bins[j]):
                        cnt = int(counts[c, j, b])
                        if cnt == 0:
                            continue  # reference only ever sees observed keys
                        bin_label = ds.bin_label(j, b)
                        counters.incr("Distribution Data", "Feature posterior binned ")
                        lines.append(f"{class_val}{delim}{ordinal}{delim}{bin_label}{delim}{cnt}")
                        counters.incr("Distribution Data", "Class prior")
                        lines.append(f"{class_val}{delim}{delim}{delim}{cnt}")
                        counters.incr("Distribution Data", "Feature prior binned ")
                        lines.append(f"{delim}{ordinal}{delim}{bin_label}{delim}{cnt}")
                else:
                    mom = moments[j]
                    cnt = int(mom[0, c])
                    if cnt == 0:
                        continue
                    vsum = int(mom[1, c])
                    vsq = int(mom[2, c])
                    mean = _jdiv(vsum, cnt)
                    std = _jstd(vsq, cnt, mean)
                    counters.incr("Distribution Data", "Feature posterior cont ")
                    lines.append(f"{class_val}{delim}{ordinal}{delim}{delim}{mean}{delim}{std}")
                    counters.incr("Distribution Data", "Class prior")
                    lines.append(f"{class_val}{delim}{delim}{delim}{cnt}")
                    pm = prior_mom[ordinal]
                    pm[0] += cnt
                    pm[1] += vsum
                    pm[2] += vsq

        # reducer cleanup: Gaussian feature priors across classes
        for ordinal, (cnt, vsum, vsq) in sorted(prior_mom.items()):
            counters.incr("Distribution Data", "Feature prior cont ")
            mean = _jdiv(int(vsum), int(cnt))
            std = _jstd(int(vsq), int(cnt), mean)
            lines.append(f"{delim}{ordinal}{delim}{delim}{mean}{delim}{std}")
        self._emit_drift(ds, counts, counters, delim)
        return lines

    def _emit_drift(self, ds: EncodedDataset, counts, counters: Counters,
                    delim: str) -> None:
        """Count-distribution drift gauges: with
        ``telemetry.drift.baseline.path`` pointing at a previously
        written NB model, diff each binned feature's marginal bin-count
        distribution (this fold's counts summed over classes) against
        the baseline's feature-prior table and emit the symmetrised-KL
        divergence as a ``drift.<feature>`` gauge (+ a scaled ``Drift``
        counter on the job's Counters).  This is the concrete sensor an
        ``--update``-style re-scan reads to decide whether the delta is
        material (ROADMAP item 4's retrain trigger)."""
        base_path = self.config.get(telemetry.KEY_DRIFT_BASELINE)
        if not base_path:
            return
        try:
            baseline = load_model_feature_counts(base_path, delim)
        except Exception as e:                          # noqa: BLE001
            # an optional gauge must never fail the training run AFTER
            # the whole fold completed — a missing, unreadable, or
            # garbled (e.g. binary / non-UTF-8) baseline is surfaced on
            # the counters, not raised
            counters.set("Drift", "Baseline load failed", 1)
            import sys
            print(f"drift: cannot load baseline {base_path!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return
        metrics = telemetry.get_metrics()
        for j, f in enumerate(ds.feature_fields):
            if not ds.binned_mask[j]:
                continue            # Gaussian features carry no bin table
            cur = {}
            per_bin = np.asarray(counts)[:, j, :].sum(axis=0)
            for b in range(ds.num_bins[j]):
                c = int(per_bin[b])
                if c:
                    cur[ds.bin_label(j, b)] = c
            div = telemetry.count_drift(baseline.get(f.ordinal, {}), cur)
            name = f.name or str(f.ordinal)
            metrics.set_gauge(f"drift.{name}", div)
            counters.set("Drift", f"{name} (KL x1e6)",
                         int(round(div * 1e6)))

    # -- text-classification mode -----------------------------------------
    TEXT_ORDINAL = 1   # fixed featureAttrOrdinal (BayesianDistribution.java:121)

    def _run_text(self, in_path: str, out_path: str, counters: Counters,
                  delim_in: str, delim: str, mesh=None) -> Counters:
        """``tabular.input=false``: each record is ``text<delim>classVal``;
        tokens are counted as binned feature values of ordinal 1
        (BayesianDistribution.java:187-196).  Tokenization and vocab
        assignment are host passes (strings never go on device); the count
        itself is the same sharded engine as the tabular path, flattened to
        one (record, token) row per token occurrence."""
        from ..core.binning import Vocab
        from .text import standard_tokenize

        vocab = Vocab()
        class_vocab = Vocab()
        tok_ids: List[int] = []
        cls_ids: List[int] = []
        for line in read_lines(in_path):
            items = split_line(line, delim_in)
            cv = class_vocab.add(items[1])
            for tok in standard_tokenize(items[0]):
                tok_ids.append(vocab.add(tok))
                cls_ids.append(cv)

        x = np.asarray(tok_ids, dtype=np.int32)[:, None]
        y = np.asarray(cls_ids, dtype=np.int32)
        counts = np.asarray(sharded_reduce(
            _nb_local, x, y, mesh=mesh,
            static_args=(len(class_vocab), max(len(vocab), 1))))

        lines: List[str] = []
        o = self.TEXT_ORDINAL
        for c, class_val in enumerate(class_vocab.values):
            for b, tok in enumerate(vocab.values):
                cnt = int(counts[c, 0, b])
                if cnt == 0:
                    continue
                counters.incr("Distribution Data", "Feature posterior binned ")
                lines.append(f"{class_val}{delim}{o}{delim}{tok}{delim}{cnt}")
                counters.incr("Distribution Data", "Class prior")
                lines.append(f"{class_val}{delim}{delim}{delim}{cnt}")
                counters.incr("Distribution Data", "Feature prior binned ")
                lines.append(f"{delim}{o}{delim}{tok}{delim}{cnt}")
        write_output(out_path, lines)
        return counters


class _NBFoldSpec(MultiScanFoldSpec):
    """Shared-scan FoldSpec for the NB trainer (core.multiscan contract):
    schema-encodes each parsed chunk (sharing the encoder — and therefore
    the per-chunk encode AND H2D copy — with any co-registered job on the
    same schema file), folds ``_nb_local`` count tables on device, and
    finalizes to the normal model file.  Fold arrays stay un-narrowed so
    they are identical objects to a sharing job's (the int8 transfer
    narrowing would fork a private copy per job).

  Split invariance (fold(A ++ B) == merge_carries(fold(A),
    fold(B)), any chunk boundaries/order) is property-tested at
    mesh=1 and 8-way by the fold-algebra verifier
    (core.algebra, tests/test_algebra.py) — the ROADMAP-1
    multi-host psum contract this spec must keep.
    """

    def __init__(self, job: "BayesianDistribution", out_path: str):
        self.job = job
        self.out_path = out_path
        self.name = type(job).__name__
        self.local_fn = _nb_local
        self.static_args: tuple = ()
        self.enc = DatasetEncoder(job.schema)
        self.delim = job.config.field_delim_out()
        self.st: Optional[_NBStreamState] = None

    def bind(self, engine) -> None:
        import os
        sp = self.job.config.get("feature.schema.file.path")
        if sp:
            self.enc = engine.shared_encoder(
                ("schema-encoder", os.path.abspath(sp)), self.enc)

    def encode(self, ctx):
        # ctx.encoded: native C single-pass encode off the raw bytes when
        # available (negative bins arrive unshifted and fail accept's
        # guard; the Python fallback raises on its per-chunk shift)
        x, values, y, n = ctx.encoded(self.enc)
        if n == 0:
            return None
        if self.st is None:
            self.st = _NBStreamState(self.enc)
            self.st.size_caps(x)
            self.static_args = (self.st.n_class_cap, self.st.bins_cap)
        return self.st.accept(x, values, y, n, narrow=False)

    def finalize(self, carry) -> Counters:
        counters = Counters()
        lines = self.job._streamed_model_lines(self.enc, self.st, carry,
                                               counters, self.delim)
        write_output(self.out_path, lines)
        return counters


# ---------------------------------------------------------------------------
# model (text-format compatible with the reference loader)
# ---------------------------------------------------------------------------

class _FeatureDistr:
    """Per-(scope, ordinal) distribution: bin counts or Gaussian params —
    the chombo FeatureCount equivalent."""

    __slots__ = ("bins", "mean", "std", "total")

    def __init__(self):
        self.bins: Dict[str, int] = defaultdict(int)
        self.mean: Optional[int] = None
        self.std: Optional[int] = None
        self.total = 0

    def prob(self, bin_or_val) -> float:
        if self.mean is not None:
            x = float(bin_or_val)
            sd = max(float(self.std), 1e-9)
            z = (x - self.mean) / sd
            return math.exp(-0.5 * z * z) / (sd * math.sqrt(2.0 * math.pi))
        if self.total <= 0:
            return 0.0
        return self.bins.get(str(bin_or_val), 0) / self.total


class NaiveBayesModel:
    """In-memory model; parses/serializes the reference text format
    (dispatch on empty-column tags per BayesianPredictor.java:193-218)."""

    def __init__(self):
        self.post: Dict[Tuple[str, int], _FeatureDistr] = defaultdict(_FeatureDistr)
        self.prior: Dict[int, _FeatureDistr] = defaultdict(_FeatureDistr)
        self.class_count: Dict[str, int] = defaultdict(int)
        self.class_prob: Dict[str, float] = {}
        self.total = 0

    @classmethod
    def load(cls, path: str, delim_regex: str = ",") -> "NaiveBayesModel":
        """Load from the model text file (or an in-memory artifact when
        a ``core.io.ArtifactStore`` overlay holds the path — the DAG
        stage handoff)."""
        return cls.from_lines(read_lines(path), delim_regex)

    @classmethod
    def from_lines(cls, lines, delim_regex: str = ",") -> "NaiveBayesModel":
        """Build the model from an iterable of model-format lines — the
        artifact-import hook core.dag uses to hand a just-trained model
        to a predictor or the serving registry without a file
        round-trip."""
        m = cls()
        for line in lines:
            items = split_line(line, delim_regex)
            ordinal = int(items[1]) if items[1] != "" else -1
            if items[0] == "":
                if items[2] != "":
                    m.prior[ordinal].bins[items[2]] += int(items[3])
                else:
                    m.prior[ordinal].mean = int(items[3])
                    m.prior[ordinal].std = int(items[4])
            elif items[1] == "" and items[2] == "":
                m.class_count[items[0]] += int(items[3])
            else:
                if items[2] != "":
                    m.post[(items[0], ordinal)].bins[items[2]] += int(items[3])
                else:
                    m.post[(items[0], ordinal)].mean = int(items[3])
                    m.post[(items[0], ordinal)].std = int(items[4])
        m.finish_up()
        return m

    def finish_up(self) -> None:
        """Reference BayesianModel.finishUp: class probs normalized by the
        summed class counts; per-feature tables by their scope's count."""
        self.total = sum(self.class_count.values())
        for cv, cnt in self.class_count.items():
            self.class_prob[cv] = cnt / self.total if self.total else 0.0
        for (cv, _), d in self.post.items():
            d.total = self.class_count[cv]
        for d in self.prior.values():
            d.total = self.total

    # -- scalar reference semantics (oracle + small-batch path) ----------
    def class_prior_prob(self, class_val: str) -> float:
        return self.class_prob.get(class_val, 0.0)

    def feature_prior_prob(self, feature_values) -> float:
        p = 1.0
        for ordinal, v in feature_values:
            p *= self.prior[ordinal].prob(v)
        return p

    def feature_post_prob(self, class_val: str, feature_values) -> float:
        p = 1.0
        for ordinal, v in feature_values:
            p *= self.post[(class_val, ordinal)].prob(v)
        return p


# ---------------------------------------------------------------------------
# predictor
# ---------------------------------------------------------------------------

class BayesianPredictor:
    """Map-only scoring job; vectorized over the row batch on device."""

    def __init__(self, config: JobConfig, schema: Optional[FeatureSchema] = None,
                 model: Optional[NaiveBayesModel] = None):
        self.config = config
        self.tabular = config.get_boolean("tabular.input", True)
        if self.tabular:
            self.schema = schema or FeatureSchema.from_file(
                config.must("feature.schema.file.path"))
        else:
            self.schema = schema
        self.model = model or NaiveBayesModel.load(
            config.must("bayesian.model.file.path"),
            config.field_delim_regex())
        # fail fast, before any input is read; text mode scores on host in
        # f64, so the precision choice only affects the tabular device
        # path.  float32 (the log-space MXU path, ~100x on TPU where f64
        # is emulated) is the default; bp.score.precision=float64 is the
        # strict reference-parity opt-out (raw double products,
        # BayesianPredictor.java:396-421) for byte-stable model rollouts —
        # the int-scaled probabilities of the two paths agree within ±1
        # (asserted at 2M-row scale and under adversarial tail densities
        # in bench.py and tests/test_bayesian.py)
        self.score_precision = config.get("bp.score.precision", "float32")
        if self.score_precision not in ("float64", "float32"):
            raise ValueError(
                f"invalid bp.score.precision: {self.score_precision}")

        delim = self.config.field_delim_out()
        pc = self.config.get("bp.predict.class")
        if pc is not None:
            self.predicting_classes = pc.split(delim)
        elif self.schema is not None:
            card = self.schema.class_attr_field().cardinality
            self.predicting_classes = [card[0], card[1]]
        else:
            # text mode without bp.predict.class: the model's classes
            self.predicting_classes = list(self.model.class_count)[:2]

        costs = self.config.get("bp.predict.class.cost")
        self.arbitrator = None
        if costs is not None:
            c = costs.split(delim)
            self.arbitrator = CostBasedArbitrator(
                self.predicting_classes[0], self.predicting_classes[1],
                int(c[0]), int(c[1]))
        self.class_prob_diff_threshold = self.config.get_int(
            "class.prob.diff.threshold", -1)
        self.output_feature_prob_only = self.config.get_boolean(
            "output.feature.prob.only", False)

    # -- vectorized scoring ------------------------------------------------
    def _build_tables(self, ds: EncodedDataset):
        """Per-class probability lookup tables aligned to the predict-time
        encoding (host-built gather tables; the device replaces the
        reference's per-record hash lookups)."""
        F = ds.n_features
        max_bins = max([b for b in ds.num_bins] + [1])
        C = len(self.predicting_classes)
        post = np.zeros((C, F, max_bins))
        prior = np.zeros((F, max_bins))
        gauss_post = np.zeros((C, F, 2))   # mean, std
        gauss_prior = np.zeros((F, 2))
        is_cont = ~ds.binned_mask
        for j, f in enumerate(ds.feature_fields):
            if ds.binned_mask[j]:
                for b in range(ds.num_bins[j]):
                    label = ds.bin_label(j, b)
                    prior[j, b] = self.model.prior[f.ordinal].prob(label)
                    for ci, cv in enumerate(self.predicting_classes):
                        post[ci, j, b] = self.model.post[(cv, f.ordinal)].prob(label)
            else:
                d = self.model.prior[f.ordinal]
                gauss_prior[j] = (d.mean or 0, d.std or 0)
                for ci, cv in enumerate(self.predicting_classes):
                    dp = self.model.post[(cv, f.ordinal)]
                    gauss_post[ci, j] = (dp.mean or 0, dp.std or 0)
        class_prior = np.asarray(
            [self.model.class_prior_prob(cv) for cv in self.predicting_classes])
        return post, prior, gauss_post, gauss_prior, class_prior, is_cont

    @staticmethod
    def _score_batch(x, values, post, prior, gauss_post, gauss_prior,
                     class_prior, is_cont):
        """classPostProb[n, C] = int(featPost*classPrior/featPrior * 100)
        (BayesianPredictor.java:416), fully vectorized."""
        n, F = x.shape
        cols = jnp.arange(F)
        xc = jnp.clip(x, 0, post.shape[2] - 1)

        def gauss(v, params):
            mean = params[..., 0]
            std = jnp.maximum(params[..., 1], 1e-9)
            z = (v - mean) / std
            return jnp.exp(-0.5 * z * z) / (std * jnp.sqrt(2.0 * jnp.pi))

        # binned factors (cont columns contribute 1.0)
        prior_f = jnp.where(is_cont[None, :], gauss(values, gauss_prior[None, :, :]),
                            prior[cols[None, :], xc])
        feat_prior = jnp.prod(prior_f, axis=1)                       # [n]

        post_f = jnp.where(
            is_cont[None, None, :],
            gauss(values[:, None, :], gauss_post[None, :, :, :]),
            jnp.take_along_axis(
                jnp.broadcast_to(post[None], (n,) + post.shape),
                xc[:, None, :, None], axis=3)[..., 0])
        feat_post = jnp.prod(post_f, axis=2)                          # [n, C]

        ratio = feat_post * class_prior[None, :] / jnp.maximum(feat_prior[:, None], 1e-300)
        return _java_int32(ratio * 100), feat_prior, feat_post

    @staticmethod
    def _score_batch_f32(x, values, post, prior, gauss_post, gauss_prior,
                         class_prior, is_cont):
        """Log-space float32 scoring — the DEFAULT path
        (``bp.score.precision=float32``; ``float64`` is the strict-parity
        opt-out).  The reference computes the posterior ratio as raw
        double products (BayesianPredictor.java:416); tail density
        products underflow f32, so this path sums f32 LOGS instead and
        exponentiates once.  Measured ~100x the f64 path on TPU (which
        emulates f64): 575 ms -> 6.7 ms at 2M rows (BASELINE.md).
        Parity contract vs the f64 path (one shared checker,
        ``f32_score_parity_violations``, asserted in
        tests/test_bayesian.py and at 2M-row scale in bench.py):

        - On HEALTHY rows — whose per-row factor products stay inside
          the f64 path's usable range (true IEEE doubles on CPU; the
          TPU's emulated f64 is a double-word f32 with full f64
          precision but f32's EXPONENT RANGE, flushing near 1e-38) —
          int probabilities agree within max(±2, ~0.1%): the measured
          on-chip f32 log-sum/exp floor is 2e-4 relative at p95 (4.4e-4
          max), i.e. exact to ±1-2 units across the percent-scale band
          the cost arbitration consumes, ~3e-3 near int32 saturation.
        - On TAIL rows the linear products underflow in ANY fixed
          range — Java's own doubles return 0 or a 1e-300-clamped
          denominator (BayesianPredictor.java:416) — and this path
          instead returns the mathematically correct ratio (log sums
          cannot underflow), checked against an f64 LOG-SPACE oracle.
          That is a deliberate, documented improvement;
          ``bp.score.precision=float64`` on a CPU host reproduces the
          reference's underflow artifacts for strict rollout parity.

        Bins unseen in training (zero posterior probability) yield
        probability 0 exactly as the f64 path does."""
        f32 = jnp.float32
        x = x.astype(jnp.int32)
        values = values.astype(f32)
        post = post.astype(f32)
        prior = prior.astype(f32)
        gauss_post = gauss_post.astype(f32)
        gauss_prior = gauss_prior.astype(f32)
        class_prior = class_prior.astype(f32)
        xc = jnp.clip(x, 0, post.shape[2] - 1)

        def log_gauss(v, params):
            mean = params[..., 0]
            std = jnp.maximum(params[..., 1], 1e-9)
            z = (v - mean) / std
            return (-0.5 * z * z - jnp.log(std)
                    - f32(0.5 * math.log(2.0 * math.pi)))

        tiny = f32(1e-30)
        # random-index gathers serialize on TPU like scatters do, so the
        # per-row bin lookups run as one-hot einsum contractions on the
        # MXU (a single 1.0 weight per row selects the value); the
        # selection is exact ONLY at HIGHEST matmul precision — the TPU
        # default rounds f32 operands to bf16, quantizing the picked
        # probabilities to 8 mantissa bits (~0.4% value drift, caught
        # by the parity checker at 2M-row scale).  Wide vocabularies
        # would make the [n, F, B] one-hot explode, so they keep the
        # gather form
        n, F = x.shape
        B = post.shape[2]
        # bound the [n, F, B] one-hot by total f32 elements (~1GB), not
        # just vocabulary width — large batches explode it too
        if n * F * B <= (1 << 28):
            oh = (xc[:, :, None]
                  == jnp.arange(B)[None, None, :]).astype(f32)
            prior_pick = jnp.einsum("nfb,fb->nf", oh, prior,
                                    precision=jax.lax.Precision.HIGHEST)
            post_pick = jnp.einsum("nfb,cfb->ncf", oh, post,
                                   precision=jax.lax.Precision.HIGHEST)
        else:
            cols = jnp.arange(F)
            prior_pick = prior[cols[None, :], xc]
            post_pick = jnp.take_along_axis(
                jnp.broadcast_to(post[None], (n,) + post.shape),
                xc[:, None, :, None], axis=3)[..., 0]
        lprior_f = jnp.where(
            is_cont[None, :], log_gauss(values, gauss_prior[None, :, :]),
            jnp.log(jnp.maximum(prior_pick, tiny)))
        lfeat_prior = jnp.sum(lprior_f, axis=1)                      # [n]
        lpost_f = jnp.where(
            is_cont[None, None, :],
            log_gauss(values[:, None, :], gauss_post[None, :, :, :]),
            jnp.log(jnp.maximum(post_pick, tiny)))
        lfeat_post = jnp.sum(lpost_f, axis=2)                        # [n, C]
        lratio = (lfeat_post + jnp.log(class_prior)[None, :]
                  - lfeat_prior[:, None])
        probs = _java_int32(jnp.exp(lratio) * 100)
        # a TRUE zero posterior factor (bin unseen in training,
        # Distribution.prob() == 0) must produce probability 0, as the f64
        # product does — the tiny clamp would otherwise cancel against the
        # matching zero prior factor in log space
        post_zero = jnp.any((~is_cont)[None, None, :] & (post_pick <= 0),
                            axis=2)                               # [n, C]
        prior_zero = jnp.any((~is_cont)[None, :] & (prior_pick <= 0),
                             axis=1)                              # [n]
        probs = jnp.where(post_zero, 0, probs)
        # the auxiliary feature probabilities exponentiate in the widest
        # available dtype — tail products below ~1e-38 would flush to 0
        # in f32 — and true-zero factors emit exact 0.0 like the f64
        # products (both outputs are written verbatim in prob-only mode)
        wide = jnp.float64 if jax.config.jax_enable_x64 else f32
        return (probs,
                jnp.where(prior_zero, 0.0,
                          jnp.exp(lfeat_prior.astype(wide))),
                jnp.where(post_zero, 0.0,
                          jnp.exp(lfeat_post.astype(wide))))

    @staticmethod
    def log_oracle(x, values, post, prior, gauss_post, gauss_prior,
                   is_cont):
        """Host f64 LOG-SPACE per-row quantities ``(lfeat_prior[n],
        lfeat_post[n, C])`` — cannot underflow; the parity checker's
        ground truth for both healthy-row gating and tail-row
        validation."""
        x = np.asarray(x)
        values = np.asarray(values, np.float64)
        xc = np.clip(x, 0, post.shape[2] - 1)
        cols = np.arange(x.shape[1])
        zp = (values - gauss_prior[None, :, 0]) / np.maximum(
            gauss_prior[None, :, 1], 1e-9)
        lg_prior = (-0.5 * zp * zp - np.log(np.maximum(
            gauss_prior[None, :, 1], 1e-9)) - 0.5 * np.log(2 * np.pi))
        with np.errstate(divide="ignore"):
            lprior_f = np.where(is_cont[None, :], lg_prior,
                                np.log(prior[cols[None, :], xc]))
            zo = ((values[:, None, :] - gauss_post[None, :, :, 0])
                  / np.maximum(gauss_post[None, :, :, 1], 1e-9))
            lg_post = (-0.5 * zo * zo - np.log(np.maximum(
                gauss_post[None, :, :, 1], 1e-9))
                - 0.5 * np.log(2 * np.pi))
            lpost_f = np.where(
                is_cont[None, None, :], lg_post,
                np.log(post[np.arange(post.shape[0])[None, :, None],
                            cols[None, None, :], xc[:, None, :]]))
        return lprior_f.sum(axis=1), lpost_f.sum(axis=2)

    @staticmethod
    def f32_score_parity_violations(p64, p32, lfeat_prior, lfeat_post,
                                    class_prior, ln_healthy):
        """Count violations of the documented f32-vs-f64 contract (see
        ``_score_batch_f32``).  ``ln_healthy`` is the log-product floor
        of the f64 path's usable range on the backend that produced
        ``p64`` (~ln(1e-30) for the TPU's range-limited f64 emulation,
        ~ln(1e-250) for true IEEE doubles).  Returns a dict of counts;
        all zero = contract holds."""
        p64 = np.asarray(p64, np.float64)
        p32 = np.asarray(p32, np.float64)
        maxi = float(np.iinfo(np.int32).max)
        sat_band = (1 - 3e-3) * maxi
        healthy = ((lfeat_prior > ln_healthy)[:, None]
                   & (lfeat_post > ln_healthy))
        # measured f32 floor on-chip at 2M rows: p95 relative drift
        # 2e-4, max 4.4e-4 (log-sum + exp rounding) -> contract 1e-3,
        # with ±2 absolute covering int-boundary double-rounding at
        # small values and 3e-3 near saturation (f32 spacing at 2^31)
        d = np.abs(p32 - p64)
        tol = np.maximum(2.0, np.abs(p64) * 1e-3)
        tol = np.maximum(tol, (np.abs(p64) > 1e8) * 3e-3 * np.abs(p64))
        ok_h = (d <= tol) | ((p64 >= sat_band) & (p32 >= sat_band))
        # tail rows: the f32 log path must match the log-space oracle
        with np.errstate(over="ignore", invalid="ignore"):
            oracle = np.exp(lfeat_post + np.log(class_prior)[None, :]
                            - lfeat_prior[:, None]) * 100.0
        o_clamp = np.minimum(oracle, maxi)
        ok_finite = ((np.abs(p32 - o_clamp)
                      <= np.maximum(1.0, 1e-3 * o_clamp))
                     | ((p32 >= sat_band) & (oracle >= sat_band)))
        finite = (np.isfinite(lfeat_post)
                  & np.isfinite(lfeat_prior)[:, None])
        post_zero = np.isneginf(lfeat_post)
        # a true-zero posterior factor must emit exactly 0; rows with a
        # zero PRIOR factor only are a clamp-semantics corner (f64 uses
        # the 1e-300 floor, f32 a per-factor one) pinned by the unseen-
        # bin unit test instead
        ok_t = np.where(post_zero, p32 == 0,
                        np.where(finite, ok_finite, True))
        return {"healthy": int((healthy & ~ok_h).sum()),
                "tail": int((~healthy & ~ok_t).sum()),
                "n_healthy": int(healthy.sum()),
                "n_tail": int((~healthy).sum())}

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        """Score ``in_path`` (map-only).  With ``mesh``, rows shard over
        the ``data`` axis and the batch scores as one ``shard_map`` pass
        (the scoring math is row-local, so sharded and single-device
        runs are bit-identical — asserted per mesh factorization by the
        dryrun's whole-job parity leg)."""
        counters = Counters()
        delim_regex = self.config.field_delim_regex()
        delim = self.config.field_delim_out()

        with get_tracer().span("phase:load"):
            raw_lines = list(read_lines(in_path))
            records = [split_line(l, delim_regex) for l in raw_lines]

        if not self.tabular:
            # text mode: host-scored through the loaded model (token vocab
            # lives in model text; see module docstring — net-new surface)
            from .text import standard_tokenize
            o = BayesianDistribution.TEXT_ORDINAL
            n, C = len(records), len(self.predicting_classes)
            probs = np.zeros((n, C), dtype=np.int64)
            feat_prior = np.zeros(n)
            feat_post = np.zeros((n, C))
            for i, items in enumerate(records):
                fv = [(o, t) for t in standard_tokenize(items[0])]
                feat_prior[i] = self.model.feature_prior_prob(fv)
                for ci, cv in enumerate(self.predicting_classes):
                    feat_post[i, ci] = self.model.feature_post_prob(cv, fv)
                    ratio = (feat_post[i, ci]
                             * self.model.class_prior_prob(cv)
                             / max(feat_prior[i], 1e-300))
                    probs[i, ci] = int(ratio * 100)
            actuals = [items[1] for items in records]
            return self._emit(raw_lines, records, actuals, probs, feat_prior,
                              feat_post, delim, counters, out_path)

        schema = self.schema
        enc = DatasetEncoder(schema)
        ds = enc.encode(records)

        with get_tracer().span("phase:score"):
            tables = self._build_tables(ds)
            score_fn = (self._score_batch_f32
                        if self.score_precision == "float32"
                        else self._score_batch)
            n = ds.x.shape[0]
            if mesh is not None and mesh.shape["data"] > 1:
                from ..parallel.mesh import shard_map
                from jax.sharding import PartitionSpec as P

                from ..parallel.mesh import pad_rows

                d = mesh.shape["data"]
                x_p, _ = pad_rows(ds.x, d)
                v_p, _ = pad_rows(ds.values, d)
                spec_t = tuple(P() for _ in tables)
                fn = jax.jit(shard_map(
                    score_fn, mesh=mesh,
                    in_specs=(P("data"), P("data")) + spec_t,
                    out_specs=(P("data"), P("data"), P("data"))))
                probs, feat_prior, feat_post = fn(
                    jnp.asarray(x_p), jnp.asarray(v_p),
                    *[jnp.asarray(t) for t in tables])
            else:
                probs, feat_prior, feat_post = jax.jit(score_fn)(
                    jnp.asarray(ds.x), jnp.asarray(ds.values),
                    *[jnp.asarray(t) for t in tables])
            probs = np.asarray(probs)[:n]
            feat_prior = np.asarray(feat_prior)[:n]
            feat_post = np.asarray(feat_post)[:n]

        cls_field = schema.class_attr_field()
        actuals = [records[i][cls_field.ordinal] for i in range(len(records))]
        return self._emit(raw_lines, records, actuals, probs, feat_prior,
                          feat_post, delim, counters, out_path)

    def _emit(self, raw_lines, records, actuals, probs, feat_prior, feat_post,
              delim, counters, out_path) -> Counters:
        """Shared arbitration + output emission (tabular and text modes)."""
        with get_tracer().span("phase:emit"):
            out = self.emit_lines(raw_lines, records, actuals, probs,
                                  feat_prior, feat_post, delim, counters)
            write_output(out_path, out)
        return counters

    def emit_lines(self, raw_lines, records, actuals, probs, feat_prior,
                   feat_post, delim, counters,
                   with_confusion: bool = True) -> List[str]:
        """Arbitration + output-line formatting without the file write —
        the piece the serving engine reuses so online responses are
        byte-identical to the batch job's output lines.
        ``with_confusion=False`` skips the confusion-matrix percentage
        counters (whose integer divisions require both classes present —
        guaranteed for a whole validation run, not for one micro-batch)."""
        conf = ConfusionMatrix(self.predicting_classes[0], self.predicting_classes[1])
        out: List[str] = []
        for i, line in enumerate(raw_lines):
            actual = actuals[i]
            if self.output_feature_prob_only:
                parts = [records[i][0], str(feat_prior[i])]
                for ci, cv in enumerate(self.predicting_classes):
                    parts += [cv, str(feat_post[i, ci])]
                parts.append(actual)
                out.append(delim.join(parts))
                continue

            row = probs[i]
            if self.arbitrator is not None:
                pos = int(row[1]); neg = int(row[0])
                pred = self.arbitrator.arbitrate(pos, neg)
                prob = 100
                suffix = ""
            else:
                order = np.argsort(-row, kind="stable")
                pred = self.predicting_classes[int(order[0])]
                prob = int(row[order[0]])
                suffix = ""
                if self.class_prob_diff_threshold > 0:
                    diff = int(row[order[0]] - row[order[1]]) if len(row) > 1 else 100
                    suffix = delim + ("classified" if diff > self.class_prob_diff_threshold
                                      else "ambiguous")
            conf.report(pred, actual)
            if pred == actual:
                counters.incr("Validation", "Correct")
            else:
                counters.incr("Validation", "Incorrect")
            out.append(f"{line}{delim}{pred}{delim}{prob}{suffix}")

        if not self.output_feature_prob_only and with_confusion:
            conf.to_counters(counters)
        return out

"""Numerical attribute stats + univariate Fisher linear discriminant.

Reference surface:
- chombo's ``NumericalAttrStats`` MR (not vendored in the reference repo but
  load-bearing: FisherDiscriminant reuses its mapper/combiner/reducer —
  discriminant/FisherDiscriminant.java:57-60).  It computes per (attribute,
  condition-value) moments; condition value "0" is the unconditioned row.
  Our stats line format: ``attr,condVal,sum,sumSq,count,mean,variance,stdDev``
  (consumed by correlation.NumericalAttrStatsManager).
- ``discriminant.FisherDiscriminant`` — reducer computes, per attribute with
  two class-conditional stats: pooled variance (count-weighted), log-odds
  prior ``log(c0/c1)``, and the decision boundary
  ``(m0+m1)/2 - logOddsPrior*pooledVar/(m0-m1)``
  (FisherDiscriminant.java:84-97); output
  ``attr,logOddsPrior,pooledVariance,discrimValue``.

TPU re-design: moments are exact host NumPy per (attr, class) — see the
models.bayesian moments note (64-bit emulation on TPU costs more than the
whole pass for a handful of scalars); the record scan is one vectorized
pass.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.config import JobConfig
from ..core.multiscan import FoldSpec as MultiScanFoldSpec
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters


def _moment_rows(vals: np.ndarray, conds: List[str],
                 attr: int) -> List[Tuple[str, np.ndarray]]:
    """(condVal, [sum, sumSq, count, mean, variance, stdDev]) rows, with the
    unconditioned "0" row first."""
    out = []

    def stats(v):
        cnt = len(v)
        s = float(v.sum()); s2 = float((v * v).sum())
        mean = s / cnt
        var = s2 / cnt - mean * mean
        return np.asarray([s, s2, cnt, mean, var, math.sqrt(max(var, 0.0))])

    out.append(("0", stats(vals)))
    for cond in sorted(set(conds)):
        sel = np.asarray([c == cond for c in conds])
        out.append((cond, stats(vals[sel])))
    return out


def _stats_lines(attrs: List[int], vals_by_attr, conds: List[str],
                 delim: str) -> List[str]:
    """NumericalAttrStats output lines from per-attribute value arrays
    (shared by the standalone job and the multi-scan FoldSpec)."""
    out = []
    for a in attrs:
        for cond, row in _moment_rows(np.asarray(vals_by_attr[a]), conds, a):
            body = delim.join(str(v) for v in row)
            out.append(f"{a}{delim}{cond}{delim}{body}")
    return out


class NumericalAttrStats:
    """Per-attribute (optionally class-conditioned) moment stats job."""

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim = cfg.field_delim_out()
        attrs = [int(v) for v in cfg.must_list("attr.list")]
        cond_ord = cfg.get_int("cond.attr.ord", -1)

        records = [split_line(l, cfg.field_delim_regex())
                   for l in read_lines(in_path)]
        vals_by_attr = {a: np.asarray([float(r[a]) for r in records])
                        for a in attrs}
        conds = ([r[cond_ord] for r in records] if cond_ord >= 0
                 else ["0"] * len(records))
        write_output(out_path, _stats_lines(attrs, vals_by_attr, conds,
                                            delim))
        counters.set("Stats", "Attributes", len(attrs))
        return counters

    def fold_spec(self, out_path: str):
        """Export this job's shared-scan ``core.multiscan.FoldSpec``
        (host-only: exact float moments are deliberately computed on
        host — see the module docstring)."""
        return _StatsFoldSpec(self, out_path)


class _StatsFoldSpec(MultiScanFoldSpec):
    """Host-only shared-scan spec for NumericalAttrStats: per chunk the
    configured attribute columns parse to float64 and buffer (a few
    columns — tiny next to the CSV the scan no longer re-reads);
    finalize concatenates and emits through the exact same
    ``_moment_rows`` math as a standalone run, so output is
    byte-identical (same full-array summation order).

  Split invariance (fold(A ++ B) == merge_carries(fold(A),
    fold(B)), any chunk boundaries/order) is property-tested at
    mesh=1 and 8-way by the fold-algebra verifier
    (core.algebra, tests/test_algebra.py) — the ROADMAP-1
    multi-host psum contract this spec must keep.
    """

    local_fn = None

    def __init__(self, job: NumericalAttrStats, out_path: str):
        cfg = job.config
        self.job = job
        self.out_path = out_path
        self.name = type(job).__name__
        self.attrs = [int(v) for v in cfg.must_list("attr.list")]
        self.cond_ord = cfg.get_int("cond.attr.ord", -1)
        self.delim = cfg.field_delim_out()
        self._vals: Dict[int, list] = {a: [] for a in self.attrs}
        self._conds: List[str] = []

    def encode(self, ctx):
        cols = self._native_columns(ctx)
        if cols is not None:
            n, vals, conds = cols
            if n == 0:
                return None
            for a in self.attrs:
                self._vals[a].append(vals[a])
            self._conds.extend(conds)
            return ()
        chunk = ctx.fields()
        if isinstance(chunk, np.ndarray) and chunk.ndim == 2:
            n = chunk.shape[0]
            if n == 0:
                return None
            for a in self.attrs:
                self._vals[a].append(chunk[:, a].astype(np.float64))
            if self.cond_ord >= 0:
                self._conds.extend(chunk[:, self.cond_ord].tolist())
            else:
                self._conds.extend(["0"] * n)
        else:
            if not chunk:
                return None
            for a in self.attrs:
                self._vals[a].append(
                    np.asarray([float(r[a]) for r in chunk]))
            if self.cond_ord >= 0:
                self._conds.extend(str(r[self.cond_ord]) for r in chunk)
            else:
                self._conds.extend(["0"] * len(chunk))
        return ()   # host-only: chunk consumed, nothing to fold

    def _native_columns(self, ctx):
        """(n, {attr: float64 array}, cond list) via the native
        column extractor (C strtod — identical values to ``float()``),
        or None to fall back to the parsed field matrix."""
        from .. import native

        want = list(self.attrs)
        kinds = [native.FLOAT64] * len(want)
        if self.cond_ord >= 0:
            if self.cond_ord in want:
                return None            # duplicate ordinal: one kind each
            want.append(self.cond_ord)
            kinds.append(native.BYTES)
        cols = ctx.columns(tuple(want), tuple(kinds))
        if cols is None:
            return None
        n = len(cols[self.attrs[0]]) if self.attrs else 0
        vals = {a: cols[a] for a in self.attrs}
        if self.cond_ord >= 0:
            conds = [s.decode() for s in cols[self.cond_ord].tolist()]
        else:
            conds = ["0"] * n
        return n, vals, conds

    def finalize(self, carry) -> Counters:
        counters = Counters()
        vals_by_attr = {
            a: (np.concatenate(v) if v else np.zeros(0))
            for a, v in self._vals.items()}
        write_output(self.out_path, _stats_lines(
            self.attrs, vals_by_attr, self._conds, self.delim))
        counters.set("Stats", "Attributes", len(self.attrs))
        return counters


class FisherDiscriminant:
    """Univariate Fisher discriminant job (reuses the stats computation the
    way the reference reuses chombo's NumericalAttrStats)."""

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim = cfg.field_delim_out()
        attrs = [int(v) for v in cfg.must_list("attr.list")]
        cond_ord = cfg.must_int("cond.attr.ord")

        records = [split_line(l, cfg.field_delim_regex())
                   for l in read_lines(in_path)]
        conds = [r[cond_ord] for r in records]

        out = []
        for a in attrs:
            vals = np.asarray([float(r[a]) for r in records])
            rows = _moment_rows(vals, conds, a)
            # stats lines (NumericalAttrStats output, emitted by the shared
            # reducer path in the reference)
            for cond, row in rows:
                body = delim.join(str(v) for v in row)
                out.append(f"{a}{delim}{cond}{delim}{body}")
            # the two class-conditional rows in sorted-value order — the MR
            # shuffle delivers keys sorted, so c0/c1 assignment follows the
            # sorted class values (flipping it would negate logOddsPrior)
            cls = [(cond, row) for cond, row in rows if cond != "0"]
            if len(cls) != 2:
                raise ValueError(
                    f"FisherDiscriminant needs exactly 2 class values, "
                    f"got {[c for c, _ in cls]}")
            (c0, r0), (c1, r1) = cls
            cnt0, m0, v0 = r0[2], r0[3], r0[4]
            cnt1, m1, v1 = r1[2], r1[3], r1[4]
            pooled_var = (v0 * cnt0 + v1 * cnt1) / (cnt0 + cnt1)
            log_odds_prior = math.log(cnt0 / cnt1)
            mean_diff = m0 - m1
            discrim = (m0 + m1) / 2 - log_odds_prior * pooled_var / mean_diff
            out.append(f"{a}{delim}{log_odds_prior}{delim}{pooled_var}"
                       f"{delim}{discrim}")
            counters.incr("Fisher", "Attributes")
        write_output(out_path, out)
        return counters

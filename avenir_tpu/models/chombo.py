"""External chombo MR jobs that reference pipelines invoke between avenir
jobs.  chombo is the sister utility library (SURVEY §2.0: declared
``mawazo:chombo:1.0`` pom dependency, source NOT vendored in the reference
repo), so these semantics are reconstructed from every call site in the
reference runbooks/properties — each job cites the exact lines it serves.

These are host-side data-wrangling legs (filter / reorder / running
aggregate) between the device-bound avenir jobs; none of them is a
counting or FLOPs workload, so they run as plain streaming host passes —
the TPU budget stays on the jobs around them.

- ``org.chombo.mr.TemporalFilter`` — the Apriori pipeline's time-range
  filter (resource/fit.sh:30-41, tef.* keys in resource/fit.properties:8-14).
- ``org.chombo.mr.Projection`` — the Markov tutorials' group-and-order
  projection (cust_churn_markov_chain_classifier_tutorial.txt:26-37,83-90;
  projection.* keys in resource/buyhist.properties:6-11).
- ``org.chombo.mr.RunningAggregator`` — the bandit round loop's reward
  re-aggregation (price_optimize_tutorial.txt:41-62; quantity.attr /
  incremental.file.prefix keys in the tutorial's Configuration section),
  delegating the math to ``models.bandit.aggregate_rewards``.
"""

from __future__ import annotations

import os
from typing import List

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import _input_files, read_lines, split_line, write_output
from ..core.metrics import Counters


class TemporalFilter:
    """Map-only epoch-time-range row filter (resource/fit.sh:30-41).

    Config (resource/fit.properties:8-14): ``time.stamp.field.ordinal``,
    ``time.range`` = comma-separated ``start:end`` windows (inclusive),
    ``time.stamp.in.mili`` (divide by 1000 first),
    ``time.zone.shift.hours`` (added before the compare), and
    ``seasonal.cycle.type``.  The reference pipeline uses
    ``anyTimeRange`` (windows in raw epoch seconds); the other chombo
    SeasonalAnalyzer cycle types interpret the windows as positions
    WITHIN the cycle — chombo's source is not vendored in the reference
    repo (SURVEY §2.0), so the cycle index definitions below are
    reconstructed and documented here: ``quarterHourOfDay`` 0-95,
    ``halfHourOfDay`` 0-47, ``hourOfDay`` 0-23 (all straight epoch
    divisions), ``dayOfWeek`` 0-6 with 0 = Sunday (Java
    Calendar.DAY_OF_WEEK order minus one), ``weekDayOrWeekEnd`` 0 =
    weekday / 1 = weekend, ``monthOfYear`` 0-11 (UTC).  Unknown types
    still fail fast.  Rows inside any window pass through unchanged.

    DOCUMENTED DIVERGENCE (timezone semantics, ADVICE r5): every cycle
    index here is computed in UTC plus the FIXED ``time.zone.shift.hours``
    offset, whereas chombo's SeasonalAnalyzer goes through
    ``java.util.Calendar`` in the JVM's DEFAULT timezone.  For non-UTC
    deployments the day/week/month boundaries can differ — in particular
    a DST transition moves Calendar-local boundaries by an hour twice a
    year, which no fixed shift can express (a row stamped inside the DST
    gap lands in the previous ``dayOfWeek``/``monthOfYear`` cell here).
    Operators needing Calendar-local parity must run with a UTC JVM
    default on the reference side or pre-shift timestamps; re-verify
    against chombo upstream if its source becomes available.
    """

    CYCLES = ("anyTimeRange", "quarterHourOfDay", "halfHourOfDay",
              "hourOfDay", "dayOfWeek", "weekDayOrWeekEnd", "monthOfYear")

    def __init__(self, config: JobConfig):
        self.config = config

    @staticmethod
    def _cycle_index(cycle: str, t: int) -> int:
        if cycle == "anyTimeRange":
            return t
        if cycle == "quarterHourOfDay":
            return (t // 900) % 96
        if cycle == "halfHourOfDay":
            return (t // 1800) % 48
        if cycle == "hourOfDay":
            return (t // 3600) % 24
        if cycle == "dayOfWeek":
            # epoch day 0 (1970-01-01) was a Thursday; 0 = Sunday per
            # Java Calendar.DAY_OF_WEEK - 1
            return ((t // 86400) + 4) % 7
        if cycle == "weekDayOrWeekEnd":
            return 1 if ((t // 86400) + 4) % 7 in (0, 6) else 0
        if cycle == "monthOfYear":
            import time as _time
            return _time.gmtime(t).tm_mon - 1
        raise AssertionError(cycle)

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        cfg = self.config
        counters = Counters()
        cycle = cfg.get("seasonal.cycle.type", "anyTimeRange")
        if cycle not in self.CYCLES:
            raise ValueError(
                f"seasonal.cycle.type {cycle!r} not supported; known "
                f"types: {', '.join(self.CYCLES)}")
        ts_ord = cfg.must_int("time.stamp.field.ordinal")
        in_mili = cfg.get_boolean("time.stamp.in.mili", False)
        shift = 3600 * (cfg.get_int("time.zone.shift.hours", 0) or 0)
        ranges = []
        for spec in (cfg.get("time.range") or "").split(","):
            lo, _, hi = spec.partition(":")
            if not hi:
                raise ValueError(f"bad time.range window {spec!r}; "
                                 "expected start:end (epoch seconds for "
                                 "anyTimeRange, cycle positions "
                                 "otherwise)")
            ranges.append((int(lo), int(hi)))
        delim_regex = cfg.field_delim_regex()

        out: List[str] = []
        for line in read_lines(in_path):
            counters.incr("Basic", "Records read")
            t = int(split_line(line, delim_regex)[ts_ord])
            if in_mili:
                t //= 1000
            t += shift
            idx = self._cycle_index(cycle, t)
            if any(lo <= idx <= hi for lo, hi in ranges):
                out.append(line)
                counters.incr("Basic", "Records emitted")
        write_output(out_path, out)
        return counters


class Projection:
    """Column projection with optional group-and-order
    (cust_churn_markov_chain_classifier_tutorial.txt:26-37).

    Config (resource/buyhist.properties:6-11): ``projection.operation``
    ``project`` (plain column projection) or ``groupingOrdering`` (group
    rows by ``key.field`` ordinals, order each group by
    ``orderBy.field`` — numeric when every value parses as a number,
    else lexicographic, which orders ISO dates correctly — then emit the
    ``projection.field`` columns).  ``format.compact=true`` emits one
    line per key (key fields, then each record's projected fields in
    order — the tutorial's "one output line per customer"); otherwise
    one line per record (key fields + projected fields), groups
    contiguous.  Sorting is stable, matching the secondary-sort tie
    behavior of a single-reducer chombo run.
    """

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        cfg = self.config
        counters = Counters()
        op = cfg.get("projection.operation", "project")
        proj = [int(f) for f in cfg.get_list("projection.field") or []]
        if not proj:
            raise ValueError("projection.field is required")
        delim_regex = cfg.field_delim_regex()
        delim = cfg.field_delim_out()

        if op == "project":
            out = []
            for line in read_lines(in_path):
                counters.incr("Basic", "Records read")
                items = split_line(line, delim_regex)
                out.append(delim.join(items[f] for f in proj))
            write_output(out_path, out)
            return counters
        if op != "groupingOrdering":
            raise ValueError(f"unknown projection.operation {op!r}; "
                             "use 'project' or 'groupingOrdering'")

        key_ords = [int(f) for f in cfg.get_list("key.field") or []]
        if not key_ords:
            raise ValueError("key.field is required for groupingOrdering")
        order_ord = cfg.must_int("orderBy.field")
        compact = cfg.get_boolean("format.compact", False)

        groups: dict = {}
        for line in read_lines(in_path):
            counters.incr("Basic", "Records read")
            items = split_line(line, delim_regex)
            key = tuple(items[f] for f in key_ords)
            groups.setdefault(key, []).append(items)

        out = []
        # reducer key-sorted group order, as a single-reducer chombo MR
        # would emit (keys are text tuples, so lexicographic)
        for key, recs in sorted(groups.items()):
            # numeric order only when the whole group's orderBy column
            # parses (the documented column-level rule); else
            # lexicographic — which orders ISO dates correctly
            try:
                order_key = [(float(r[order_ord]), i)
                             for i, r in enumerate(recs)]
                if any(v != v for v, _ in order_key):   # NaN literals
                    raise ValueError
            except ValueError:
                order_key = [(r[order_ord], i) for i, r in enumerate(recs)]
            recs = [recs[i] for _, i in sorted(order_key)]
            if compact:
                fields = list(key)
                for items in recs:
                    fields.extend(items[f] for f in proj)
                out.append(delim.join(fields))
            else:
                for items in recs:
                    out.append(delim.join(
                        list(key) + [items[f] for f in proj]))
        counters.set("Basic", "Groups", len(groups))
        write_output(out_path, out)
        return counters


class RunningAggregator:
    """Inter-round running-average aggregation
    (price_optimize_tutorial.txt:41-62): the input dir holds the previous
    running-aggregate state (``group,item,count,avg`` — the bandit jobs'
    input format) plus incremental reward files whose basenames start
    with ``incremental.file.prefix`` (``group,item,...,reward`` with the
    reward at ``quantity.attr``); the output is the updated state the
    next round's bandit job reads.  The math is
    ``models.bandit.aggregate_rewards`` (integer running average, Java
    long-division parity) — this job is its CLI packaging, completing
    the tutorial's literal run-job/score/re-aggregate/bump-round loop.
    """

    def __init__(self, config: JobConfig):
        self.config = config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        from .bandit import aggregate_rewards

        cfg = self.config
        counters = Counters()
        qty_ord = cfg.get_int("quantity.attr", 2)
        prefix = cfg.get("incremental.file.prefix", "inc")
        delim_regex = cfg.field_delim_regex()
        delim = cfg.field_delim_out()

        prev: List[str] = []
        incr: List[str] = []
        for path in _input_files(in_path):
            incremental = os.path.basename(path).startswith(prefix)
            for line in read_lines(path):
                items = split_line(line, delim_regex)
                if incremental:
                    counters.incr("Basic", "Incremental records")
                    incr.append(delim.join(
                        items[:2] + [items[qty_ord]]))
                else:
                    counters.incr("Basic", "State records")
                    prev.append(delim.join(items[:4]))

        out = aggregate_rewards(incr, prev, delim=delim)
        counters.set("Basic", "State records out", len(out))
        write_output(out_path, out)
        return counters

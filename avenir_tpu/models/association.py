"""Association mining: Apriori frequent itemsets, rule miner, marker.

Reference surface:
- ``association.FrequentItemsApriori`` — one MR pass per itemset length k
  (driven per resource/freq_items_apriori_tutorial.txt:37-46).  k=1: emit
  each token -> transId|1 (FrequentItemsApriori.java:138-150).  k>1: for each
  frequent (k-1)-itemset the transaction supports, extend by each new
  non-marker item, sort, emit (:151-196); combiner/reducer union trans-id
  sets or sum counts; support threshold strictly, support printed with 3
  decimals (:306-342).  In count mode a candidate reached from m frequent
  (k-1)-subsets is emitted m times per supporting transaction — that
  multiplicity is part of the reference's observable output and is
  reproduced here.
- ``association.ItemSetList`` — text loader: items, [transIds,] support.
- ``association.AssociationRuleMiner`` — per frequent itemset emits
  antecedent sublists (size <= arm.max.ante.size) and computes
  confidence = support(whole)/support(antecedent), strict threshold,
  output ``a1,a2 -> c1,c2`` (AssociationRuleMiner.java:111-196).
- ``association.InfrequentItemMarker`` — rewrites transactions replacing
  items absent from the frequent 1-itemset list with a marker
  (InfrequentItemMarker.java:77-150).

TPU re-design (SURVEY §7.2 stage 3): the transaction set becomes a boolean
incidence matrix ``inc[t, item]`` sharded over transactions.  The support of
every candidate s ∪ {x} for all frequent (k-1)-itemsets s and all items x is
ONE MXU matmul: ``co = v_s^T @ inc`` where ``v_s[t] = prod_{i in s} inc[t,i]``
is the itemset-support indicator — the mapper's triple loop and the shuffle
vanish into a [n_s, n_t] x [n_t, V] contraction with psum over the
transaction shards.  Distinct-transaction semantics are inherent (boolean
algebra); count-mode multiplicities are applied host-side.

Host-side scaffolding is bulk NumPy, not per-token Python:
- parsing/vocab/counting is done ONCE per input file and cached
  (``_EncodedTransactions``), so the per-k CLI passes of the reference's
  manual loop (resource/freq_items_apriori_tutorial.txt:37-46) re-use it;
- k=1 is a vectorized ``bincount`` over the token stream (occurrences) or the
  deduped (transaction, item) pairs (distinct mode);
- k>1 prunes the extension vocabulary to items that can still reach the
  support threshold before building the incidence matrix.  Support is
  monotone — support(s ∪ {x}) <= support({x}) — so in distinct mode only
  items with pass-1 support > threshold can appear in an emitted itemset; in
  count mode the emitted value is distinct-count x multiplicity with
  multiplicity <= k, so items with pass-1 count <= threshold x total / k are
  unreachable.  The pruning never changes the output, it only shrinks V from
  the full vocabulary (50k in the tutorial) to the frequent few hundred;
- candidate extraction from the co-occurrence matrix thresholds first and
  only materializes Python tuples for survivors.
"""

from __future__ import annotations

import os
from itertools import combinations
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from ..core.config import JobConfig
from ..core.obs import traced_run
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters
from ..parallel.mesh import get_mesh, pad_rows
from ..utils.caches import bounded_cache_get, bounded_cache_put


def _fmt_support(v: float) -> str:
    """Utility.formatDouble(support, 3) equivalent."""
    return f"{v:.3f}"


class ItemSet:
    """(items, transactionIds) pair (association/ItemSetList.java:65-101)."""

    def __init__(self, items: Sequence[str], trans_ids: Sequence[str] = ()):
        self.items = list(items)
        self.transaction_ids = list(trans_ids)

    def contains_item(self, item: str) -> bool:
        return item in self.items

    def contains_trans(self, trans_id: str) -> bool:
        return trans_id in self.transaction_ids


class ItemSetList:
    """Loader for itemset output lines: items, [transIds,] support."""

    def __init__(self, path: str, item_set_length: int,
                 contains_trans_ids: bool, delim: str = ","):
        self.item_sets: List[ItemSet] = []
        for line in read_lines(path):
            tokens = line.split(delim)
            items = tokens[:item_set_length]
            tids = tokens[item_set_length:-1] if contains_trans_ids else ()
            self.item_sets.append(ItemSet(items, tids))

    def get_item_set_list(self) -> List[ItemSet]:
        return self.item_sets


def _apriori_chunk_support_local(inc, mask, sets_idx):
    """Streaming-fold twin of ``_apriori_support_local``: one transaction
    ROW CHUNK's contribution to the candidate-support matrix, summed
    across chunks by ``core.pipeline``'s donated accumulator.  f32 sums
    of 0/1 products are exact below 2^24 supporting rows, so the folded
    counts are bit-identical to the monolithic matmul after rounding."""
    incb = inc.astype(jnp.bfloat16) * mask[:, None].astype(jnp.bfloat16)
    km1 = sets_idx.shape[2]

    def step(_, idx_chunk):                          # [S, k-1]
        v = incb[:, idx_chunk[:, 0]]                 # [nt, S]
        for i in range(1, km1):
            v = v * incb[:, idx_chunk[:, i]]
        co = jax.lax.dot_general(
            v, incb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [S, V]
        return None, co

    _, cos = jax.lax.scan(step, None, sets_idx)      # [n_chunks, S, V]
    return cos.reshape(-1, incb.shape[1])


def _apriori_support_local(inc, sets_idx, mask):
    """Per-shard candidate support: v = prod of candidate-member columns,
    co = v^T @ inc (bf16 on the MXU), psum'd over transaction shards.

    inc: [nt, V] uint8 (0/1 — transferred narrow, widened on device);
    sets_idx: [n_chunks, S, k-1] int32 column ids (chunked over the
    candidate axis so the [nt, S] indicator block is the only large
    intermediate — an unchunked [nt, n_s, k-1] gather OOMs when a pass
    produces thousands of candidates); mask [nt].
    """
    incb = inc.astype(jnp.bfloat16) * mask[:, None].astype(jnp.bfloat16)
    km1 = sets_idx.shape[2]

    def step(_, idx_chunk):                          # [S, k-1]
        v = incb[:, idx_chunk[:, 0]]                 # [nt, S]
        for i in range(1, km1):
            v = v * incb[:, idx_chunk[:, i]]
        co = jax.lax.dot_general(
            v, incb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [S, V]
        return None, co

    _, cos = jax.lax.scan(step, None, sets_idx)      # [n_chunks, S, V]
    return jax.lax.psum(cos.reshape(-1, incb.shape[1]), "data")


# One compiled support kernel per mesh: jit re-specializes per shape, and a
# stable function object lets repeated passes (k=2,3,... and bench rounds)
# hit the jit cache instead of retracing.
_support_fn_cache: Dict = {}

# Row-sharded incidence matrices kept on device across k passes (keyed by
# encoded-input identity + mode + pruned-vocab signature + mesh).
_inc_device_cache: Dict = {}


def _support_fn(mesh):
    fn = _support_fn_cache.get(mesh)
    if fn is None:
        fn = jax.jit(shard_map(
            _apriori_support_local, mesh=mesh,
            in_specs=(P("data"), P(), P("data")),
            out_specs=P()))
        _support_fn_cache[mesh] = fn
    return fn


class _EncodedTransactions:
    """Bulk-parsed transaction file: flat (row, item-id) token streams,
    sorted vocabulary, and pass-1 counts — computed once, shared by every k
    pass over the same input (the reference re-reads per pass;
    FrequentItemsApriori.java:109-128)."""

    def __init__(self, in_path: str, delim_regex: str, skip: int,
                 trans_ord: int, marker: Optional[str]):
        records = [split_line(l, delim_regex) for l in read_lines(in_path)]
        self.nt = len(records)
        # transaction IDENTITY is the id string, not the input line: the
        # reference reducer unions trans-id strings, so a transaction split
        # across lines counts once in distinct mode
        # (FrequentItemsApriori.java:311-326).  tid_vocab is sorted by
        # np.unique, matching the sorted tid emission.
        trans_id_strs = [r[trans_ord] for r in records]
        self.tid_vocab, tid_codes = np.unique(
            np.asarray(trans_id_strs, dtype=object).astype(str),
            return_inverse=True)
        self.n_tid = len(self.tid_vocab)
        lengths = np.asarray([max(len(r) - skip, 0) for r in records],
                             dtype=np.int64)
        rows = np.repeat(np.arange(self.nt, dtype=np.int64), lengths)
        tokens = np.asarray([it for r in records for it in r[skip:]],
                            dtype=object)
        if marker is not None:
            keep = tokens != marker
            rows, tokens = rows[keep], tokens[keep]
        # np.unique sorts -> vocab order == the reference's sorted emission
        self.vocab, ids = np.unique(tokens.astype(str), return_inverse=True)
        self.ids = ids.astype(np.int64)
        self.rows = rows
        V = len(self.vocab)
        self.occ_counts = np.bincount(self.ids, minlength=V)
        # count mode counts supporting input ROWS: dedupe (row, item)
        rpair = np.unique(self.rows * V + self.ids)
        self.drows = (rpair // V).astype(np.int64)
        self.dids = (rpair % V).astype(np.int64)
        # distinct mode counts distinct TRANSACTION IDS: dedupe (tid, item)
        tcodes = tid_codes.astype(np.int64)[self.rows]
        tpair = np.unique(tcodes * V + self.ids)
        self.dtids = (tpair // V).astype(np.int64)
        self.dtids_item = (tpair % V).astype(np.int64)
        self.distinct_counts = np.bincount(self.dtids_item, minlength=V)
        # (item-major ordering of the (tid, item) pairs, for tid lists)
        order = np.argsort(self.dtids_item, kind="stable")
        self._items_sorted = self.dtids_item[order]
        self._tids_by_item = self.dtids[order]
        self._item_starts = np.searchsorted(
            self._items_sorted, np.arange(V + 1))
        self.vocab_index = {it: i for i, it in enumerate(self.vocab)}

    def tid_codes_for_item(self, item_id: int) -> np.ndarray:
        """Codes (into tid_vocab) of the distinct transactions containing
        the item, in sorted-tid order."""
        s, e = self._item_starts[item_id], self._item_starts[item_id + 1]
        return np.sort(self._tids_by_item[s:e])


_encode_cache: Dict = {}


def _encode_transactions(in_path: str, delim_regex: str, skip: int,
                         trans_ord: int,
                         marker: Optional[str]) -> _EncodedTransactions:
    if os.path.isdir(in_path):
        # a job-output directory of part files: stamp each member (a part
        # file rewritten in place changes its own mtime, not the dir's)
        stamp = tuple(sorted(
            (f, os.stat(os.path.join(in_path, f)).st_mtime_ns,
             os.stat(os.path.join(in_path, f)).st_size)
            for f in os.listdir(in_path)))
    else:
        st = os.stat(in_path)
        stamp = (st.st_mtime_ns, st.st_size)
    key = (os.path.abspath(in_path), stamp, delim_regex, skip, trans_ord,
           marker)
    enc = bounded_cache_get(_encode_cache, key)
    if enc is None:
        enc = _EncodedTransactions(in_path, delim_regex, skip, trans_ord,
                                   marker)
        bounded_cache_put(_encode_cache, key, enc)
    return enc


class FrequentItemsApriori:
    """One Apriori pass (one k); config prefix ``fia``."""

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("fia") if not config.prefix else config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        delim = cfg.field_delim_out()
        skip = cfg.get_int("skip.field.count", 1)
        k = cfg.must_int("item.set.length", "missing item set length")
        trans_ord = cfg.must_int("tans.id.ord", "missing transaction id ordinal")
        emit_trans_id = cfg.get_boolean("emit.trans.id", True)
        threshold = cfg.must_float("support.threshold", "missing support threshold")
        total_trans = cfg.must_int("total.tans.count", "missing total transaction count")
        trans_id_output = cfg.get_boolean("trans.id.output", True)
        marker = cfg.get("infreq.item.marker")

        enc = _encode_transactions(in_path, delim_regex, skip, trans_ord,
                                   marker)
        if k == 1:
            lines = self._pass_one(enc, emit_trans_id, threshold, total_trans,
                                   trans_id_output, delim)
        else:
            prev = ItemSetList(cfg.must("item.set.file.path"), k - 1,
                               emit_trans_id, ",")
            lines = self._pass_k(enc, prev, k, emit_trans_id, threshold,
                                 total_trans, trans_id_output, delim, mesh)
        write_output(out_path, lines)
        counters.set("Apriori", "FrequentItemSets", len(lines))
        return counters

    # -- k == 1: vectorized token counting ---------------------------------
    def _pass_one(self, enc: _EncodedTransactions, emit_trans_id, threshold,
                  total_trans, trans_id_output, delim) -> List[str]:
        # reference counts every token occurrence at k=1 in count mode,
        # distinct transactions in trans-id mode
        counts = enc.distinct_counts if emit_trans_id else enc.occ_counts
        support = counts / total_trans
        frequent = np.nonzero(support > threshold)[0]
        lines = []
        for i in frequent:          # vocab is sorted; emission order matches
            it = enc.vocab[i]
            if emit_trans_id:
                if trans_id_output:
                    tids = list(enc.tid_vocab[enc.tid_codes_for_item(i)])
                    lines.append(delim.join([it] + tids +
                                            [_fmt_support(support[i])]))
                else:
                    lines.append(f"{it}{delim}{_fmt_support(support[i])}")
            else:
                lines.append(f"{it}{delim}{counts[i]}{delim}"
                             f"{_fmt_support(support[i])}")
        return lines

    # -- k > 1: incidence matmul on device ---------------------------------
    def _pass_k(self, enc: _EncodedTransactions, prev: ItemSetList, k,
                emit_trans_id, threshold, total_trans, trans_id_output,
                delim, mesh) -> List[str]:
        mesh = mesh or get_mesh()
        V = len(enc.vocab)
        vocab_index = enc.vocab_index
        prev_sets = [s for s in prev.get_item_set_list()
                     if all(it in vocab_index for it in s.items)]
        if not prev_sets:
            return []

        # prune the extension vocabulary to items that can still reach the
        # threshold (support monotonicity — see module docstring).  Emission
        # is strict >, so the bound is strict too.  Count mode emits
        # distinct x multiplicity with multiplicity <= k.
        counts1 = enc.distinct_counts if emit_trans_id else enc.occ_counts
        bound = threshold * total_trans / (1 if emit_trans_id else k)
        keep = counts1 > bound
        # previous-itemset members are provably above the bound already
        # (their (k-1)-set passed the threshold); include them defensively
        sets_idx_full = np.asarray(
            [[vocab_index[it] for it in s.items] for s in prev_sets],
            dtype=np.int64)                            # [n_s, k-1]
        keep[sets_idx_full.ravel()] = True
        kept = np.nonzero(keep)[0]
        col_of = np.full(V, -1, dtype=np.int64)
        col_of[kept] = np.arange(len(kept))
        V_eff = len(kept)

        # incidence over the pruned vocabulary, built by one bulk scatter.
        # Distinct mode counts distinct TRANSACTION IDS (one incidence row
        # per tid, so a transaction split across input lines counts once);
        # count mode counts supporting input ROWS (one emission per record,
        # FrequentItemsApriori.java:151-196).
        if emit_trans_id:
            prows, pitems = enc.dtids, enc.dtids_item
            n_rows = enc.n_tid
        else:
            prows, pitems = enc.drows, enc.dids
            n_rows = enc.nt
        sel = col_of[pitems] >= 0

        def build_inc():
            m = np.zeros((n_rows, V_eff), dtype=np.uint8)
            m[prows[sel], col_of[pitems[sel]]] = 1
            return m

        sets_idx = col_of[sets_idx_full].astype(np.int32)
        n_s = sets_idx.shape[0]

        # out-of-core chunked support counting (pipeline.chunk.rows /
        # pipeline.device.budget.bytes): incidence rows stream through
        # core.pipeline in bounded chunks instead of one resident array —
        # the path for transaction sets larger than device memory
        chunk_rows = self.config.pipeline_chunk_rows(
            row_bytes=max(V_eff, 1))
        if chunk_rows is not None and chunk_rows < n_rows:
            def inc_chunk(start, stop, dtype=np.uint8):
                lo, hi = np.searchsorted(prows, [start, stop])
                pr, pi = prows[lo:hi], pitems[lo:hi]
                s = sel[lo:hi]
                m = np.zeros((stop - start, V_eff), dtype=dtype)
                m[pr[s] - start, col_of[pi[s]]] = 1
                return m

            co = self._support_streamed(
                inc_chunk, n_rows, V_eff, sets_idx, k, mesh, chunk_rows,
                self.config.pipeline_prefetch_depth())
            return self._emit_pass_k(
                enc, prev_sets, sets_idx, co, k, emit_trans_id, threshold,
                total_trans, trans_id_output, delim, col_of, kept, V_eff,
                vocab_index,
                tid_rows_fn=lambda cands: self._tid_rows_chunked(
                    inc_chunk, n_rows, chunk_rows, cands))

        d = mesh.shape["data"]
        # device-resident incidence across k passes: the pruned vocabulary
        # is k-invariant in distinct mode and usually so in count mode
        # (frequent-item counts sit far from the k-scaled bound), so the
        # row-sharded device array survives the reference's per-k job
        # re-runs and the host build + transfer happen once per input
        # (VERDICT r2 item 4).  Keyed on the encode's identity through a
        # weakref whose callback drops the entry, so the HBM incidence
        # (hundreds of MB at bench scale) is released as soon as
        # _encode_cache evicts the encode — a strong key would pin both
        # for the process lifetime.
        import weakref

        inc = None
        ckey = (id(enc), emit_trans_id, mesh, kept.tobytes())
        cached = bounded_cache_get(_inc_device_cache, ckey)
        if cached is not None and cached[0]() is not enc:
            cached = None                      # id reuse after gc
        if cached is None:
            from ..parallel.mesh import shard_rows
            inc = build_inc()
            inc_p, mask = pad_rows(inc, d)
            inc_dev = shard_rows(inc_p, mesh)
            mask_dev = shard_rows(mask, mesh)
            ref = weakref.ref(
                enc, lambda _: _inc_device_cache.pop(ckey, None))
            bounded_cache_put(_inc_device_cache, ckey,
                              (ref, inc_dev, mask_dev), cap=2)
        else:
            _, inc_dev, mask_dev = cached
        # candidate-axis chunking: keep the [nt, S] indicator block under
        # ~2^28 bf16 elements per shard
        nt_local = max(-(-n_rows // d), 1)
        S = max(min(n_s, (1 << 28) // max(nt_local, 1)), 16)
        C = -(-n_s // S)
        pad_s = C * S - n_s
        sets_idx_p = sets_idx if not pad_s else np.concatenate(
            [sets_idx, np.zeros((pad_s, k - 1), np.int32)])
        co = np.asarray(_support_fn(mesh)(
            inc_dev, sets_idx_p.reshape(C, S, k - 1),
            mask_dev))[:n_s]                            # [n_s, V_eff]

        def tid_rows_full(cand_cols):
            inc_bool = (inc if inc is not None else build_inc()).astype(bool)
            return {cand: np.nonzero(inc_bool[:, cols].all(axis=1))[0]
                    for cand, cols in cand_cols.items()}

        return self._emit_pass_k(
            enc, prev_sets, sets_idx, co, k, emit_trans_id, threshold,
            total_trans, trans_id_output, delim, col_of, kept, V_eff,
            vocab_index, tid_rows_fn=tid_rows_full)

    def _support_streamed(self, inc_chunk, n_rows, V_eff, sets_idx, k,
                          mesh, chunk_rows, depth):
        """Candidate supports by streaming incidence ROW chunks through
        ``core.pipeline``: chunk c+1's build + H2D copy overlap chunk c's
        MXU contraction, and only (depth + 2) chunks are ever resident —
        the out-of-core form of the device-resident support matmul."""
        from ..core import pipeline
        from ..parallel.mesh import get_mesh as _get_mesh

        mesh = mesh or _get_mesh()
        d = int(mesh.devices.size)
        n_s = sets_idx.shape[0]
        nt_loc = max(-(-min(chunk_rows, max(n_rows, 1)) // d), 1)
        S = max(min(n_s, (1 << 28) // nt_loc), 16)
        C = -(-n_s // S)
        pad_s = C * S - n_s
        sets_p = sets_idx if not pad_s else np.concatenate(
            [sets_idx, np.zeros((pad_s, k - 1), np.int32)])

        def chunks():
            for start in range(0, n_rows, chunk_rows):
                yield (inc_chunk(start, min(start + chunk_rows, n_rows)),)

        co = pipeline.streaming_fold(
            chunks(), _apriori_chunk_support_local,
            broadcast_args=(sets_p.reshape(C, S, k - 1),),
            mesh=mesh, prefetch_depth=depth, capacity=chunk_rows)
        if co is None:
            return np.zeros((n_s, V_eff), dtype=np.float32)
        return np.asarray(co)[:n_s]

    @staticmethod
    def _tid_rows_chunked(inc_chunk, n_rows, chunk_rows, cand_cols):
        """Per-candidate supporting row codes without materializing the
        full incidence: one more chunked host pass (ascending starts keep
        the sorted-tid emission order)."""
        out = {cand: [] for cand in cand_cols}
        for start in range(0, n_rows, chunk_rows):
            m = inc_chunk(start, min(start + chunk_rows, n_rows),
                          dtype=bool)
            for cand, cols in cand_cols.items():
                r = np.nonzero(m[:, cols].all(axis=1))[0]
                if r.size:
                    out[cand].append(r + start)
        return {cand: (np.concatenate(rs) if rs
                       else np.zeros(0, dtype=np.int64))
                for cand, rs in out.items()}

    def _emit_pass_k(self, enc, prev_sets, sets_idx, co, k, emit_trans_id,
                     threshold, total_trans, trans_id_output, delim,
                     col_of, kept, V_eff, vocab_index,
                     tid_rows_fn) -> List[str]:
        """Threshold + line emission shared by the resident and streamed
        support paths (the reference shuffles every candidate and filters
        in the reducer, FrequentItemsApriori.java:306-342 — same output).
        Thresholding happens BEFORE materializing candidates: only
        survivors get Python tuples."""
        cnt_mat = np.rint(co).astype(np.int64)
        member = np.zeros((len(prev_sets), V_eff), dtype=bool)
        member[np.arange(len(prev_sets))[:, None], sets_idx] = True
        if emit_trans_id:
            survive = (cnt_mat > threshold * total_trans) & ~member
        else:
            # multiplicity (#frequent (k-1)-subsets) is at most k
            survive = (cnt_mat * k > threshold * total_trans) & ~member \
                & (cnt_mat > 0)

        distinct: Dict[Tuple[str, ...], int] = {}
        prev_keys = {tuple(sorted(s.items)) for s in prev_sets}
        for si, x in zip(*np.nonzero(survive)):
            cand = tuple(sorted(prev_sets[si].items +
                                [enc.vocab[kept[x]]]))
            distinct[cand] = int(cnt_mat[si, x])

        lines = []
        tid_rows = None
        if emit_trans_id and trans_id_output and distinct:
            # incidence rows are tid codes; tid_vocab is sorted and row
            # codes ascend, so the emission order is sorted-tid order
            tid_rows = tid_rows_fn(
                {cand: [col_of[vocab_index[it]] for it in cand]
                 for cand in distinct})
        for cand in sorted(distinct):
            cnt = distinct[cand]
            if not emit_trans_id:
                m = sum(1 for sub in combinations(cand, k - 1)
                        if tuple(sorted(sub)) in prev_keys)
                cnt = cnt * m
            support = (distinct[cand] if emit_trans_id else cnt) / total_trans
            if support > threshold:
                if emit_trans_id:
                    if trans_id_output:
                        tids = list(enc.tid_vocab[tid_rows[cand]])
                        lines.append(delim.join(list(cand) + tids +
                                                [_fmt_support(support)]))
                    else:
                        lines.append(delim.join(list(cand) +
                                                [_fmt_support(support)]))
                else:
                    lines.append(delim.join(list(cand) +
                                            [str(cnt), _fmt_support(support)]))
        return lines


class AssociationRuleMiner:
    """Rules from frequent itemsets (+supports); config prefix ``arm``."""

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("arm") if not config.prefix else config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        max_ante = cfg.get_int("max.ante.size", 3)
        conf_threshold = cfg.must_float("conf.threshold",
                                        "missing confidence threshold")

        supports: Dict[Tuple[str, ...], float] = {}
        itemsets: List[Tuple[Tuple[str, ...], float]] = []
        for line in read_lines(in_path):
            tokens = split_line(line, delim_regex)
            items = tuple(tokens[:-1])
            support = float(tokens[-1])
            supports[tuple(sorted(items))] = support
            itemsets.append((items, support))

        out = []
        for items, support in itemsets:
            if len(items) <= 1:
                continue
            for size in range(1, min(max_ante, len(items) - 1) + 1):
                for ante in combinations(items, size):
                    ante_support = supports.get(tuple(sorted(ante)))
                    if ante_support is None:
                        continue  # antecedent itself not frequent
                    confidence = support / ante_support
                    if confidence > conf_threshold:
                        cons = [it for it in items if it not in ante]
                        out.append(",".join(ante) + " -> " + ",".join(cons))
                        counters.incr("Rules", "Emitted")
        write_output(out_path, out)
        return counters


class InfrequentItemMarker:
    """Rewrite transactions, masking infrequent items; prefix ``iim``."""

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("iim") if not config.prefix else config

    @traced_run
    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        delim_out = cfg.field_delim_out()
        skip = cfg.get_int("skip.field.count", 1)
        length = cfg.must_int("item.set.length", "missing item set length")
        if length != 1:
            raise ValueError("expecting item set of length 1")
        contains_tid = cfg.get_boolean("contains.trans.id", True)
        marker = cfg.get("infreq.item.marker", "*")
        isl = ItemSetList(cfg.must("item.set.file.path"), 1, contains_tid,
                          cfg.get("itemset.delim", ","))
        freq = {s.items[0] for s in isl.get_item_set_list()}

        out = []
        for line in read_lines(in_path):
            items = split_line(line, delim_regex)
            for i in range(skip, len(items)):
                if items[i] not in freq:
                    items[i] = marker
                    counters.incr("Marker", "Masked")
            out.append(delim_out.join(items))
        write_output(out_path, out)
        return counters

"""Association mining: Apriori frequent itemsets, rule miner, marker.

Reference surface:
- ``association.FrequentItemsApriori`` — one MR pass per itemset length k
  (driven per resource/freq_items_apriori_tutorial.txt:37-46).  k=1: emit
  each token -> transId|1 (FrequentItemsApriori.java:138-150).  k>1: for each
  frequent (k-1)-itemset the transaction supports, extend by each new
  non-marker item, sort, emit (:151-196); combiner/reducer union trans-id
  sets or sum counts; support threshold strictly, support printed with 3
  decimals (:306-342).  In count mode a candidate reached from m frequent
  (k-1)-subsets is emitted m times per supporting transaction — that
  multiplicity is part of the reference's observable output and is
  reproduced here.
- ``association.ItemSetList`` — text loader: items, [transIds,] support.
- ``association.AssociationRuleMiner`` — per frequent itemset emits
  antecedent sublists (size <= arm.max.ante.size) and computes
  confidence = support(whole)/support(antecedent), strict threshold,
  output ``a1,a2 -> c1,c2`` (AssociationRuleMiner.java:111-196).
- ``association.InfrequentItemMarker`` — rewrites transactions replacing
  items absent from the frequent 1-itemset list with a marker
  (InfrequentItemMarker.java:77-150).

TPU re-design (SURVEY §7.2 stage 3): the transaction set becomes a boolean
incidence matrix ``inc[t, item]`` sharded over transactions.  The support of
every candidate s ∪ {x} for all frequent (k-1)-itemsets s and all items x is
ONE MXU matmul: ``co = v_s^T @ inc`` where ``v_s[t] = prod_{i in s} inc[t,i]``
is the itemset-support indicator — the mapper's triple loop and the shuffle
vanish into a [n_s, n_t] x [n_t, V] contraction with psum over the
transaction shards.  Distinct-transaction semantics are inherent (boolean
algebra); count-mode multiplicities are applied host-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..core.config import JobConfig
from ..core.io import read_lines, split_line, write_output
from ..core.metrics import Counters
from ..parallel.mesh import get_mesh, pad_rows


def _fmt_support(v: float) -> str:
    """Utility.formatDouble(support, 3) equivalent."""
    return f"{v:.3f}"


class ItemSet:
    """(items, transactionIds) pair (association/ItemSetList.java:65-101)."""

    def __init__(self, items: Sequence[str], trans_ids: Sequence[str] = ()):
        self.items = list(items)
        self.transaction_ids = list(trans_ids)

    def contains_item(self, item: str) -> bool:
        return item in self.items

    def contains_trans(self, trans_id: str) -> bool:
        return trans_id in self.transaction_ids


class ItemSetList:
    """Loader for itemset output lines: items, [transIds,] support."""

    def __init__(self, path: str, item_set_length: int,
                 contains_trans_ids: bool, delim: str = ","):
        self.item_sets: List[ItemSet] = []
        for line in read_lines(path):
            tokens = line.split(delim)
            items = tokens[:item_set_length]
            tids = tokens[item_set_length:-1] if contains_trans_ids else ()
            self.item_sets.append(ItemSet(items, tids))

    def get_item_set_list(self) -> List[ItemSet]:
        return self.item_sets


def _apriori_support_local(inc, sets_idx, mask):
    """Per-shard candidate support: v = prod of candidate-member columns,
    co = v^T @ inc (bf16 on the MXU), psum'd over transaction shards.

    inc: [nt, V] uint8 (0/1 — transferred narrow, widened on device);
    sets_idx: [n_s, k-1] int32 column ids; mask [nt].
    """
    incb = inc.astype(jnp.bfloat16)
    v = jnp.prod(incb[:, sets_idx], axis=2)          # [nt, n_s]
    v = v * mask[:, None].astype(jnp.bfloat16)
    co = jax.lax.dot_general(
        v, incb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [n_s, V]
    return jax.lax.psum(co, "data")


class FrequentItemsApriori:
    """One Apriori pass (one k); config prefix ``fia``."""

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("fia") if not config.prefix else config

    def run(self, in_path: str, out_path: str, mesh=None) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        delim = cfg.field_delim_out()
        skip = cfg.get_int("skip.field.count", 1)
        k = cfg.must_int("item.set.length", "missing item set length")
        trans_ord = cfg.must_int("tans.id.ord", "missing transaction id ordinal")
        emit_trans_id = cfg.get_boolean("emit.trans.id", True)
        threshold = cfg.must_float("support.threshold", "missing support threshold")
        total_trans = cfg.must_int("total.tans.count", "missing total transaction count")
        trans_id_output = cfg.get_boolean("trans.id.output", True)
        marker = cfg.get("infreq.item.marker")

        records = [split_line(l, delim_regex) for l in read_lines(in_path)]
        trans_ids = [r[trans_ord] for r in records]
        baskets = [[it for it in r[skip:] if it != marker] for r in records]

        if k == 1:
            lines = self._pass_one(baskets, trans_ids, emit_trans_id,
                                   threshold, total_trans, trans_id_output,
                                   delim)
        else:
            prev = ItemSetList(cfg.must("item.set.file.path"), k - 1,
                               emit_trans_id, ",")
            lines = self._pass_k(baskets, trans_ids, prev, k, emit_trans_id,
                                 threshold, total_trans, trans_id_output,
                                 delim, mesh)
        write_output(out_path, lines)
        counters.set("Apriori", "FrequentItemSets", len(lines))
        return counters

    # -- k == 1: token counting --------------------------------------------
    def _pass_one(self, baskets, trans_ids, emit_trans_id, threshold,
                  total_trans, trans_id_output, delim) -> List[str]:
        token_counts: Dict[str, int] = {}
        token_trans: Dict[str, Set[str]] = {}
        for tid, basket in zip(trans_ids, baskets):
            for it in basket:
                if emit_trans_id:
                    token_trans.setdefault(it, set()).add(tid)
                else:
                    # reference counts every token occurrence at k=1
                    token_counts[it] = token_counts.get(it, 0) + 1
        lines = []
        keys = sorted(token_trans if emit_trans_id else token_counts)
        for it in keys:
            if emit_trans_id:
                tids = sorted(token_trans[it])
                cnt = len(tids)
            else:
                cnt = token_counts[it]
            support = cnt / total_trans
            if support > threshold:
                if emit_trans_id:
                    if trans_id_output:
                        lines.append(delim.join([it] + tids +
                                                [_fmt_support(support)]))
                    else:
                        lines.append(f"{it}{delim}{_fmt_support(support)}")
                else:
                    lines.append(f"{it}{delim}{cnt}{delim}{_fmt_support(support)}")
        return lines

    # -- k > 1: incidence matmul on device ---------------------------------
    def _pass_k(self, baskets, trans_ids, prev: ItemSetList, k,
                emit_trans_id, threshold, total_trans, trans_id_output,
                delim, mesh) -> List[str]:
        mesh = mesh or get_mesh()
        # vocabulary over current items + previous itemset members
        vocab: Dict[str, int] = {}
        for b in baskets:
            for it in b:
                vocab.setdefault(it, len(vocab))
        prev_sets = [s for s in prev.get_item_set_list()
                     if all(it in vocab for it in s.items)]
        if not prev_sets:
            return []
        V = len(vocab)
        nt = len(baskets)
        inc = np.zeros((nt, V), dtype=np.uint8)
        for t, b in enumerate(baskets):
            for it in b:
                inc[t, vocab[it]] = 1.0
        sets_idx = np.asarray(
            [[vocab[it] for it in s.items] for s in prev_sets],
            dtype=np.int32)                            # [n_s, k-1]

        d = mesh.shape["data"]
        inc_p, mask = pad_rows(inc, d)
        fn = jax.jit(shard_map(
            _apriori_support_local, mesh=mesh,
            in_specs=(P("data"), P(), P("data")),
            out_specs=P()))
        co = np.asarray(fn(inc_p, sets_idx, mask))     # [n_s, V]

        # merge duplicate candidates and compute count-mode multiplicities
        inv = list(vocab)
        distinct: Dict[Tuple[str, ...], int] = {}
        multiplicity: Dict[Tuple[str, ...], int] = {}
        prev_keys = {tuple(sorted(s.items)) for s in prev_sets}
        for si, s in enumerate(prev_sets):
            s_items = set(s.items)
            for x in range(V):
                if inv[x] in s_items:
                    continue
                cnt = int(round(co[si, x]))
                if cnt <= 0:
                    continue
                cand = tuple(sorted(s.items + [inv[x]]))
                distinct[cand] = cnt
        for cand in distinct:
            from itertools import combinations
            m = sum(1 for sub in combinations(cand, k - 1)
                    if tuple(sorted(sub)) in prev_keys)
            multiplicity[cand] = m

        lines = []
        inc_bool = inc.astype(bool)
        for cand in sorted(distinct):
            cnt = distinct[cand]
            if not emit_trans_id:
                cnt = cnt * multiplicity[cand]
            support = (distinct[cand] if emit_trans_id else cnt) / total_trans
            if support > threshold:
                if emit_trans_id:
                    if trans_id_output:
                        cols = [vocab[it] for it in cand]
                        sel = inc_bool[:, cols].all(axis=1)
                        tids = sorted(trans_ids[t] for t in np.nonzero(sel)[0])
                        lines.append(delim.join(list(cand) + tids +
                                                [_fmt_support(support)]))
                    else:
                        lines.append(delim.join(list(cand) +
                                                [_fmt_support(support)]))
                else:
                    lines.append(delim.join(list(cand) +
                                            [str(cnt), _fmt_support(support)]))
        return lines


class AssociationRuleMiner:
    """Rules from frequent itemsets (+supports); config prefix ``arm``."""

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("arm") if not config.prefix else config

    def run(self, in_path: str, out_path: str) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        max_ante = cfg.get_int("max.ante.size", 3)
        conf_threshold = cfg.must_float("conf.threshold",
                                        "missing confidence threshold")

        supports: Dict[Tuple[str, ...], float] = {}
        itemsets: List[Tuple[Tuple[str, ...], float]] = []
        for line in read_lines(in_path):
            tokens = split_line(line, delim_regex)
            items = tuple(tokens[:-1])
            support = float(tokens[-1])
            supports[tuple(sorted(items))] = support
            itemsets.append((items, support))

        from itertools import combinations
        out = []
        for items, support in itemsets:
            if len(items) <= 1:
                continue
            for size in range(1, min(max_ante, len(items) - 1) + 1):
                for ante in combinations(items, size):
                    ante_support = supports.get(tuple(sorted(ante)))
                    if ante_support is None:
                        continue  # antecedent itself not frequent
                    confidence = support / ante_support
                    if confidence > conf_threshold:
                        cons = [it for it in items if it not in ante]
                        out.append(",".join(ante) + " -> " + ",".join(cons))
                        counters.incr("Rules", "Emitted")
        write_output(out_path, out)
        return counters


class InfrequentItemMarker:
    """Rewrite transactions, masking infrequent items; prefix ``iim``."""

    def __init__(self, config: JobConfig):
        self.config = config.with_prefix("iim") if not config.prefix else config

    def run(self, in_path: str, out_path: str) -> Counters:
        counters = Counters()
        cfg = self.config
        delim_regex = cfg.field_delim_regex()
        delim_out = cfg.field_delim_out()
        skip = cfg.get_int("skip.field.count", 1)
        length = cfg.must_int("item.set.length", "missing item set length")
        if length != 1:
            raise ValueError("expecting item set of length 1")
        contains_tid = cfg.get_boolean("contains.trans.id", True)
        marker = cfg.get("infreq.item.marker", "*")
        isl = ItemSetList(cfg.must("item.set.file.path"), 1, contains_tid,
                          cfg.get("itemset.delim", ","))
        freq = {s.items[0] for s in isl.get_item_set_list()}

        out = []
        for line in read_lines(in_path):
            items = split_line(line, delim_regex)
            for i in range(skip, len(items)):
                if items[i] not in freq:
                    items[i] = marker
                    counters.incr("Marker", "Masked")
            out.append(delim_out.join(items))
        write_output(out_path, out)
        return counters

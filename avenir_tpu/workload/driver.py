"""The open-loop client fleet: execute a schedule against a live port.

Each worker thread owns one persistent TCP connection (the frontend is
an event loop, so connections are cheap — but a production client holds
its socket) and its deterministic slice of the phase schedule.  The
contract that makes the numbers honest:

- **Intended-start accounting.**  Every event carries the offset it was
  *supposed* to start at.  A worker that falls behind does NOT skip or
  re-space events — it fires immediately, and the recorded latency runs
  from the intended start, so server backlog surfaces in the
  percentiles instead of silently shrinking the offered rate (the
  coordinated-omission fix; closed-loop clients understate tail latency
  under queueing by construction).
- **No coordination.**  Workers never wait on each other mid-phase;
  the only barrier is the phase boundary (per-phase verdicts need a
  clean cut).

Client-side observations land in the fleet's OWN ``obs.Metrics``
registry — one latency histogram per phase (with trace-id exemplars
from the server's sampled/errorish responses) plus outcome counters —
which the runner merges with the server's snapshot via
``telemetry.merge_snapshots`` into the run's single telemetry artifact.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Dict, List, Optional

from ..core import obs, telemetry
from .generators import Event, partition

#: outcome classes the verdict engine judges; "deferred" is the
#: documented cold_start/quota_exceeded retry signal (a correct client
#: retries — the harness counts it separately from hard errors)
OUTCOMES = ("ok", "error", "shed", "poison", "timeout", "deferred")


class PhaseStats:
    """One phase's fleet-side aggregates (merged across workers after
    the join — no cross-thread mutation)."""

    __slots__ = ("name", "sent", "outcomes", "latencies_ms",
                 "innocents_dropped", "worst", "duration_s", "offered")

    def __init__(self, name: str):
        self.name = name
        self.sent = 0
        self.outcomes: Dict[str, int] = {k: 0 for k in OUTCOMES}
        self.latencies_ms: List[float] = []
        self.innocents_dropped = 0
        #: (latency_ms, trace_id, kind, tenant) of the slowest event —
        #: the worst-offender exemplar a failing verdict ships to the
        #: flight recorder
        self.worst: Optional[tuple] = None
        self.duration_s = 0.0
        self.offered = 0

    def merge(self, other: "PhaseStats") -> None:
        self.sent += other.sent
        for k, v in other.outcomes.items():
            self.outcomes[k] += v
        self.latencies_ms.extend(other.latencies_ms)
        self.innocents_dropped += other.innocents_dropped
        if other.worst is not None and (self.worst is None
                                        or other.worst[0] > self.worst[0]):
            self.worst = other.worst

    def percentile_ms(self, q: float) -> Optional[float]:
        if not self.latencies_ms:
            return None
        xs = sorted(self.latencies_ms)
        i = min(max(int(q * len(xs) + 0.999999) - 1, 0), len(xs) - 1)
        return xs[i]

    def fraction(self, outcome: str) -> float:
        return self.outcomes[outcome] / self.sent if self.sent else 0.0

    def summary(self) -> dict:
        return {
            "sent": self.sent,
            "offered": self.offered,
            "duration_s": round(self.duration_s, 3),
            "achieved_rps": round(self.sent / self.duration_s, 2)
            if self.duration_s else 0.0,
            "outcomes": dict(self.outcomes),
            "innocents_dropped": self.innocents_dropped,
            "p50_ms": _r3(self.percentile_ms(0.50)),
            "p95_ms": _r3(self.percentile_ms(0.95)),
            "p99_ms": _r3(self.percentile_ms(0.99)),
            "max_ms": _r3(max(self.latencies_ms)
                          if self.latencies_ms else None),
            "worst": ({"latency_ms": _r3(self.worst[0]),
                       "trace_id": self.worst[1], "kind": self.worst[2],
                       "tenant": self.worst[3]}
                      if self.worst is not None else None),
        }


def _r3(v: Optional[float]) -> Optional[float]:
    return round(v, 3) if v is not None else None


class LineClient:
    """One persistent JSON-lines connection (reconnects lazily after a
    transport error so a single reset does not sink the worker)."""

    def __init__(self, host: str, port: int, timeout_s: float):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._buf = b""

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._buf = b""
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def call(self, obj: dict) -> dict:
        """One request/response round trip; raises ``OSError`` on
        transport failure (the caller counts it and the next call
        reconnects)."""
        try:
            sock = self._connect()
            sock.sendall((json.dumps(obj) + "\n").encode())
            while b"\n" not in self._buf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise OSError("connection closed mid-response")
                self._buf += chunk
            line, self._buf = self._buf.split(b"\n", 1)
            return json.loads(line.decode())
        except OSError:
            self.close()
            raise


def _wire_request(ev: Event, model_for: Dict[str, str]) -> dict:
    if ev.kind == "feedback":
        return {"cmd": "feedback", "event": ev.rows[0]}
    if ev.kind == "decide":
        return {"model": model_for.get(ev.tenant, ev.tenant),
                "decide": ev.rows[0]}
    model = model_for.get(ev.tenant, ev.tenant)
    if len(ev.rows) == 1:
        return {"model": model, "row": ev.rows[0]}
    return {"model": model, "rows": ev.rows}


def classify(resp: dict) -> str:
    """Map one wire response onto its outcome class (the server's
    structured signals: shed / poison / timeout / cold_start /
    quota_exceeded / error; anything else is a success)."""
    if resp.get("shed"):
        return "shed"
    if resp.get("poison"):
        return "poison"
    if resp.get("timeout"):
        return "timeout"
    if resp.get("cold_start") or resp.get("quota_exceeded"):
        return "deferred"
    if "error" in resp:
        return "error"
    return "ok"


class Fleet:
    """The multi-threaded open-loop driver for one scenario run."""

    def __init__(self, host: str, port: int, threads: int,
                 timeout_s: float, metrics: Optional[obs.Metrics] = None,
                 model_for: Optional[Dict[str, str]] = None):
        self.host = host
        self.port = port
        self.threads = max(int(threads), 1)
        self.timeout_s = timeout_s
        #: the fleet's private registry: merged into the run snapshot by
        #: the runner (client-side and server-side metric names are
        #: disjoint, so the merge is a union, not a double count)
        self.metrics = metrics if metrics is not None else obs.Metrics()
        self.model_for = model_for or {}

    # -- one worker --------------------------------------------------------
    def _run_slice(self, events: List[Event], t0: float,
                   stats: PhaseStats, poison_phase: bool) -> None:
        client = LineClient(self.host, self.port, self.timeout_s)
        hist = self.metrics.histogram(
            telemetry.labeled("workload.latency", phase=stats.name))
        counters = self.metrics.counters
        tracer = obs.get_tracer()
        try:
            for ev in events:
                intended = t0 + ev.offset_s
                delay = intended - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                stats.sent += 1
                counters.incr("Workload", "Requests sent")
                try:
                    resp = client.call(_wire_request(ev, self.model_for))
                except (OSError, ValueError) as e:
                    resp = {"error": f"transport: {e}"}
                lat_s = time.monotonic() - intended
                outcome = classify(resp)
                stats.outcomes[outcome] += 1
                counters.incr("Workload", f"Outcome {outcome}")
                if poison_phase and not ev.poison and outcome not in (
                        "ok", "deferred"):
                    # a well-formed request harmed during the storm: the
                    # zero-dropped-innocents envelope counts exactly this
                    stats.innocents_dropped += 1
                    counters.incr("Workload", "Innocents dropped")
                lat_ms = lat_s * 1000.0
                trace_id = resp.get("trace_id")
                hist.record(lat_s, trace_id=trace_id)
                stats.latencies_ms.append(lat_ms)
                if stats.worst is None or lat_ms > stats.worst[0]:
                    stats.worst = (lat_ms, trace_id, ev.kind, ev.tenant)
                if tracer.enabled and outcome != "ok":
                    tracer.record_span(
                        "workload.anomaly",
                        time.perf_counter_ns() - int(lat_s * 1e9),
                        int(lat_s * 1e9), parent=None,
                        outcome=outcome, phase=stats.name,
                        tenant=ev.tenant,
                        trace=trace_id or "")
        finally:
            client.close()

    # -- one phase ---------------------------------------------------------
    def run_phase(self, name: str, events: List[Event],
                  poison_phase: bool = False) -> PhaseStats:
        """Execute one phase's schedule open-loop; returns the merged
        fleet-side stats.  Workers are joined before return — the phase
        boundary is the run's only barrier."""
        tracer = obs.get_tracer()
        slices = partition(events, self.threads)
        per_thread = [PhaseStats(name) for _ in slices]
        started = time.monotonic()
        with tracer.span("workload.phase", phase=name,
                         events=len(events)):
            t0 = time.monotonic() + 0.05    # common epoch: workers align
            workers = [
                threading.Thread(
                    target=self._run_slice,
                    args=(sl, t0, st, poison_phase),
                    name=f"workload-client-{i}", daemon=True)
                for i, (sl, st) in enumerate(zip(slices, per_thread))]
            for w in workers:
                w.start()
            for w in workers:
                w.join()
        stats = PhaseStats(name)
        for st in per_thread:
            stats.merge(st)
        stats.duration_s = time.monotonic() - started
        stats.offered = len(events)
        self.metrics.set_gauge(
            telemetry.labeled("workload.achieved.rps", phase=name),
            stats.sent / stats.duration_s if stats.duration_s else 0.0)
        return stats

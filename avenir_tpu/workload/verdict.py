"""SLO-envelope verdicts: judge a run against its declared envelope.

Each phase's :class:`~avenir_tpu.workload.scenario.Envelope` turns into
a list of named checks (p99 ceiling, error/shed fraction ceilings,
dropped-innocents ceiling, deferred-fraction ceiling) evaluated over the
fleet's intended-start latency samples; the run-level compile-flatness
gate compares the serve tier's scorer-compilation count after warmup
with the count at run end (a steady-state traffic mix must not compile —
the PR-8/PR-14 invariant, now enforceable per scenario).

The verdict is one JSON document (written atomically — a crashed run
never leaves a half-verdict that reads as a pass) and one exit code:
``--assert`` maps any violated check to a nonzero exit naming the
violating phase, and fires exactly one flight-recorder dump
(``flight-workload-<scenario>-*.jsonl``) carrying the violating phase's
summary, its merged telemetry snapshot, and the worst-offender trace
exemplar — the black box for "the envelope broke, start HERE".
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..core import flight
from ..core.io import atomic_write_text
from .driver import PhaseStats
from .scenario import Scenario

VERDICT_VERSION = 1


class Check:
    """One envelope dimension's evaluation: declared limit vs observed
    value, and whether the observation stayed inside the envelope."""

    __slots__ = ("key", "limit", "actual", "ok")

    def __init__(self, key: str, limit, actual, ok: bool):
        self.key = key
        self.limit = limit
        self.actual = actual
        self.ok = bool(ok)

    def as_dict(self) -> dict:
        return {"key": self.key, "limit": self.limit,
                "actual": self.actual, "ok": self.ok}


def _ceiling(key: str, limit: Optional[float],
             actual: Optional[float]) -> Optional[Check]:
    if limit is None:
        return None
    if actual is None:
        # an envelope over zero samples is vacuously met only for
        # fraction checks; a declared p99 ceiling with no samples is a
        # broken run and must fail loudly
        return Check(key, limit, None, False)
    return Check(key, limit, round(float(actual), 4),
                 float(actual) <= float(limit))


def evaluate_phase(scenario: Scenario, phase_name: str,
                   stats: PhaseStats) -> List[Check]:
    """The declared checks for one phase (absent envelope keys add no
    checks — scenarios constrain only what they claim)."""
    spec = next(p for p in scenario.phases if p.name == phase_name)
    env = spec.envelope
    checks: List[Check] = []
    for c in (
            _ceiling("slo.p99.ms", env.p99_ms, stats.percentile_ms(0.99)),
            _ceiling("slo.error.max.fraction", env.error_max_fraction,
                     stats.fraction("error") + stats.fraction("timeout")),
            _ceiling("slo.shed.max.fraction", env.shed_max_fraction,
                     stats.fraction("shed")),
            _ceiling("slo.deferred.max.fraction",
                     env.deferred_max_fraction,
                     stats.fraction("deferred"))):
        if c is not None:
            checks.append(c)
    if env.innocents_dropped_max is not None:
        checks.append(Check(
            "slo.innocents.dropped.max", env.innocents_dropped_max,
            stats.innocents_dropped,
            stats.innocents_dropped <= env.innocents_dropped_max))
    return checks


def evaluate_run(scenario: Scenario, per_phase: Dict[str, PhaseStats],
                 compiles_after_warmup: Optional[int] = None,
                 compiles_at_end: Optional[int] = None,
                 usage_after_warmup: Optional[dict] = None,
                 usage_at_end: Optional[dict] = None,
                 cycles_after_warmup: Optional[int] = None,
                 cycles_at_end: Optional[int] = None) -> dict:
    """The whole run's verdict document: per-phase summaries + checks,
    the run-level compile-flatness and resource-leak gates, and the
    overall pass flag.

    ``usage_*`` are :func:`~avenir_tpu.workload.runner.process_usage`
    samples (``{"fds": int|None, "rss_mb": float|None}``) and
    ``cycles_*`` the model cache's cumulative demote count — soak
    profiles gate on their growth between the post-warmup baseline and
    run end.  A declared ceiling the platform cannot measure fails
    loudly (same contract as a p99 ceiling over zero samples)."""
    phases = []
    violations: List[dict] = []
    for spec in scenario.phases:
        stats = per_phase[spec.name]
        checks = evaluate_phase(scenario, spec.name, stats)
        ok = all(c.ok for c in checks)
        phases.append({"name": spec.name, "ok": ok,
                       "summary": stats.summary(),
                       "checks": [c.as_dict() for c in checks]})
        violations.extend({"phase": spec.name, **c.as_dict()}
                          for c in checks if not c.ok)
    run_checks: List[Check] = []
    if scenario.compile_flat:
        known = (compiles_after_warmup is not None
                 and compiles_at_end is not None)
        delta = (compiles_at_end - compiles_after_warmup) if known else None
        run_checks.append(Check("slo.compile.flat", 0, delta,
                                known and delta == 0))

    def _growth(field):
        a = (usage_after_warmup or {}).get(field)
        b = (usage_at_end or {}).get(field)
        return (b - a) if (a is not None and b is not None) else None

    if scenario.fd_growth_max is not None:
        d = _growth("fds")
        run_checks.append(Check("slo.fd.growth.max",
                                scenario.fd_growth_max, d,
                                d is not None
                                and d <= scenario.fd_growth_max))
    if scenario.rss_growth_max_mb is not None:
        d = _growth("rss_mb")
        run_checks.append(Check(
            "slo.rss.growth.max.mb", scenario.rss_growth_max_mb,
            round(d, 2) if d is not None else None,
            d is not None and d <= scenario.rss_growth_max_mb))
    if scenario.soak_cycles_min is not None:
        known = (cycles_after_warmup is not None
                 and cycles_at_end is not None)
        d = (cycles_at_end - cycles_after_warmup) if known else None
        # a FLOOR, not a ceiling: the run must have driven at least
        # this many promote/demote cycles for its flatness gates to
        # have judged real churn
        run_checks.append(Check("soak.cycles.min",
                                scenario.soak_cycles_min, d,
                                known and d >= scenario.soak_cycles_min))
    violations.extend({"phase": "__run__", **c.as_dict()}
                      for c in run_checks if not c.ok)
    return {
        "v": VERDICT_VERSION,
        "scenario": scenario.name,
        "seed": scenario.seed,
        "target": scenario.target,
        "threads": scenario.threads,
        "pass": not violations,
        "phases": phases,
        "run_checks": [c.as_dict() for c in run_checks],
        "violations": violations,
        "compiles": {"after_warmup": compiles_after_warmup,
                     "at_end": compiles_at_end},
        "resources": {"after_warmup": usage_after_warmup,
                      "at_end": usage_at_end,
                      "cycles_after_warmup": cycles_after_warmup,
                      "cycles_at_end": cycles_at_end},
    }


def write_verdict(path: str, verdict: dict) -> None:
    """Atomic publish (core.io): readers never see a torn verdict."""
    atomic_write_text(path, json.dumps(verdict, indent=2) + "\n")


def dump_violation(scenario: Scenario, verdict: dict,
                   per_phase: Dict[str, PhaseStats],
                   phase_snapshot: Optional[dict]) -> Optional[str]:
    """Exactly one ``flight-workload-<scenario>`` black-box dump for a
    failed ``--assert``: the first violating phase's summary + checks,
    its merged telemetry snapshot, and the worst-offender exemplar.
    ``force=True`` bypasses the recorder's rate limit — an operator
    asked this run to assert, so the dump must exist."""
    if verdict["pass"]:
        return None
    first = verdict["violations"][0]
    phase = first["phase"]
    stats = per_phase.get(phase)
    worst = None
    if stats is not None and stats.worst is not None:
        worst = {"latency_ms": round(stats.worst[0], 3),
                 "trace_id": stats.worst[1], "kind": stats.worst[2],
                 "tenant": stats.worst[3]}
    return flight.trigger(
        f"workload-{scenario.name}", force=True,
        trace_id=(worst or {}).get("trace_id"),
        phase=phase,
        violations=verdict["violations"],
        phase_summary=(stats.summary() if stats is not None else None),
        phase_snapshot=phase_snapshot,
        worst_offender=worst)

"""Scenario runner: ``python -m avenir_tpu workload``.

One command runs one scenario end-to-end:

1. parse the manifest (``workload.*`` + any ``serve.*``/``stream.*``
   keys riding in the same file);
2. bootstrap the system under test in-process — train the Naive Bayes
   artifact for ``serve`` targets (``workload.bootstrap=churn_nb``),
   register a cold tenant catalog against the managed model cache
   (``tenant_fleet``), or compose the streaming decision service;
3. build the deterministic event schedule, warm the target, then drive
   each phase with the open-loop fleet;
4. emit the run's three artifacts into ``workload.out.dir``:
   ``telemetry.json`` (ONE merged snapshot: server registry + overlay
   merged with the fleet's client-side registry via
   ``telemetry.merge_snapshots``), ``trace.json`` (one connected
   Chrome/Perfetto trace — server spans and fleet phase spans share the
   in-process tracer), and ``verdict.json`` (atomic; the SLO-envelope
   judgment);
5. with ``--assert``, exit nonzero on any envelope violation, naming
   the violating phase and leaving exactly one
   ``flight-workload-<scenario>`` black-box dump behind.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..core import flight, obs, telemetry
from ..core.config import JobConfig, parse_cli_args, parse_properties
from ..core.io import atomic_write_text, write_output
from . import scenario as scn
from .driver import Fleet, PhaseStats
from .generators import churn_row
from .scenario import Scenario, build_schedule, tenant_universe
from .verdict import dump_violation, evaluate_run, write_verdict

#: the bootstrap-trained model's schema (same field extents as
#: resource/serving/teleComChurn.json — generators.churn_row emits rows
#: inside these ranges)
CHURN_SCHEMA = {"fields": [
    {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
    {"name": "plan", "ordinal": 1, "dataType": "categorical",
     "feature": True, "cardinality": ["planA", "planB"]},
    {"name": "minUsed", "ordinal": 2, "dataType": "int", "feature": True,
     "min": 0, "max": 2200, "bucketWidth": 200},
    {"name": "dataUsed", "ordinal": 3, "dataType": "int", "feature": True,
     "min": 0, "max": 1000, "bucketWidth": 100},
    {"name": "csCall", "ordinal": 4, "dataType": "int", "feature": True,
     "min": 0, "max": 14, "bucketWidth": 2},
    {"name": "csEmail", "ordinal": 5, "dataType": "int", "feature": True,
     "min": 0, "max": 22, "bucketWidth": 4},
    {"name": "network", "ordinal": 6, "dataType": "int", "feature": True},
    {"name": "churned", "ordinal": 7, "dataType": "categorical",
     "cardinality": ["N", "Y"]},
]}

BOOTSTRAP_MODEL = "churn"
BOOTSTRAP_TRAIN_ROWS = 1200


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# bootstrap: build the system under test from the manifest
# ---------------------------------------------------------------------------

def _train_artifact(scenario: Scenario, boot_dir: str) -> Tuple[str, str]:
    """Train the shared Naive Bayes artifact once per out dir; returns
    (schema path, model path).  Reuses an existing artifact so repeated
    scenario runs (and the CI smoke) skip the training leg."""
    from ..datagen import gen_telecom_churn
    from ..models.bayesian import BayesianDistribution

    schema_path = os.path.join(boot_dir, "teleComChurn.json")
    model_path = os.path.join(boot_dir, "nb_model")
    if not os.path.exists(os.path.join(model_path, "_SUCCESS")):
        os.makedirs(boot_dir, exist_ok=True)
        atomic_write_text(schema_path, json.dumps(CHURN_SCHEMA))
        train_dir = os.path.join(boot_dir, "train")
        rows = gen_telecom_churn(BOOTSTRAP_TRAIN_ROWS, seed=scenario.seed)
        write_output(train_dir, [",".join(r) for r in rows])
        BayesianDistribution(JobConfig(
            {"feature.schema.file.path": schema_path})).run(
            train_dir, model_path)
    return schema_path, model_path


def bootstrap_target(scenario: Scenario, tenants: List[str]
                     ) -> Dict[str, str]:
    """Mutate the scenario config so the target can be constructed, and
    return the tenant -> served-model-name map the fleet addresses
    requests with."""
    config = scenario.config
    if scenario.target == "stream":
        # StreamDecisionService auto-declares the banditDecision model
        # from the stream.* manifest; every tenant decides against it
        from ..stream.service import DEFAULT_MODEL_NAME, KEY_MODEL_NAME
        name = config.get(KEY_MODEL_NAME, DEFAULT_MODEL_NAME)
        return {t: name for t in tenants}
    if scenario.bootstrap == "none":
        model = (config.get_list("serve.models") or [tenants[0]])[0].strip()
        return {t: model for t in tenants}
    boot_dir = os.path.join(scenario.out_dir, "bootstrap")
    schema_path, model_path = _train_artifact(scenario, boot_dir)
    if scenario.bootstrap == "churn_nb":
        config.set("serve.models", BOOTSTRAP_MODEL)
        config.set(f"serve.model.{BOOTSTRAP_MODEL}.kind", "naiveBayes")
        config.set(f"serve.model.{BOOTSTRAP_MODEL}."
                   f"feature.schema.file.path", schema_path)
        config.set(f"serve.model.{BOOTSTRAP_MODEL}."
                   f"bayesian.model.file.path", model_path)
        return {t: BOOTSTRAP_MODEL for t in tenants}
    # tenant_fleet: the PR-14 shape — N cold tenants sharing one
    # artifact behind the HBM-budget-aware cache (the manifest carries
    # the serve.cache.* budget/quota dials)
    conf_path = os.path.join(boot_dir, "tenant.properties")
    atomic_write_text(conf_path,
                      f"feature.schema.file.path={schema_path}\n"
                      f"bayesian.model.file.path={model_path}\n")
    config.set("serve.cache.models", ",".join(tenants))
    for t in tenants:
        config.set(f"serve.model.{t}.kind", "naiveBayes")
        config.set(f"serve.model.{t}.conf", conf_path)
    return {t: t for t in tenants}


def build_target(scenario: Scenario):
    """Construct + start the in-process system under test; returns
    (stop fn, host, port, telemetry exporter, stats fn)."""
    config = scenario.config
    if scenario.target_port:
        # external target: the system under test is already running
        # (e.g. a fleet router fronting N backends) — nothing to build,
        # nothing to stop; stats go over the wire like any client, and
        # a local exporter still collects THIS process's driver-side
        # registry for the snapshot merge
        from ..serve.server import request
        host, port = scenario.target_host, scenario.target_port
        exporter = telemetry.TelemetryExporter(0.0)

        def stats_fn():
            return request(host, port, {"cmd": "stats"},
                           timeout=scenario.timeout_s)

        return ((lambda: None), host, port, exporter, stats_fn)
    if config.get("serve.port") is None:
        config.set("serve.port", "0")
    host = config.get("serve.host", "127.0.0.1")
    if scenario.target == "stream":
        from ..core import checkpoint
        from ..stream.service import StreamDecisionService
        # keep the feedback consumer's offset sidecar inside the
        # scenario's out dir (the service defaults to cwd)
        if config.get(checkpoint.KEY_PATH) is None:
            config.set(checkpoint.KEY_PATH,
                       os.path.join(scenario.out_dir, "stream.ckpt"))
        service = StreamDecisionService(config)
        port = service.start()
        return (service.stop, host, port, service.server.telemetry,
                service.server._stats)
    from ..serve.server import PredictionServer
    server = PredictionServer(config)
    port = server.start()
    return ((lambda: server.stop(drain=True)), host, port,
            server.telemetry, server._stats)


# ---------------------------------------------------------------------------
# run accounting
# ---------------------------------------------------------------------------

def run_snapshot(scenario: Scenario, exporter, fleet,
                 publisher=None) -> Tuple[dict, Optional[List[str]]]:
    """One merged run snapshot; returns (snapshot, contributing feeds).

    Default: the in-process exporter's registry+overlay merged with the
    fleet driver's client-side registry — one process's truth.  With
    ``workload.fleet.snapshot=true`` the run publishes its own snapshot
    into the fleetobs spool first, then folds EVERY feed's latest
    snapshot (this process plus any sibling publishers pointed at the
    same ``fleetobs.spool.dir``) and merges the client registry on top,
    so the artifacts judge the fleet, not one process."""
    local = exporter.snapshot()
    client = fleet.metrics.mergeable_snapshot()
    if publisher is None:
        return telemetry.merge_snapshots(local, client), None
    from ..fleetobs import fleet_fold
    from ..fleetobs import publisher as pub
    from ..fleetobs import stitch
    publisher.publish(local)
    feeds: Dict[str, dict] = {}
    for d in stitch.feed_dirs(scenario.config.get(pub.KEY_SPOOL_DIR)):
        try:
            with open(os.path.join(d, pub.SNAPSHOT_FILE), "r") as fh:
                feeds[os.path.basename(d)] = json.load(fh)["snapshot"]
        except (OSError, ValueError, KeyError):
            continue        # a feed mid-first-publish folds next time
    return (telemetry.merge_snapshots(fleet_fold(feeds), client),
            sorted(feeds))


def compile_count(stats: dict) -> int:
    """Total scorer compilations visible in a ``stats`` response.

    With the shared compile tier active (model-cache mode) the tier's
    cumulative count IS the fleet-wide series — per-model ``Serve /
    Scorer compilations`` bill the same tier compiles to the model that
    caused them, and an evicted model takes its counter out of the stats
    surface, so summing both would double-count real compiles and read
    eviction/re-promote churn as compile movement.  Without the tier,
    the per-model counters are the only (and complete) source."""
    tier = ((stats.get("cache") or {}).get("compile_tier") or {})
    if "compiles" in tier:
        return tier["compiles"]
    total = 0
    for m in (stats.get("models") or {}).values():
        total += ((m.get("counters") or {}).get("Serve") or {}).get(
            "Scorer compilations", 0)
    return total


def process_usage() -> dict:
    """This process's resource footprint for the soak gates:
    ``{"fds": open-fd count, "rss_mb": resident set in MB}``, each None
    where the platform offers no ``/proc/self`` view (the verdict then
    fails a DECLARED gate loudly instead of passing it vacuously)."""
    fds = None
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    rss_mb = None
    try:
        with open("/proc/self/statm", "r") as fh:
            pages = int(fh.read().split()[1])
        rss_mb = pages * (os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0))
    except (OSError, ValueError, IndexError):
        pass
    return {"fds": fds, "rss_mb": rss_mb}


def demote_cycles(stats: dict) -> int:
    """Cumulative promote/demote cycles visible in a ``stats``
    response.  A residency cycle a completed promote opened closes one
    of two ways — LRU eviction by a later promote (``Evictions``) or an
    operator demote (``Demotes``) — so the cycle count is their sum.
    Zero without the cache — single-model targets have no residency
    churn to count."""
    c = ((stats.get("cache") or {}).get("counters") or {})
    return c.get("Evictions", 0) + c.get("Demotes", 0)


def _warmup(scenario: Scenario, fleet: Fleet, tenants: List[str]) -> None:
    """Pre-phase warmup (uncounted): touch the hot head of the tenant
    ranking so steady-state phases measure serving, not first-compile —
    the compile-flat gate snapshots its baseline AFTER this."""
    import random as _random
    from ..serve.server import request

    rng = _random.Random(scenario.seed ^ 0xBEEF)
    n = max(scenario.warmup_requests, 0)
    hot = tenants[:max(min(scenario.tenants_hot, len(tenants)), 1)]
    for i in range(n):
        tenant = hot[i % len(hot)]
        model = fleet.model_for.get(tenant, tenant)
        if scenario.target == "stream":
            obj = {"model": model, "decide": f"warm{i:06d},{tenant}"}
        else:
            obj = {"model": model, "row": churn_row(rng, i)}
        try:
            request(fleet.host, fleet.port, obj,
                    timeout=scenario.timeout_s)
        except OSError:
            pass        # warmup is best-effort; phases will measure it


def _quiesce_compiles(stats_fn: Callable[[], dict],
                      settle_s: float = 0.25,
                      deadline_s: float = 10.0) -> int:
    """Wait for the post-warmup compile count to stop moving and return
    it.  Model-cache promotion warms scorer buckets on ASYNC worker
    threads — a baseline snapshotted while a promote is still warming
    would bill that warmup's final compile to the run and fail the
    compile-flat gate on a race, not a regression."""
    t_end = time.monotonic() + deadline_s
    last = compile_count(stats_fn())
    while time.monotonic() < t_end:
        time.sleep(settle_s)
        now = compile_count(stats_fn())
        if now == last:
            return now
        last = now
    return last


def run_scenario(config: JobConfig, do_assert: bool = False,
                 log: Callable[[str], None] = _log) -> int:
    """Execute one scenario; returns the process exit code."""
    scenario = Scenario(config)
    os.makedirs(scenario.out_dir, exist_ok=True)
    publisher = None
    if scenario.fleet_snapshot:
        # validated before any bootstrap work: fleet mode without a
        # spool is a manifest error, not a mid-run surprise
        from ..fleetobs.publisher import KEY_SPOOL_DIR, publisher_for_job
        publisher = publisher_for_job(config, role="workload")
        if publisher is None:
            raise KeyError(
                f"{scn.KEY_FLEET_SNAPSHOT}=true needs {KEY_SPOOL_DIR} "
                f"naming the fleet spool this run publishes into")
    tenants = tenant_universe(scenario)
    model_for = bootstrap_target(scenario, tenants)
    schedule = build_schedule(scenario, tenants)
    stop, host, port, exporter, stats_fn = build_target(scenario)
    if publisher is not None:
        publisher.attach(exporter)
    per_phase: Dict[str, PhaseStats] = {}
    phase_snapshots: Dict[str, dict] = {}
    fleet = Fleet(host, port, scenario.threads, scenario.timeout_s,
                  model_for=model_for)
    trace_path = os.path.join(scenario.out_dir, "trace.json")
    try:
        log(f"workload {scenario.name!r}: target={scenario.target} "
            f"on {host}:{port}, {len(tenants)} tenants, "
            f"{len(schedule)} scheduled events, "
            f"{scenario.threads} client threads, seed={scenario.seed}")
        _warmup(scenario, fleet, tenants)
        compiles0 = _quiesce_compiles(stats_fn)
        usage0 = process_usage()
        cycles0 = demote_cycles(stats_fn())
        for spec in scenario.phases:
            events = [e for e in schedule if e.phase == spec.name]
            stats = fleet.run_phase(
                spec.name, events,
                poison_phase=spec.poison_fraction > 0 or spec.chaos)
            per_phase[spec.name] = stats
            phase_snapshots[spec.name], _ = run_snapshot(
                scenario, exporter, fleet, publisher)
            s = stats.summary()
            log(f"  phase {spec.name!r}: {s['sent']} sent @ "
                f"{s['achieved_rps']}/s, p99 {s['p99_ms']} ms, "
                f"outcomes {s['outcomes']}")
        final_stats = stats_fn()
        compiles1 = compile_count(final_stats)
        cycles1 = demote_cycles(final_stats)
        usage1 = process_usage()
        if scenario.soak_cycles_min is not None:
            log(f"  soak: {cycles1 - cycles0} promote/demote cycles, "
                f"fd {usage0['fds']} -> {usage1['fds']}, "
                f"rss {usage0['rss_mb'] and round(usage0['rss_mb'], 1)}"
                f" -> {usage1['rss_mb'] and round(usage1['rss_mb'], 1)}"
                f" MB")
    finally:
        stop()
        n = obs.get_tracer().export_chrome_trace(trace_path)
        log(f"  trace: {n} events -> {trace_path}")

    merged, fold_feeds = run_snapshot(scenario, exporter, fleet, publisher)
    telemetry_path = os.path.join(scenario.out_dir, "telemetry.json")
    atomic_write_text(telemetry_path, json.dumps(merged) + "\n")
    log(f"  telemetry: merged snapshot -> {telemetry_path}"
        + (f" (fleet fold over {len(fold_feeds)} feeds)"
           if fold_feeds is not None else ""))

    verdict = evaluate_run(scenario, per_phase,
                           compiles_after_warmup=compiles0,
                           compiles_at_end=compiles1,
                           usage_after_warmup=usage0,
                           usage_at_end=usage1,
                           cycles_after_warmup=cycles0,
                           cycles_at_end=cycles1)
    if fold_feeds is not None:
        # the verdict names its evidence: which spool feeds the judged
        # snapshots folded (the run's own feed plus any siblings)
        verdict["fleet"] = {"feeds": fold_feeds,
                            "source": "fleetobs-spool"}
    verdict_path = os.path.join(scenario.out_dir, "verdict.json")
    write_verdict(verdict_path, verdict)
    log(f"  verdict: {'PASS' if verdict['pass'] else 'FAIL'} "
        f"-> {verdict_path}")
    if verdict["pass"]:
        return 0
    first = verdict["violations"][0]
    log(f"workload {scenario.name!r}: envelope VIOLATED in phase "
        f"{first['phase']!r}: {first['key']} = {first['actual']} "
        f"(limit {first['limit']})"
        + "".join(f"\n  also: phase {v['phase']!r} {v['key']} = "
                  f"{v['actual']} (limit {v['limit']})"
                  for v in verdict["violations"][1:]))
    if not do_assert:
        return 0
    dump = dump_violation(scenario, verdict, per_phase,
                          phase_snapshots.get(first["phase"]))
    if dump:
        log(f"  flight: black-box dump -> {dump}")
    return 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def workload_main(argv) -> int:
    """``python -m avenir_tpu workload --scenario <file.properties>
    [--assert] [-Dkey=value ...]``."""
    from ..cli import _extract_value_flag, configure_resilience

    argv = list(argv)
    if argv and argv[0] in ("-h", "--help"):
        print("usage: python -m avenir_tpu workload --scenario "
              "<scenario.properties> [--assert] [-Dkey=value ...]",
              file=sys.stderr)
        return 2
    argv, scenario_path = _extract_value_flag(argv, "--scenario")
    do_assert = "--assert" in argv
    argv = [a for a in argv if a != "--assert"]
    defines, positional = parse_cli_args(argv)
    if scenario_path is None or positional:
        print("workload: expected --scenario <scenario.properties> "
              "[--assert] [-Dkey=value ...]", file=sys.stderr)
        return 2
    with open(scenario_path, "r") as fh:
        config = JobConfig(parse_properties(fh.read()))
    for k, v in defines.items():
        config.set(k, v)
    # the verdict's flight dump lands next to the run's artifacts unless
    # the manifest routes it elsewhere
    if config.get(flight.KEY_DUMP_DIR) is None:
        config.set(flight.KEY_DUMP_DIR,
                   config.get(scn.KEY_OUT_DIR, "workload-out"))
    os.makedirs(config.get(scn.KEY_OUT_DIR, "workload-out"), exist_ok=True)
    # the run always exports its trace artifact, so tracing is on
    # regardless of obs.trace.enable (same force as --trace elsewhere)
    obs.configure_from_config(config, force_enable=True)
    configure_resilience(config)
    telemetry.configure_from_config(config)
    t0 = time.monotonic()
    try:
        rc = run_scenario(config, do_assert=do_assert)
    except BaseException as exc:
        flight.fatal(exc)
        raise
    _log(f"workload: done in {time.monotonic() - t0:.1f}s (exit {rc})")
    return rc

"""Production-shaped workload harness (``python -m avenir_tpu workload``).

A seeded, replayable scenario factory plus an SLO-envelope verdict
engine — the serving-side descendant of avenir's synthetic-data
generators.  Scenarios are properties manifests (``workload.*``)
declaring phased arrival processes, Zipf tenant popularity, payload
mixes, and chaos dials; the open-loop client fleet drives them against
the real ``serve`` frontend or ``stream`` consumer, and the run is
judged against the envelope the scenario declares.  See the README
"Workload harness" section and ``resource/workload/`` for the canned
scenarios (``flash_crowd``, ``zipf_tenant_storm``, ``poison_storm``,
``feedback_chaos``, ``workload_smoke``).
"""

from .driver import Fleet, LineClient, PhaseStats, classify     # noqa: F401
from .generators import (Event, ZipfSampler, arrival_offsets,   # noqa: F401
                         hot_share, partition, payload_rows,
                         schedule_bytes, zipf_weights)
from .runner import run_scenario, workload_main                 # noqa: F401
from .scenario import (Envelope, PhaseSpec, Scenario,           # noqa: F401
                       build_schedule, tenant_universe)
from .verdict import evaluate_phase, evaluate_run               # noqa: F401

__all__ = [
    "Event", "ZipfSampler", "arrival_offsets", "hot_share", "partition",
    "payload_rows", "schedule_bytes", "zipf_weights",
    "Envelope", "PhaseSpec", "Scenario", "build_schedule",
    "tenant_universe", "Fleet", "LineClient", "PhaseStats", "classify",
    "evaluate_phase", "evaluate_run", "run_scenario", "workload_main",
]

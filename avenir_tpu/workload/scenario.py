"""Scenario manifests: the ``workload.*`` properties surface.

A scenario is ONE properties file (the same Java-properties dialect
every other subsystem uses) declaring three things:

- the **fleet**: seed, client thread count, target (``serve`` or
  ``stream``), tenant universe and its Zipf popularity, payload mix;
- the **phases**: an ordered list of named traffic phases, each with an
  arrival process (constant / poisson / flash / diurnal), a rate, a
  duration, and optional poison / feedback-chaos dials;
- the **SLO envelope**: per-phase ceilings (p99, error fraction, shed
  fraction, dropped innocents) plus the run-level compile-flatness
  gate.  The verdict engine (``workload.verdict``) judges the run
  against exactly these declared numbers — a scenario file IS the
  regression test.

Per-phase keys follow the ``workload.phase.<name>.<suffix>`` grammar
(runtime-derived like ``serve.model.<name>.*`` — documented as a key
FAMILY in the README; the config-keys rule governs the scalar
``workload.*`` keys below).  The manifest may also carry ``serve.*`` /
``stream.*`` keys verbatim: the runner builds the system under test
from the same config object, so one file describes the whole
experiment.
"""

from __future__ import annotations

import random
from typing import List, Optional

from ..core.config import JobConfig
from ..stream import posterior
from . import generators as gen
from .generators import Event

# -- governed scenario keys (config-keys rule: KEY_-bound, accessor-read,
# README-documented) --------------------------------------------------------
KEY_NAME = "workload.scenario.name"
KEY_SEED = "workload.seed"
KEY_THREADS = "workload.threads"
KEY_TARGET = "workload.target"
KEY_TARGET_HOST = "workload.target.host"
KEY_TARGET_PORT = "workload.target.port"
KEY_BOOTSTRAP = "workload.bootstrap"
KEY_TENANTS = "workload.tenants"
KEY_TENANTS_HOT = "workload.tenants.hot"
KEY_ZIPF_EXPONENT = "workload.tenants.zipf.exponent"
KEY_PAYLOAD_MEDIAN = "workload.payload.rows.median"
KEY_PAYLOAD_SIGMA = "workload.payload.rows.sigma"
KEY_PAYLOAD_MAX = "workload.payload.rows.max"
KEY_PHASES = "workload.phases"
KEY_OUT_DIR = "workload.out.dir"
KEY_TIMEOUT_SEC = "workload.request.timeout.sec"
KEY_WARMUP_REQUESTS = "workload.warmup.requests"
KEY_COMPILE_FLAT = "workload.slo.compile.flat"
KEY_FD_GROWTH_MAX = "workload.slo.fd.growth.max"
KEY_RSS_GROWTH_MAX_MB = "workload.slo.rss.growth.max.mb"
KEY_SOAK_CYCLES_MIN = "workload.soak.cycles.min"
KEY_FLEET_SNAPSHOT = "workload.fleet.snapshot"

DEFAULT_THREADS = 4
DEFAULT_TENANTS = 1
DEFAULT_TENANTS_HOT = 20
DEFAULT_ZIPF_EXPONENT = 1.5
DEFAULT_PAYLOAD_MEDIAN = 2
DEFAULT_PAYLOAD_SIGMA = 0.8
DEFAULT_PAYLOAD_MAX = 64
DEFAULT_TIMEOUT_SEC = 30.0
DEFAULT_WARMUP_REQUESTS = 32

TARGETS = ("serve", "stream")
BOOTSTRAPS = ("churn_nb", "tenant_fleet", "none")


def _phase_key(phase: str, suffix: str) -> str:
    """The per-phase derived key family ``workload.phase.<name>.<suffix>``
    (runtime-derived, like ``serve.model.<name>.*`` — see module doc)."""
    return f"workload.phase.{phase}.{suffix}"


class Envelope:
    """One phase's declared SLO ceilings.  ``None`` means the dimension
    is unconstrained for this phase."""

    __slots__ = ("p99_ms", "error_max_fraction", "shed_max_fraction",
                 "innocents_dropped_max", "deferred_max_fraction")

    def __init__(self, p99_ms: Optional[float],
                 error_max_fraction: Optional[float],
                 shed_max_fraction: Optional[float],
                 innocents_dropped_max: Optional[int],
                 deferred_max_fraction: Optional[float]):
        self.p99_ms = p99_ms
        self.error_max_fraction = error_max_fraction
        self.shed_max_fraction = shed_max_fraction
        self.innocents_dropped_max = innocents_dropped_max
        self.deferred_max_fraction = deferred_max_fraction


class PhaseSpec:
    """One named traffic phase: arrival process + chaos dials + SLO
    envelope, parsed from its ``workload.phase.<name>.*`` key family."""

    __slots__ = ("name", "arrival", "rate", "duration_s", "surge_factor",
                 "surge_start_s", "surge_duration_s", "period_s",
                 "amplitude", "poison_fraction", "feedback_fraction",
                 "feedback_dup_fraction", "feedback_reorder_fraction",
                 "feedback_lag_ms_max", "chaos", "envelope")

    def __init__(self, name: str, config: JobConfig):
        self.name = name

        def g(suffix: str, default=None):
            return config.get(_phase_key(name, suffix), default)

        def gf(suffix: str, default=None):
            v = g(suffix)
            return float(v) if v is not None else default

        self.arrival = g("arrival", "constant")
        self.rate = gf("rate")
        self.duration_s = gf("duration.sec")
        if self.rate is None or self.duration_s is None:
            raise KeyError(
                f"phase {name!r} needs workload.phase.{name}.rate and "
                f"workload.phase.{name}.duration.sec")
        self.surge_factor = gf("surge.factor", 10.0)
        self.surge_start_s = gf("surge.start.sec")
        self.surge_duration_s = gf("surge.duration.sec")
        self.period_s = gf("diurnal.period.sec")
        self.amplitude = gf("diurnal.amplitude", 0.5)
        self.poison_fraction = gf("poison.fraction", 0.0)
        # chaos phases expect EXTERNAL failure injection (a harness
        # killing a backend mid-phase); the flag arms the same
        # dropped-innocents accounting poison phases get, so the
        # envelope can declare "zero innocents dropped under the kill"
        self.chaos = config.get_boolean(_phase_key(name, "chaos"), False)
        self.feedback_fraction = gf("feedback.fraction", 0.0)
        self.feedback_dup_fraction = gf("feedback.dup.fraction", 0.0)
        self.feedback_reorder_fraction = gf("feedback.reorder.fraction", 0.0)
        self.feedback_lag_ms_max = gf("feedback.lag.ms.max", 0.0)
        inno = g("slo.innocents.dropped.max")
        self.envelope = Envelope(
            gf("slo.p99.ms"),
            gf("slo.error.max.fraction"),
            gf("slo.shed.max.fraction"),
            int(inno) if inno is not None else None,
            gf("slo.deferred.max.fraction"))


class Scenario:
    """A parsed scenario manifest: fleet shape + ordered phases."""

    __slots__ = ("name", "seed", "threads", "target", "target_host",
                 "target_port", "bootstrap", "tenants", "tenants_hot",
                 "zipf_exponent", "payload_median", "payload_sigma",
                 "payload_max", "phases", "out_dir", "timeout_s",
                 "warmup_requests", "compile_flat", "fd_growth_max",
                 "rss_growth_max_mb", "soak_cycles_min", "fleet_snapshot",
                 "config")

    def __init__(self, config: JobConfig):
        self.config = config
        self.name = config.must(KEY_NAME)
        self.seed = config.get_int(KEY_SEED, 0)
        self.threads = max(config.get_int(KEY_THREADS, DEFAULT_THREADS), 1)
        self.target = config.get(KEY_TARGET, "serve")
        if self.target not in TARGETS:
            raise ValueError(
                f"{KEY_TARGET} must be one of {TARGETS}, got "
                f"{self.target!r}")
        # external target: point the fleet at an ALREADY-RUNNING wire
        # endpoint (a fleet router, a remote serve process) instead of
        # building one in-process — port 0 means in-process (default)
        self.target_host = config.get(KEY_TARGET_HOST, "127.0.0.1")
        self.target_port = config.get_int(KEY_TARGET_PORT, 0)
        self.bootstrap = config.get(
            KEY_BOOTSTRAP,
            "churn_nb" if self.target == "serve" and not self.target_port
            else "none")
        if self.bootstrap not in BOOTSTRAPS:
            raise ValueError(
                f"{KEY_BOOTSTRAP} must be one of {BOOTSTRAPS}, got "
                f"{self.bootstrap!r}")
        self.tenants = config.get_int(KEY_TENANTS, DEFAULT_TENANTS)
        self.tenants_hot = config.get_int(KEY_TENANTS_HOT,
                                          DEFAULT_TENANTS_HOT)
        self.zipf_exponent = config.get_float(KEY_ZIPF_EXPONENT,
                                              DEFAULT_ZIPF_EXPONENT)
        self.payload_median = config.get_int(KEY_PAYLOAD_MEDIAN,
                                             DEFAULT_PAYLOAD_MEDIAN)
        self.payload_sigma = config.get_float(KEY_PAYLOAD_SIGMA,
                                              DEFAULT_PAYLOAD_SIGMA)
        self.payload_max = config.get_int(KEY_PAYLOAD_MAX,
                                          DEFAULT_PAYLOAD_MAX)
        names = config.must_list(KEY_PHASES)
        self.phases = [PhaseSpec(n.strip(), config) for n in names]
        self.out_dir = config.get(KEY_OUT_DIR, "workload-out")
        self.timeout_s = config.get_float(KEY_TIMEOUT_SEC,
                                          DEFAULT_TIMEOUT_SEC)
        self.warmup_requests = config.get_int(KEY_WARMUP_REQUESTS,
                                              DEFAULT_WARMUP_REQUESTS)
        self.compile_flat = config.get_boolean(KEY_COMPILE_FLAT, False)
        # run-level resource-leak gates (soak profiles): net fd-count /
        # RSS growth ceilings between the post-warmup baseline and run
        # end, and a promote/demote cycle FLOOR so a flatness verdict
        # cannot pass vacuously on a run that never actually churned
        self.fd_growth_max = config.get_int(KEY_FD_GROWTH_MAX)
        self.rss_growth_max_mb = config.get_float(KEY_RSS_GROWTH_MAX_MB)
        self.soak_cycles_min = config.get_int(KEY_SOAK_CYCLES_MIN)
        # fleet-snapshot mode: phase/final snapshots fold EVERY feed in
        # the fleetobs spool (this run publishes its own feed there),
        # not just the in-process exporter — the verdict then judges the
        # fleet, not one process
        self.fleet_snapshot = config.get_boolean(KEY_FLEET_SNAPSHOT, False)


# ---------------------------------------------------------------------------
# the schedule: manifest -> deterministic event list
# ---------------------------------------------------------------------------

def tenant_universe(scenario: Scenario) -> List[str]:
    """The ranked tenant id list traffic is drawn over.  ``stream``
    targets use the declared ``stream.tenants`` manifest (decide
    requests must name known tenants); ``tenant_fleet`` bootstraps use
    the synthetic ``seg%04d`` catalog the runner registers with the
    model cache; single-model serve scenarios have one pseudo-tenant —
    the served model itself."""
    if scenario.target == "stream":
        tenants = scenario.config.get_list(posterior.KEY_TENANTS)
        if not tenants:
            raise KeyError(
                "stream-target scenarios need stream.tenants declared in "
                "the manifest")
        return [t.strip() for t in tenants]
    if scenario.bootstrap == "tenant_fleet":
        return [f"seg{i:04d}" for i in range(max(scenario.tenants, 1))]
    return ["__single__"]       # replaced with the model name by the runner


def build_phase_events(scenario: Scenario, phase: PhaseSpec,
                       tenants: List[str], arms: List[str],
                       rng: random.Random) -> List[Event]:
    """One phase's events, time-sorted.  Draw order is fixed (arrivals,
    then per-arrival tenant/payload/fault draws in schedule order), so
    the stream of rng consumption — and therefore the bytes — is a pure
    function of (manifest, seed)."""
    offsets = gen.arrival_offsets(
        phase.arrival, phase.rate, phase.duration_s, rng,
        surge_factor=phase.surge_factor,
        surge_start_s=phase.surge_start_s,
        surge_duration_s=phase.surge_duration_s,
        period_s=phase.period_s,
        amplitude=phase.amplitude)
    sampler = (gen.ZipfSampler(len(tenants), scenario.zipf_exponent)
               if len(tenants) > 1 else None)
    events: List[Event] = []
    feedback: List[Event] = []
    for i, off in enumerate(offsets):
        tenant = tenants[sampler.draw(rng)] if sampler else tenants[0]
        ident = rng.randrange(1 << 30)
        if phase.poison_fraction and rng.random() < phase.poison_fraction:
            events.append(Event(phase.name, off, "predict", tenant,
                                [gen.poison_row(rng, ident)], poison=True))
            continue
        if scenario.target == "stream":
            events.append(Event(phase.name, off, "decide", tenant,
                                [f"e{ident:08x},{tenant}"]))
            if (phase.feedback_fraction
                    and rng.random() < phase.feedback_fraction):
                arm = arms[rng.randrange(len(arms))]
                reward = rng.randrange(2)
                lag = (rng.random() * phase.feedback_lag_ms_max / 1000.0
                       if phase.feedback_lag_ms_max else 0.0)
                fault = gen.feedback_fault(
                    rng, phase.feedback_dup_fraction,
                    phase.feedback_reorder_fraction)
                fb = Event(phase.name, off + lag, "feedback", tenant,
                           [f"{tenant},{arm},{reward}"], fault=fault)
                feedback.append(fb)
                if fault == "dup":
                    feedback.append(Event(phase.name, off + lag, "feedback",
                                          tenant, list(fb.rows),
                                          fault="dup"))
            continue
        n_rows = gen.payload_rows(rng, scenario.payload_median,
                                  scenario.payload_sigma,
                                  scenario.payload_max)
        rows = [gen.churn_row(rng, (ident + j) % (1 << 30))
                for j in range(n_rows)]
        events.append(Event(phase.name, off, "predict", tenant, rows))
    # reorder chaos: swap each tagged feedback event's offset with its
    # successor — the consumer sees the later event first
    for i, fb in enumerate(feedback[:-1]):
        if fb.fault == "reorder":
            fb.offset_s, feedback[i + 1].offset_s = (
                feedback[i + 1].offset_s, fb.offset_s)
    events.extend(feedback)
    events.sort(key=lambda e: (e.offset_s, e.kind, e.tenant))
    return events


def build_schedule(scenario: Scenario,
                   tenants: Optional[List[str]] = None) -> List[Event]:
    """The full deterministic schedule: every phase's events (offsets
    are phase-relative; phases execute sequentially).  Thread count is
    deliberately NOT an input — partitioning happens later
    (:func:`generators.partition`), so replay is fleet-shape-invariant."""
    tenants = tenants if tenants is not None else tenant_universe(scenario)
    arms = [a.strip()
            for a in (scenario.config.get_list(posterior.KEY_ARMS)
                      or ["arm0"])]
    rng = random.Random(scenario.seed)
    events: List[Event] = []
    for phase in scenario.phases:
        events.extend(
            build_phase_events(scenario, phase, tenants, arms, rng))
    return events

"""Cross-process trace stitching: N spool feeds, ONE Perfetto file.

Each process's trace JSONL records timestamps relative to its OWN
tracer epoch (a ``perf_counter_ns`` instant, meaningless outside the
process).  The identity record carries that epoch expressed on the Unix
wall clock (``trace_epoch_unix_ns``), so stitching is pure arithmetic:
pick the earliest anchor across the selected feeds as t=0, offset every
record by ``(feed anchor - t0) + t0_ns``, and emit Chrome
``trace_event`` JSON with one ``pid`` lane per process (process_name =
the identity label).  The PR-10 fan-in links (trace ids + explicit
parent span ids in ``args``) make a request's spans connect across
lanes in Perfetto.

``--trace-id X`` filters to span records whose attrs carry that trace
id — the "show me THIS request across the fleet" view; without it,
every record from every feed lands on the shared timeline.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Tuple

from ..core.io import atomic_write_text
from .identity import RESERVED_PREFIX
from .publisher import IDENTITY_FILE, TRACE_FILE


def feed_dirs(spool_dir: str) -> List[str]:
    """Every feed directory under the spool (has an identity.json;
    aggregator-reserved ``_*`` entries excluded), sorted by label."""
    out = []
    try:
        entries = sorted(os.listdir(spool_dir))
    except OSError:
        return []
    for name in entries:
        if name.startswith(RESERVED_PREFIX):
            continue
        d = os.path.join(spool_dir, name)
        if os.path.isfile(os.path.join(d, IDENTITY_FILE)):
            out.append(d)
    return out


def read_identity(feed_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(feed_dir, IDENTITY_FILE)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def read_trace_records(feed_dir: str) -> List[dict]:
    """The feed's flushed tracer records, oldest first: rotations
    (``trace.jsonl.N`` … ``trace.jsonl.1``) then the live file.
    Truncated tail lines (a crash mid-append) are skipped."""
    base = os.path.join(feed_dir, TRACE_FILE)
    paths = sorted(
        (p for p in glob.glob(base + ".*")
         if p.rsplit(".", 1)[1].isdigit()),
        key=lambda p: -int(p.rsplit(".", 1)[1]))
    paths.append(base)
    records: List[dict] = []
    for path in paths:
        try:
            with open(path) as fh:
                for line in fh:
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return records


def _matches(rec: dict, trace_id: Optional[str]) -> bool:
    if trace_id is None:
        return True
    if rec.get("type") != "span":
        return False
    return str((rec.get("attrs") or {}).get("trace")) == str(trace_id)


def stitch_traces(spool_dir: str, trace_id: Optional[str] = None,
                  out_path: str = "fleet-trace.json"
                  ) -> Tuple[int, List[str]]:
    """Merge every feed's trace JSONL onto one wall-clock timeline;
    returns ``(events written, labels of processes contributing
    events)``.  Feeds publishing no matching record get no lane."""
    feeds = []
    for d in feed_dirs(spool_dir):
        ident = read_identity(d)
        if ident is None or not ident.get("trace_epoch_unix_ns"):
            continue
        recs = [r for r in read_trace_records(d) if _matches(r, trace_id)]
        if recs:
            feeds.append((ident, recs))
    if not feeds:
        atomic_write_text(out_path, json.dumps(
            {"traceEvents": [], "displayTimeUnit": "ms"}))
        return 0, []

    t0 = min(int(ident["trace_epoch_unix_ns"]) for ident, _ in feeds)
    events: List[dict] = []
    labels: List[str] = []
    for lane, (ident, recs) in enumerate(sorted(
            feeds, key=lambda f: f[0].get("label", "")), start=1):
        label = str(ident.get("label", f"proc-{lane}"))
        labels.append(label)
        offset_ns = int(ident["trace_epoch_unix_ns"]) - t0
        events.append({"ph": "M", "name": "process_name", "pid": lane,
                       "tid": 0, "args": {"name": label}})
        tid_map: Dict[str, int] = {}

        def tid_of(thread_name: str) -> int:
            t = tid_map.get(thread_name)
            if t is None:
                t = tid_map[thread_name] = len(tid_map) + 1
                events.append({"ph": "M", "name": "thread_name",
                               "pid": lane, "tid": t,
                               "args": {"name": thread_name}})
            return t

        for r in recs:
            if r.get("type") == "span":
                events.append({
                    "name": r.get("name"), "cat": "avenir", "ph": "X",
                    "ts": (offset_ns + int(r.get("t0_ns", 0))) / 1000.0,
                    "dur": int(r.get("dur_ns", 0)) / 1000.0,
                    "pid": lane,
                    "tid": tid_of(str(r.get("thread", "main"))),
                    "args": {"id": r.get("id"), "parent": r.get("parent"),
                             "proc": label, **(r.get("attrs") or {})}})
            elif r.get("type") == "gauge":
                events.append({
                    "name": r.get("name"), "cat": "avenir", "ph": "C",
                    "ts": (offset_ns + int(r.get("t_ns", 0))) / 1000.0,
                    "pid": lane, "args": {"value": r.get("value")}})

    events.sort(key=lambda e: e.get("ts", -1.0))
    atomic_write_text(out_path, json.dumps(
        {"traceEvents": events, "displayTimeUnit": "ms"}))
    return len(events), labels


def trace_tail(feed_dir: str, trace_id: str, limit: int = 2000
               ) -> List[dict]:
    """The LAST ``limit`` records in a feed's trace JSONL that belong to
    ``trace_id`` — the incident correlator's per-process evidence."""
    recs = [r for r in read_trace_records(feed_dir)
            if _matches(r, trace_id)]
    return recs[-limit:]

"""The spool publisher: one process's telemetry, atomically on disk.

With ``fleetobs.spool.dir`` configured, a long-running entry point
publishes into ``<spool>/<identity label>/``:

- ``identity.json``  — the process identity record, written once
- ``snapshot.json``  — the latest full exporter snapshot (identity
  section included), wrapped with a monotone ``seq`` and the publish
  wall time; replaced atomically (PR-9 ``atomic_write_text``: mkstemp +
  fsync + rename), so the aggregator NEVER reads a torn snapshot
- ``trace.jsonl``    — incremental tracer records (rotations
  ``trace.jsonl.1`` …), flushed on each publish tick — the stitcher's
  input
- ``flight/``        — the process's flight dumps (``flight.dump.dir``
  is routed here unless explicitly configured elsewhere)

The publisher rides the existing :class:`TelemetryExporter` as a sink:
no second thread, no second snapshot — the JSONL line, the ``metrics``
scrape, and the spooled snapshot are the SAME dict per tick.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ..core import flight, obs, sanitizer, telemetry
from ..core.io import atomic_write_text
from .identity import ProcessIdentity, new_identity

KEY_SPOOL_DIR = "fleetobs.spool.dir"
KEY_ROLE = "fleetobs.role"

SNAPSHOT_FILE = "snapshot.json"
IDENTITY_FILE = "identity.json"
TRACE_FILE = "trace.jsonl"
FLIGHT_SUBDIR = "flight"


class SpoolPublisher:
    """Publishes one process's telemetry into its spool feed.  Attach
    to a running exporter with :meth:`attach`; every exporter tick then
    atomically replaces ``snapshot.json`` and flushes new tracer
    records to the feed's ``trace.jsonl``."""

    def __init__(self, spool_dir: str, identity: ProcessIdentity,
                 tracer=None):
        self.identity = identity
        self.spool_dir = spool_dir
        self.dir = os.path.join(spool_dir, identity.label)
        self.seq = 0
        self._lock = sanitizer.make_lock("fleetobs.publisher")
        os.makedirs(self.dir, exist_ok=True)
        atomic_write_text(os.path.join(self.dir, IDENTITY_FILE),
                          json.dumps(identity.to_dict(), indent=2) + "\n")
        # interval 0 = never self-started: the flusher is driven
        # manually from publish(), so the publisher adds no thread
        self._flusher = telemetry.TraceFlusher(
            tracer if tracer is not None else obs.get_tracer(),
            os.path.join(self.dir, TRACE_FILE), interval_sec=0.0)

    @property
    def flight_dir(self) -> str:
        return os.path.join(self.dir, FLIGHT_SUBDIR)

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.dir, SNAPSHOT_FILE)

    def publish(self, snapshot: dict) -> str:
        """One atomic publish (the exporter-sink entry point)."""
        with self._lock:
            self.seq += 1
            doc = {"seq": self.seq, "published_unix": time.time(),
                   "label": self.identity.label, "snapshot": snapshot}
            atomic_write_text(self.snapshot_path, json.dumps(doc) + "\n")
        try:
            self._flusher.flush()
        except Exception:                               # noqa: BLE001
            pass            # trace flush must never break the publish
        return self.snapshot_path

    def attach(self, exporter: Optional[telemetry.TelemetryExporter],
               config=None) -> telemetry.TelemetryExporter:
        """Wire this publisher into ``exporter`` (identity stamp + sink).
        When the entry point had no exporter (a batch dag/multi run with
        no ``--metrics-out``), a spool-only exporter is created and
        STARTED — the caller owns stopping whatever comes back."""
        if exporter is None:
            interval = (config.get_float(telemetry.KEY_INTERVAL,
                                         telemetry.DEFAULT_INTERVAL_SEC)
                        if config is not None
                        else telemetry.DEFAULT_INTERVAL_SEC)
            exporter = telemetry.TelemetryExporter(interval).start()
        exporter.identity = self.identity.to_dict()
        exporter.sinks.append(self.publish)
        return exporter


def publisher_for_job(config, role: str) -> Optional[SpoolPublisher]:
    """A :class:`SpoolPublisher` when ``fleetobs.spool.dir`` is set,
    else None.  Call AFTER ``obs.configure_from_config`` (the identity's
    trace anchor must describe the configured tracer) and BEFORE the
    flight recorder is configured — this routes ``flight.dump.dir``
    into the spool feed unless the job explicitly configured one."""
    spool = config.get(KEY_SPOOL_DIR)
    if not spool:
        return None
    pub = SpoolPublisher(spool, new_identity(config.get(KEY_ROLE) or role))
    if not config.get(flight.KEY_DUMP_DIR):
        config.set(flight.KEY_DUMP_DIR, pub.flight_dir)
    return pub

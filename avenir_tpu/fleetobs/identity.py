"""Process identity records: who published this spool feed.

A fleet merge erases process boundaries by construction (that is its
job), so attribution has to ride ALONGSIDE the merged state: every
spool feed carries one identity record — role (which entry point),
host, pid, and a start-time nonce so a restarted process with a
recycled pid publishes under a FRESH feed instead of silently
continuing the dead one's series — plus the tracer's wall-clock epoch
anchor, which is what lets the stitcher place N processes' relative
span timestamps on one shared timeline.
"""

from __future__ import annotations

import os
import re
import socket
import time
from typing import Mapping, Optional

from ..core import obs

#: spool entries starting with this prefix are aggregator-owned
#: (incident bundles, the aggregator's own flight dir), never feeds
RESERVED_PREFIX = "_"

_LABEL_SAFE_RE = re.compile(r"[^A-Za-z0-9._-]+")


class ProcessIdentity:
    """One publishing process's identity: ``label`` is its spool
    directory name — filesystem-safe and unique per process START
    (role, host, pid, and a nanosecond start nonce), so two publishers
    can never collide and a restart never aliases its predecessor."""

    __slots__ = ("role", "host", "pid", "start_ns", "trace_epoch_unix_ns")

    def __init__(self, role: str, host: str, pid: int, start_ns: int,
                 trace_epoch_unix_ns: int):
        self.role = str(role)
        self.host = str(host)
        self.pid = int(pid)
        self.start_ns = int(start_ns)
        self.trace_epoch_unix_ns = int(trace_epoch_unix_ns)

    @property
    def label(self) -> str:
        nonce = format(self.start_ns & 0xFFFFFFFFFF, "x")
        return "-".join(_LABEL_SAFE_RE.sub("_", part)
                        for part in (self.role, self.host, str(self.pid),
                                     nonce))

    def to_dict(self) -> dict:
        return {"role": self.role, "host": self.host, "pid": self.pid,
                "start_ns": self.start_ns, "label": self.label,
                "trace_epoch_unix_ns": self.trace_epoch_unix_ns}

    @classmethod
    def from_dict(cls, d: Mapping[str, object]) -> "ProcessIdentity":
        return cls(role=str(d["role"]), host=str(d["host"]),
                   pid=int(d["pid"]), start_ns=int(d["start_ns"]),
                   trace_epoch_unix_ns=int(d.get("trace_epoch_unix_ns", 0)))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ProcessIdentity({self.label})"


def new_identity(role: str, tracer: Optional[object] = None
                 ) -> ProcessIdentity:
    """This process's identity record.  Build it AFTER the tracer is
    configured (``obs.configure_from_config``) — the wall-clock anchor
    must describe the tracer whose records actually get spooled."""
    tr = tracer if tracer is not None else obs.get_tracer()
    return ProcessIdentity(
        role=role, host=socket.gethostname(), pid=os.getpid(),
        start_ns=time.time_ns(),
        trace_epoch_unix_ns=tr.wall_epoch_unix_ns())

"""The fleet fold: N per-process snapshots into ONE, without lies.

``merge_snapshots`` is already a certified commutative fold — counters
sum, histogram buckets add, span summaries count-weight — so the only
genuinely fleet-specific problem is GAUGES: they merge
latest-timestamp-wins, which is correct inside one process (two samples
of the same series) and silently wrong across processes (two processes'
``device.hbm.bytes`` are two different devices, not two samples of
one).  The fix is namespacing, applied HERE, at the fleet boundary:
every gauge name gains a ``proc="<identity label>"`` label before the
fold, so per-process series survive side by side and latest-ts-wins
never sees two processes under one name.  Single-process callers never
pass through this module, so single-process merge behavior stays
byte-identical (asserted in tests/test_fleetobs.py).

:class:`FleetSLO` drives a regular :class:`~avenir_tpu.serve.slo.SLOBoard`
from the merged per-model ``serve.e2e.latency{model=...}`` histograms
through the :class:`~avenir_tpu.serve.slo.SnapshotStats` facade — the
fleet p99 is computed by the SAME rolling-window code that watches a
single process.
"""

from __future__ import annotations

import re
from typing import Dict, Mapping, Optional

from ..core import telemetry
from ..serve.slo import SLOBoard, SnapshotStats

#: merged-hist family the fleet SLO watches (the serve overlay's name)
E2E_FAMILY = "serve.e2e.latency"

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
_UNESC_RE = re.compile(r"\\(.)")


def parse_labels(label_str: str) -> Dict[str, str]:
    """Inverse of :func:`telemetry.labeled`'s label rendering: the
    ``k="v"`` pairs with escapes undone."""
    out: Dict[str, str] = {}
    for m in _LABEL_RE.finditer(label_str):
        out[m.group(1)] = _UNESC_RE.sub(
            lambda e: {"n": "\n"}.get(e.group(1), e.group(1)), m.group(2))
    return out


def namespace_gauges(snap: Mapping[str, object], label: str) -> dict:
    """A shallow copy of ``snap`` whose every gauge name carries
    ``proc="<label>"`` (appended to existing labels) — the fleet-merge
    precondition that keeps latest-ts-wins from clobbering two
    processes' like-named gauges.  Counters/hists/spans are untouched:
    their merges are sums, where folding across processes is the POINT
    (fleet requests == sum of per-process requests)."""
    out = dict(snap)
    esc = telemetry._esc(str(label))
    named = {}
    for name, g in (snap.get("gauges") or {}).items():
        if name.endswith("}"):
            named[f'{name[:-1]},proc="{esc}"}}'] = g
        else:
            named[f'{name}{{proc="{esc}"}}'] = g
    out["gauges"] = named
    return out


def fleet_fold(snapshots_by_label: Mapping[str, dict]) -> dict:
    """Fold per-process snapshots (keyed by identity label) into one
    fleet snapshot: gauges namespaced per process first, then the
    certified ``merge_snapshots`` fold.  Identity/pid sections are
    consumed here and dropped, per SNAPSHOT_NON_MERGED."""
    merged: Optional[dict] = None
    for label in sorted(snapshots_by_label):
        ns = namespace_gauges(snapshots_by_label[label], label)
        merged = ns if merged is None else telemetry.merge_snapshots(
            merged, ns)
    if merged is None:
        return {"v": telemetry.SNAPSHOT_VERSION, "ts": 0.0, "mono": 0.0,
                "counters": {}, "gauges": {}, "hists": {}, "spans": {}}
    for section in telemetry.SNAPSHOT_NON_MERGED:
        merged.pop(section, None)
    return merged


class FleetSLO:
    """Fleet-level SLO boards over a merged snapshot.  One monitor per
    model parsed out of the merged ``serve.e2e.latency{model=...}``
    histograms; each keeps a stable :class:`SnapshotStats` facade so the
    rolling window survives across observations (ModelSLO keys its
    window on histogram identity)."""

    def __init__(self, config):
        self.board = SLOBoard(config)
        self._stats: Dict[str, SnapshotStats] = {}

    def observe(self, merged: dict) -> Dict[str, dict]:
        """Evaluate every model found in ``merged``; returns the window
        stats per model (also retained on the board for ``section``)."""
        out: Dict[str, dict] = {}
        counters = merged.get("counters") or {}
        for name, st in sorted((merged.get("hists") or {}).items()):
            m = telemetry._LABELED_RE.match(name)
            if not m or m.group(1) != E2E_FAMILY:
                continue
            model = parse_labels(m.group(2)).get("model")
            if not model:
                continue
            sv = self._stats.get(model)
            if sv is None:
                sv = self._stats[model] = SnapshotStats()
            sv.update(st, counters.get(f"Serve.{model}", {}))
            out[model] = self.board.observe(model, sv, config_name=model)
        return out

    def section(self) -> Dict[str, dict]:
        return self.board.section()

    def verdicts(self) -> Dict[str, dict]:
        """Machine-readable per-model SLO verdict from the LAST
        evaluation — the router tier's routing input and the aggregator
        ``stats`` surface, so runbooks stop recomputing it from merged
        histograms.  ``ok`` is the dispatch-grade bit: False the moment
        the window violates (the sustained bit additionally marks the
        degrade-grade signal)."""
        out: Dict[str, dict] = {}
        for model, stats in self.board.section().items():
            out[model] = {
                "ok": not (stats.get("violation")
                           or stats.get("sustained")),
                "p99_ms": stats.get("p99_ms"),
                "target_p99_ms": stats.get("target_p99_ms"),
                "error_pct": stats.get("error_pct"),
                "violation": bool(stats.get("violation")),
                "sustained": bool(stats.get("sustained")),
                "n": stats.get("n", 0),
            }
        return out

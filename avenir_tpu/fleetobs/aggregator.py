"""The fleet aggregator: watch N spools, serve ONE merged surface.

``python -m avenir_tpu fleetobs -Dfleetobs.spool.dir=<dir>`` polls
every feed under the spool, folds the per-process snapshots
(:func:`fleet_fold` — gauges namespaced per process, counters/hists
summed by the certified merge), drives fleet-level SLO boards from the
merged per-model histograms, and serves the result over the SAME
JSON-lines frontend the prediction server uses:

- ``{"cmd": "metrics"}`` — merged Prometheus exposition (``# EOF``
  terminated), scrapeable by :func:`serve.server.request_text`
- ``{"cmd": "health"}``  — ok iff no feed is stale; fleet SLO section
- ``{"cmd": "stats"}``   — per-feed detail (seq, age, staleness),
  fleet SLO windows, incident count

Feed staleness (a process died or stopped publishing) becomes a
``fleetobs.feed.stale{proc=...}`` gauge and, on the fresh→stale EDGE,
a flight-recorder anomaly dump — the aggregator's own black box
records what the fleet looked like when the feed went dark.  New
flight dumps in any feed are correlated into incident bundles
(:mod:`.incidents`).

Deliberately jax-free: the aggregator imports only the observability
substrate, so it can run beside N serving processes at the cost of an
OS process, not an accelerator runtime.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core import flight, sanitizer, telemetry
from ..core.config import load_job_config, parse_cli_args
from .aggregate import FleetSLO, fleet_fold
from .incidents import IncidentCorrelator
from .publisher import (FLIGHT_SUBDIR, KEY_SPOOL_DIR, SNAPSHOT_FILE,
                        SpoolPublisher)
from .stitch import feed_dirs, read_identity, stitch_traces

KEY_POLL_SEC = "fleetobs.poll.sec"
KEY_STALE_SEC = "fleetobs.stale.sec"
KEY_HOST = "fleetobs.host"
KEY_PORT = "fleetobs.port"
KEY_INCIDENT_DIR = "fleetobs.incident.dir"

DEFAULT_POLL_SEC = 1.0
DEFAULT_STALE_SEC = 10.0

#: thread-name prefix of the aggregator's poll thread (the shutdown
#: discipline: stop() joins it, mirroring telemetry.THREAD_PREFIXES)
THREAD_PREFIX = "avenir-fleetobs"


class _Feed:
    __slots__ = ("label", "dir", "identity", "snapshot", "seq",
                 "published_unix", "stale")

    def __init__(self, label: str, d: str, identity: dict):
        self.label = label
        self.dir = d
        self.identity = identity
        self.snapshot: Optional[dict] = None
        self.seq = 0
        self.published_unix = 0.0
        self.stale = False


class FleetAggregator:
    """Poll loop + merged surface.  Exposes ``dispatch_line`` /
    ``max_line_bytes`` so :class:`~avenir_tpu.serve.frontend.
    EventLoopFrontend` can serve it unchanged."""

    max_line_bytes = 1 << 20

    def __init__(self, spool_dir: str, config):
        self.spool_dir = spool_dir
        self.poll_sec = config.get_float(KEY_POLL_SEC, DEFAULT_POLL_SEC)
        self.stale_sec = config.get_float(KEY_STALE_SEC, DEFAULT_STALE_SEC)
        incident_dir = (config.get(KEY_INCIDENT_DIR)
                        or os.path.join(spool_dir, "_incidents"))
        self.config = config
        self.fleet_slo = FleetSLO(config)
        # per-feed SLO boards: the same rolling-window evaluation the
        # fleet board runs on the merged snapshot, applied to each RAW
        # feed — machine-readable per-feed, per-model verdicts in
        # ``stats`` so routers and runbooks stop recomputing them
        self._feed_slo: Dict[str, FleetSLO] = {}
        self.incidents = IncidentCorrelator(incident_dir)
        self._feeds: Dict[str, _Feed] = {}
        self._lock = sanitizer.make_lock("fleetobs.aggregator")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scans = 0

    # -- polling -----------------------------------------------------------
    def scan(self, now: Optional[float] = None) -> dict:
        """One poll pass: refresh feeds, mark staleness edges (flight
        anomaly on fresh→stale), correlate new flight dumps, evaluate
        the fleet SLO boards.  Returns the merged fleet snapshot."""
        now = time.time() if now is None else float(now)
        with self._lock:
            for d in feed_dirs(self.spool_dir):
                label = os.path.basename(d)
                feed = self._feeds.get(label)
                if feed is None:
                    ident = read_identity(d)
                    if ident is None:
                        continue
                    feed = self._feeds[label] = _Feed(label, d, ident)
                self._refresh(feed)
            for feed in self._feeds.values():
                was = feed.stale
                feed.stale = (feed.published_unix > 0
                              and now - feed.published_unix
                              > self.stale_sec)
                if feed.stale and not was:
                    # edge-triggered: the moment a feed goes dark, dump
                    # the aggregator's black box naming it
                    flight.trigger(
                        "fleet_feed_stale", force=True, proc=feed.label,
                        age_sec=round(now - feed.published_unix, 3),
                        stale_sec=self.stale_sec)
            merged = self._fleet_snapshot(now)
            self.scans += 1
            snapshots = [(f.label, f.snapshot)
                         for f in self._feeds.values()
                         if f.snapshot is not None]
        for label, snap in snapshots:
            with self._lock:
                slo = self._feed_slo.get(label)
                if slo is None:
                    slo = self._feed_slo[label] = FleetSLO(self.config)
            # fold OFF the lock: window math must not block dispatch
            slo.observe(snap)
        dirs = {f.label: f.dir for f in self._feeds.values()}
        # the aggregator's own black box (feed-stale anomalies land in
        # the reserved _aggregator spool entry) correlates too — a feed
        # going dark should produce an incident, not just a gauge
        own = os.path.join(self.spool_dir, "_aggregator")
        if os.path.isdir(os.path.join(own, FLIGHT_SUBDIR)):
            dirs["_aggregator"] = own
        self.incidents.scan(dirs)
        self.fleet_slo.observe(merged)
        return merged

    def _refresh(self, feed: _Feed) -> None:
        try:
            with open(os.path.join(feed.dir, SNAPSHOT_FILE)) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return          # not yet published (or mid-replace on a
                            # non-atomic filesystem): keep the last one
        snap = doc.get("snapshot")
        if not isinstance(snap, dict):
            return
        feed.snapshot = snap
        feed.seq = int(doc.get("seq", 0))
        feed.published_unix = float(doc.get("published_unix", 0.0))

    def _fleet_snapshot(self, now: float) -> dict:
        """The fold + the aggregator's own fleet gauges.  Stale feeds
        STAY in the fold: their counters are cumulative history that
        still happened — staleness is surfaced, never silently
        subtracted."""
        merged = fleet_fold({f.label: f.snapshot
                             for f in self._feeds.values()
                             if f.snapshot is not None})
        g = merged.setdefault("gauges", {})

        def gauge(name, value, **labels):
            g[telemetry.labeled(name, **labels)] = {
                "value": float(value), "ts": now}

        live = [f for f in self._feeds.values() if f.snapshot is not None]
        gauge("fleetobs.feeds", len(live))
        gauge("fleetobs.feeds.stale", sum(1 for f in live if f.stale))
        for f in live:
            gauge("fleetobs.feed.stale", 1 if f.stale else 0,
                  proc=f.label)
            gauge("fleetobs.feed.age.sec",
                  round(max(now - f.published_unix, 0.0), 3),
                  proc=f.label)
        return merged

    def fleet_snapshot(self) -> dict:
        """The current merged snapshot (fresh fold over cached feeds —
        a scrape between polls sees the latest published state)."""
        now = time.time()
        with self._lock:
            return self._fleet_snapshot(now)

    # -- the JSON-lines surface -------------------------------------------
    def dispatch_line(self, line: str, cb: Callable[[dict], None],
                      conn=None) -> Optional[dict]:
        try:
            obj = json.loads(line)
            if not isinstance(obj, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            cb({"error": f"bad request: {exc}"})
            return None
        cmd = obj.get("cmd")
        try:
            if cmd == "metrics":
                cb({"_text": telemetry.prometheus_text(
                    self.fleet_snapshot())})
            elif cmd == "health":
                cb(self._health())
            elif cmd == "stats":
                cb(self._stats())
            else:
                cb({"error": f"unknown cmd: {cmd!r} "
                             f"(metrics|health|stats)"})
        except Exception as exc:                        # noqa: BLE001
            cb({"error": f"{type(exc).__name__}: {exc}"})
        return None

    def _health(self) -> dict:
        with self._lock:
            stale = sorted(f.label for f in self._feeds.values()
                           if f.stale)
            feeds = sum(1 for f in self._feeds.values()
                        if f.snapshot is not None)
        return {"ok": not stale, "feeds": feeds, "stale": stale,
                "slo": self.fleet_slo.section()}

    def _stats(self) -> dict:
        now = time.time()
        with self._lock:
            feeds = {f.label: {
                "role": f.identity.get("role"),
                "pid": f.identity.get("pid"),
                "seq": f.seq,
                "age_sec": (round(now - f.published_unix, 3)
                            if f.published_unix else None),
                "stale": f.stale,
                "slo": (self._feed_slo[f.label].verdicts()
                        if f.label in self._feed_slo else {}),
            } for f in sorted(self._feeds.values(),
                              key=lambda f: f.label)}
            scans = self.scans
        return {"feeds": feeds, "scans": scans,
                "slo": self.fleet_slo.section(),
                "slo_verdicts": self.fleet_slo.verdicts(),
                "incidents": self.incidents.bundled,
                "flight": flight.get_recorder().stats()}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetAggregator":
        if self.poll_sec <= 0 or self._thread is not None:
            return self

        def run():
            while not self._stop.wait(self.poll_sec):
                try:
                    self.scan()
                except Exception:                       # noqa: BLE001
                    pass        # one bad pass must not kill the plane

        self._thread = threading.Thread(target=run, name=THREAD_PREFIX,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None


def _stitch_main(argv) -> int:
    from ..cli import _extract_value_flag
    argv, trace_id = _extract_value_flag(list(argv), "--trace-id")
    argv, out_path = _extract_value_flag(argv, "--out")
    argv, spool = _extract_value_flag(argv, "--spool")
    defines, positional = parse_cli_args(argv)
    config = load_job_config(defines)
    spool = spool or config.get(KEY_SPOOL_DIR) or (
        positional[0] if positional else None)
    if not spool:
        print("fleetobs stitch: no spool "
              "(--spool <dir> or -Dfleetobs.spool.dir=<dir>)",
              file=sys.stderr)
        return 2
    out_path = out_path or "fleet-trace.json"
    n, labels = stitch_traces(spool, trace_id=trace_id, out_path=out_path)
    print(f"fleetobs: stitched {n} events from {len(labels)} "
          f"process(es) {labels} into {out_path} "
          f"(open in ui.perfetto.dev)", file=sys.stderr)
    return 0 if n else 1


def fleetobs_main(argv) -> int:
    """``python -m avenir_tpu fleetobs [-Dfleetobs.spool.dir=<dir> ...]
    [--once]`` or ``... fleetobs stitch --trace-id X [--out f.json]``."""
    argv = list(argv)
    if argv and argv[0] == "stitch":
        return _stitch_main(argv[1:])
    once = "--once" in argv
    argv = [a for a in argv if a != "--once"]
    defines, positional = parse_cli_args(argv)
    config = load_job_config(defines)
    spool = config.get(KEY_SPOOL_DIR) or (
        positional[0] if positional else None)
    if not spool:
        print("fleetobs: no spool configured "
              "(-Dfleetobs.spool.dir=<dir>)", file=sys.stderr)
        return 2
    from ..cli import configure_resilience
    from ..core import obs
    obs.configure_from_config(config)
    # the aggregator's own flight dumps (feed-stale anomalies) default
    # into a reserved spool entry — never mistaken for a feed
    if not config.get(flight.KEY_DUMP_DIR):
        config.set(flight.KEY_DUMP_DIR,
                   os.path.join(spool, "_aggregator", FLIGHT_SUBDIR))
    configure_resilience(config)
    telemetry.configure_from_config(config)

    agg = FleetAggregator(spool, config)
    if once:
        merged = agg.scan()
        sys.stdout.write(telemetry.prometheus_text(merged))
        return 0
    agg.start()
    from ..serve.frontend import EventLoopFrontend
    frontend = EventLoopFrontend(
        agg, config.get(KEY_HOST, "127.0.0.1"),
        config.get_int(KEY_PORT, 0), io_threads=1)
    print(f"fleetobs: aggregating {spool} on "
          f"{config.get(KEY_HOST, '127.0.0.1')}:{frontend.port} "
          f"(poll {agg.poll_sec}s, stale after {agg.stale_sec}s)",
          file=sys.stderr, flush=True)
    stop_evt = threading.Event()
    import signal
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop_evt.set())
        except (ValueError, OSError):
            pass
    try:
        stop_evt.wait()
    except KeyboardInterrupt:
        pass
    finally:
        frontend.stop()
        agg.stop()
        dump = flight.flush_on_exit()
        if dump:
            print(f"flight: wrote final black-box dump to {dump}",
                  file=sys.stderr)
    return 0


# referenced by __init__ re-exports and the runbook; kept here so the
# CLI branch imports one module
__all__ = ["FleetAggregator", "SpoolPublisher", "fleetobs_main"]

"""Fleet observability plane: cross-process telemetry aggregation,
stitched traces, and fleet-wide SLO verdicts.

Every observability primitive in this repo was built mergeable on
purpose — ``merge_snapshots`` is a certified commutative fold (PR 12),
SLO boards diff cumulative histograms, trace ids propagate through
batching fan-in (PR 10) — but until this package nothing ever *did* the
merge across processes.  This is the Dapper + Monarch move (PAPERS.md,
"Observability"): per-process collection stays local and cheap, and
aggregation is a hierarchical fold of already-mergeable state.

Three legs:

- **Publisher** (:mod:`.publisher`) — with ``fleetobs.spool.dir`` set,
  every long-running entry point (serve, stream, workload, dag, multi)
  atomically publishes its ``TelemetryExporter`` snapshot per tick into
  a per-process spool directory, tagged with a process identity record
  (:mod:`.identity`: role, host, pid, start-time nonce, trace epoch
  anchor); incremental trace JSONL and flight dumps land in the same
  spool.
- **Aggregator** (:mod:`.aggregator`) — ``python -m avenir_tpu
  fleetobs`` watches N spools, folds the snapshots (per-process gauges
  namespaced with the identity label so latest-ts-wins merging cannot
  clobber them — :mod:`.aggregate`), drives fleet-level ``SLOBoard``s
  from the merged per-model histograms, serves the merged Prometheus
  exposition + ``health``/``stats`` over the existing JSON-lines
  frontend, and turns feed staleness into a gauge plus a
  flight-recorder anomaly.
- **Trace stitching + flight correlation** (:mod:`.stitch`,
  :mod:`.incidents`) — ``python -m avenir_tpu fleetobs stitch
  --trace-id X`` merges per-process trace JSONL into ONE
  Perfetto-loadable file with one lane per process; a flight dump in
  any process makes the aggregator bundle sibling-spool dumps and trace
  tails sharing the trace id into a single incident directory.

The aggregator is deliberately jax-free: it imports only the core
observability substrate, so one more aggregator process costs an OS
process, not an accelerator runtime.
"""

from __future__ import annotations

from .aggregate import FleetSLO, fleet_fold, namespace_gauges
from .identity import ProcessIdentity, new_identity
from .publisher import SpoolPublisher, publisher_for_job

__all__ = [
    "FleetSLO", "ProcessIdentity", "SpoolPublisher", "fleet_fold",
    "namespace_gauges", "new_identity", "publisher_for_job",
]

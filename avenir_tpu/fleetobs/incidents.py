"""Flight-dump correlation: one anomaly, one incident directory.

A flight dump is a single process's black box.  In a fleet the question
is almost never "what did THIS process see" but "what was everyone
doing when it happened" — so when any feed's ``flight/`` directory
grows a new dump, the aggregator bundles, into ONE incident directory:

- every sibling dump (across ALL feeds) whose ``flight.header`` carries
  the same trace id,
- each contributing feed's trace tail for that trace id
  (``<label>-trace.jsonl``), and
- a ``manifest.json`` naming the trigger, the members, and the feeds.

Dumps are keyed by their header's ``trace_id`` — the first line of the
dump file — never by parsing the filename back (the filename tag
doubles as a timestamp when the trigger carried no trace).  An
untraced dump still gets an incident directory (keyed by its file
stem) so no black box is ever orphaned; it just has nothing to
correlate.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Dict, List, Optional, Tuple

from ..core import flight
from ..core.io import atomic_write_text
from .publisher import FLIGHT_SUBDIR
from .stitch import trace_tail

_NAME_SAFE_RE = re.compile(r"[^A-Za-z0-9._-]+")


def _dumps_in(feed_dir: str) -> List[str]:
    d = os.path.join(feed_dir, FLIGHT_SUBDIR)
    try:
        return sorted(os.path.join(d, n) for n in os.listdir(d)
                      if n.startswith("flight-") and n.endswith(".jsonl"))
    except OSError:
        return []


class IncidentCorrelator:
    """Tracks seen dumps across feeds; ``scan`` turns new ones into
    incident bundles under ``incident_dir`` (created lazily)."""

    def __init__(self, incident_dir: str, tail_limit: int = 2000):
        self.incident_dir = incident_dir
        self.tail_limit = int(tail_limit)
        self._seen: set = set()
        self.bundled = 0

    def scan(self, feed_dirs_by_label: Dict[str, str]) -> List[str]:
        """One correlation pass; returns incident directories created
        or refreshed this pass."""
        fresh: List[Tuple[str, str, Optional[dict]]] = []
        for label, d in sorted(feed_dirs_by_label.items()):
            for path in _dumps_in(d):
                if path in self._seen:
                    continue
                self._seen.add(path)
                fresh.append((label, path, flight.read_dump_header(path)))
        out: List[str] = []
        done_keys: set = set()
        for label, path, header in fresh:
            trace_id = (header or {}).get("trace_id")
            key = (str(trace_id) if trace_id
                   else os.path.splitext(os.path.basename(path))[0])
            if key in done_keys:
                continue        # a sibling already bundled this pass
            done_keys.add(key)
            out.append(self._bundle(key, trace_id, (label, path),
                                    feed_dirs_by_label))
        return out

    def _bundle(self, key: str, trace_id: Optional[str],
                trigger: Tuple[str, str],
                feed_dirs_by_label: Dict[str, str]) -> str:
        inc_dir = os.path.join(self.incident_dir,
                               f"incident-{_NAME_SAFE_RE.sub('_', key)}")
        os.makedirs(inc_dir, exist_ok=True)
        members: List[dict] = []
        for label, d in sorted(feed_dirs_by_label.items()):
            feed_dumps = []
            for path in _dumps_in(d):
                header = flight.read_dump_header(path)
                if trace_id is not None:
                    if (header or {}).get("trace_id") != trace_id:
                        continue
                elif path != trigger[1]:
                    continue    # untraced: bundle only the trigger dump
                self._seen.add(path)    # siblings need no own incident
                dst = os.path.join(inc_dir,
                                   f"{label}-{os.path.basename(path)}")
                try:
                    shutil.copy2(path, dst)
                except OSError:
                    continue
                feed_dumps.append({"feed": label, "dump": dst,
                                   "reason": (header or {}).get("reason")})
            if feed_dumps:
                members.extend(feed_dumps)
            if trace_id is not None:
                tail = trace_tail(d, str(trace_id), self.tail_limit)
                if tail:
                    tail_path = os.path.join(inc_dir,
                                             f"{label}-trace.jsonl")
                    atomic_write_text(tail_path, "".join(
                        json.dumps(r) + "\n" for r in tail))
                    members.append({"feed": label, "trace_tail": tail_path,
                                    "records": len(tail)})
        atomic_write_text(
            os.path.join(inc_dir, "manifest.json"),
            json.dumps({"incident": key, "trace_id": trace_id,
                        "trigger": {"feed": trigger[0],
                                    "dump": trigger[1]},
                        "members": members}, indent=2) + "\n")
        self.bundled += 1
        return inc_dir

"""Device-mesh construction and row-sharding helpers.

Every batch job in the framework runs the same SPMD shape: the record matrix
is sharded over the ``data`` mesh axis (the analogue of Hadoop input splits),
small model/count tensors are replicated (the analogue of HDFS side-file
broadcast, e.g. bayesian/BayesianPredictor.java:186-224 loading the model in
every mapper), and reductions ride ICI via ``psum`` inside ``shard_map``.

A second ``model`` axis is available for the O(n^2) kernels (kNN / clustering
distance matmuls shard both operand row-spaces — 2-D sharding, the TP
analogue for this workload family).
"""

from __future__ import annotations

import inspect
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.6 exports it at top level
    from jax import shard_map as _shard_map
except ImportError:                     # jax 0.4/0.5 keep it experimental
    from jax.experimental.shard_map import shard_map as _shard_map

_SM_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """Version-stable ``shard_map``: one import site for the whole package
    (the top-level export only exists from jax 0.6; the replication-checker
    flag was renamed ``check_rep`` -> ``check_vma`` along the way)."""
    kw = {}
    if check_vma is not None:
        kw["check_vma" if "check_vma" in _SM_PARAMS else "check_rep"] = \
            check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              data: Optional[int] = None,
              model: int = 1) -> Mesh:
    """Build a (data, model) mesh over the given (default: all) devices."""
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if data is None:
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    arr = np.asarray(devs).reshape(data, model)
    return Mesh(arr, axis_names=("data", "model"))


_default_mesh: Optional[Mesh] = None


def _mesh_from_env() -> Optional[Mesh]:
    """Honor ``AVENIR_MESH=<data>x<model>`` (e.g. ``4x2``) so CLI users can
    pick the 2-D split without code — the mesh-shape knob of the rebuild's
    execution layer (the reference's analogue was the reducer-count /
    parallelism properties)."""
    import os
    spec = os.environ.get("AVENIR_MESH")
    if not spec:
        return None
    try:
        data_s, model_s = spec.lower().split("x")
        return make_mesh(data=int(data_s), model=int(model_s))
    except (ValueError, TypeError) as e:
        raise ValueError(
            f"bad AVENIR_MESH={spec!r}; expected <data>x<model> with "
            f"data*model == device count ({len(jax.devices())})") from e


def get_mesh() -> Mesh:
    """Process-wide default mesh over all visible devices: ``AVENIR_MESH``
    shape if set, else all devices on the data axis."""
    global _default_mesh
    if _default_mesh is None or _default_mesh.devices.size != len(jax.devices()):
        _default_mesh = _mesh_from_env() or make_mesh()
    return _default_mesh


def data_axis_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape["data"]


def pad_rows(arr: np.ndarray, multiple: int,
             fill=0) -> Tuple[np.ndarray, np.ndarray]:
    """Pad axis 0 to a multiple of the data-axis size so rows shard evenly.

    Returns (padded array, bool validity mask) — padding rows carry
    ``mask=False`` so counting kernels weight them zero instead of branching
    on a dynamic shape (static shapes keep XLA on the fast path).
    """
    n = arr.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    mask = np.zeros(target, dtype=bool)
    mask[:n] = True
    if target == n:
        return arr, mask
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill), mask


def shard_rows(arr, mesh: Optional[Mesh] = None, axis: str = "data"):
    """Place an array with axis 0 sharded over the given mesh axis."""
    mesh = mesh or get_mesh()
    spec = P(axis, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def replicate(arr, mesh: Optional[Mesh] = None):
    """Place an array replicated on every device of the mesh (broadcast)."""
    mesh = mesh or get_mesh()
    return jax.device_put(arr, NamedSharding(mesh, P()))

"""Distributed substrate: mesh construction + collective helpers.

This package is the rebuild's "communication backend".  The reference's
backend is the Hadoop shuffle (sort-merge over HTTP), HDFS side files, and
Hadoop counters (SURVEY §2.3); here every one of those becomes an XLA
construct: hash-shuffle + reducer-sum -> ``lax.psum`` over ICI inside
``shard_map``; HDFS side-file broadcast -> replicated device arrays; the
reducer count is the mesh size.
"""

from .mesh import (  # noqa: F401
    get_mesh,
    make_mesh,
    data_axis_size,
    pad_rows,
    shard_rows,
    replicate,
)

"""Seeded synthetic-data generators (test fixtures).

The reference ships per-tutorial generator scripts with planted signals
(resource/telecom_churn.py, freq_items.py, price_opt.py, xaction_seq.rb, ...)
that double as its only test strategy (SURVEY §4).  These NumPy rebuilds are
seeded and deterministic so unit/integration tests can assert planted-signal
recovery.
"""

from .generators import (  # noqa: F401
    gen_telecom_churn,
    gen_transactions,
    gen_state_sequences,
    gen_hmm_sequences,
    gen_price_rounds,
    gen_numeric_classed,
    gen_text_classified,
    gen_elearn,
    gen_retarget,
    gen_hosp_readmit,
    gen_disease,
    gen_usage,
    gen_visit_history,
    gen_event_seq,
    gen_xactions,
    ctr_reward_sampler,
    RETARGET_CONVERSION,
    EVENT_SEQ_STATES,
)

"""Deterministic synthetic datasets with planted signals.

Each generator mirrors a reference tutorial fixture (citations inline) but is
rewritten on seeded ``numpy.random.Generator`` so tests are reproducible; the
reference used unseeded ``random``/``Math.random`` everywhere (SURVEY §7.3.5),
so only statistical — not bitwise — equivalence is meaningful.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def _clip_int(rng, mean, sd, lo, hi, size=None):
    v = np.rint(rng.normal(mean, sd, size)).astype(int)
    return np.clip(v, lo, hi)


def gen_telecom_churn(n: int, seed: int = 42) -> List[List[str]]:
    """Telecom-churn rows per resource/telecom_churn.py:13-76 /
    resource/teleComChurn.json: id,plan,minUsed,dataUsed,csCall,csEmail,
    network,churned.  ~20% churners via three planted causes: bad plan +
    heavy usage; excess customer-service contact; small network.
    """
    rng = np.random.default_rng(seed)
    rows = []
    min_usage = [(600, 50), (1200, 300)]
    data_usage = [(200, 50), (500, 150)]
    cs_call = [(4, 1), (8, 2)]
    cs_email = [(6, 2), (10, 3)]
    network = [(3, 1), (6, 2)]

    def draw(dist, i, lo, hi):
        m, s = dist[i]
        return int(_clip_int(rng, m, s, lo, hi))

    for i in range(n):
        cust_id = f"C{seed:02d}{i:07d}"
        churn = rng.integers(1, 100) > 80
        if churn:
            case = rng.integers(1, 4)
            churned = "Y"
            if case == 1:        # bad plan, heavy usage
                plan = "planA"
                mu = draw(min_usage, 1, 0, 2200)
                du = draw(data_usage, 1, 0, 1000)
                cc = draw(cs_call, 0, 0, 14)
                ce = draw(cs_email, 0, 0, 22)
                nw = draw(network, 0, 0, 12)
            elif case == 2:      # too many CS contacts
                plan = "planB"
                mu = draw(min_usage, 1, 0, 2200)
                du = draw(data_usage, 1, 0, 1000)
                cc = max(draw(cs_call, 1, 0, 14), 6)
                ce = max(draw(cs_email, 1, 0, 22), 8)
                nw = draw(network, 0, 0, 12)
            else:                # small network
                plan = "planB"
                mu = min(draw(min_usage, 1, 0, 2200) + 200, 2200)
                du = min(draw(data_usage, 1, 0, 1000) + 100, 1000)
                cc = draw(cs_call, 0, 0, 14)
                ce = draw(cs_email, 0, 0, 22)
                nw = draw(network, 0, 0, 12)
        else:
            churned = "N"
            plan = "planA" if rng.random() < 0.5 else "planB"
            p = 0 if plan == "planA" else 1
            mu = draw(min_usage, p, 0, 2200)
            du = draw(data_usage, p, 0, 1000)
            cc = min(draw(cs_call, 0, 0, 14), 2)
            ce = min(draw(cs_email, 0, 0, 22), 3)
            nw = draw(network, 1, 0, 12)
        rows.append([cust_id, plan, str(mu), str(du), str(cc), str(ce),
                     str(nw), churned])
    return rows


def gen_transactions(n_trans: int, n_items: int,
                     planted: Sequence[Sequence[int]] = ((3, 7, 11),),
                     planted_support: float = 0.2,
                     items_per_trans: Tuple[int, int] = (4, 10),
                     with_time: bool = False,
                     time_range: Tuple[int, int] = (1446336000, 1447545600),
                     seed: int = 42) -> List[List[str]]:
    """Market-basket transactions with planted frequent itemsets per
    resource/freq_items.py / freq_items_apriori_tutorial.txt:19-24.
    Row = transId, itemId, itemId, ...  (items as string ids); with
    ``with_time`` an epoch-second timestamp is inserted at field 1 —
    the raw format fit.sh feeds through org.chombo.mr.TemporalFilter
    (tef.time.stamp.field.ordinal=1, resource/fit.properties:10)."""
    rng = np.random.default_rng(seed)
    rows = []
    for t in range(n_trans):
        k = int(rng.integers(items_per_trans[0], items_per_trans[1] + 1))
        items = set(rng.integers(0, n_items, k).tolist())
        for pset in planted:
            if rng.random() < planted_support:
                items.update(pset)
        row = [f"T{t:06d}"] + [f"I{i:05d}" for i in sorted(items)]
        if with_time:
            row.insert(1, str(int(rng.integers(*time_range))))
        rows.append(row)
    return rows


def gen_state_sequences(n_seqs: int, states: Sequence[str],
                        trans_by_class: dict,
                        seq_len: Tuple[int, int] = (10, 30),
                        class_probs: Sequence[float] = None,
                        seed: int = 42) -> List[List[str]]:
    """Per-entity state sequences from class-conditional Markov chains
    (the xaction_state.rb / cust_churn_markov_chain pipeline shape:
    entityId, classLabel, s1, s2, ...).  ``trans_by_class`` maps class label
    -> row-stochastic matrix [S, S]."""
    rng = np.random.default_rng(seed)
    classes = list(trans_by_class.keys())
    if class_probs is None:
        class_probs = [1.0 / len(classes)] * len(classes)
    S = len(states)
    rows = []
    for i in range(n_seqs):
        c = classes[rng.choice(len(classes), p=np.asarray(class_probs))]
        T = np.asarray(trans_by_class[c], dtype=float)
        L = int(rng.integers(seq_len[0], seq_len[1] + 1))
        s = int(rng.integers(0, S))
        seq = [states[s]]
        for _ in range(L - 1):
            s = int(rng.choice(S, p=T[s]))
            seq.append(states[s])
        rows.append([f"E{i:06d}", c] + seq)
    return rows


def gen_hmm_sequences(n_seqs: int, states: Sequence[str], obs: Sequence[str],
                      A: np.ndarray, B: np.ndarray, pi: np.ndarray,
                      seq_len: Tuple[int, int] = (8, 20),
                      seed: int = 42) -> List[List[str]]:
    """Fully-tagged HMM training rows: entityId, obs1:state1, obs2:state2 ...
    (the HiddenMarkovModelBuilder fully-tagged input form,
    markov/HiddenMarkovModelBuilder.java:136-166)."""
    rng = np.random.default_rng(seed)
    A = np.asarray(A, float); B = np.asarray(B, float); pi = np.asarray(pi, float)
    rows = []
    for i in range(n_seqs):
        L = int(rng.integers(seq_len[0], seq_len[1] + 1))
        s = int(rng.choice(len(states), p=pi))
        pairs = []
        for t in range(L):
            o = int(rng.choice(len(obs), p=B[s]))
            pairs.append(f"{obs[o]}:{states[s]}")
            s = int(rng.choice(len(states), p=A[s]))
        rows.append([f"E{i:06d}"] + pairs)
    return rows


def gen_price_rounds(n_products: int, n_prices: int = 5, seed: int = 42):
    """Bandit price-optimization fixture per resource/price_opt.py /
    price_optimize_tutorial.txt:8-13: each product has candidate prices with
    hidden mean profits; returns (price labels per product, hidden mean
    reward matrix [product, price], reward-sampler fn)."""
    rng = np.random.default_rng(seed)
    base = rng.uniform(20, 100, n_products)
    prices = np.stack([base * (0.8 + 0.1 * k) for k in range(n_prices)], axis=1)
    # hidden best price index differs per product
    best = rng.integers(0, n_prices, n_products)
    mean_profit = np.empty((n_products, n_prices))
    for p in range(n_products):
        for k in range(n_prices):
            mean_profit[p, k] = 10.0 - 2.0 * abs(k - best[p]) + rng.uniform(-0.5, 0.5)

    def sample_reward(product: int, price_idx: int, rng2=None) -> float:
        r = (rng2 or rng)
        return float(mean_profit[product, price_idx] + r.normal(0, 1.0))

    return prices, mean_profit, sample_reward


def _weighted_choice(rng, values_weights) -> str:
    """CategoricalField equivalent (resource util.rb): weighted draw."""
    values = [v for v, _ in values_weights]
    w = np.asarray([w for _, w in values_weights], dtype=float)
    return values[int(rng.choice(len(values), p=w / w.sum()))]


def gen_elearn(n: int, seed: int = 42) -> List[List[str]]:
    """E-learning outcome rows per resource/elearn.py:27-105:
    userId + 9 activity features (content/discussion/organizer time, email
    count, test/assignment scores, chat messages, search time, bookmarks)
    with P/F status from an accumulated fail probability — low scores and
    low engagement plant the failure signal."""
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        fail_prob = 10
        user_id = 1000000 + int(rng.integers(0, 1000001))
        content = max(int(rng.normal(300, 100)), 0)
        fail_prob += 10 if content < 100 else (6 if content < 150 else 0)
        discuss = max(int(rng.normal(80, 40)), 0)
        fail_prob += 8 if discuss < 30 else (4 if discuss < 50 else 0)
        organizer = max(int(rng.normal(40, 20)), 0)
        fail_prob += 5 if discuss < 10 else 0   # reference checks discuss here
        email = max(int(rng.normal(10, 6)), 0)
        fail_prob += 6 if email < 3 else 0
        test = int(np.clip(rng.normal(50, 30), 10, 100))
        fail_prob += 34 if test < 30 else (20 if test < 40 else
                                           (14 if test < 50 else 0))
        assign = int(np.clip(rng.normal(60, 40), 10, 100))
        fail_prob += 28 if assign < 35 else (18 if assign < 50 else
                                             (10 if assign < 60 else 0))
        chat = max(int(rng.normal(100, 60)), 0)
        fail_prob += 4 if chat < 20 else 0
        search = max(int(rng.normal(60, 40)), 0)
        fail_prob += 7 if search < 15 else (3 if search < 30 else 0)
        bookmarks = max(int(rng.normal(12, 8)), 0)
        fail_prob += 8 if bookmarks < 4 else 0
        status = "F" if rng.integers(0, 101) < fail_prob else "P"
        rows.append([str(user_id), str(content), str(discuss), str(organizer),
                     str(email), str(test), str(assign), str(chat),
                     str(search), str(bookmarks), status])
    return rows


RETARGET_CONVERSION = {"1C": 75, "1S": 60, "1N": 50, "2C": 60, "2S": 40,
                       "2N": 30, "3C": 20, "3S": 20, "3N": 15}


def gen_retarget(n: int, seed: int = 42) -> List[List[str]]:
    """Abandoned-shopping-cart retarget rows per resource/retarget.py:9-23:
    custID, retarget type (send hour 1/2/3 x recommendation C/S/N), cart
    amount, converted Y/N with the planted per-type conversion rates —
    the decision-tree / split-gain fixture."""
    rng = np.random.default_rng(seed)
    types = list(RETARGET_CONVERSION)
    rows = []
    for _ in range(n):
        cust = 1000000 + int(rng.integers(0, 1000000))
        t = types[int(rng.integers(9))]
        conv = "Y" if rng.integers(1, 101) < RETARGET_CONVERSION[t] else "N"
        amount = 20 + int(rng.integers(0, 301))
        rows.append([str(cust), t, str(amount), conv])
    return rows


def gen_hosp_readmit(n: int, seed: int = 42) -> List[List[str]]:
    """Hospital-readmission rows per resource/hosp_readmit.rb:5-99:
    patID, age, weight, height, employment, family status, diet, exercise,
    follow-up, smoking, alcohol, readmitted Y/N.  Age, living alone, and
    poor follow-up carry the strongest planted readmission signal — the MI
    feature-selection fixture (tutorial_hospital_readmit.txt:15-17)."""
    rng = np.random.default_rng(seed)
    age_d = [((10, 20), 2), ((21, 30), 3), ((31, 40), 6), ((41, 50), 10),
             ((51, 60), 14), ((61, 70), 19), ((71, 80), 25), ((81, 90), 21)]
    wt_d = [((130, 140), 9), ((141, 150), 13), ((151, 160), 16),
            ((161, 170), 20), ((171, 180), 23), ((181, 190), 20),
            ((191, 200), 17), ((201, 210), 14), ((211, 220), 10),
            ((221, 230), 7), ((231, 240), 5), ((241, 250), 3)]
    ht_d = [((50, 55), 9), ((56, 60), 12), ((61, 65), 16), ((66, 70), 23),
            ((71, 75), 14)]

    def ranged(dist):
        (lo, hi) = _weighted_choice(rng, [(r, w) for r, w in dist])
        return int(rng.integers(lo, hi + 1))

    rows = []
    for i in range(n):
        p = 20
        pid = f"{int(rng.integers(10**11, 10**12))}"
        age = ranged(age_d)
        p += 10 if age > 80 else (5 if age > 70 else (3 if age > 60 else 0))
        wt, ht = ranged(wt_d), ranged(ht_d)
        if wt > 200 and ht < 70:
            p += 5
        elif wt > 180 and ht < 60:
            p += 3
        emp = _weighted_choice(rng, [("employed", 10), ("unemployed", 1),
                                     ("retired", 3)])
        if age > 68 and rng.integers(10) < 8:
            emp = "retired"
        p += 6 if emp == "unemployed" else (4 if emp == "retired" else 0)
        fam = _weighted_choice(rng, [("alone", 10), ("withPartner", 15)])
        p += 9 if fam == "alone" else 0
        diet = _weighted_choice(rng, [("average", 10), ("poor", 4), ("good", 2)])
        if emp == "unemployed" and rng.integers(10) < 7:
            diet = "poor"
        p += 4 if diet == "poor" else (2 if diet == "average" else 0)
        ex = _weighted_choice(rng, [("average", 10), ("low", 12), ("high", 4)])
        p += 3 if ex == "low" else (1 if ex == "average" else 0)
        fup = _weighted_choice(rng, [("average", 10), ("low", 14), ("high", 3)])
        p += 8 if fup == "low" else (3 if fup == "average" else 0)
        smoke = _weighted_choice(rng, [("nonSmoker", 10), ("smoker", 3)])
        p += 6 if smoke == "smoker" else 0
        alco = _weighted_choice(rng, [("average", 10), ("low", 16), ("high", 4)])
        p += 5 if alco == "high" else (2 if alco == "average" else 0)
        readmit = "Y" if rng.integers(100) < p else "N"
        rows.append([pid, str(age), str(wt), str(ht), emp, fam, diet, ex,
                     fup, smoke, alco, readmit])
    return rows


def gen_disease(n: int, seed: int = 42) -> List[List[str]]:
    """Disease-risk rows per resource/disease.rb:8-75: id, age, race,
    weight, diet, family history, domestic life, status Yes/No.  Risk
    multiplies up with age, AFA race, high-fat diet, family history, and
    living alone — the rule-mining fixture (tutorial_diesase_rule_mining)."""
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        pid = f"{int(rng.integers(10**11, 10**12))}"
        age = 20 + int(rng.integers(60))
        race = _weighted_choice(rng, [("EUA", 10), ("AFA", 3), ("LAA", 1),
                                      ("ASA", 1)])
        weight = 120 + int(rng.integers(120))
        diet = _weighted_choice(rng, [("LF", 2), ("REG", 8), ("HF", 4)])
        fam = _weighted_choice(rng, [("NFH", 5), ("FH", 1)])
        dom = _weighted_choice(rng, [("S", 2), ("DP", 4)])
        pr = 15.0
        pr *= 1.0 if age < 40 else (1.05 if age < 50 else
                                    (1.15 if age < 60 else
                                     (1.4 if age < 70 else 1.5)))
        pr *= {"AFA": 1.2, "ASA": 0.9, "LAA": 0.95}.get(race, 1.0)
        pr *= 1.15 if diet == "HF" else 1.0
        pr *= 1.2 if fam == "FH" else 1.0
        pr *= 1.2 if dom == "S" else 1.0
        pr = min(pr, 99.0)
        status = "Yes" if rng.integers(100) < pr else "No"
        rows.append([pid, str(age), race, str(weight), diet, fam, dom, status])
    return rows


def gen_usage(n: int, seed: int = 42) -> List[List[str]]:
    """Categorical account-usage churn rows per resource/usage.rb:5-86:
    id, minute usage, data usage, CS calls, payment history, account age,
    status open/closed — heavy usage + poor payment plant the closure."""
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        uid = f"{int(rng.integers(10**11, 10**12))}"
        mins = _weighted_choice(rng, [("low", 2), ("med", 5), ("high", 3),
                                      ("overage", 2)])
        data = _weighted_choice(rng, [("low", 4), ("med", 6), ("high", 2)])
        cs = _weighted_choice(rng, [("low", 6), ("med", 3), ("high", 1)])
        pay = _weighted_choice(rng, [("poor", 2), ("average", 5), ("good", 4)])
        acct_age = int(rng.integers(4)) + 1
        pr = 25.0
        pr *= {"low": 1.2, "high": 1.4, "overage": 1.8}.get(mins, 1.0)
        pr *= {"low": 1.1, "med": 1.3, "high": 1.6}.get(data, 1.0)
        pr *= {"med": 1.2, "high": 1.6}.get(cs, 1.0)
        pr *= 1.3 if pay == "poor" else 1.0
        pr *= {3: 1.05, 4: 1.2}.get(acct_age, 1.0)
        pr = min(pr, 99.0)
        status = "closed" if rng.integers(100) < pr else "open"
        rows.append([uid, mins, data, cs, pay, str(acct_age), status])
    return rows


def gen_visit_history(n: int, conv_rate: int = 30, label: bool = False,
                      seed: int = 42) -> List[List[str]]:
    """Site-visit session sequences per resource/visit_history.py:12-77:
    userID [, T/F conversion label], then session-summary states combining
    elapsed-time and duration letters (HL, MM, ...).  Converted users skew
    to short-elapsed / long-duration sessions — the PST / Markov sequence
    fixture."""
    rng = np.random.default_rng(seed)

    def state(conv: bool) -> str:
        s = int(rng.integers(0, 101))
        if conv:
            elapsed = "H" if s <= 15 else ("M" if s <= 40 else "L")
        else:
            elapsed = "L" if s <= 20 else ("M" if s <= 45 else "H")
        s = int(rng.integers(0, 101))
        if conv:
            duration = "L" if s <= 15 else ("M" if s <= 40 else "H")
        else:
            duration = "H" if s <= 20 else ("M" if s <= 45 else "L")
        return elapsed + duration

    rows = []
    for _ in range(n):
        uid = f"U{int(rng.integers(10**10, 10**11))}"
        row = [uid]
        converted = rng.integers(0, 101) < conv_rate
        if label:
            truth = rng.integers(0, 101) < 90
            row.append(("T" if truth else "F") if converted
                       else ("F" if truth else "T"))
        n_sess = int(rng.integers(2, 21 if converted else 13))
        row += [state(converted) for _ in range(n_sess)]
        rows.append(row)
    return rows


EVENT_SEQ_STATES = ["SL", "SS", "SM", "ML", "MS", "MM", "LL", "LS", "LM"]


def gen_event_seq(n: int, seed: int = 42) -> List[List[str]]:
    """Customer event sequences with planted locality bursts per
    resource/event_seq.rb:5-30: ~30% of events are followed by a short
    burst of 1-3 events from the same size-group (same first letter) —
    the sequence positional-cluster fixture."""
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        cid = f"C{int(rng.integers(10**9, 10**10))}"
        events = []
        for _ in range(5 + int(rng.integers(20))):
            idx = int(rng.integers(len(EVENT_SEQ_STATES)))
            events.append(EVENT_SEQ_STATES[idx])
            if rng.integers(10) < 3:
                for _ in range(1 + int(rng.integers(3))):
                    idx = (idx // 3) * 3 + int(rng.integers(2))
                    events.append(EVENT_SEQ_STATES[idx])
        rows.append([cid] + events)
    return rows


def gen_xactions(n_cust: int, n_days: int, visitor_percent: float = 0.05,
                 seed: int = 42) -> List[List[str]]:
    """Raw purchase transactions per resource/buy_xaction.rb:5-58:
    custID, xactionID, ISO date, amount.  Amounts alternate between small
    frequent and large infrequent purchases depending on days since the
    customer's previous transaction — the input to the state-conversion +
    Markov marketing-plan pipeline (mark_plan.rb)."""
    import datetime

    rng = np.random.default_rng(seed)
    cust_ids = [f"C{int(rng.integers(10**9, 10**10))}" for _ in range(n_cust)]
    hist = {}
    rows = []
    xid = 1360000000
    date = datetime.date(2013, 1, 1)
    for _ in range(n_days):
        n_x = int(visitor_percent * n_cust * (85 + int(rng.integers(30))) / 100)
        for _ in range(n_x):
            cid = cust_ids[int(rng.integers(len(cust_ids)))]
            if cid in hist:
                last_date, last_amt = hist[cid][-1]
                days = (date - last_date).days
                if days < 30:
                    amount = (50 + int(rng.integers(20)) - 10 if last_amt < 40
                              else 30 + int(rng.integers(10)) - 5)
                elif days < 60:
                    amount = (100 + int(rng.integers(40)) - 20 if last_amt < 80
                              else 60 + int(rng.integers(20)) - 10)
                else:
                    amount = (180 + int(rng.integers(60)) - 30 if last_amt < 150
                              else 120 + int(rng.integers(40)) - 20)
            else:
                hist[cid] = []
                amount = 40 + int(rng.integers(180))
            hist[cid].append((date, amount))
            xid += 1
            rows.append([cid, str(xid), date.isoformat(), str(amount)])
        date += datetime.timedelta(days=1)
    return rows


def ctr_reward_sampler(seed: int = 42):
    """Click-through-rate reward simulator per resource/lead_gen.py:12-66:
    three page actions with hidden Gaussian CTR distributions (page3 best).
    Returns (actions, sample(action) -> int reward) for driving the
    streaming RL loop the way the reference's Redis simulator does."""
    rng = np.random.default_rng(seed)
    distr = {"page1": (30, 12), "page2": (60, 30), "page3": (80, 10)}

    def sample(action: str) -> int:
        mean, sd = distr[action]
        # reference sums 12 uniform draws (Irwin-Hall approx of a Gaussian)
        s = int(sum(rng.integers(1, 100) for _ in range(12)))
        r = int(((s - 600) / 100.0) * sd + mean)
        return max(r, 0)

    return list(distr), sample


def gen_text_classified(n: int, seed: int = 42) -> List[List[str]]:
    """Short review texts with a planted sentiment signal for the Naive
    Bayes text mode (BayesianDistribution.java:187-196): positive rows draw
    mostly from a positive word pool, negative rows from a negative pool,
    both mixed with shared neutral filler.  Row = [text, classVal]."""
    rng = np.random.default_rng(seed)
    pos = ["excellent", "great", "fantastic", "loved", "wonderful", "superb"]
    neg = ["terrible", "awful", "broken", "refund", "worst", "disappointed"]
    neutral = ["product", "delivery", "box", "ordered", "arrived", "item",
               "week", "store", "price", "color"]
    rows = []
    for _ in range(n):
        positive = rng.random() < 0.5
        pool = pos if positive else neg
        k_sig = int(rng.integers(2, 5))
        k_neu = int(rng.integers(3, 8))
        words = [pool[rng.integers(len(pool))] for _ in range(k_sig)]
        words += [neutral[rng.integers(len(neutral))] for _ in range(k_neu)]
        rng.shuffle(words)
        rows.append([" ".join(words), "P" if positive else "N"])
    return rows


def gen_numeric_classed(n: int, n_features: int = 4, n_classes: int = 2,
                        sep: float = 2.0, seed: int = 42) -> List[List[str]]:
    """Generic numeric classification rows (id, f1..fk, class) with
    class-separated Gaussian features — fixture for logistic regression,
    Fisher discriminant, and kNN."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        c = int(rng.integers(0, n_classes))
        feats = rng.normal(c * sep, 1.0, n_features)
        rows.append([f"R{i:06d}"] + [f"{v:.3f}" for v in feats] + [f"C{c}"])
    return rows

"""Deterministic synthetic datasets with planted signals.

Each generator mirrors a reference tutorial fixture (citations inline) but is
rewritten on seeded ``numpy.random.Generator`` so tests are reproducible; the
reference used unseeded ``random``/``Math.random`` everywhere (SURVEY §7.3.5),
so only statistical — not bitwise — equivalence is meaningful.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def _clip_int(rng, mean, sd, lo, hi, size=None):
    v = np.rint(rng.normal(mean, sd, size)).astype(int)
    return np.clip(v, lo, hi)


def gen_telecom_churn(n: int, seed: int = 42) -> List[List[str]]:
    """Telecom-churn rows per resource/telecom_churn.py:13-76 /
    resource/teleComChurn.json: id,plan,minUsed,dataUsed,csCall,csEmail,
    network,churned.  ~20% churners via three planted causes: bad plan +
    heavy usage; excess customer-service contact; small network.
    """
    rng = np.random.default_rng(seed)
    rows = []
    min_usage = [(600, 50), (1200, 300)]
    data_usage = [(200, 50), (500, 150)]
    cs_call = [(4, 1), (8, 2)]
    cs_email = [(6, 2), (10, 3)]
    network = [(3, 1), (6, 2)]

    def draw(dist, i, lo, hi):
        m, s = dist[i]
        return int(_clip_int(rng, m, s, lo, hi))

    for i in range(n):
        cust_id = f"C{seed:02d}{i:07d}"
        churn = rng.integers(1, 100) > 80
        if churn:
            case = rng.integers(1, 4)
            churned = "Y"
            if case == 1:        # bad plan, heavy usage
                plan = "planA"
                mu = draw(min_usage, 1, 0, 2200)
                du = draw(data_usage, 1, 0, 1000)
                cc = draw(cs_call, 0, 0, 14)
                ce = draw(cs_email, 0, 0, 22)
                nw = draw(network, 0, 0, 12)
            elif case == 2:      # too many CS contacts
                plan = "planB"
                mu = draw(min_usage, 1, 0, 2200)
                du = draw(data_usage, 1, 0, 1000)
                cc = max(draw(cs_call, 1, 0, 14), 6)
                ce = max(draw(cs_email, 1, 0, 22), 8)
                nw = draw(network, 0, 0, 12)
            else:                # small network
                plan = "planB"
                mu = min(draw(min_usage, 1, 0, 2200) + 200, 2200)
                du = min(draw(data_usage, 1, 0, 1000) + 100, 1000)
                cc = draw(cs_call, 0, 0, 14)
                ce = draw(cs_email, 0, 0, 22)
                nw = draw(network, 0, 0, 12)
        else:
            churned = "N"
            plan = "planA" if rng.random() < 0.5 else "planB"
            p = 0 if plan == "planA" else 1
            mu = draw(min_usage, p, 0, 2200)
            du = draw(data_usage, p, 0, 1000)
            cc = min(draw(cs_call, 0, 0, 14), 2)
            ce = min(draw(cs_email, 0, 0, 22), 3)
            nw = draw(network, 1, 0, 12)
        rows.append([cust_id, plan, str(mu), str(du), str(cc), str(ce),
                     str(nw), churned])
    return rows


def gen_transactions(n_trans: int, n_items: int,
                     planted: Sequence[Sequence[int]] = ((3, 7, 11),),
                     planted_support: float = 0.2,
                     items_per_trans: Tuple[int, int] = (4, 10),
                     seed: int = 42) -> List[List[str]]:
    """Market-basket transactions with planted frequent itemsets per
    resource/freq_items.py / freq_items_apriori_tutorial.txt:19-24.
    Row = transId, itemId, itemId, ...  (items as string ids)."""
    rng = np.random.default_rng(seed)
    rows = []
    for t in range(n_trans):
        k = int(rng.integers(items_per_trans[0], items_per_trans[1] + 1))
        items = set(rng.integers(0, n_items, k).tolist())
        for pset in planted:
            if rng.random() < planted_support:
                items.update(pset)
        rows.append([f"T{t:06d}"] + [f"I{i:05d}" for i in sorted(items)])
    return rows


def gen_state_sequences(n_seqs: int, states: Sequence[str],
                        trans_by_class: dict,
                        seq_len: Tuple[int, int] = (10, 30),
                        class_probs: Sequence[float] = None,
                        seed: int = 42) -> List[List[str]]:
    """Per-entity state sequences from class-conditional Markov chains
    (the xaction_state.rb / cust_churn_markov_chain pipeline shape:
    entityId, classLabel, s1, s2, ...).  ``trans_by_class`` maps class label
    -> row-stochastic matrix [S, S]."""
    rng = np.random.default_rng(seed)
    classes = list(trans_by_class.keys())
    if class_probs is None:
        class_probs = [1.0 / len(classes)] * len(classes)
    S = len(states)
    rows = []
    for i in range(n_seqs):
        c = classes[rng.choice(len(classes), p=np.asarray(class_probs))]
        T = np.asarray(trans_by_class[c], dtype=float)
        L = int(rng.integers(seq_len[0], seq_len[1] + 1))
        s = int(rng.integers(0, S))
        seq = [states[s]]
        for _ in range(L - 1):
            s = int(rng.choice(S, p=T[s]))
            seq.append(states[s])
        rows.append([f"E{i:06d}", c] + seq)
    return rows


def gen_hmm_sequences(n_seqs: int, states: Sequence[str], obs: Sequence[str],
                      A: np.ndarray, B: np.ndarray, pi: np.ndarray,
                      seq_len: Tuple[int, int] = (8, 20),
                      seed: int = 42) -> List[List[str]]:
    """Fully-tagged HMM training rows: entityId, obs1:state1, obs2:state2 ...
    (the HiddenMarkovModelBuilder fully-tagged input form,
    markov/HiddenMarkovModelBuilder.java:136-166)."""
    rng = np.random.default_rng(seed)
    A = np.asarray(A, float); B = np.asarray(B, float); pi = np.asarray(pi, float)
    rows = []
    for i in range(n_seqs):
        L = int(rng.integers(seq_len[0], seq_len[1] + 1))
        s = int(rng.choice(len(states), p=pi))
        pairs = []
        for t in range(L):
            o = int(rng.choice(len(obs), p=B[s]))
            pairs.append(f"{obs[o]}:{states[s]}")
            s = int(rng.choice(len(states), p=A[s]))
        rows.append([f"E{i:06d}"] + pairs)
    return rows


def gen_price_rounds(n_products: int, n_prices: int = 5, seed: int = 42):
    """Bandit price-optimization fixture per resource/price_opt.py /
    price_optimize_tutorial.txt:8-13: each product has candidate prices with
    hidden mean profits; returns (price labels per product, hidden mean
    reward matrix [product, price], reward-sampler fn)."""
    rng = np.random.default_rng(seed)
    base = rng.uniform(20, 100, n_products)
    prices = np.stack([base * (0.8 + 0.1 * k) for k in range(n_prices)], axis=1)
    # hidden best price index differs per product
    best = rng.integers(0, n_prices, n_products)
    mean_profit = np.empty((n_products, n_prices))
    for p in range(n_products):
        for k in range(n_prices):
            mean_profit[p, k] = 10.0 - 2.0 * abs(k - best[p]) + rng.uniform(-0.5, 0.5)

    def sample_reward(product: int, price_idx: int, rng2=None) -> float:
        r = (rng2 or rng)
        return float(mean_profit[product, price_idx] + r.normal(0, 1.0))

    return prices, mean_profit, sample_reward


def gen_numeric_classed(n: int, n_features: int = 4, n_classes: int = 2,
                        sep: float = 2.0, seed: int = 42) -> List[List[str]]:
    """Generic numeric classification rows (id, f1..fk, class) with
    class-separated Gaussian features — fixture for logistic regression,
    Fisher discriminant, and kNN."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        c = int(rng.integers(0, n_classes))
        feats = rng.normal(c * sep, 1.0, n_features)
        rows.append([f"R{i:06d}"] + [f"{v:.3f}" for v in feats] + [f"C{c}"])
    return rows

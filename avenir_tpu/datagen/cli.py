"""Datagen CLI: the reference's per-tutorial generator scripts as one tool.

The reference drives every runbook from a seeded generator script
(resource/telecom_churn.py, freq_items.py, xaction_state.rb, hosp_readmit.rb,
...).  Here the same step is::

    python -m avenir_tpu.datagen <preset> [sizes...] [--seed N] [--out FILE]

Rows print to stdout (or ``--out``) as comma-joined CSV, ready for the job
CLI.  Presets that need model matrices (state/HMM sequences) carry the
canonical tutorial parameterizations so runbooks stay one-liners.
"""

from __future__ import annotations

import sys
from typing import Callable, Dict, List

import numpy as np

from . import generators as g

# canonical tutorial parameterizations -------------------------------------

_CHURN_STATES = ["LL", "LH", "HL", "HH"]
_HMM_STATES = ["s0", "s1", "s2"]
_HMM_OBS = ["a", "b", "c", "d"]
_HMM_A = np.array([[.7, .2, .1], [.1, .7, .2], [.2, .1, .7]])
_HMM_B = np.array([[.7, .1, .1, .1], [.1, .7, .1, .1], [.1, .1, .1, .7]])
_HMM_PI = np.array([.5, .3, .2])


def _churn_state_seqs(n: int, seed: int = 42) -> List[List[str]]:
    """Loyal chain mixes states; churner chain absorbs into HH (the
    cust_churn_markov_chain_classifier_tutorial.txt planted signal)."""
    t_loyal = np.full((4, 4), 0.25)
    t_churn = np.asarray([[0.1, 0.1, 0.1, 0.7]] * 4)
    return g.gen_state_sequences(n, _CHURN_STATES,
                                 {"L": t_loyal, "C": t_churn},
                                 seq_len=(15, 25), seed=seed)


def _hmm_seqs(n: int, seed: int = 42) -> List[List[str]]:
    return g.gen_hmm_sequences(n, _HMM_STATES, _HMM_OBS, _HMM_A, _HMM_B,
                               _HMM_PI, seed=seed)


def _hmm_obs(n: int, seed: int = 67) -> List[List[str]]:
    """Observation-only rows (states stripped) for the Viterbi decoder."""
    rows = _hmm_seqs(n, seed=seed)
    return [[r[0]] + [p.split(":")[0] for p in r[1:]] for r in rows]


def _blobs(n: int, seed: int = 41) -> List[List[str]]:
    """Two Gaussian blobs, the knn_elearning-style 2-feature fixture."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        c = "A" if i % 2 == 0 else "B"
        cx = 0.0 if c == "A" else 8.0
        rows.append([f"E{i}", f"{cx + rng.normal():.3f}",
                     f"{cx + rng.normal():.3f}", c])
    return rows


def _transactions(n_trans: int, n_items: int, seed: int = 42):
    return g.gen_transactions(n_trans, n_items, planted=((3, 7, 11),),
                              planted_support=0.5, seed=seed)


def _timed_transactions(n_trans: int, n_items: int, seed: int = 42):
    """Transactions with an epoch timestamp at field 1 — the raw format
    the fit.sh pipeline feeds through org.chombo.mr.TemporalFilter."""
    return g.gen_transactions(n_trans, n_items, planted=((3, 7, 11),),
                              planted_support=0.5, with_time=True,
                              seed=seed)


def _visit_history(n: int, seed: int = 42):
    return g.gen_visit_history(n, conv_rate=50, label=True, seed=seed)


# preset -> (callable, number of positional int sizes)
PRESETS: Dict[str, tuple] = {
    "telecom_churn": (g.gen_telecom_churn, 1),
    "transactions": (_transactions, 2),
    "timed_transactions": (_timed_transactions, 2),
    "churn_state_seqs": (_churn_state_seqs, 1),
    "hmm_seqs": (_hmm_seqs, 1),
    "hmm_obs": (_hmm_obs, 1),
    "elearn": (g.gen_elearn, 1),
    "retarget": (g.gen_retarget, 1),
    "hosp_readmit": (g.gen_hosp_readmit, 1),
    "disease": (g.gen_disease, 1),
    "usage": (g.gen_usage, 1),
    "visit_history": (_visit_history, 1),
    "event_seq": (g.gen_event_seq, 1),
    "xactions": (g.gen_xactions, 2),
    "text_classified": (g.gen_text_classified, 1),
    "numeric_classed": (g.gen_numeric_classed, 1),
    "blobs": (_blobs, 1),
}


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    usage = ("usage: python -m avenir_tpu.datagen <preset> <sizes...> "
             "[--seed N] [--out FILE]\npresets:\n  "
             + "\n  ".join(sorted(PRESETS)))
    if not argv or argv[0] in ("-h", "--help"):
        print(usage, file=sys.stderr)
        return 2
    name, rest = argv[0], argv[1:]
    if name not in PRESETS:
        print(f"unknown preset: {name}\n{usage}", file=sys.stderr)
        return 2
    fn, n_sizes = PRESETS[name]
    seed = None
    out = None
    sizes: List[int] = []
    i = 0
    try:
        while i < len(rest):
            a = rest[i]
            if a == "--seed":
                seed = int(rest[i + 1]); i += 2
            elif a == "--out":
                out = rest[i + 1]; i += 2
            elif a.startswith("--"):
                raise ValueError(f"unknown option {a}")
            else:
                sizes.append(int(a)); i += 1
    except (IndexError, ValueError) as e:
        print(f"bad arguments for {name}: {e}\n{usage}", file=sys.stderr)
        return 2
    if len(sizes) != n_sizes:
        print(f"{name} takes {n_sizes} size argument(s), got {len(sizes)}\n"
              f"{usage}", file=sys.stderr)
        return 2
    kwargs = {} if seed is None else {"seed": seed}
    rows = fn(*sizes, **kwargs)
    text = "\n".join(",".join(r) for r in rows) + "\n"
    if out:
        import os
        d = os.path.dirname(out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out, "w") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text)
    return 0

"""Per-(tenant, arm) bandit posterior state: the streaming-decision monoid.

Every tenant owns one row of per-arm sufficient statistics — pull counts
and reward sums — held device-resident as a fold carry exactly like the
batch count-table models: reward events fold in as tiny donated-carry
scatter-adds (``_posterior_local``, the same ``local_fn`` contract every
``core.pipeline`` fold uses), and two carries combine by elementwise add
(``core.multiscan.merge_carries``) — a commutative monoid, certified by
the PR-12 split-invariance verifier through :class:`FeedbackFoldSpec`
(registered in ``core.algebra.verification_jobs``).  Rewards are
INTEGERS on the wire (the reference's ``actionID,reward`` format), so
their float sums are exact in any association order — byte-identical
posteriors however the event stream is chunked, replayed, or merged.

Three layers:

- :class:`ArmPosterior` — the host-form state value: ``state_dict`` /
  ``from_state`` / ``merge`` (the telemetry-snapshot merge contract,
  linted by the merge-closure rule) plus the canonical emitted line
  format shared by the batch aggregator and the streaming audit.
- :class:`PosteriorStore` — the live device-resident store: donated-
  carry folds for the feedback consumer, a donation-free serving
  snapshot for the decide path, and the jitted Thompson-sampling / UCB
  decision kernels (per-decision keys derive from the event id's CRC,
  so a decision is a pure function of (posterior, seed, event id) —
  byte-identical across batching, restarts, and replica pools).
- :class:`FeedbackFoldSpec` — the shared-scan FoldSpec replaying a
  reward-event CSV log into posterior state; the batch twin the
  byte-equivalence gate compares the online consumer against.

Config surface (``stream.*``; README "Streaming decisioning"):
``stream.tenants`` / ``stream.tenants.path`` (tenant manifest),
``stream.arms``, ``stream.algorithm`` (``thompson`` | ``ucb``),
``stream.seed``, ``stream.thompson.sigma``, ``stream.posterior.dtype``
(``float64`` | ``float32``), ``stream.store`` (process-local store
registry key), and the batch-replay column mapping
``stream.tenant.ordinal`` / ``stream.arm.ordinal`` /
``stream.reward.ordinal``.
"""

from __future__ import annotations

import re
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import sanitizer, telemetry
from ..core.metrics import Counters
from ..core.multiscan import FoldSpec as MultiScanFoldSpec
from ..core.io import read_lines, write_output
from ..ops.counting import count_table

KEY_TENANTS = "stream.tenants"
KEY_TENANTS_PATH = "stream.tenants.path"
KEY_ARMS = "stream.arms"
KEY_ALGORITHM = "stream.algorithm"
KEY_SEED = "stream.seed"
KEY_SIGMA = "stream.thompson.sigma"
KEY_DTYPE = "stream.posterior.dtype"
KEY_STORE = "stream.store"
KEY_TENANT_ORD = "stream.tenant.ordinal"
KEY_ARM_ORD = "stream.arm.ordinal"
KEY_REWARD_ORD = "stream.reward.ordinal"

DEFAULT_SEED = 2026
DEFAULT_SIGMA = 1.0
DEFAULT_DTYPE = "float64"
DEFAULT_STORE = "default"

ALGO_THOMPSON = "thompson"
ALGO_UCB = "ucb"

STREAM_GROUP = "Stream"

#: strict integer reward syntax (int() alone would admit '1_0'/' 10') —
#: the same guard the streaming learner loop applies to its reward queue
_INT_RE = re.compile(r"-?\d+", re.ASCII)


def _posterior_local(t, a, r, mask, n_tenants, n_arms, dtype_name):
    """The per-chunk fold: scatter one event batch's (tenant, arm,
    reward) triples into the ``{"pulls": [T, A] int64, "reward": [T, A]
    <dtype>}`` carry.  Pure (no clock/RNG/globals — the fold-purity
    rule) and elementwise-additive, so ``merge_carries`` is its monoid
    merge; out-of-range or masked rows contribute nothing (the
    ``count_table`` range drop)."""
    import jax.numpy as jnp

    sizes = (n_tenants, n_arms)
    return {
        "pulls": count_table(sizes, (t, a), mask=mask, dtype=jnp.int64),
        "reward": count_table(sizes, (t, a), weights=r, mask=mask,
                              dtype=np.dtype(dtype_name)),
    }


def _ucb_decide(pulls, reward, tid):
    """Deterministic UCB1 over normalized posterior means: untried arms
    first (infinite bonus; ties resolve to the lowest arm index), else
    ``mean + sqrt(2 ln N_tenant / n_arm)``."""
    import jax.numpy as jnp

    n = pulls[tid].astype(reward.dtype)                    # [B, A]
    mean = reward[tid] / jnp.maximum(n, 1.0)
    total = jnp.maximum(jnp.sum(pulls, axis=1), 1)[tid]
    bonus = jnp.sqrt(2.0 * jnp.log(total.astype(reward.dtype))[:, None]
                     / jnp.maximum(n, 1.0))
    val = jnp.where(n == 0, jnp.inf, mean + bonus)
    return jnp.argmax(val, axis=1)


def _thompson_decide(pulls, reward, tid, crc, seed, sigma):
    """Gaussian Thompson sampling: per-arm draw ``N(mean, sigma /
    sqrt(n + 1))`` with the per-decision PRNG key derived by folding the
    event id's CRC32 into the configured seed — a decision is a pure
    function of (posterior, seed, event id), independent of how requests
    batch together, so responses are byte-identical across micro-batch
    composition, replica choice, and kill/resume."""
    import jax
    import jax.numpy as jnp

    n = pulls[tid].astype(reward.dtype)                    # [B, A]
    mean = reward[tid] / jnp.maximum(n, 1.0)
    sd = sigma / jnp.sqrt(n + 1.0)
    base = jax.random.PRNGKey(seed)
    n_arms = mean.shape[1]

    def draw(c):
        return jax.random.normal(jax.random.fold_in(base, c), (n_arms,),
                                 mean.dtype)

    z = jax.vmap(draw)(crc)
    return jnp.argmax(mean + sd * z, axis=1)


def event_crc(event_id: str) -> int:
    """The per-decision RNG discriminator: CRC32 of the event id (stable
    across processes and platforms)."""
    return zlib.crc32(event_id.encode("utf-8"))


def parse_event(fields: Sequence[str], t_ord: int, a_ord: int, r_ord: int,
                tenant_index: Dict[str, int], arm_index: Dict[str, int]
                ) -> Optional[Tuple[int, int, int]]:
    """One reward event's (tenant idx, arm idx, reward) — or None for a
    malformed event (short row, unknown tenant/arm, non-integer reward).
    ONE parser shared by the online consumer and the batch replay spec,
    so the two paths cannot drift on what counts as an event."""
    need = max(t_ord, a_ord, r_ord) + 1
    if len(fields) < need:
        return None
    ti = tenant_index.get(str(fields[t_ord]))
    ai = arm_index.get(str(fields[a_ord]))
    rs = str(fields[r_ord])
    if ti is None or ai is None or not _INT_RE.fullmatch(rs):
        return None
    return ti, ai, int(rs)


def posterior_lines(tenants: Sequence[str], arms: Sequence[str],
                    pulls: np.ndarray, reward: np.ndarray,
                    delim: str = ",") -> List[str]:
    """The canonical posterior emission: one ``tenant,arm,pulls,
    rewardSum`` line per (tenant, arm), in manifest order — the format
    both the batch aggregator's output file and the streaming audit
    produce, so byte equality IS posterior equality."""
    out = []
    for i, tenant in enumerate(tenants):
        for j, arm in enumerate(arms):
            out.append(f"{tenant}{delim}{arm}{delim}{int(pulls[i, j])}"
                       f"{delim}{float(reward[i, j])!r}")
    return out


def _dtype_from_name(name: str) -> np.dtype:
    if name not in ("float32", "float64"):
        raise ValueError(
            f"{KEY_DTYPE} must be float32 or float64: {name!r}")
    return np.dtype(name)


def tenants_from_config(config) -> List[str]:
    """The declared tenant manifest: the inline ``stream.tenants`` list,
    or one tenant id per line of ``stream.tenants.path``.  Declared up
    front (never discovered from traffic) so carry shapes are fixed,
    checkpoints are portable, and per-host encoder alignment can never
    be an issue for this fold."""
    inline = config.get(KEY_TENANTS)
    if inline:
        names = [s.strip() for s in inline.split(",") if s.strip()]
    else:
        path = config.get(KEY_TENANTS_PATH)
        if not path:
            raise KeyError(
                f"missing tenant manifest: set {KEY_TENANTS} or "
                f"{KEY_TENANTS_PATH}")
        names = [l.strip() for l in read_lines(path) if l.strip()]
    if not names:
        raise ValueError(f"{KEY_TENANTS} is empty")
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate tenant ids in {KEY_TENANTS}")
    return names


def arms_from_config(config) -> List[str]:
    names = [s.strip() for s in config.must(KEY_ARMS).split(",")
             if s.strip()]
    if len(names) < 2:
        raise ValueError(f"{KEY_ARMS} needs at least two arms: {names}")
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate arm ids in {KEY_ARMS}")
    return names


class ArmPosterior:
    """Host-form per-(tenant, arm) posterior state — the monoid value.

    ``merge`` is elementwise add over identical manifests (exactly
    ``core.multiscan.merge_carries`` on the host side); ``state_dict`` /
    ``from_state`` round-trip it for checkpoints and snapshots.  Rewards
    are integer-valued, so merges are exact in any order."""

    __slots__ = ("tenants", "arms", "pulls", "reward")

    def __init__(self, tenants: Sequence[str], arms: Sequence[str],
                 pulls: Optional[np.ndarray] = None,
                 reward: Optional[np.ndarray] = None,
                 dtype: np.dtype = np.dtype(DEFAULT_DTYPE)):
        self.tenants = list(tenants)
        self.arms = list(arms)
        shape = (len(self.tenants), len(self.arms))
        self.pulls = (np.zeros(shape, np.int64) if pulls is None
                      else np.asarray(pulls, np.int64).reshape(shape))
        self.reward = (np.zeros(shape, dtype) if reward is None
                       else np.asarray(reward, dtype).reshape(shape))

    def state_dict(self) -> dict:
        return {"tenants": list(self.tenants), "arms": list(self.arms),
                "pulls": np.asarray(self.pulls),
                "reward": np.asarray(self.reward),
                "dtype": str(self.reward.dtype)}

    @classmethod
    def from_state(cls, state: dict) -> "ArmPosterior":
        return cls(state["tenants"], state["arms"], pulls=state["pulls"],
                   reward=state["reward"],
                   dtype=np.dtype(state["dtype"]))

    def merge(self, other: "ArmPosterior") -> "ArmPosterior":
        if (self.tenants != other.tenants or self.arms != other.arms):
            raise ValueError(
                "cannot merge posteriors over different manifests")
        self.pulls = self.pulls + other.pulls
        self.reward = self.reward + other.reward.astype(self.reward.dtype)
        return self

    def apply(self, t_idx: np.ndarray, a_idx: np.ndarray,
              rewards: np.ndarray) -> None:
        """Host-side fold of one event batch (the consumer's mirror —
        integer adds, so it stays byte-equal to the device carry)."""
        np.add.at(self.pulls, (t_idx, a_idx), 1)
        np.add.at(self.reward, (t_idx, a_idx),
                  np.asarray(rewards).astype(self.reward.dtype))

    def means(self) -> np.ndarray:
        """Per-(tenant, arm) posterior mean reward (0 for untried)."""
        return self.reward / np.maximum(self.pulls, 1)

    def lines(self, delim: str = ",") -> List[str]:
        return posterior_lines(self.tenants, self.arms, self.pulls,
                               self.reward, delim)


class PosteriorStore:
    """The live device-resident posterior for one tenant fleet.

    The feedback consumer folds event batches in through a donated-carry
    :class:`~avenir_tpu.core.pipeline.ChunkFold` (the same jitted
    machinery every batch fold uses); the decide path scores against a
    donation-free on-device SNAPSHOT republished after every fold, so a
    concurrent decision can never read a donated buffer.  Decisions are
    pure functions of (snapshot, seed, event id) — see
    :func:`_thompson_decide`."""

    def __init__(self, key: str, tenants: Sequence[str],
                 arms: Sequence[str], algorithm: str = ALGO_THOMPSON,
                 seed: int = DEFAULT_SEED, sigma: float = DEFAULT_SIGMA,
                 dtype: str = DEFAULT_DTYPE, mesh=None):
        from ..core import pipeline
        from ..parallel.mesh import get_mesh

        if algorithm not in (ALGO_THOMPSON, ALGO_UCB):
            raise ValueError(
                f"{KEY_ALGORITHM} must be {ALGO_THOMPSON} or {ALGO_UCB}: "
                f"{algorithm!r}")
        self.key = key
        self.tenants = list(tenants)
        self.arms = list(arms)
        self.tenant_index = {t: i for i, t in enumerate(self.tenants)}
        self.arm_index = {a: i for i, a in enumerate(self.arms)}
        self.algorithm = algorithm
        self.seed = int(seed)
        self.sigma = float(sigma)
        self.dtype = _dtype_from_name(dtype)
        self.mesh = mesh or get_mesh()
        self._lock = sanitizer.make_lock("stream.posterior")
        self._xfer = pipeline.ChunkTransfer(self.mesh, capacity=None)
        self._fold = pipeline.ChunkFold(
            _posterior_local,
            static_args=(len(self.tenants), len(self.arms),
                         str(self.dtype)),
            mesh=self.mesh, span_name="stream.fold",
            span_attrs={"store": key})
        self._fold.seed(self._zero_state())
        self._serve_state = self._fold.snapshot()
        self._decide_fns: dict = {}

    def _zero_state(self) -> dict:
        shape = (len(self.tenants), len(self.arms))
        return {"pulls": np.zeros(shape, np.int64),
                "reward": np.zeros(shape, self.dtype)}

    @classmethod
    def from_config(cls, key: str, config, mesh=None) -> "PosteriorStore":
        return cls(key,
                   tenants_from_config(config),
                   arms_from_config(config),
                   algorithm=config.get(KEY_ALGORITHM, ALGO_THOMPSON),
                   seed=config.get_int(KEY_SEED, DEFAULT_SEED),
                   sigma=config.get_float(KEY_SIGMA, DEFAULT_SIGMA),
                   dtype=config.get(KEY_DTYPE, DEFAULT_DTYPE),
                   mesh=mesh)

    # -- the feedback fold (consumer side) ---------------------------------
    def fold_events(self, t_idx: np.ndarray, a_idx: np.ndarray,
                    rewards: np.ndarray) -> None:
        """Fold one parsed event batch into the device carry (donated,
        async dispatch) and republish the serving snapshot."""
        n = len(t_idx)
        if n == 0:
            return
        arrs = (np.asarray(t_idx, np.int32), np.asarray(a_idx, np.int32),
                np.asarray(rewards, np.int64))
        with self._lock:
            self._fold.fold(self._xfer(arrs))
            self._serve_state = self._fold.snapshot()

    def restore(self, state: dict) -> None:
        """Seed the carry from a checkpointed host posterior (resume)."""
        post = ArmPosterior.from_state(state)
        if post.tenants != self.tenants or post.arms != self.arms:
            raise ValueError(
                "checkpointed posterior manifest does not match this "
                "store's tenant/arm manifest")
        with self._lock:
            self._fold.seed({"pulls": post.pulls,
                             "reward": post.reward.astype(self.dtype)})
            self._serve_state = self._fold.snapshot()

    def host_posterior(self) -> ArmPosterior:
        """The carry materialized to host (blocks on pending folds)."""
        with self._lock:
            carry = self._fold.result()
        return ArmPosterior(self.tenants, self.arms,
                            pulls=np.asarray(carry["pulls"]),
                            reward=np.asarray(carry["reward"]),
                            dtype=self.dtype)

    # -- the decide path (serving side) ------------------------------------
    def _decide_fn(self):
        fn = self._decide_fns.get(self.algorithm)
        if fn is None:
            if self.algorithm == ALGO_UCB:
                fn = telemetry.profiled_jit(
                    _ucb_decide, f"stream.decide.ucb:{self.key}")
            else:
                seed, sigma = self.seed, self.sigma

                def thompson(pulls, reward, tid, crc):
                    return _thompson_decide(pulls, reward, tid, crc,
                                            seed, sigma)

                fn = telemetry.profiled_jit(
                    thompson, f"stream.decide.thompson:{self.key}")
            self._decide_fns[self.algorithm] = fn
        return fn

    def decide(self, tenant_idx: np.ndarray,
               crcs: np.ndarray) -> np.ndarray:
        """Arm index per request row (rows pre-padded by the caller; pad
        rows score against tenant 0 and are discarded)."""
        with self._lock:
            state = self._serve_state
            fn = self._decide_fn()     # memo mutation under the lock
        tid = np.asarray(tenant_idx, np.int32)
        if self.algorithm == ALGO_UCB:
            sels = fn(state["pulls"], state["reward"], tid)
        else:
            sels = fn(state["pulls"], state["reward"], tid,
                      np.asarray(crcs, np.uint32))
        return np.asarray(sels)


# ---------------------------------------------------------------------------
# the process-local store registry (shared by replicas + the consumer)
# ---------------------------------------------------------------------------

_STORES: Dict[str, PosteriorStore] = {}
_STORES_LOCK = sanitizer.make_lock("stream.stores")


def get_store(key: str) -> Optional[PosteriorStore]:
    with _STORES_LOCK:
        return _STORES.get(key)


def register_store(store: PosteriorStore) -> PosteriorStore:
    with _STORES_LOCK:
        _STORES[store.key] = store
    return store


def _check_store_config(store: PosteriorStore, config) -> None:
    """A config resolving to an already-registered store must not
    silently disagree with it: every stream.* identity field the config
    DECLARES (an adapter built from just ``stream.store`` declares
    none) is checked against the registered store, so a stale-manifest
    store can never quietly serve a newer config's decisions."""
    declared = []
    if config.get(KEY_TENANTS) or config.get(KEY_TENANTS_PATH):
        declared.append(("tenants", tenants_from_config(config),
                         store.tenants))
    if config.get(KEY_ARMS):
        declared.append(("arms", arms_from_config(config), store.arms))
    if config.get(KEY_ALGORITHM):
        declared.append(("algorithm", config.get(KEY_ALGORITHM),
                         store.algorithm))
    if config.get(KEY_SEED) is not None:
        declared.append(("seed", config.get_int(KEY_SEED), store.seed))
    if config.get(KEY_DTYPE):
        declared.append(("dtype", str(_dtype_from_name(
            config.get(KEY_DTYPE))), str(store.dtype)))
    for field, want, have in declared:
        if want != have:
            raise ValueError(
                f"stream.store {store.key!r} is already registered with "
                f"{field}={have!r}, but this config declares {want!r} — "
                f"use a different {KEY_STORE} key (or restart) instead "
                f"of silently serving from the stale manifest")


def ensure_store(config, mesh=None) -> PosteriorStore:
    """The store named by ``stream.store`` — the registered instance
    when one exists (every pool replica's adapter and the feedback
    consumer resolve to the SAME device state; any stream.* identity
    fields the config declares must MATCH it — see
    :func:`_check_store_config`), else built from the config manifest
    and registered (idempotent, thread-safe)."""
    key = config.get(KEY_STORE, DEFAULT_STORE)
    with _STORES_LOCK:
        store = _STORES.get(key)
        if store is None:
            store = _STORES[key] = PosteriorStore.from_config(
                key, config, mesh=mesh)
        else:
            _check_store_config(store, config)
        return store


def clear_stores() -> None:
    """Drop every registered store (test isolation)."""
    with _STORES_LOCK:
        _STORES.clear()


# ---------------------------------------------------------------------------
# the shared-scan FoldSpec (batch replay of a reward-event log)
# ---------------------------------------------------------------------------

class FeedbackFoldSpec(MultiScanFoldSpec):
    """Shared-scan FoldSpec replaying a ``tenant,arm,reward`` event CSV
    into per-arm posterior state — the batch twin of the online feedback
    consumer, and the byte-equivalence reference the streaming gate
    compares against.  Tenant/arm manifests are DECLARED
    (``stream.tenants`` / ``stream.arms``), so ``static_args`` are fixed
    at construction and no discovery-order state exists; malformed
    events (unknown tenant/arm, non-integer reward) are skipped and
    counted, identically to the online consumer's
    :func:`parse_event` guard.

    Split invariance (fold(A ++ B) == merge_carries(fold(A), fold(B)),
    any chunk boundaries/order) is property-tested at mesh=1 and 8-way
    by the fold-algebra verifier (core.algebra, tests/test_algebra.py —
    jid ``bandit_fb``); rewards are integers, so float sums are exact
    under every arrangement.
    """

    fixed_capacity = False

    def __init__(self, config, out_path: str):
        self.out_path = out_path
        self.name = "FeedbackFold"
        self.tenants = tenants_from_config(config)
        self.arms = arms_from_config(config)
        self.tenant_index = {t: i for i, t in enumerate(self.tenants)}
        self.arm_index = {a: i for i, a in enumerate(self.arms)}
        self.dtype = _dtype_from_name(config.get(KEY_DTYPE, DEFAULT_DTYPE))
        self.t_ord = config.get_int(KEY_TENANT_ORD, 0)
        self.a_ord = config.get_int(KEY_ARM_ORD, 1)
        self.r_ord = config.get_int(KEY_REWARD_ORD, 2)
        self.delim_out = config.field_delim_out()
        self.local_fn = _posterior_local
        self.static_args = (len(self.tenants), len(self.arms),
                            str(self.dtype))
        self.malformed = 0
        self.events = 0

    def encode(self, ctx):
        t_idx, a_idx, rewards = [], [], []
        for fields in ctx.fields():
            ev = parse_event(fields, self.t_ord, self.a_ord, self.r_ord,
                             self.tenant_index, self.arm_index)
            if ev is None:
                self.malformed += 1
                continue
            t_idx.append(ev[0])
            a_idx.append(ev[1])
            rewards.append(ev[2])
        if not t_idx:
            return None
        self.events += len(t_idx)
        return (np.asarray(t_idx, np.int32), np.asarray(a_idx, np.int32),
                np.asarray(rewards, np.int64))

    def finalize(self, carry) -> Counters:
        counters = Counters()
        if carry is None:
            pulls = np.zeros((len(self.tenants), len(self.arms)), np.int64)
            reward = np.zeros_like(pulls, dtype=self.dtype)
        else:
            pulls = np.asarray(carry["pulls"])
            reward = np.asarray(carry["reward"])
        write_output(self.out_path, posterior_lines(
            self.tenants, self.arms, pulls, reward, self.delim_out))
        counters.set(STREAM_GROUP, "Events folded", self.events)
        counters.set(STREAM_GROUP, "Malformed events", self.malformed)
        return counters

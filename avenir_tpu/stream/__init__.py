"""Streaming decision service: real-time bandit serving with
exactly-once feedback folding over Redis streams.

The reference avenir's real-time layer was Storm topologies fed by Redis
queues doing online reinforcement learning; ``models/streaming.py``
rebuilt the queue topology as a pull loop.  This package is the
production half (ROADMAP item 6): a per-tenant bandit scorer (Thompson
sampling / UCB over device-resident per-arm posterior state) served
through the event-loop frontend/pool/router path, and a feedback
consumer that reads reward events from a Redis stream and folds them
into the posterior carry online — registered as a ``FoldSpec`` so the
fold-algebra verifier certifies it like every batch fold, with
exactly-once application riding the checkpoint layer (stream offset +
carry in ONE sidecar, generation fallback on corruption).

Modules:

- :mod:`.posterior` — the per-(tenant, arm) posterior monoid: the pure
  fold ``local_fn``, the host-form :class:`~.posterior.ArmPosterior`
  (state_dict/from_state/merge), the device-resident
  :class:`~.posterior.PosteriorStore` (donated-carry folds + jitted
  Thompson/UCB decisions), and the shared-scan
  :class:`~.posterior.FeedbackFoldSpec`.
- :mod:`.consumer` — the exactly-once Redis-stream feedback consumer
  (XREADGROUP + watermark dedup + offset checkpointing + regret
  anomaly triggers).
- :mod:`.service` — the ``python -m avenir_tpu stream`` entry point
  composing a :class:`~avenir_tpu.serve.server.PredictionServer` with
  the consumer.
"""

"""The streaming decision service: ``python -m avenir_tpu stream``.

Composes the serving stack with the feedback loop:

- a :class:`~avenir_tpu.serve.server.PredictionServer` serving
  ``decide`` requests over the event-loop frontend/pool/router path
  through a ``banditDecision`` model (auto-declared from the
  ``stream.*`` manifest when the config names no ``serve.models``);
- a :class:`~avenir_tpu.stream.consumer.FeedbackConsumer` daemon thread
  folding reward events from the Redis stream into the shared
  :class:`~avenir_tpu.stream.posterior.PosteriorStore` with
  exactly-once checkpointing;
- two frontend command extensions: ``{"cmd": "feedback", "event":
  "tenant,arm,reward"[, "trace": ...]}`` XADDs a reward event into the
  feedback stream through the service's transport (the runbook path
  when no external producer owns a Redis connection — the event still
  flows through XREADGROUP like any other), and ``{"cmd": "stream"}``
  reports consumer offsets/counters/regret plus a posterior audit.

Redis wiring: ``stream.redis.host``/``stream.redis.port`` name a real
server (the optional ``redis`` package); when no host is configured the
service runs against an in-process :class:`~avenir_tpu.models.
streaming.FakeRedis` — same stream semantics, no dependency — which the
``feedback`` command feeds.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

from ..core import flight, obs, telemetry
from ..core.config import JobConfig, load_job_config, parse_cli_args
from ..models.streaming import FakeRedis, RedisStreamTransport
from .consumer import (DEFAULT_CONSUMER, DEFAULT_GROUP, DEFAULT_STREAM,
                       FeedbackConsumer, KEY_CONSUMER, KEY_GROUP,
                       KEY_STREAM, checkpointer_from_config)
from .posterior import (DEFAULT_STORE, KEY_STORE, PosteriorStore,
                        ensure_store)

KEY_REDIS_HOST = "stream.redis.host"
KEY_REDIS_PORT = "stream.redis.port"
KEY_MODEL_NAME = "stream.model.name"

DEFAULT_MODEL_NAME = "decisions"
DEFAULT_REDIS_PORT = 6379


def transport_from_config(config, client=None) -> RedisStreamTransport:
    """The feedback-stream transport: a real server when
    ``stream.redis.host`` is set, else the in-process FakeRedis."""
    host = config.get(KEY_REDIS_HOST)
    if client is None and not host:
        client = FakeRedis()
    return RedisStreamTransport(
        host or "127.0.0.1",
        config.get_int(KEY_REDIS_PORT, DEFAULT_REDIS_PORT),
        config.get(KEY_STREAM, DEFAULT_STREAM),
        config.get(KEY_GROUP, DEFAULT_GROUP),
        config.get(KEY_CONSUMER, DEFAULT_CONSUMER),
        client=client)


def declare_decision_model(config: JobConfig) -> str:
    """Auto-declare the served ``banditDecision`` model from the
    ``stream.*`` manifest when the config names no ``serve.models`` —
    the one-properties-file service shape the runbook uses.  Returns the
    model name serving decide requests."""
    name = config.get(KEY_MODEL_NAME, DEFAULT_MODEL_NAME)
    if not config.get("serve.models"):
        config.set("serve.models", name)
        config.set(f"serve.model.{name}.kind", "banditDecision")
        config.set(f"serve.model.{name}.stream.store",
                   config.get(KEY_STORE, DEFAULT_STORE))
    return name


class StreamDecisionService:
    """One process's streaming decision service: shared posterior store
    + serving stack + feedback consumer thread."""

    def __init__(self, config: JobConfig, mesh=None, client=None):
        from ..serve.server import PredictionServer

        self.config = config
        self.store: PosteriorStore = ensure_store(config, mesh=mesh)
        self.model_name = declare_decision_model(config)
        self.transport = transport_from_config(config, client=client)
        self.transport.ensure_group()
        default_ckpt = os.path.join(
            os.getcwd(), f"stream-{self.store.key}.ckpt")
        self.consumer = FeedbackConsumer(
            config, self.store, self.transport,
            checkpointer=checkpointer_from_config(config, self.store,
                                                  default_ckpt))
        # FakeRedis mode: the in-process broker's id clock restarts at 1
        # each process while the checkpoint watermark carries the
        # previous epoch's ids (a real server's ms-based ids are
        # monotonic across restarts) — advance the fake clock past the
        # watermark so post-resume events are never mistaken for
        # duplicates
        from ..models.streaming import _sid
        client = self.transport._r
        if isinstance(client, FakeRedis):
            client.advance_id_clock(self.transport.stream,
                                    _sid(self.consumer.last_applied)[0])
        self.server = PredictionServer(config, mesh=mesh)
        self.server.command_extensions["feedback"] = self._feedback_cmd
        self.server.command_extensions["stream"] = self._stream_cmd
        self._consumer_thread: Optional[threading.Thread] = None

    # -- frontend command extensions ---------------------------------------
    def _feedback_cmd(self, obj: dict) -> dict:
        """XADD one reward event (``event``: ``tenant,arm,reward``;
        optional ``trace``: the decide response's trace id, joining the
        decision to its reward) into the feedback stream."""
        event = obj.get("event")
        if not isinstance(event, str) or event.count(",") < 2:
            return {"error": '"event" must be a '
                             '"tenant,arm,reward" string'}
        fields = {"data": event}
        trace = obj.get("trace")
        if isinstance(trace, str) and trace:
            fields["trace"] = trace
        eid = self.transport.publish(fields)
        return {"ok": True, "id": eid}

    def _stream_cmd(self, _obj: dict) -> dict:
        """Consumer offsets/counters/regret + a posterior audit (the
        per-(tenant, arm) pulls and reward sums, in the canonical
        emitted-line format so operators can diff it against a batch
        replay byte-for-byte)."""
        return {"ok": True,
                "store": self.store.key,
                "model": self.model_name,
                "consumer": self.consumer.stats(),
                "stream_length": self.transport.length(),
                "pending": self.transport.pending_count(),
                "posterior": self.store.host_posterior().lines()}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        """Bind the TCP frontend and start the consumer thread; returns
        the bound port."""
        port = self.server.start()
        t = threading.Thread(target=self._consume,
                             name="stream-feedback", daemon=True)
        self._consumer_thread = t
        t.start()
        return port

    def _consume(self) -> None:
        try:
            self.consumer.run(idle_timeout=None)
        except BaseException as exc:               # noqa: BLE001 — the
            # consumer thread's death is an anomaly the black box must
            # document (the serving half keeps answering decide requests
            # from the last-folded posterior)
            flight.trigger("stream-consumer-death", force=True,
                           error=f"{type(exc).__name__}: {exc}")
            raise

    def stop(self) -> None:
        """Graceful stop: the consumer writes its final checkpoint (a
        clean stop resumes exactly), then the server drains."""
        self.consumer.stop()
        t = self._consumer_thread
        if t is not None:
            t.join(timeout=10.0)
            self._consumer_thread = None
        self.server.stop(drain=True)


def stream_main(argv) -> int:
    """``python -m avenir_tpu stream -Dconf.path=stream.properties
    [--trace out.json] [--metrics-out series.jsonl] [--resume]``."""
    from ..cli import (configure_resilience, extract_metrics_out_flag,
                      extract_resume_flag, extract_trace_flag)

    argv, trace_path = extract_trace_flag(list(argv))
    argv, metrics_out = extract_metrics_out_flag(argv)
    argv, resume = extract_resume_flag(argv)
    defines, positional = parse_cli_args(argv)
    if positional and positional[0] in ("-h", "--help"):
        print("usage: python -m avenir_tpu stream -Dconf.path=<stream."
              "properties> [-Dserve.port=N ...] [--trace out.json] "
              "[--metrics-out series.jsonl] [--resume]",
              file=sys.stderr)
        return 2
    config = load_job_config(defines)
    if resume:
        config.set("checkpoint.resume", "true")
    if metrics_out:
        config.set(telemetry.KEY_JSONL_PATH, metrics_out)
    obs.configure_from_config(config, force_enable=bool(trace_path))
    # before configure_resilience: the fleet publisher routes
    # flight.dump.dir into its spool feed when fleetobs.spool.dir is set
    from ..fleetobs.publisher import publisher_for_job
    publisher = publisher_for_job(config, role="stream")
    configure_resilience(config)
    service = StreamDecisionService(config)
    if publisher is not None:
        publisher.attach(service.server.telemetry)
    flusher = telemetry.flusher_for_job(config, trace_path)
    port = service.start()
    print(f"streaming decisions for model {service.model_name!r} "
          f"({len(service.store.tenants)} tenants x "
          f"{len(service.store.arms)} arms, {service.store.algorithm}) on "
          f"{config.get('serve.host', '127.0.0.1')}:{port}",
          file=sys.stderr, flush=True)
    stop_evt = threading.Event()
    import signal
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop_evt.set())
        except (ValueError, OSError):       # non-main thread / platform
            pass
    try:
        stop_evt.wait()
    except KeyboardInterrupt:
        pass
    finally:
        service.stop()
        if flusher is not None:
            flusher.stop()
        if trace_path:
            n = obs.get_tracer().export_chrome_trace(trace_path)
            print(f"obs: wrote {n} trace events to {trace_path} "
                  f"(open in chrome://tracing or ui.perfetto.dev)",
                  file=sys.stderr)
        dump = flight.flush_on_exit()
        if dump:
            print(f"flight: wrote final black-box dump to {dump}",
                  file=sys.stderr)
    return 0

"""Exactly-once feedback consumer: Redis stream -> per-arm posterior fold.

Reward events arrive as stream entries (``data`` field
``tenant,arm,reward``, optional ``trace`` field carrying the decision's
trace id) read through a consumer group — at-least-once delivery with
per-consumer pending redelivery.  Exactly-once application is built
from three pieces:

1. **One sidecar.** The last-applied entry id (the watermark) and the
   fold carry persist together in a single
   :class:`~avenir_tpu.core.checkpoint.OffsetCheckpointer` payload, so
   a kill anywhere leaves a consistent (offset, carry) pair; corruption
   falls back a generation (a lower watermark just replays more pending
   entries — the integer-exact fold keeps the result byte-identical).
2. **Watermark dedup.** Each delivered batch is sorted by entry id
   (restoring order under injected reordering) and applied in id order;
   an entry at or below the watermark was already folded into the carry
   and is skipped as a duplicate (and acknowledged, since it is covered
   by a checkpoint).
3. **Ack one generation behind.** Applied entries stay UNACKNOWLEDGED
   until a checkpoint KNOWN VALID covers them.  The just-written
   sidecar is not yet known valid — the chaos model corrupts exactly
   the newest generation — so each periodic save acknowledges only up
   to the PREVIOUS save's offset (the ack horizon); a clean stop's
   final save is read back through the validating loader before its
   offset becomes the horizon.  Corrupting the newest generation then
   costs nothing: resume falls back to the previous generation, and
   every entry above that generation's offset is still pending —
   redelivered and re-applied against exactly the carry that excludes
   it.  A crash after apply but before checkpoint leaves entries
   pending above the watermark (re-applied once); after checkpoint but
   before ack, pending at or below it (deduped, acked).

Decision -> reward causality: the ``trace`` field joins a reward to the
decide request that produced it; a tenant whose cumulative regret
(best-arm posterior mean minus observed reward, floored at 0) crosses
``stream.regret.threshold`` triggers exactly one flight-recorder dump
(latched per tenant) naming the offending trace.

Fault points (``core.faultinject``): ``feedback_dup`` (a batch is
delivered twice), ``feedback_reorder`` (a batch arrives reversed),
``feedback_drop`` (the consumer dies after delivery, before apply) —
each recovery is a deterministic test (tests/test_stream.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import faultinject, flight, telemetry
from ..core.checkpoint import OffsetCheckpointer
from ..core.metrics import Counters
from ..core.obs import get_tracer
from ..models.streaming import _sid
from .posterior import (ArmPosterior, PosteriorStore, STREAM_GROUP,
                        parse_event)

KEY_STREAM = "stream.feedback.stream"
KEY_GROUP = "stream.consumer.group"
KEY_CONSUMER = "stream.consumer.name"
KEY_BATCH = "stream.consumer.batch"
KEY_BLOCK_MS = "stream.consumer.block.ms"
KEY_CKPT_EVENTS = "stream.checkpoint.interval.events"
KEY_REGRET_THRESHOLD = "stream.regret.threshold"
KEY_TRIM = "stream.trim.enable"

DEFAULT_STREAM = "avenir-feedback"
DEFAULT_GROUP = "deciders"
DEFAULT_CONSUMER = "consumer-1"
DEFAULT_BATCH = 256
DEFAULT_BLOCK_MS = 50
DEFAULT_CKPT_EVENTS = 256

#: the watermark before anything was applied (below every real id)
ZERO_OFFSET = "0-0"


class FeedbackConsumer:
    """One posterior store's stream consumer (runs on the caller's
    thread; :class:`~avenir_tpu.stream.service.StreamDecisionService`
    wraps it in a daemon thread)."""

    def __init__(self, config, store: PosteriorStore, transport,
                 checkpointer: Optional[OffsetCheckpointer] = None):
        self.config = config
        self.store = store
        self.transport = transport
        self.checkpointer = checkpointer
        self.batch = config.get_int(KEY_BATCH, DEFAULT_BATCH)
        self.block_ms = config.get_int(KEY_BLOCK_MS, DEFAULT_BLOCK_MS)
        self.regret_threshold = config.get_float(KEY_REGRET_THRESHOLD, 0.0)
        #: stream trimming (ROADMAP: the feedback stream otherwise grows
        #: forever): after each checkpoint's acks, XTRIM entries at or
        #: below the ack horizon — every one of them is applied, acked,
        #: AND covered by a known-valid checkpoint, so a resumed
        #: consumer never needs them again (byte-identical resume from
        #: the watermark asserted in tests/test_stream.py).  The trim is
        #: clamped to the ALL-consumer-groups ack floor by the transport.
        self.trim = config.get_boolean(KEY_TRIM, False)
        self.counters = Counters()
        self.last_applied = ZERO_OFFSET
        #: the ack horizon: the offset of the newest checkpoint KNOWN
        #: VALID (previous save, validated resume load, or read-back-
        #: validated final save) — only entries at or below it are ever
        #: acknowledged, so newest-generation corruption never strands
        #: an acked-but-uncheckpointed entry
        self._ack_horizon = ZERO_OFFSET
        #: the newest save's offset (becomes the horizon at the NEXT
        #: save, once a younger sidecar shields it)
        self._last_saved = ZERO_OFFSET
        #: applied-but-unacknowledged entry ids (acked once the horizon
        #: passes them)
        self._unacked: List[str] = []
        self._since_save = 0
        self._batches = 0
        self._pending_drained = False
        #: PEL drain cursor: pending entries are walked ONCE (applied or
        #: deduped entries stay pending until their covering checkpoint
        #: acks them, so a plain re-read would loop forever)
        self._pending_cursor = ZERO_OFFSET
        self._stopped = False
        #: host mirror of the carry (integer adds — stays byte-equal to
        #: the device fold) feeding the regret monitor and gauges
        self.mirror = ArmPosterior(store.tenants, store.arms,
                                   dtype=store.dtype)
        self.regret: np.ndarray = np.zeros(len(store.tenants))
        self._regret_latched: set = set()
        if checkpointer is not None and checkpointer.resume:
            self._resume()

    # -- resume ------------------------------------------------------------
    def _resume(self) -> None:
        payload = self.checkpointer.load()
        if payload is None:
            return
        self.store.restore(payload["carry"])
        self.mirror = ArmPosterior.from_state(payload["carry"])
        self.last_applied = payload["offset"]
        # the loaded sidecar passed validation, so its offset is a
        # proven-valid horizon
        self._ack_horizon = payload["offset"]
        self._last_saved = payload["offset"]
        state = payload["state"]
        self.regret = np.asarray(state["regret"], float)
        self._regret_latched = set(state["latched"])
        for name, value in state["counters"].items():
            self.counters.set(STREAM_GROUP, name, value)

    # -- the apply path ----------------------------------------------------
    def _parse(self, fields: Dict[str, str]):
        """(tenant idx, arm idx, reward, trace id | None) or None for a
        malformed entry — the SAME validation the batch replay spec
        applies (one shared :func:`~.posterior.parse_event`)."""
        data = fields.get("data", "")
        ev = parse_event(data.split(","), 0, 1, 2,
                         self.store.tenant_index, self.store.arm_index)
        if ev is None:
            return None
        return ev[0], ev[1], ev[2], (fields.get("trace") or None)

    def _watch_regret(self, t_idx: Sequence[int], rewards: Sequence[int],
                      traces: Sequence[Optional[str]]) -> None:
        """Per-event regret accounting against the post-batch posterior
        means; a tenant crossing ``stream.regret.threshold`` triggers
        EXACTLY ONE flight dump (latched) naming the event that crossed
        it.  Monitoring surface only — NOT part of the byte-parity
        contract (redelivery may legitimately re-batch events, shifting
        which post-batch means each event is scored against)."""
        if not len(t_idx):
            return
        means = self.mirror.means()
        best = means.max(axis=1)
        for ti, r, trace in zip(t_idx, rewards, traces):
            self.regret[ti] += max(float(best[ti]) - float(r), 0.0)
            if (self.regret_threshold > 0
                    and ti not in self._regret_latched
                    and self.regret[ti] > self.regret_threshold):
                self._regret_latched.add(ti)
                self.counters.incr(STREAM_GROUP, "Regret anomalies")
                flight.trigger(
                    "regret-anomaly", trace_id=trace,
                    tenant=self.store.tenants[ti],
                    regret=round(float(self.regret[ti]), 6),
                    threshold=self.regret_threshold)
        metrics = telemetry.get_metrics()
        metrics.set_gauge("stream.regret.total", float(self.regret.sum()))
        for ti in sorted(set(int(t) for t in t_idx)):
            tenant = self.store.tenants[ti]
            for aj, arm in enumerate(self.store.arms):
                metrics.set_gauge(
                    telemetry.labeled("stream.posterior.mean",
                                      tenant=tenant, arm=arm),
                    float(means[ti, aj]))
                metrics.set_gauge(
                    telemetry.labeled("stream.posterior.pulls",
                                      tenant=tenant, arm=arm),
                    float(self.mirror.pulls[ti, aj]))

    def _apply_entries(self, entries: List[tuple],
                       redelivered: bool) -> int:
        """Sort, dedupe against the watermark, fold the fresh events,
        and advance the watermark.  Returns fresh events applied."""
        fi = faultinject.get_injector()
        if fi is not None:
            if fi.armed("feedback_dup", index=self._batches) is not None:
                entries = list(entries) + list(entries)
                self.counters.incr(STREAM_GROUP, "Injected duplicates",
                                   len(entries) // 2)
            if fi.armed("feedback_reorder",
                        index=self._batches) is not None:
                entries = list(entries)[::-1]
            # the crash-between-delivery-and-apply fault: entries stay
            # pending unacked; the resumed consumer must redeliver them
            fi.fire("feedback_drop", index=self._batches)
        self._batches += 1
        entries = sorted(entries, key=lambda e: _sid(e[0]))
        t_idx: List[int] = []
        a_idx: List[int] = []
        rewards: List[int] = []
        traces: List[Optional[str]] = []
        dup_ids: List[str] = []
        fresh_ids: List[str] = []
        watermark = _sid(self.last_applied)
        horizon = _sid(self._ack_horizon)
        for eid, fields in entries:
            if _sid(eid) <= watermark:
                # duplicate delivery: already folded into this carry —
                # skip.  Ack ONLY when a known-valid checkpoint covers
                # the id (the ack horizon); a duplicate above it is
                # already tracked in _unacked by its first copy and
                # must wait for a covering checkpoint, or a crash (or a
                # corrupted newest generation) would silently drop the
                # event.
                self.counters.incr(STREAM_GROUP, "Duplicates skipped")
                if _sid(eid) <= horizon:
                    dup_ids.append(eid)
                continue
            watermark = _sid(eid)
            self.last_applied = eid
            fresh_ids.append(eid)
            parsed = self._parse(fields)
            if parsed is None:
                self.counters.incr(STREAM_GROUP, "Malformed events")
                continue
            ti, ai, r, trace = parsed
            t_idx.append(ti)
            a_idx.append(ai)
            rewards.append(r)
            traces.append(trace)
        if dup_ids:
            self.transport.ack(dup_ids)
        if redelivered and fresh_ids:
            self.counters.incr(STREAM_GROUP, "Redelivered applied",
                               len(fresh_ids))
        if t_idx:
            ti = np.asarray(t_idx, np.int32)
            ai = np.asarray(a_idx, np.int32)
            rs = np.asarray(rewards, np.int64)
            with get_tracer().span("stream.feedback.apply",
                                   events=len(t_idx)):
                self.store.fold_events(ti, ai, rs)
            self.mirror.apply(ti, ai, rs)
            self.counters.incr(STREAM_GROUP, "Events applied", len(t_idx))
            self._watch_regret(t_idx, rewards, traces)
        self._unacked.extend(fresh_ids)
        self._since_save += len(fresh_ids)
        if (self.checkpointer is not None
                and self._since_save >= self.checkpointer.interval):
            self.checkpoint()
        return len(fresh_ids)

    # -- checkpointing -----------------------------------------------------
    def checkpoint(self, final: bool = False) -> None:
        """Persist (watermark, carry, consumer state) as ONE sidecar,
        then acknowledge up to the ack horizon — the PREVIOUS save's
        offset, now shielded by this younger generation (``final=True``
        instead reads the just-written sidecar back through the
        validating loader, so a clean stop acks everything its proven
        final checkpoint covers)."""
        if self.checkpointer is None:
            return
        state = {
            "regret": np.asarray(self.regret),
            "latched": sorted(self._regret_latched),
            "counters": dict(self.counters.as_dict().get(
                STREAM_GROUP, {})),
        }
        with get_tracer().span("stream.checkpoint",
                               offset=self.last_applied):
            self.checkpointer.save(self.last_applied,
                                   self.mirror.state_dict(), state)
        horizon = self._last_saved
        self._last_saved = self.last_applied
        self.counters.incr(STREAM_GROUP, "Checkpoints")
        if final:
            from ..core.checkpoint import CheckpointCorrupt
            try:
                payload = self.checkpointer.load()
            except CheckpointCorrupt:
                payload = None
            if payload is not None:
                horizon = payload["offset"]
        if _sid(horizon) > _sid(self._ack_horizon):
            self._ack_horizon = horizon
        cut = _sid(self._ack_horizon)
        ack = [i for i in self._unacked if _sid(i) <= cut]
        self._unacked = [i for i in self._unacked if _sid(i) > cut]
        self.transport.ack(ack)
        self._since_save = 0
        if self.trim and cut > _sid(ZERO_OFFSET):
            # everything at or below the horizon is applied + acked +
            # checkpoint-covered; the transport clamps to the slowest
            # consumer group's floor before issuing XTRIM
            removed = self.transport.trim_acked(self._ack_horizon)
            if removed:
                self.counters.incr(STREAM_GROUP, "Trimmed entries",
                                   removed)

    # -- the pull loop -----------------------------------------------------
    def step(self) -> int:
        """One read+apply cycle; returns fresh events applied.  The
        FIRST cycles after (re)start drain this consumer's pending
        entries (crash redelivery) before any new reads."""
        if not self._pending_drained:
            entries = self.transport.read_pending(
                self.batch, after=self._pending_cursor)
            if entries:
                self._pending_cursor = entries[-1][0]
                return self._apply_entries(entries, redelivered=True)
            self._pending_drained = True
        entries = self.transport.read_new(self.batch,
                                          block_ms=self.block_ms)
        if not entries:
            return 0
        return self._apply_entries(entries, redelivered=False)

    def run(self, max_events: Optional[int] = None,
            idle_timeout: Optional[float] = None,
            poll_interval: float = 0.01) -> int:
        """Pull until stopped / ``max_events`` / ``idle_timeout`` idle
        seconds (None = forever, the service loop).  A CLEAN exit (stop
        flag, event bound, idle timeout) writes a read-back-validated
        final checkpoint so the next start resumes exactly; an exception
        is a crash — no final save, the last periodic checkpoint plus
        pending redelivery carry the exactly-once contract."""
        processed = 0
        idle_since = None
        while not self._stopped and (max_events is None
                                     or processed < max_events):
            n = self.step()
            if n:
                processed += n
                idle_since = None
                continue
            if idle_timeout is not None:
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since > idle_timeout:
                    break
            time.sleep(poll_interval)
        if self.checkpointer is not None and (self._unacked
                                              or self._since_save):
            self.checkpoint(final=True)
        return processed

    def stop(self) -> None:
        self._stopped = True

    # -- reporting ---------------------------------------------------------
    def stats(self) -> dict:
        return {"counters": self.counters.as_dict().get(STREAM_GROUP, {}),
                "offset": self.last_applied,
                "unacked": len(self._unacked),
                "regret_total": float(self.regret.sum()),
                "regret_latched": [self.store.tenants[t]
                                   for t in sorted(self._regret_latched)]}


def consumer_identity(config, store: PosteriorStore) -> Dict[str, object]:
    """The declared stream identity the offset sidecar validates: a
    checkpoint from a different stream/group/manifest/dtype must never
    resume this consumer."""
    return {"stream": config.get(KEY_STREAM, DEFAULT_STREAM),
            "group": config.get(KEY_GROUP, DEFAULT_GROUP),
            "tenants": ",".join(store.tenants),
            "arms": ",".join(store.arms),
            "dtype": str(store.dtype)}


def checkpointer_from_config(config, store: PosteriorStore,
                             default_path: str
                             ) -> Optional[OffsetCheckpointer]:
    return OffsetCheckpointer.from_config(
        config, config.get_int(KEY_CKPT_EVENTS, DEFAULT_CKPT_EVENTS),
        consumer_identity(config, store), default_path)

"""avenir-analyze: the unified static-analysis engine.

The repo's worst historical bugs were exactly the class static analysis
catches — the unlocked ``Counters.incr`` RMW (PR 3), the unlocked
``utils/caches.py`` (PR 2), the prefetch worker-death deadlock (PR 5) —
and four tier-2 coverage modules had each grown an ad-hoc AST walker to
keep one cross-cutting rule checked.  This package promotes that pattern
to a first-class subsystem, in the spirit of Engler et al., *"Bugs as
Deviant Behavior"* (SOSP 2001: infer the codebase's own invariants and
flag deviations) and Savage et al., *"Eraser"* (TOCS 1997: lockset
discipline — here checked statically, with a runtime lock-order
sanitizer twin in :mod:`avenir_tpu.core.sanitizer`).

Shape:

- **one parse per source file** — :class:`~.engine.Corpus` parses every
  package module once and shares the trees across all rules;
- **a rule registry** — every check registers under a stable rule id and
  returns structured :class:`~.engine.Finding` s (rule id, ``file:line``,
  message, fix hint);
- **exclusion registries that require a written reason and fail on
  stale entries** — the ``NON_RETRYABLE`` / ``NON_ATOMIC_WRITES`` /
  ``NON_FUSABLE`` / ``NON_DAG_STAGES`` pattern, generalized by
  :class:`~.registries.ExclusionRegistry` and extended with
  ``SHARED_UNLOCKED`` (lock discipline), ``HOST_SYNC_ALLOWED`` (JAX
  hot-path hygiene) and ``UNMANAGED_THREADS`` (thread lifecycle);
- **a CLI** — ``python -m avenir_tpu analyze [--strict] [--json p]``
  (see :mod:`~.cli`), run as one tier-1 test so the whole rule catalog
  gates every PR.

The four legacy coverage modules (``tests/test_*_coverage.py``) are thin
shims over this engine: same test names, same violations caught.
"""

from .engine import (Corpus, Finding, Rule, RULES, all_rule_ids,
                     load_package_corpus, run_rules)
from .registries import ExclusionRegistry

# importing the rule modules registers every rule with the engine
from . import rules_io          # noqa: F401  (io-retry, io-atomic-write)
from . import rules_config      # noqa: F401  (config-keys)
from . import rules_drivers     # noqa: F401  (driver-* / foldspec-*)
from . import rules_serve       # noqa: F401  (flight-anomaly, wire-identity)
from . import rules_concurrency  # noqa: F401  (lock-discipline, thread-*)
from . import rules_jax         # noqa: F401  (jax-hot-path, jax-bare-jit)
from . import rules_algebra     # noqa: F401  (fold-purity, merge-closure,
#                                              carry-portability)

__all__ = ["Corpus", "Finding", "Rule", "RULES", "ExclusionRegistry",
           "all_rule_ids", "load_package_corpus", "run_rules"]

"""Exclusion registries: deliberate rule opt-outs with written reasons.

Generalizes the ``NON_RETRYABLE`` / ``NON_ATOMIC_WRITES`` /
``NON_FUSABLE`` / ``NON_DAG_STAGES`` convention the repo already trusts:
an exclusion is a dict entry ``site-key -> reason``, and the engine
turns registry hygiene into findings —

- an entry with an empty reason is an ``empty-reason`` finding;
- an entry whose key no longer names a live candidate violation is a
  ``stale-exclusion`` finding (the site was removed or fixed: drop the
  entry so the registry never rots into a list of historical lies).

The concurrency/JAX registries live here; the four legacy registries
stay in their owning core modules (their import paths are load-bearing
for the tier-2 shims) and are wrapped by the same class at rule-run
time.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from .engine import Finding


class ExclusionRegistry:
    """One rule's exclusion dict plus the hygiene checks.

    ``entries`` maps a site key (rule-defined grammar, typically
    ``module.py:Qual.name``) to a non-empty written reason."""

    def __init__(self, rule_id: str, name: str, entries: Dict[str, str]):
        self.rule_id = rule_id
        self.name = name
        self.entries = entries

    def excuses(self, key: str) -> bool:
        return key in self.entries

    def hygiene_findings(self, candidates: Iterable[str],
                         file_of=None) -> List[Finding]:
        """Findings for empty reasons and stale entries.  ``candidates``
        is every site key that WOULD violate the rule absent exclusions;
        an entry not among them is stale.  ``file_of`` optionally maps a
        key to a file for the finding location (defaults to the key's
        ``module:`` prefix when it has one)."""
        cand = set(candidates)
        out: List[Finding] = []
        for key, reason in sorted(self.entries.items()):
            where = (file_of(key) if file_of
                     else (key.split(":", 1)[0] if ":" in key else ""))
            if not (reason and str(reason).strip()):
                out.append(Finding(
                    self.rule_id, where or self.name, 0,
                    f"{self.name} entry {key!r} has no written reason",
                    hint="every exclusion documents WHY it is safe",
                    tag="empty-reason"))
                continue
            if key not in cand:
                out.append(Finding(
                    self.rule_id, where or self.name, 0,
                    f"stale {self.name} entry {key!r}: no such violating "
                    f"site exists anymore",
                    hint="the site was removed or fixed — drop the entry",
                    tag="stale-exclusion"))
        return out


# ---------------------------------------------------------------------------
# the concurrency / JAX registries (new with avenir-analyze)
# ---------------------------------------------------------------------------

#: lock-discipline opt-outs: ``module.py:Class.attr`` (or
#: ``module.py:<module>.global``) -> why the unlocked mutation is safe.
SHARED_UNLOCKED: Dict[str, str] = {
    "serve/frontend.py:_Shard._posted":
        "single-consumer work queue: producers only append, the shard "
        "loop thread only popleft()s, and collections.deque append/"
        "popleft are atomic under the GIL; the wake pipe provides the "
        "ordering edge — an intentional lock-free handoff",
    "serve/frontend.py:_Shard._conns":
        "every mutation runs on the shard's own loop thread: adopt() "
        "is called directly only from shard 0's acceptor loop (same "
        "thread) and otherwise marshaled via post(); _close runs "
        "inside run() — single-threaded by construction, asserted by "
        "the frontend hammer tests",
}

#: JAX hot-path host-sync opt-outs: ``module.py:Qual:callname`` -> why
#: this deliberate host sync belongs on the hot path.
HOST_SYNC_ALLOWED: Dict[str, str] = {
    "core/pipeline.py:HostStager._buffer:block_until_ready":
        "the copy-proof reuse gate: a staging buffer may only be "
        "reused after the device array that aliased it retires — the "
        "sync IS the correctness mechanism, and it fires only when a "
        "slot is re-requested while its put is still in flight",
    "core/pipeline.py:ChunkTransfer.__call__:np.asarray":
        "host-side dtype/layout normalization of the encoder's output "
        "BEFORE the H2D put — the operands are host arrays already, so "
        "no device sync occurs",
    "core/pipeline.py:ChunkFold.__init__:np.asarray":
        "one-time broadcast-argument upload at scan construction "
        "(host constants -> device); not in the per-chunk loop",
    "core/pipeline.py:ChunkFold.seed:np.asarray":
        "one-time carry seeding at scan start / checkpoint resume "
        "(host snapshot -> device); not in the per-chunk loop",
    "core/pipeline.py:ChunkFold.block:block_until_ready":
        "the explicit end-of-scan / checkpoint barrier: callers invoke "
        "block() exactly when the design WANTS a device sync (async "
        "checkpoint materialization one chunk later — PR 5)",
}

#: thread-lifecycle opt-outs: ``module.py:Qual`` (the scope creating the
#: Thread) -> why the thread needs neither a daemon flag nor a join.
UNMANAGED_THREADS: Dict[str, str] = {}

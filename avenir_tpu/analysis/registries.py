"""Exclusion registries: deliberate rule opt-outs with written reasons.

Generalizes the ``NON_RETRYABLE`` / ``NON_ATOMIC_WRITES`` /
``NON_FUSABLE`` / ``NON_DAG_STAGES`` convention the repo already trusts:
an exclusion is a dict entry ``site-key -> reason``, and the engine
turns registry hygiene into findings —

- an entry with an empty reason is an ``empty-reason`` finding;
- an entry whose key no longer names a live candidate violation is a
  ``stale-exclusion`` finding (the site was removed or fixed: drop the
  entry so the registry never rots into a list of historical lies).

The concurrency/JAX registries live here; the four legacy registries
stay in their owning core modules (their import paths are load-bearing
for the tier-2 shims) and are wrapped by the same class at rule-run
time.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from .engine import Finding


class ExclusionRegistry:
    """One rule's exclusion dict plus the hygiene checks.

    ``entries`` maps a site key (rule-defined grammar, typically
    ``module.py:Qual.name``) to a non-empty written reason."""

    def __init__(self, rule_id: str, name: str, entries: Dict[str, str]):
        self.rule_id = rule_id
        self.name = name
        self.entries = entries

    def excuses(self, key: str) -> bool:
        return key in self.entries

    def hygiene_findings(self, candidates: Iterable[str],
                         file_of=None) -> List[Finding]:
        """Findings for empty reasons and stale entries.  ``candidates``
        is every site key that WOULD violate the rule absent exclusions;
        an entry not among them is stale.  ``file_of`` optionally maps a
        key to a file for the finding location (defaults to the key's
        ``module:`` prefix when it has one)."""
        cand = set(candidates)
        out: List[Finding] = []
        for key, reason in sorted(self.entries.items()):
            where = (file_of(key) if file_of
                     else (key.split(":", 1)[0] if ":" in key else ""))
            if not (reason and str(reason).strip()):
                out.append(Finding(
                    self.rule_id, where or self.name, 0,
                    f"{self.name} entry {key!r} has no written reason",
                    hint="every exclusion documents WHY it is safe",
                    tag="empty-reason"))
                continue
            if key not in cand:
                out.append(Finding(
                    self.rule_id, where or self.name, 0,
                    f"stale {self.name} entry {key!r}: no such violating "
                    f"site exists anymore",
                    hint="the site was removed or fixed — drop the entry",
                    tag="stale-exclusion"))
        return out


# ---------------------------------------------------------------------------
# the concurrency / JAX registries (new with avenir-analyze)
# ---------------------------------------------------------------------------

#: lock-discipline opt-outs: ``module.py:Class.attr`` (or
#: ``module.py:<module>.global``) -> why the unlocked mutation is safe.
SHARED_UNLOCKED: Dict[str, str] = {
    "serve/frontend.py:_Shard._posted":
        "single-consumer work queue: producers only append, the shard "
        "loop thread only popleft()s, and collections.deque append/"
        "popleft are atomic under the GIL; the wake pipe provides the "
        "ordering edge — an intentional lock-free handoff",
    "serve/frontend.py:_Shard._conns":
        "every mutation runs on the shard's own loop thread: adopt() "
        "is called directly only from shard 0's acceptor loop (same "
        "thread) and otherwise marshaled via post(); _close runs "
        "inside run() — single-threaded by construction, asserted by "
        "the frontend hammer tests",
}

#: JAX hot-path host-sync opt-outs: ``module.py:Qual:callname`` -> why
#: this deliberate host sync belongs on the hot path.
HOST_SYNC_ALLOWED: Dict[str, str] = {
    "core/pipeline.py:HostStager._buffer:block_until_ready":
        "the copy-proof reuse gate: a staging buffer may only be "
        "reused after the device array that aliased it retires — the "
        "sync IS the correctness mechanism, and it fires only when a "
        "slot is re-requested while its put is still in flight",
    "core/pipeline.py:ChunkTransfer.__call__:np.asarray":
        "host-side dtype/layout normalization of the encoder's output "
        "BEFORE the H2D put — the operands are host arrays already, so "
        "no device sync occurs",
    "core/pipeline.py:ChunkFold.__init__:np.asarray":
        "one-time broadcast-argument upload at scan construction "
        "(host constants -> device); not in the per-chunk loop",
    "core/pipeline.py:ChunkFold.seed:np.asarray":
        "one-time carry seeding at scan start / checkpoint resume "
        "(host snapshot -> device); not in the per-chunk loop",
    "core/pipeline.py:ChunkFold.block:block_until_ready":
        "the explicit end-of-scan / checkpoint barrier: callers invoke "
        "block() exactly when the design WANTS a device sync (async "
        "checkpoint materialization one chunk later — PR 5)",
}

#: thread-lifecycle opt-outs: ``module.py:Qual`` (the scope creating the
#: Thread) -> why the thread needs neither a daemon flag nor a join.
UNMANAGED_THREADS: Dict[str, str] = {}


# ---------------------------------------------------------------------------
# the distributed-readiness registries (fold-algebra rule family)
# ---------------------------------------------------------------------------

#: fold-purity opt-outs: ``module.py:Qual:token`` (token = the impure
#: call's dotted name, or ``global:<name>`` for a mutable-global read)
#: -> why the host-local nondeterminism cannot diverge fold OUTPUT
#: across hosts.  Everything here is observability bookkeeping or a
#: deterministic memo — none of it flows into a fold carry or an
#: emitted artifact byte.
FOLD_IMPURE_ALLOWED: Dict[str, str] = {
    "core/pipeline.py:_fold_fns:global:_fold_cache":
        "deterministic compile memo: the key (local_fn, mesh, static "
        "args, shapes) fully determines the cached executables, so a "
        "hit and a rebuild produce identical folds; eviction only costs "
        "a recompile",
    "core/telemetry.py:sample_device_memory:time.monotonic":
        "rate-limit clock for the device.hbm.bytes observability gauge; "
        "the sampled value feeds telemetry only, never a fold carry or "
        "output line",
    "core/telemetry.py:sample_device_memory:global:_DEVICE_SAMPLE":
        "rate-limiter bookkeeping for the same observability gauge "
        "(last-sample timestamp + interval); no data-path effect",
    "core/telemetry.py:profiled_jit.wrapped:time.perf_counter_ns":
        "XLA compile-time billing (the Telemetry/xla.compile.ms "
        "counter): wall time measured around the jitted call is "
        "observability, never fold data",
    "core/telemetry.py:get_metrics:global:_GLOBAL_METRICS":
        "the process-global metrics registry read: every write through "
        "it is a counter/gauge/histogram sample, never fold data",
    "core/obs.py:get_tracer:global:_GLOBAL_TRACER":
        "the process-global tracer handle: spans/gauges recorded "
        "through it are observability; fold outputs never read it",
    "native/__init__.py:get_lib:global:_lib":
        "lazily-built native CSV kernel handle: byte-parity between the "
        "native and Python encode paths is asserted by the ingest "
        "tests, so host-varying availability cannot change output",
    "native/__init__.py:get_lib:global:_lib_failed":
        "same native-kernel handle bookkeeping: a host where the build "
        "fails falls back to the byte-identical Python encode path",
    "core/faultinject.py:get_injector:global:_INJECTOR":
        "seeded, config-driven fault-injection plan (test tooling): "
        "deterministic per configuration, and empty in production",
    "core/flight.py:trigger:global:_GLOBAL_RECORDER":
        "flight-recorder anomaly hook: dump-on-anomaly bookkeeping, "
        "write-only from the fold path's perspective",
    "core/io.py:validate_artifact_dir:global:_REQUIRE_SUCCESS":
        "io.require.success strict-mode flag, set once by the CLI "
        "before any engine runs; identical across hosts by the shared "
        "job config",
    "core/io.py:validate_artifact_dir:global:_VALIDATED":
        "manifest-validation memo keyed (dir, stat): a hit and a "
        "re-validation return the same verdict for the same bytes",
    "core/io.py:read_lines:global:_ARTIFACTS":
        "the in-memory ArtifactStore overlay (DAG stage handoff): the "
        "first memory read is asserted byte-identical to the file "
        "round-trip, so overlay presence cannot change consumed bytes",
    "core/io.py:write_output:global:_ARTIFACTS":
        "same ArtifactStore overlay on the write side: registered "
        "outputs also record in memory; bytes written are unchanged",
    "core/resilience.py:with_retries:global:_POLICY":
        "retry policy (backoff shape) configured once at CLI startup; "
        "retries re-execute the same read, they never alter its result",
    "core/sanitizer.py:make_lock:global:_STATE":
        "lock-sanitizer enablement flag read at lock construction; "
        "tracked vs plain locks behave identically for data",
}

#: merge-closure opt-outs: class names exporting ``state_dict`` whose
#: state is DELIBERATELY not a mergeable snapshot type.
MERGE_EXEMPT: Dict[str, str] = {
    "CircuitBreaker":
        "state_dict is a per-replica health-report surface (the serve "
        "`health`/`stats` commands), not a cross-process snapshot: "
        "breaker state is local by design — merging two replicas' trip "
        "counts would manufacture a breaker no replica is actually in",
}

#: carry-portability opt-outs: ``module.py:Qual:token`` -> why this
#: host-topology read inside carry-producing code cannot bake a
#: host-count-dependent value into a fold carry or checkpoint.
HOST_TOPOLOGY_ALLOWED: Dict[str, str] = {
    "parallel/mesh.py:make_mesh:jax.devices":
        "mesh construction IS the topology surface: the mesh shapes "
        "how a fold executes, while carries stay replicated pytrees "
        "whose dtype/shape derive from data caps, not device count — "
        "asserted by the mesh1-vs-mesh8 byte-parity suite and the "
        "split-invariance verifier (core.algebra)",
    "parallel/mesh.py:get_mesh:jax.devices":
        "default-mesh staleness check (device count changed under a "
        "test fixture): same argument as make_mesh — the mesh is "
        "execution shape, not carry content",
    "parallel/mesh.py:_mesh_from_env:jax.devices":
        "device count quoted in the AVENIR_MESH validation error "
        "message only; the mesh shape itself is operator config",
    "core/telemetry.py:sample_device_memory:jax.devices":
        "device-memory residency sampling for the device.hbm.bytes "
        "gauge: reads per-device stats into telemetry, writes nothing "
        "into carries or checkpoints",
}

"""Config-key rule: every governed ``*.*`` key is KEY_-bound, read
through a JobConfig accessor, and README-documented.

The three coverage modules each carried a copy of this walker for their
own namespaces; here one rule owns the union (and new namespaces join by
adding a prefix group).  Per governed key:

- a ``KEY_`` constant must bind the literal (no ad-hoc string reads that
  drift from the docs),
- some module must read it through a JobConfig accessor referencing
  that constant,
- the README must document it.

Gauge/metric NAMES reuse the dotted vocabulary but never flow through an
accessor, so they stay out; ``serve.model.<name>.*`` per-model override
keys are derived at runtime and stay out.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from .engine import Corpus, Finding, rule

#: the governed namespace groups (regex fragments).  A legacy coverage
#: shim asserts its own group's keys; the engine rule checks the union.
NAMESPACE_GROUPS: Dict[str, str] = {
    "durability": r"(?:checkpoint|io|serve\.poison)",
    "telemetry": (r"(?:telemetry|serve\.slo|serve\.pool|serve\.router|"
                  r"serve\.frontend|serve\.drain|serve\.breaker|"
                  r"obs\.sample|flight)"),
    "workflow": r"(?:workflow|dag)",
    "sanitizer": r"(?:sanitize)",
    # the streaming decision service (avenir_tpu/stream); the literal
    # dot keeps the legacy `streaming.max.pending.batches` key out
    "stream": r"(?:stream)",
    # the multi-tenant managed model cache (serve/modelcache.py +
    # serve/admission.py): serve.cache.* residency/cold-start/quota keys
    "cache": r"(?:serve\.cache)",
    # host ingest: the parallel-parse pool (core/parparse.py) and the
    # parse-once binary cache (core/ingestcache.py).  Deliberately NOT
    # bare `ingest` — the legacy `ingest.chunk.bytes` /
    # `ingest.error.budget` literals predate the rule and stay out
    "ingest": r"(?:ingest\.parse|ingest\.cache)",
    # the workload harness (avenir_tpu/workload): scenario/fleet/SLO
    # keys.  The per-phase `workload.phase.<name>.*` family is derived
    # at runtime (f-strings over declared phase names, like
    # `serve.model.<name>.*`) and is deliberately outside governance —
    # only the scalar workload.* keys are KEY_-bound
    "workload": r"(?:workload)",
    # the fleet observability plane (avenir_tpu/fleetobs): spool
    # publisher + cross-process aggregator keys
    "fleetobs": r"(?:fleetobs)",
    # the pod-scale fleet router (serve/fleet): dispatch, feed-watch,
    # autoscale/residency control keys.  Anchored `router` — distinct
    # from the in-process variant router's serve.router.* family
    "router": r"(?:router)",
}

_ACCESSORS = (r"\.(?:get|get_int|get_float|get_boolean|get_list|must|"
              r"must_int|must_float|must_list)\(")


def _const_re(prefixes: str) -> re.Pattern:
    return re.compile(
        r'^(KEY_[A-Z0-9_]+)\s*=\s*"(' + prefixes + r'\.[a-z0-9.]+)"',
        re.MULTILINE)


def _literal_re(prefixes: str) -> re.Pattern:
    return re.compile(
        _ACCESSORS + r'\s*"(' + prefixes + r'\.[a-z0-9.]+)"')


def collect_config_keys(corpus: Corpus,
                        prefixes: str) -> Dict[str, Optional[str]]:
    """Every governed config key under ``prefixes``: bound to a KEY_
    constant, or (a lint violation) read as a bare literal (None)."""
    keys: Dict[str, Optional[str]] = {}
    cre, lre = _const_re(prefixes), _literal_re(prefixes)
    for _rel, sf in corpus.items():
        for m in cre.finditer(sf.text):
            keys.setdefault(m.group(2), m.group(1))
        for m in lre.finditer(sf.text):
            keys.setdefault(m.group(1), None)
    return keys


def config_key_findings(corpus: Corpus, prefixes: str,
                        check_readme: bool = True) -> List[Finding]:
    """The three checks for one namespace group."""
    keys = collect_config_keys(corpus, prefixes)
    out: List[Finding] = []
    texts = [(rel, sf.text) for rel, sf in corpus.items()]

    def _where(needle: str):
        for rel, text in texts:
            idx = text.find(needle)
            if idx >= 0:
                return rel, text[:idx].count("\n") + 1
        return "", 0

    for key, const in sorted(keys.items()):
        if const is None:
            rel, line = _where(f'"{key}"')
            out.append(Finding(
                "config-keys", rel, line,
                f"config key {key!r} read as a bare literal — no KEY_ "
                f"constant binds it",
                hint="declare KEY_... = \"<key>\" and read through it"))
            continue
        accessor = re.compile(
            _ACCESSORS + r"\s*(?:\w+\.)?" + const + r"\b")
        if not any(accessor.search(text) for _rel, text in texts):
            rel, line = _where(f"{const} ")
            out.append(Finding(
                "config-keys", rel, line,
                f"config key {key!r}: {const} never read via a JobConfig "
                f"accessor",
                hint="read the key through config.get*(KEY_...)"))
        if check_readme and key not in corpus.readme:
            rel, line = _where(f'"{key}"')
            out.append(Finding(
                "config-keys", rel, line,
                f"config key {key!r} missing from README",
                hint="document the key in the README key table"))
    return out


@rule("config-keys",
      "every governed config key is KEY_-bound, JobConfig-accessor-read "
      "and README-documented (durability/telemetry/workflow/sanitize "
      "namespaces)")
def _config_keys(corpus: Corpus) -> List[Finding]:
    out: List[Finding] = []
    for _group, prefixes in sorted(NAMESPACE_GROUPS.items()):
        out.extend(config_key_findings(corpus, prefixes))
    # de-dup keys matched by more than one group (serve.poison vs flight
    # never overlap today, but a future group might)
    seen = set()
    uniq = []
    for f in out:
        k = (f.file, f.line, f.message)
        if k not in seen:
            seen.add(k)
            uniq.append(f)
    return uniq

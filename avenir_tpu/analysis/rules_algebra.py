"""Distributed-readiness rules: the fold-algebra family.

ROADMAP item 1 (multi-host, pod-scale execution) assumes every
registered fold/merge is a true commutative monoid — the Hadoop shuffle
becomes a ``psum`` and multi-host aggregation is "just a fold" over
mergeable snapshots.  Non-commutative / impure reducers are a
well-documented silent-corruption class (Xiao et al., *"Nondeterminism
in MapReduce Considered Harmful?"*, ICSE 2014 — PAPERS.md), so these
rules prove the assumption statically; the runtime twin
(:mod:`avenir_tpu.core.algebra`, ``analyze --dynamic``) property-tests
it on real folds.

- **fold-purity** — code reachable (via the engine's dataflow pass)
  from any FoldSpec ``encode``/``finalize``, any bound ``local_fn``, or
  the jitted pipeline fold machinery must not read wall clock, unseeded
  RNG, env vars, or mutable process-global state: host-local
  nondeterminism that silently diverges across hosts.  Deliberate
  observability bookkeeping sits on
  :data:`~.registries.FOLD_IMPURE_ALLOWED` with a written reason.
- **merge-closure** — every class exporting ``state_dict`` pairs it
  with ``from_state`` + a ``merge`` path (or sits on
  :data:`~.registries.MERGE_EXEMPT`), and every section written into a
  mergeable telemetry snapshot is handled by ``merge_snapshots`` (or
  sits on ``core.telemetry.SNAPSHOT_NON_MERGED``) — a new snapshot
  field can never be silently dropped by the multi-host fold.  The same
  closure holds between ``LatencyHistogram.state_dict`` and the
  bucket-state merge.
- **carry-portability** — code reachable from carry-producing scopes
  (FoldSpec classes, the fold/checkpoint machinery) must not read host
  topology (device counts, process indices, cpu counts, hostnames):
  a carry whose dtype/shape bakes in per-host facts cannot resume or
  merge on a differently-sized pod.  Deliberate topology surfaces (mesh
  construction) sit on :data:`~.registries.HOST_TOPOLOGY_ALLOWED`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from .engine import Corpus, Finding, dotted_name, rule
from . import registries
from .registries import ExclusionRegistry

#: fold-machinery scopes rooted in addition to discovered FoldSpec
#: subclasses: the jitted pipeline fold pair, the shared-scan chunk
#: loop, and the per-chunk context views.
PIPELINE_FOLD_ROOTS: Dict[str, Tuple[str, ...]] = {
    "core/pipeline.py": ("ChunkFold.fold", "_fold_fns"),
    "core/multiscan.py": ("MultiScanEngine.run", "ChunkContext"),
}

#: carry-producing scopes beyond the FoldSpec classes themselves: fold
#: carry construction/seeding/snapshot and the checkpoint capture path.
CARRY_ROOTS: Dict[str, Tuple[str, ...]] = {
    "core/pipeline.py": ("ChunkFold", "streaming_fold",
                         "AsyncCheckpointSaver"),
    "core/multiscan.py": ("MultiScanEngine.run",),
    "core/checkpoint.py": ("CheckpointToken", "StreamCheckpointer.token",
                           "StreamCheckpointer.save"),
}

#: wall-clock reads that diverge across hosts
WALL_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.today", "date.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
})

#: RNG namespaces whose module-level draws are process-seeded (hosts
#: draw different streams); a seeded ``default_rng(seed)`` passes.
RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")

#: host-topology reads that bake per-host facts into values
HOST_TOPOLOGY_CALLS = frozenset({
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_index", "jax.process_count",
    "os.cpu_count", "multiprocessing.cpu_count", "socket.gethostname",
    "platform.node", "os.uname",
})


# ---------------------------------------------------------------------------
# root discovery (shared by fold-purity and carry-portability)
# ---------------------------------------------------------------------------

def _is_foldspec_class(bases: Sequence[str]) -> bool:
    return any(b.endswith("FoldSpec") for b in bases)


def foldspec_classes(corpus: Corpus) -> List[Tuple[str, str]]:
    """(rel, class name) of every FoldSpec subclass in the corpus."""
    df = corpus.dataflow()
    out = []
    for rel, idx in sorted(df.modules.items()):
        for cls, bases in sorted(idx.class_bases.items()):
            if _is_foldspec_class(bases):
                out.append((rel, cls))
    return out


def _local_fn_roots(corpus: Corpus) -> List[Tuple[str, str]]:
    """Functions bound as a spec's ``local_fn`` (``self.local_fn = f``
    in __init__ or a class-level ``local_fn = f``) — the jitted fold
    bodies themselves."""
    df = corpus.dataflow()
    roots = []
    spec_classes = {(rel, cls) for rel, cls in foldspec_classes(corpus)}
    for rel, sf in corpus.items():
        idx = df.modules[rel]
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if (rel, node.name) not in spec_classes:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                for t in sub.targets:
                    name = None
                    if (isinstance(t, ast.Attribute)
                            and t.attr == "local_fn"):
                        name = t.attr
                    elif isinstance(t, ast.Name) and t.id == "local_fn":
                        name = t.id
                    if name is None or not isinstance(sub.value,
                                                      ast.Name):
                        continue
                    fname = sub.value.id
                    if fname in idx.functions:
                        roots.append((rel, fname))
                    elif fname in idx.from_imports:
                        trel, orig = idx.from_imports[fname]
                        roots.append((trel, orig))
    return roots


def fold_roots(corpus: Corpus,
               extra: Optional[Dict[str, Tuple[str, ...]]] = None
               ) -> List[Tuple[str, str]]:
    """Every (rel, qual) the fold-purity rule treats as a root: the
    encode/finalize of each FoldSpec subclass, each bound ``local_fn``,
    and the pipeline fold machinery."""
    df = corpus.dataflow()
    roots: List[Tuple[str, str]] = []
    for rel, cls in foldspec_classes(corpus):
        roots.extend(df.expand_prefixes(
            rel, (f"{cls}.encode", f"{cls}.finalize",
                  f"{cls}.<class>")))
    roots.extend(_local_fn_roots(corpus))
    table = PIPELINE_FOLD_ROOTS if extra is None else extra
    for rel, prefixes in table.items():
        roots.extend(df.expand_prefixes(rel, prefixes))
    return sorted(set(roots))


# ---------------------------------------------------------------------------
# impure-site scanning
# ---------------------------------------------------------------------------

def _direct_body_walk(fn_node):
    """Walk a function's own body, NOT descending into nested function
    defs (each nested def is a separate dataflow node, reached through
    the parent's implicit nested edge)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _impure_call_sites(fn_node) -> List[Tuple[str, int]]:
    """(token, lineno) wall-clock / RNG / env-var read sites in one
    function body."""
    sites: List[Tuple[str, int]] = []
    for node in _direct_body_walk(fn_node):
        if isinstance(node, ast.Call):
            dotted = dotted_name(node.func)
            if dotted is None:
                continue
            if dotted in WALL_CLOCK_CALLS:
                sites.append((dotted, node.lineno))
            elif dotted.rsplit(".", 1)[-1] == "default_rng":
                # a SEEDED generator is deterministic and fine; only a
                # default (OS-entropy) construction diverges per host
                if not node.args and not node.keywords:
                    sites.append((dotted, node.lineno))
            elif dotted.startswith(RNG_PREFIXES):
                sites.append((dotted, node.lineno))
            elif dotted in ("os.getenv", "os.environ.get"):
                sites.append((dotted, node.lineno))
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.ctx, ast.Load)
              and dotted_name(node.value) == "os.environ"):
            sites.append(("os.environ", node.lineno))
    return sites


def fold_purity_findings(corpus: Corpus,
                         exclusions: Optional[Dict[str, str]] = None,
                         extra_roots=None) -> List[Finding]:
    reg = ExclusionRegistry(
        "fold-purity", "FOLD_IMPURE_ALLOWED",
        registries.FOLD_IMPURE_ALLOWED if exclusions is None
        else exclusions)
    df = corpus.dataflow()
    reached = df.reachable(fold_roots(corpus, extra=extra_roots))
    out: List[Finding] = []
    candidates: List[str] = []
    for rel, qual in sorted(reached):
        info = df.function(rel, qual)
        if info is None:
            continue
        idx = df.modules[rel]
        sites = list(_impure_call_sites(info.node))
        for g in sorted((info.global_reads | info.global_writes)
                        & idx.effectively_mutable_globals()):
            sites.append((f"global:{g}", info.node.lineno))
        for token, line in sites:
            key = f"{rel}:{qual}:{token}"
            if key in candidates:
                continue
            candidates.append(key)
            if reg.excuses(key):
                continue
            what = (f"reads mutable process-global "
                    f"'{token.partition(':')[2]}'"
                    if token.startswith("global:")
                    else f"calls {token}()")
            out.append(Finding(
                "fold-purity", rel, line,
                f"fold-reachable {qual} {what}: host-local "
                f"nondeterminism diverges across hosts (multi-host "
                f"folds silently corrupt — Xiao ICSE 2014)",
                hint="make the fold path deterministic (seeded RNG, "
                     "config-passed values), or add "
                     f"{key!r} to analysis.registries."
                     "FOLD_IMPURE_ALLOWED with a reason"))
    out.extend(reg.hygiene_findings(candidates))
    return out


@rule("fold-purity",
      "code reachable from FoldSpec encode/finalize, bound local_fns, "
      "or the jitted pipeline fold reads no wall clock, unseeded RNG, "
      "env vars, or mutable globals (FOLD_IMPURE_ALLOWED excludes)")
def _fold_purity(corpus: Corpus) -> List[Finding]:
    return fold_purity_findings(corpus)


# ---------------------------------------------------------------------------
# merge-closure
# ---------------------------------------------------------------------------

def _find_function(tree, name: str, cls: Optional[str] = None):
    """The (possibly method) FunctionDef named ``name``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and cls and node.name == cls:
            for sub in node.body:
                if (isinstance(sub, ast.FunctionDef)
                        and sub.name == name):
                    return sub
        elif (cls is None and isinstance(node, ast.FunctionDef)
              and node.name == name):
            return node
    return None


def _written_sections(fn_node) -> Dict[str, int]:
    """TOP-LEVEL snapshot sections a builder writes: the first (outer)
    dict literal's keys plus ``snap["X"] = ...`` subscript-assign keys
    -> lineno.  Nested per-entry dicts (e.g. an exemplar record) are
    values INSIDE a section, not sections."""
    out: Dict[str, int] = {}
    if fn_node is None:
        return out
    first_dict = None
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Dict):
            if first_dict is None or node.lineno < first_dict.lineno:
                first_dict = node
    if first_dict is not None:
        for k in first_dict.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.setdefault(k.value, k.lineno)
    for node in ast.walk(fn_node):
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)):
            sl = node.targets[0].slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                out.setdefault(sl.value, node.lineno)
    return out


def _handled_keys(fn_node) -> set:
    """Keys a merge function genuinely CARRIES: literal keys of its
    dict literals (the returned/accumulated output shape), string args
    of ``.get(...)`` reads, and plain-Assign subscript stores
    (``out["x"] = ...``).  Deliberately NOT every string constant and
    NOT AugAssign subscripts: ``cur["count"] += s["count"]`` mutates a
    nested entry field, and a future top-level section named "count"
    must still be reported as dropped (review finding)."""
    out = set()
    if fn_node is None:
        return out
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value,
                                                              str):
                    out.add(k.value)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "get" and node.args
              and isinstance(node.args[0], ast.Constant)
              and isinstance(node.args[0].value, str)):
            out.add(node.args[0].value)
        elif (isinstance(node, ast.Assign)
              and len(node.targets) == 1
              and isinstance(node.targets[0], ast.Subscript)):
            sl = node.targets[0].slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value,
                                                           str):
                out.add(sl.value)
    return out


def merge_closure_findings(corpus: Corpus,
                           exclusions: Optional[Dict[str, str]] = None,
                           non_merged: Optional[Dict[str, str]] = None
                           ) -> List[Finding]:
    reg = ExclusionRegistry(
        "merge-closure", "MERGE_EXEMPT",
        registries.MERGE_EXEMPT if exclusions is None else exclusions)
    df = corpus.dataflow()
    out: List[Finding] = []

    # (a) state_dict exporters pair with from_state + merge
    candidates: List[str] = []
    for rel, idx in sorted(df.modules.items()):
        for cls, methods in sorted(idx.classes.items()):
            if "state_dict" not in methods:
                continue
            missing = [m for m in ("from_state", "merge")
                       if m not in methods]
            if not missing:
                continue
            candidates.append(cls)
            if reg.excuses(cls):
                continue
            out.append(Finding(
                "merge-closure", rel, idx.class_lines.get(cls, 0),
                f"{cls} exports state_dict without {'/'.join(missing)}: "
                f"its snapshots cannot round-trip or fold across "
                f"processes",
                hint="pair state_dict with from_state + merge (the "
                     "LatencyHistogram contract), or add the class to "
                     "analysis.registries.MERGE_EXEMPT with a reason"))
    out.extend(reg.hygiene_findings(candidates, file_of=lambda k: ""))

    # (b) snapshot-section closure: everything the builders write,
    # merge_snapshots must handle (or SNAPSHOT_NON_MERGED documents)
    tele = next((sf for rel, sf in corpus.items()
                 if rel.endswith("telemetry.py")), None)
    obs = next((sf for rel, sf in corpus.items()
                if rel.endswith("obs.py")), None)
    if tele is not None:
        if non_merged is None:
            try:
                from ..core.telemetry import SNAPSHOT_NON_MERGED
                non_merged = SNAPSHOT_NON_MERGED
            except ImportError:      # fixture corpus without the package
                non_merged = {}
        nreg = ExclusionRegistry("merge-closure", "SNAPSHOT_NON_MERGED",
                                 non_merged)
        sections: Dict[str, int] = {}
        sections.update(_written_sections(
            _find_function(tele.tree, "build_snapshot")))
        if obs is not None:
            sections.update(_written_sections(
                _find_function(obs.tree, "mergeable_snapshot",
                               cls="Metrics")))
        handled = _handled_keys(
            _find_function(tele.tree, "merge_snapshots"))
        ncand = []
        for sec, line in sorted(sections.items()):
            if sec in handled:
                continue
            ncand.append(sec)
            if nreg.excuses(sec):
                continue
            out.append(Finding(
                "merge-closure", tele.rel, line,
                f"snapshot section {sec!r} is written by the snapshot "
                f"builders but silently dropped by merge_snapshots",
                hint="merge the section (sum/add/latest-wins), or add "
                     "it to core.telemetry.SNAPSHOT_NON_MERGED with a "
                     "reason"))
        out.extend(nreg.hygiene_findings(ncand,
                                         file_of=lambda k: tele.rel))

        # (c) histogram-state closure: LatencyHistogram.state_dict keys
        # all appear in the bucket-state merge
        if obs is not None:
            st = _written_sections(_find_function(
                obs.tree, "state_dict", cls="LatencyHistogram"))
            hm = _handled_keys(_find_function(tele.tree,
                                              "_merge_hist_state"))
            if hm:
                for k, line in sorted(st.items()):
                    if k not in hm and not k.isdigit():
                        out.append(Finding(
                            "merge-closure", obs.rel, line,
                            f"LatencyHistogram.state_dict key {k!r} is "
                            f"not handled by _merge_hist_state: merged "
                            f"histogram states silently drop it",
                            hint="extend _merge_hist_state (and the "
                                 "merge tests) for the new key"))
    return out


@rule("merge-closure",
      "state_dict exporters pair with from_state+merge; every snapshot "
      "section/histogram-state key survives merge_snapshots (or is on "
      "SNAPSHOT_NON_MERGED with a reason)")
def _merge_closure(corpus: Corpus) -> List[Finding]:
    return merge_closure_findings(corpus)


# ---------------------------------------------------------------------------
# carry-portability
# ---------------------------------------------------------------------------

def carry_roots(corpus: Corpus,
                extra: Optional[Dict[str, Tuple[str, ...]]] = None
                ) -> List[Tuple[str, str]]:
    df = corpus.dataflow()
    roots: List[Tuple[str, str]] = []
    for rel, cls in foldspec_classes(corpus):
        roots.extend(df.expand_prefixes(rel, (cls,)))
    table = CARRY_ROOTS if extra is None else extra
    for rel, prefixes in table.items():
        roots.extend(df.expand_prefixes(rel, prefixes))
    return sorted(set(roots))


def carry_portability_findings(
        corpus: Corpus,
        exclusions: Optional[Dict[str, str]] = None,
        extra_roots=None) -> List[Finding]:
    reg = ExclusionRegistry(
        "carry-portability", "HOST_TOPOLOGY_ALLOWED",
        registries.HOST_TOPOLOGY_ALLOWED if exclusions is None
        else exclusions)
    df = corpus.dataflow()
    reached = df.reachable(carry_roots(corpus, extra=extra_roots))
    out: List[Finding] = []
    candidates: List[str] = []
    for rel, qual in sorted(reached):
        info = df.function(rel, qual)
        if info is None:
            continue
        for node in _direct_body_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted not in HOST_TOPOLOGY_CALLS:
                continue
            key = f"{rel}:{qual}:{dotted}"
            if key in candidates:
                continue
            candidates.append(key)
            if reg.excuses(key):
                continue
            out.append(Finding(
                "carry-portability", rel, node.lineno,
                f"carry-producing {qual} reads host topology via "
                f"{dotted}(): a carry sized/indexed by per-host facts "
                f"cannot resume or merge on a different pod shape",
                hint="derive carry dtypes/shapes from data caps and "
                     "config only, or add "
                     f"{key!r} to analysis.registries."
                     "HOST_TOPOLOGY_ALLOWED with a reason"))
    out.extend(reg.hygiene_findings(candidates))
    return out


@rule("carry-portability",
      "carry-producing code (FoldSpec classes, fold/checkpoint "
      "machinery) reads no host topology — carries stay valid across "
      "pod shapes (HOST_TOPOLOGY_ALLOWED excludes)")
def _carry_portability(corpus: Corpus) -> List[Finding]:
    return carry_portability_findings(corpus)

"""I/O rules: retry coverage on the ingest path, atomic publish
coverage package-wide.

Ported byte-for-byte from the walkers in
``tests/test_resilience_coverage.py`` (now a shim over these rules):

- **io-retry** — every raw I/O call site (``open``, ``subprocess.*``,
  ``os.fdopen``/``tempfile.mkstemp``) in the ingest-path modules must
  run under ``core.resilience.with_retries`` (directly, or as a helper
  invoked through it) or sit on ``core.resilience.NON_RETRYABLE`` with
  a written reason.
- **io-atomic-write** — every truncate-mode write (``open``/
  ``os.fdopen`` with a ``w*`` mode) anywhere in the package must live
  inside the atomic publish primitives (``core.io.OutputWriter`` /
  ``core.io.atomic_write_text``) or sit on ``core.io.NON_ATOMIC_WRITES``
  with a written reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .engine import Corpus, Finding, ScopedVisitor, rule
from .registries import ExclusionRegistry

#: the ingest-path modules the retry lint patrols
INGEST_MODULES = [
    "core/io.py",
    "core/config.py",
    "core/pipeline.py",
    "core/binning.py",
    "core/multiscan.py",
    "core/checkpoint.py",
    "core/resilience.py",
    "native/__init__.py",
    "models/streaming.py",
]

#: call spellings that count as raw I/O
RAW_NAME_CALLS = {"open"}
RAW_ATTR_CALLS = {
    ("subprocess", "run"), ("subprocess", "Popen"),
    ("subprocess", "check_output"), ("subprocess", "check_call"),
    ("os", "fdopen"), ("tempfile", "mkstemp"),
    ("redis", "Redis"),
}

#: redis network commands: ANY ``<expr>.<cmd>(...)`` call with one of
#: these attribute names is a network round trip (the redis-py client
#: surface the transports use) — patrolled in the ingest modules like
#: every other raw I/O site.  The FakeRedis double DEFINES these names
#: but never calls them on another object, so it stays clean.
RAW_NET_ATTR_NAMES = {
    "rpop", "lpush", "llen", "lrange",
    "xadd", "xread", "xreadgroup", "xack", "xrange", "xlen",
    "xgroup_create", "xpending",
}

#: quals that ARE the atomic publish layer (writes inside them stage to
#: a temp path and land via fsync + os.replace)
ATOMIC_PRIMITIVES = ("core/io.py:atomic_write_text",
                     "core/io.py:atomic_write_bytes",
                     "core/io.py:OutputWriter.")


class _RetryScan(ScopedVisitor):
    """Raw I/O call sites + with_retries wrapper/invoked-helper names."""

    def __init__(self):
        super().__init__()
        self.raw_sites: Dict[str, List[int]] = {}
        self.wrapper_funcs = set()   # funcs whose body calls with_retries
        self.retry_invoked = set()   # helper names passed to with_retries

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id == "open":
                self.raw_sites.setdefault(self.qual(), []).append(
                    node.lineno)
            elif fn.id == "with_retries":
                self.wrapper_funcs.add(self.qual())
                if node.args and isinstance(node.args[0], ast.Name):
                    self.retry_invoked.add(node.args[0].id)
        elif isinstance(fn, ast.Attribute):
            base = fn.value
            if (isinstance(base, ast.Name)
                    and (base.id, fn.attr) in RAW_ATTR_CALLS):
                self.raw_sites.setdefault(self.qual(), []).append(
                    node.lineno)
            elif fn.attr in RAW_NET_ATTR_NAMES:
                # a redis network command on any client expression
                self.raw_sites.setdefault(self.qual(), []).append(
                    node.lineno)
            if fn.attr == "with_retries":
                self.wrapper_funcs.add(self.qual())
                if node.args and isinstance(node.args[0], ast.Name):
                    self.retry_invoked.add(node.args[0].id)
        self.generic_visit(node)


def scan_ingest_io(corpus: Corpus,
                   modules=None) -> Tuple[Dict[str, List[int]], set]:
    """``(sites, wrapped)``: every raw I/O call site on the ingest path
    keyed ``module.py:qualname`` -> line numbers, and the subset keys
    considered retry-covered (the scan the legacy
    ``test_retry_wrappers_exist`` guards)."""
    sites: Dict[str, List[int]] = {}
    wrapped = set()
    retry_invoked = set()
    per_module = {}
    for rel in (INGEST_MODULES if modules is None else modules):
        sf = corpus.get(rel)
        if sf is None:
            continue
        scan = _RetryScan()
        scan.visit(sf.tree)
        per_module[rel] = scan
        retry_invoked |= scan.retry_invoked
    for rel, scan in per_module.items():
        for qual, lines in scan.raw_sites.items():
            key = f"{rel}:{qual}"
            sites[key] = lines
            leaf = qual.rsplit(".", 1)[-1]
            if qual in scan.wrapper_funcs or leaf in retry_invoked:
                wrapped.add(key)
    return sites, wrapped


def io_retry_findings(corpus: Corpus,
                      exclusions: Optional[Dict[str, str]] = None,
                      modules=None) -> List[Finding]:
    from ..core.resilience import NON_RETRYABLE
    reg = ExclusionRegistry(
        "io-retry", "NON_RETRYABLE",
        NON_RETRYABLE if exclusions is None else exclusions)
    sites, wrapped = scan_ingest_io(corpus, modules=modules)
    out: List[Finding] = []
    for key, lines in sorted(sites.items()):
        if key in wrapped or reg.excuses(key):
            continue
        out.append(Finding(
            "io-retry", key.split(":", 1)[0], lines[0],
            f"raw I/O call site {key} (lines {lines}) on the ingest path "
            f"runs outside with_retries",
            hint="wrap in core.resilience.with_retries or add to "
                 "core.resilience.NON_RETRYABLE with a reason"))
    candidates = [k for k in sites if k not in wrapped]
    out.extend(reg.hygiene_findings(candidates))
    return out


@rule("io-retry",
      "raw I/O on the ingest path is retry-wrapped or excluded with a "
      "reason (core.resilience.NON_RETRYABLE)")
def _io_retry(corpus: Corpus) -> List[Finding]:
    return io_retry_findings(corpus)


# ---------------------------------------------------------------------------
# io-atomic-write
# ---------------------------------------------------------------------------

class _WriteScan(ScopedVisitor):
    """``open``/``os.fdopen`` calls whose mode argument is a ``w*``
    constant (truncate-rewrite: the torn-on-crash shape) or a
    non-constant expression (flagged conservatively).  Read-mode and
    append-mode calls pass."""

    def __init__(self):
        super().__init__()
        self.sites: Dict[str, List[int]] = {}

    @staticmethod
    def _truncating(node) -> bool:
        mode = node.args[1] if len(node.args) >= 2 else None
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is None:
            return False                      # default: read
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return mode.value.startswith("w")
        return True                           # dynamic mode: flag it

    def visit_Call(self, node):
        fn = node.func
        is_write = False
        if isinstance(fn, ast.Name) and fn.id == "open":
            is_write = self._truncating(node)
        elif (isinstance(fn, ast.Attribute) and fn.attr == "fdopen"
              and isinstance(fn.value, ast.Name)
              and fn.value.id == "os"):
            is_write = self._truncating(node)
        if is_write:
            self.sites.setdefault(self.qual(), []).append(node.lineno)
        self.generic_visit(node)


def scan_truncate_writes(corpus: Corpus) -> Dict[str, List[int]]:
    """Every truncate-mode write site in the corpus, keyed
    ``module.py:qualname`` -> line numbers."""
    sites: Dict[str, List[int]] = {}
    for rel, sf in corpus.items():
        scan = _WriteScan()
        scan.visit(sf.tree)
        for qual, lines in scan.sites.items():
            sites[f"{rel}:{qual}"] = lines
    return sites


def is_atomic_site(key: str) -> bool:
    return key.startswith(ATOMIC_PRIMITIVES)


def io_atomic_findings(corpus: Corpus,
                       exclusions: Optional[Dict[str, str]] = None
                       ) -> List[Finding]:
    from ..core.io import NON_ATOMIC_WRITES
    reg = ExclusionRegistry(
        "io-atomic-write", "NON_ATOMIC_WRITES",
        NON_ATOMIC_WRITES if exclusions is None else exclusions)
    sites = scan_truncate_writes(corpus)
    out: List[Finding] = []
    for key, lines in sorted(sites.items()):
        if is_atomic_site(key) or reg.excuses(key):
            continue
        out.append(Finding(
            "io-atomic-write", key.split(":", 1)[0], lines[0],
            f"truncate-mode write {key} (lines {lines}) outside the "
            f"atomic publish layer (OutputWriter / atomic_write_text)",
            hint="route through core.io.atomic_write_text or add to "
                 "core.io.NON_ATOMIC_WRITES with a reason"))
    candidates = [k for k in sites if not is_atomic_site(k)]
    out.extend(reg.hygiene_findings(candidates))
    return out


@rule("io-atomic-write",
      "truncate-mode writes live inside the atomic publish layer or on "
      "core.io.NON_ATOMIC_WRITES with a reason")
def _io_atomic(corpus: Corpus) -> List[Finding]:
    return io_atomic_findings(corpus)

"""JAX hot-path hygiene rules.

**jax-hot-path** — no host syncs inside the registered fold/score hot
paths.  The streaming-fold engine's whole design is that the per-chunk
loop never blocks on the device (prefetch overlap, donated carries); a
``.block_until_ready()`` / ``np.asarray(...)`` / ``.item()`` / device
``float(...)`` dropped into one of the :data:`HOT_PATHS` scopes
serializes host and device and silently erases the 1.58× overlap.
Deliberate syncs (the copy-proof staging check, the carry
materialization at checkpoint boundaries) sit on
``registries.HOST_SYNC_ALLOWED`` with a written reason, so every hot
host sync in the tree is documented.

**jax-bare-jit** — no bare ``jax.jit`` on serving/pipeline paths.
Every compile on those paths must ride ``telemetry.profiled_jit`` so
XLA compile time is billed to the ``Telemetry/xla.compile.ms`` counter
and warmup regressions stay visible; a bare ``jax.jit`` bypasses
compile billing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .engine import Corpus, Finding, ScopedVisitor, rule
from . import registries
from .registries import ExclusionRegistry

#: the registered fold/score hot paths: ``module.py`` -> qualname
#: prefixes whose scopes form the per-chunk / per-batch loop.  A scope
#: matches when its qualname equals a prefix or extends it
#: (``prefix.<nested>``).
HOT_PATHS: Dict[str, Tuple[str, ...]] = {
    "core/pipeline.py": ("ChunkTransfer", "ChunkFold", "HostStager",
                         "drive_prefetched", "_prefetch_worker"),
    "core/multiscan.py": ("MultiScanEngine._run_scan", "ChunkContext"),
    "serve/engine.py": ("NaiveBayesAdapter.score_batch",
                        "MarkovAdapter.score_batch"),
    "serve/batcher.py": ("MicroBatcher._run_loop",
                         "MicroBatcher._score_lines",
                         "MicroBatcher._isolate"),
}

#: host-sync call shapes flagged inside hot paths
_SYNC_ATTRS = {"block_until_ready", "item"}

#: modules where a bare ``jax.jit`` bypasses profiled_jit compile
#: billing (the serving + pipeline compile surfaces)
BARE_JIT_MODULES = ("serve/", "core/pipeline.py", "core/multiscan.py")


def _in_hot_path(rel: str, qual: str,
                 hot_paths: Dict[str, Tuple[str, ...]]) -> bool:
    prefixes = hot_paths.get(rel)
    if not prefixes:
        return False
    return any(qual == p or qual.startswith(p + ".") for p in prefixes)


class _SyncScan(ScopedVisitor):
    def __init__(self):
        super().__init__()
        self.sites: List[Tuple[str, int, str]] = []   # (qual, line, call)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTRS:
            self.sites.append((self.qual(), node.lineno, fn.attr))
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in ("asarray", "array")
              and isinstance(fn.value, ast.Name)
              and fn.value.id == "np"):
            self.sites.append((self.qual(), node.lineno,
                               f"np.{fn.attr}"))
        elif isinstance(fn, ast.Name) and fn.id == "float":
            # float(<device value>) — conservatively flagged on calls
            # whose argument is an attribute/subscript (not a literal)
            if node.args and not isinstance(node.args[0], ast.Constant):
                self.sites.append((self.qual(), node.lineno, "float"))
        self.generic_visit(node)


def jax_hot_path_findings(corpus: Corpus, hot_paths=None,
                          exclusions=None) -> List[Finding]:
    hp = HOT_PATHS if hot_paths is None else hot_paths
    reg = ExclusionRegistry(
        "jax-hot-path", "HOST_SYNC_ALLOWED",
        registries.HOST_SYNC_ALLOWED if exclusions is None
        else exclusions)
    out: List[Finding] = []
    candidates: List[str] = []
    for rel, sf in corpus.items():
        if rel not in hp:
            continue
        scan = _SyncScan()
        scan.visit(sf.tree)
        for qual, line, call in scan.sites:
            if not _in_hot_path(rel, qual, hp):
                continue
            key = f"{rel}:{qual}:{call}"
            candidates.append(key)
            if reg.excuses(key):
                continue
            out.append(Finding(
                "jax-hot-path", rel, line,
                f"host sync {call}() inside registered hot path {qual}",
                hint="keep the per-chunk/per-batch loop async (device "
                     "syncs serialize the prefetch overlap), or add "
                     f"{key!r} to analysis.registries.HOST_SYNC_ALLOWED "
                     "with a reason"))
    out.extend(reg.hygiene_findings(candidates))
    return out


@rule("jax-hot-path",
      "no undocumented host syncs (block_until_ready/np.asarray/.item()/"
      "float) inside registered fold/score hot paths")
def _jax_hot_path(corpus: Corpus) -> List[Finding]:
    return jax_hot_path_findings(corpus)


class _JitScan(ScopedVisitor):
    def __init__(self):
        super().__init__()
        self.sites: List[Tuple[str, int]] = []

    def visit_Call(self, node):
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "jit"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "jax"):
            self.sites.append((self.qual(), node.lineno))
        self.generic_visit(node)


def jax_bare_jit_findings(corpus: Corpus,
                          modules=BARE_JIT_MODULES) -> List[Finding]:
    out: List[Finding] = []
    for rel, sf in corpus.items():
        if not (rel.startswith(tuple(m for m in modules
                                     if m.endswith("/")))
                or rel in modules):
            continue
        scan = _JitScan()
        scan.visit(sf.tree)
        for qual, line in scan.sites:
            out.append(Finding(
                "jax-bare-jit", rel, line,
                f"bare jax.jit in {qual} on a serving/pipeline path "
                f"bypasses profiled_jit compile billing",
                hint="wrap with core.telemetry.profiled_jit so XLA "
                     "compiles bill to Telemetry/xla.compile.ms"))
    return out


@rule("jax-bare-jit",
      "no bare jax.jit on serving/pipeline paths (profiled_jit bills "
      "every compile)")
def _jax_bare_jit(corpus: Corpus) -> List[Finding]:
    return jax_bare_jit_findings(corpus)

"""Incremental analyze: per-file parse cache + whole-run report cache.

A cold ``analyze --strict`` costs ~3.5 s (78 parses + 17 rules, two of
which import the whole driver registry); on a pre-commit hook that is
the difference between "runs on every commit" and "gets skipped".  The
sidecar under ``.avenir-analyze/`` makes the warm path sub-second:

- **parse cache** (``corpus.pkl``): every parsed tree keyed by the
  file's rel path with its FULL TEXT compared on load (files are still
  read each run — only the ``ast.parse`` is skipped, which is where the
  time goes; the pickle round-trips ``ast`` trees exactly).
- **report cache** (``report-<rules>.json``): when the corpus digest
  (every file's sha1 + the README's + the cache/interpreter version)
  matches the stored run for the same rule selection, the previous
  findings are replayed without running any rule — correct because
  every rule input (sources, registries, rule code itself) lives
  inside the digested corpus.

Any change to any ``.py`` under the package (or the README) changes the
digest, so invalidation is automatic — asserted by the touch-one-file
test in tests/test_analysis.py.  Both sidecar writes are atomic
(``core.io.atomic_write_text``/``atomic_write_bytes``); a torn or
unreadable sidecar silently degrades to a cold run.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import sys
from typing import List, Optional, Sequence, Tuple

from .engine import Corpus, Finding, run_rules

#: bump on cache-format changes; the interpreter version rides along so
#: a Python upgrade (whose ast node shapes and parser may differ) never
#: replays trees or findings pickled under the old runtime
CACHE_VERSION = "1-py{}.{}.{}".format(*sys.version_info[:3])
CACHE_DIR_NAME = ".avenir-analyze"


def default_cache_dir() -> str:
    """The repo-level sidecar dir: next to the installed package (the
    directory holding ``avenir_tpu/`` and README.md)."""
    import avenir_tpu
    pkg = os.path.dirname(os.path.abspath(avenir_tpu.__file__))
    return os.path.join(os.path.dirname(pkg), CACHE_DIR_NAME)


def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


class AnalysisCache:
    """One cache directory's load/store surface.  ``stats`` records
    what the last :meth:`run` reused (reparsed file count, report
    hit/miss) so tests — and curious operators — can see the warm path
    actually engage."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.dir = cache_dir or default_cache_dir()
        self.stats = {"parsed": None, "reused": None, "report_hit": False}

    # -- parse cache -------------------------------------------------------
    def _corpus_pkl(self) -> str:
        return os.path.join(self.dir, "corpus.pkl")

    def _load_parse_cache(self) -> dict:
        try:
            with open(self._corpus_pkl(), "rb") as fh:
                payload = pickle.load(fh)
            if payload.get("version") == CACHE_VERSION:
                return payload.get("files", {})
        except (OSError, pickle.PickleError, EOFError, AttributeError,
                ValueError):
            pass                    # torn/stale sidecar: cold parse
        return {}

    def load_corpus(self, root: str,
                    readme_path: Optional[str] = None) -> Corpus:
        """Corpus over ``root`` reusing every cached unchanged parse;
        refreshes the sidecar when anything was (re)parsed."""
        from ..core.io import atomic_write_bytes

        cached = self._load_parse_cache()
        corpus = Corpus(root, readme_path=readme_path,
                        parse_cache=cached)
        self.stats["parsed"] = corpus.parsed_files
        self.stats["reused"] = len(corpus.files) - corpus.parsed_files
        fresh = {rel: (sf.text, sf.tree)
                 for rel, sf in corpus.items()}
        if corpus.parsed_files or set(fresh) != set(cached):
            try:
                atomic_write_bytes(self._corpus_pkl(), pickle.dumps(
                    {"version": CACHE_VERSION, "files": fresh},
                    protocol=pickle.HIGHEST_PROTOCOL))
            except OSError:
                pass                # unwritable cache dir: still correct
        return corpus

    # -- report cache ------------------------------------------------------
    @staticmethod
    def _digest_entries(entries, readme_text: str) -> str:
        """ONE digest definition for both paths: sorted (rel, text)
        pairs + the readme — corpus_digest and tree_digest MUST agree
        for the same tree or the two callers would thrash each other's
        report sidecars."""
        h = hashlib.sha1()
        h.update(f"v{CACHE_VERSION}".encode())
        for rel, text in sorted(entries):
            h.update(rel.encode())
            h.update(_sha1(text.encode()).encode())
        h.update(_sha1(readme_text.encode()).encode())
        return h.hexdigest()

    def corpus_digest(self, corpus: Corpus) -> str:
        return self._digest_entries(
            ((rel, sf.text) for rel, sf in corpus.items()),
            corpus.readme)

    def tree_digest(self, root: str,
                    readme_path: Optional[str] = None) -> str:
        """The identical digest straight from the files — no parse, no
        unpickle: the warm path's first (and usually only) work.
        Equality with :meth:`corpus_digest` is asserted in tests."""
        entries = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path) as fh:
                    entries.append((rel, fh.read()))
        readme = ""
        if readme_path and os.path.exists(readme_path):
            with open(readme_path) as fh:
                readme = fh.read()
        return self._digest_entries(entries, readme)

    def load_report(self, digest: str,
                    rule_ids: Optional[Sequence[str]] = None
                    ) -> Optional[Tuple[List[Finding], dict]]:
        """The stored (findings, report) for this digest + rule
        selection, or None on miss/torn sidecar."""
        import time

        t0 = time.monotonic()
        try:
            with open(self._report_path(rule_ids)) as fh:
                stored = json.load(fh)
            if stored.get("digest") != digest:
                return None
            report = stored["report"]
        except (OSError, ValueError, KeyError, TypeError):
            return None
        report["cached"] = True
        report["cold_duration_ms"] = report.get("duration_ms")
        report["duration_ms"] = round((time.monotonic() - t0) * 1e3, 2)
        self.stats["report_hit"] = True
        findings = [Finding(**d) for d in report.get("findings", [])]
        return findings, report

    def store_report(self, digest: str, report: dict,
                     rule_ids: Optional[Sequence[str]] = None) -> None:
        from ..core.io import atomic_write_text

        try:
            atomic_write_text(self._report_path(rule_ids), json.dumps(
                {"digest": digest, "report": report}) + "\n")
        except OSError:
            pass

    def _report_path(self, rule_ids: Optional[Sequence[str]]) -> str:
        key = _sha1(",".join(sorted(rule_ids)).encode())[:12] \
            if rule_ids else "all"
        return os.path.join(self.dir, f"report-{key}.json")

    def run(self, corpus: Corpus,
            rule_ids: Optional[Sequence[str]] = None,
            use_cache: bool = True
            ) -> Tuple[List[Finding], dict]:
        """``run_rules`` with the report cache in front: a digest hit
        replays the stored findings (``report["cached"] = True``)
        without executing a single rule."""
        from ..core.io import atomic_write_text

        digest = self.corpus_digest(corpus)
        self.stats["report_hit"] = False
        if use_cache:
            hit = self.load_report(digest, rule_ids)
            if hit is not None:
                return hit
        findings, report = run_rules(corpus, rule_ids=rule_ids)
        report["cached"] = False
        self.store_report(digest, report, rule_ids)
        return findings, report


def cached_package_run(rule_ids: Optional[Sequence[str]] = None,
                       use_cache: bool = True,
                       cache_dir: Optional[str] = None
                       ) -> Tuple[List[Finding], dict]:
    """The CLI's default path: the installed package corpus through the
    cache (parse reuse + report replay)."""
    import avenir_tpu

    pkg = os.path.dirname(os.path.abspath(avenir_tpu.__file__))
    readme = os.path.join(os.path.dirname(pkg), "README.md")
    cache = AnalysisCache(cache_dir)
    if use_cache:
        # report-first: a digest hit replays findings WITHOUT building
        # (or unpickling) any corpus — the sub-second warm path
        digest = cache.tree_digest(pkg, readme_path=readme)
        hit = cache.load_report(digest, rule_ids)
        if hit is not None:
            findings, report = hit
            cache.stats["parsed"] = 0
            report["cache_stats"] = dict(cache.stats)
            return findings, report
        corpus = cache.load_corpus(pkg, readme_path=readme)
        findings, report = run_rules(corpus, rule_ids=rule_ids)
        report["cached"] = False
        cache.store_report(digest, report, rule_ids)
    else:
        corpus = Corpus(pkg, readme_path=readme)
        findings, report = run_rules(corpus, rule_ids=rule_ids)
        report["cached"] = False
    report["cache_stats"] = dict(cache.stats)
    return findings, report

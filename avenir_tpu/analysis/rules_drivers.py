"""Driver-registry rules (project scope: they introspect the real
package through ``avenir_tpu.cli.JOBS``).

Ported from ``tests/test_obs_coverage.py`` / ``test_dag_coverage.py`` /
``test_multiscan_coverage.py``:

- **driver-traced** — every registered batch driver's ``run()`` carries
  ``@traced_run`` (the unified tracing surface).
- **driver-counters** — every registered driver's ``run()`` is annotated
  to return ``Counters`` (or sits on ``RETURN_ALLOWED`` with a reason).
- **foldspec-fusable** — every streaming-fold consumer exports a
  shared-scan ``fold_spec`` or sits on ``core.multiscan.NON_FUSABLE``.
- **foldspec-dag** — every FoldSpec exporter is DAG-registrable
  (standard ``run(in, out, mesh)`` surface) or sits on
  ``core.dag.NON_DAG_STAGES``.
- **dag-builtins** — the workflow-only built-in stages honor the traced
  ``run(in, out, mesh) -> Counters`` driver contract, and the per-stage
  manifest template keys are README-documented.
"""

from __future__ import annotations

import importlib
import inspect
from typing import Dict, List, Optional

from .engine import Corpus, Finding, rule
from .registries import ExclusionRegistry

#: run() returns something other than Counters by DESIGN for these
RETURN_ALLOWED: Dict[str, str] = {
    "org.avenir.regress.LogisticRegressionJob":
        "returns the reference's convergence status int (the outer "
        "do-while protocol; its Counters live on self.counters)",
    "org.avenir.reinforce.ReinforcementLearnerTopology":
        "the streaming event loop (its return is unannotated but IS a "
        "Counters; signature differs too)",
}


def _driver_classes():
    from ..cli import JOBS
    for fqcn, (modname, clsname, _) in sorted(JOBS.items()):
        mod = importlib.import_module(f"avenir_tpu.models.{modname}")
        yield fqcn, getattr(mod, clsname)


def _class_site(cls):
    """(package-relative file, lineno) of a driver class."""
    try:
        rel = f"models/{cls.__module__.rsplit('.', 1)[-1]}.py"
        _src, line = inspect.getsourcelines(cls)
        return rel, line
    except (OSError, TypeError):
        return f"models/{cls.__module__.rsplit('.', 1)[-1]}.py", 0


@rule("driver-traced",
      "every registered driver's run() carries @traced_run (core.obs)",
      scope="project")
def driver_traced_findings(_corpus: Corpus) -> List[Finding]:
    out: List[Finding] = []
    for fqcn, cls in _driver_classes():
        if not getattr(cls.run, "__obs_traced__", False):
            rel, line = _class_site(cls)
            out.append(Finding(
                "driver-traced", rel, line,
                f"{fqcn}.run() lacks @traced_run",
                hint="decorate run() with core.obs.traced_run"))
    return out


@rule("driver-counters",
      "every registered driver's run() returns Counters (or sits on "
      "RETURN_ALLOWED with a reason)", scope="project")
def driver_counters_findings(_corpus: Corpus) -> List[Finding]:
    reg = ExclusionRegistry("driver-counters", "RETURN_ALLOWED",
                            RETURN_ALLOWED)
    out: List[Finding] = []
    candidates = []
    for fqcn, cls in _driver_classes():
        ann = inspect.signature(cls.run).return_annotation
        name = ann if isinstance(ann, str) else getattr(ann, "__name__",
                                                        ann)
        if name == "Counters":
            continue
        candidates.append(fqcn)
        if reg.excuses(fqcn):
            continue
        rel, line = _class_site(cls)
        out.append(Finding(
            "driver-counters", rel, line,
            f"{fqcn}.run() does not return Counters (annotation: {name})",
            hint="return a Counters snapshot, or add to "
                 "rules_drivers.RETURN_ALLOWED with a reason"))
    out.extend(reg.hygiene_findings(candidates, file_of=lambda k: ""))
    return out


# ---------------------------------------------------------------------------
# shared-scan fusability (NON_FUSABLE)
# ---------------------------------------------------------------------------

def _consumes_streaming_fold(cls) -> bool:
    try:
        src = inspect.getsource(cls)
    except (OSError, TypeError):  # pragma: no cover - C/builtin classes
        return False
    return "streaming_fold" in src


def foldspec_fusable_findings(
        exclusions: Optional[Dict[str, str]] = None) -> List[Finding]:
    from ..core.multiscan import NON_FUSABLE
    reg = ExclusionRegistry(
        "foldspec-fusable", "NON_FUSABLE",
        NON_FUSABLE if exclusions is None else exclusions)
    out: List[Finding] = []
    candidates = []
    for fqcn, cls in _driver_classes():
        if not _consumes_streaming_fold(cls):
            continue
        if callable(getattr(cls, "fold_spec", None)):
            continue
        candidates.append(cls.__name__)
        if reg.excuses(cls.__name__):
            continue
        rel, line = _class_site(cls)
        out.append(Finding(
            "foldspec-fusable", rel, line,
            f"streaming-fold consumer {fqcn} exports no fold_spec",
            hint="export a shared-scan fold_spec or add the class to "
                 "core.multiscan.NON_FUSABLE with a reason"))
    out.extend(reg.hygiene_findings(candidates, file_of=lambda k: ""))
    return out


@rule("foldspec-fusable",
      "every streaming-fold consumer exports a shared-scan fold_spec or "
      "sits on core.multiscan.NON_FUSABLE with a reason",
      scope="project")
def _foldspec_fusable(_corpus: Corpus) -> List[Finding]:
    return foldspec_fusable_findings()


# ---------------------------------------------------------------------------
# DAG registrability (NON_DAG_STAGES)
# ---------------------------------------------------------------------------

def dag_registrable(cls) -> bool:
    """A class the workflow engine can run as a stage: the standard
    driver surface run(self, in_path, out_path, mesh=...)."""
    run = getattr(cls, "run", None)
    if run is None:
        return False
    params = list(inspect.signature(run).parameters)
    return params[:3] == ["self", "in_path", "out_path"] and "mesh" in params


def foldspec_dag_findings(
        exclusions: Optional[Dict[str, str]] = None) -> List[Finding]:
    from ..core.dag import NON_DAG_STAGES
    reg = ExclusionRegistry(
        "foldspec-dag", "NON_DAG_STAGES",
        NON_DAG_STAGES if exclusions is None else exclusions)
    out: List[Finding] = []
    candidates = []
    for fqcn, cls in _driver_classes():
        if not callable(getattr(cls, "fold_spec", None)):
            continue
        if dag_registrable(cls):
            continue
        candidates.append(cls.__name__)
        if reg.excuses(cls.__name__):
            continue
        rel, line = _class_site(cls)
        out.append(Finding(
            "foldspec-dag", rel, line,
            f"FoldSpec exporter {fqcn} cannot run as a DAG stage "
            f"(non-standard run() surface)",
            hint="fix the run(in, out, mesh) surface or add to "
                 "core.dag.NON_DAG_STAGES with a reason"))
    out.extend(reg.hygiene_findings(candidates, file_of=lambda k: ""))
    return out


@rule("foldspec-dag",
      "every FoldSpec exporter is DAG-registrable or sits on "
      "core.dag.NON_DAG_STAGES with a reason", scope="project")
def _foldspec_dag(_corpus: Corpus) -> List[Finding]:
    return foldspec_dag_findings()


# ---------------------------------------------------------------------------
# workflow built-ins + per-stage manifest template keys
# ---------------------------------------------------------------------------

@rule("dag-builtins",
      "workflow built-in stages honor the traced run(in, out, mesh) -> "
      "Counters contract; per-stage manifest template keys are "
      "README-documented", scope="project")
def dag_builtin_findings(corpus: Corpus) -> List[Finding]:
    from ..core.dag import BUILTIN_STAGES, STAGE_RESERVED
    out: List[Finding] = []
    for name, cls in sorted(BUILTIN_STAGES.items()):
        problems = []
        if not dag_registrable(cls):
            problems.append("non-standard run(in, out, mesh) surface")
        if not getattr(cls.run, "__obs_traced__", False):
            problems.append("run lacks @traced_run")
        ann = inspect.signature(cls.run).return_annotation
        label = ann if isinstance(ann, str) else getattr(ann, "__name__",
                                                         ann)
        if label != "Counters":
            problems.append(f"run() returns {label}, not Counters")
        if problems:
            out.append(Finding(
                "dag-builtins", "core/dag.py", 0,
                f"built-in stage {name}: {'; '.join(problems)}",
                hint="built-ins honor the same driver contract the "
                     "scheduler assumes of every stage"))
    template_keys = ("workflow.stage.<id>.class",) + tuple(
        f"workflow.stage.<id>.{k}" for k in STAGE_RESERVED
        if k != "class")
    for key in template_keys:
        if key not in corpus.readme:
            out.append(Finding(
                "dag-builtins", "core/dag.py", 0,
                f"per-stage manifest key {key!r} missing from README",
                hint="document the template key in the manifest section"))
    return out

"""Serving-layer rules: flight-recorder anomaly coverage and
wire-response identity echo.

Ported from ``tests/test_obs_coverage.py``:

- **flight-anomaly** — every anomaly trigger site in the package
  (breaker trips, SLO soft-degrades, poison quarantines, torn
  artifacts, systemic scorer failures) calls the flight-dump hook
  (``flight.trigger``) in its enclosing scope, or sits on
  ``ANOMALY_EXCLUDED`` with a reason.
- **wire-identity** — every response-construction site in
  ``serve/server.py`` is on the ``_finish_response`` funnel (which
  echoes ``request_id``/``trace_id``) or pinned in
  ``RESPONSE_SITES_OK`` with a reason; the frontend's out-of-funnel
  renderers are pinned likewise, and the drain filler demonstrably
  echoes the captured ``request_id``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from .engine import Corpus, Finding, enclosing_scope_source, rule

#: every anomaly trigger site in the package: description ->
#: (module path, a regex that locates the site).  The enclosing
#: function/class scope must call ``flight.trigger`` — or the
#: description sits on ANOMALY_EXCLUDED with a reason.
ANOMALY_SITES: Dict[str, tuple] = {
    "breaker trip (closed/half-open -> open)":
        ("serve/breaker.py", r"self\.trips \+= 1"),
    "SLO sustained violation -> soft-degrade":
        ("serve/slo.py", r"set_soft_degraded\(\s*True"),
    "systemic scorer failure (whole-batch exception)":
        ("serve/batcher.py", r"record_failure\("),
    "poison row crosses into quarantine":
        ("serve/batcher.py", r"quarantine\.record\("),
    "torn artifact detected":
        ("core/io.py", r"class TornArtifactError"),
    "lock-order cycle detected (sanitizer teardown)":
        ("core/sanitizer.py", r"raise LockOrderCycle\("),
}

#: sites deliberately NOT wired to the flight hook, with reasons
ANOMALY_EXCLUDED: Dict[str, str] = {
    "lock-order cycle detected (sanitizer teardown)":
        "the sanitizer is a test-harness teardown check: the raising "
        "test IS the report, and a flight dump from inside the lock "
        "instrumentation layer could itself take locks",
}

#: serve/server.py functions allowed to BUILD response dicts
RESPONSE_SITES_OK: Dict[str, str] = {
    "_finish_response": "the chokepoint itself",
    "handle_line": "pre-parse JSON errors only: request_id unreadable "
                   "by definition; parsed requests funnel through "
                   "_finish_response",
    "dispatch_line": "pre-parse errors before the cb wrapper installs; "
                     "all post-parse cb calls ride the funnel",
    "_handle_obj": "returns into handle_line/dispatch_line funnels",
    "_command": "returns into the funnels via _handle_obj",
    "_submit": "returns into _predict -> funnels",
    "_evicted_mid_request": "returns into _submit's cold-start paths "
                            "-> _predict -> funnels",
    "_assemble": "returns into _predict/_AsyncCollector -> funnels",
    "_finish": "_AsyncCollector: fires the wrapped (funnel) callback",
}

#: frontend.py response-producing functions (they render bytes directly,
#: outside the server funnel) and why each is identity-correct
FRONTEND_SITES_OK: Dict[str, str] = {
    "_dispatch_error": "oversized/skimmed line: the request was never "
                       "parsed, so no request_id exists to echo",
    "fail_pending": "drain-timeout filler: echoes request_id from "
                    "conn.meta (captured at dispatch) — asserted by the "
                    "rule",
}


@rule("flight-anomaly",
      "every anomaly trigger site calls flight.trigger in its enclosing "
      "scope or sits on ANOMALY_EXCLUDED with a reason")
def flight_anomaly_findings(corpus: Corpus) -> List[Finding]:
    out: List[Finding] = []
    for what, (rel, pattern) in sorted(ANOMALY_SITES.items()):
        excluded = what in ANOMALY_EXCLUDED
        if excluded and not ANOMALY_EXCLUDED[what].strip():
            out.append(Finding(
                "flight-anomaly", rel, 0,
                f"ANOMALY_EXCLUDED entry {what!r} has no written "
                f"reason", tag="empty-reason"))
            continue
        sf = corpus.get(rel)
        text = sf.text if sf is not None else ""
        matches = list(re.finditer(pattern, text))
        if not matches:
            # the staleness check runs for EXCLUDED entries too: an
            # exclusion whose locator no longer matches is a rotten
            # registry entry, same as everywhere else
            out.append(Finding(
                "flight-anomaly", rel, 0,
                f"anomaly site pattern for {what!r} no longer matches "
                f"{rel}",
                hint="stale ANOMALY_SITES entry? update the locator",
                tag="stale-exclusion"))
            continue
        if excluded:
            continue
        for m in matches:
            lineno = text[:m.start()].count("\n") + 1
            scope = enclosing_scope_source(text, lineno, tree=sf.tree)
            if "flight.trigger" not in scope:
                out.append(Finding(
                    "flight-anomaly", rel, lineno,
                    f"anomaly site ({what}) scope has no flight.trigger "
                    f"call",
                    hint="dump the black box at the anomaly edge, or "
                         "add to ANOMALY_EXCLUDED with a reason"))
    return out


# ---------------------------------------------------------------------------
# wire-identity
# ---------------------------------------------------------------------------

def response_building_functions(sf) -> Dict[str, List[int]]:
    """{enclosing function name: [line numbers]} for every dict literal
    carrying an ``"error"``/``"output"``/``"outputs"`` key — the
    response-construction sites."""
    tree = sf.tree
    sites: Dict[str, List[int]] = {}
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    wire_keys = {"error", "output", "outputs"}

    def hit(node) -> bool:
        if isinstance(node, ast.Dict):
            keys = {k.value for k in node.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            return bool(keys & wire_keys)
        if isinstance(node, ast.Assign):
            # resp["error"] = ... — assembled responses, not literals
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and t.slice.value in wire_keys):
                    return True
        return False

    for node in ast.walk(tree):
        if not hit(node):
            continue
        owner = None
        for f in funcs:
            if f.lineno <= node.lineno <= (f.end_lineno or f.lineno):
                if owner is None or f.lineno > owner.lineno:
                    owner = f
        sites.setdefault(owner.name if owner else "<module>",
                         []).append(node.lineno)
    return sites


@rule("wire-identity",
      "every wire response construction site rides the request_id/"
      "trace_id echo funnel or is pinned with a reason")
def wire_identity_findings(corpus: Corpus) -> List[Finding]:
    out: List[Finding] = []
    srv = corpus.get("serve/server.py")
    fe = corpus.get("serve/frontend.py")
    if srv is None or fe is None:
        return out          # fixture corpora carry no serve layer
    srv_sites = response_building_functions(srv)
    for fn in sorted(set(srv_sites) - set(RESPONSE_SITES_OK)):
        out.append(Finding(
            "wire-identity", "serve/server.py", srv_sites[fn][0],
            f"new response-construction site {fn}() not classified for "
            f"identity echo",
            hint="route through _finish_response or add to "
                 "RESPONSE_SITES_OK with a reason"))
    for fn in sorted(set(RESPONSE_SITES_OK) - set(srv_sites)):
        out.append(Finding(
            "wire-identity", "serve/server.py", 0,
            f"stale RESPONSE_SITES_OK entry {fn!r}: no such "
            f"response-construction site exists anymore",
            hint="drop the entry", tag="stale-exclusion"))
    # the funnel really exists and echoes both identities
    for needle in ('setdefault("request_id"', 'setdefault("trace_id"'):
        if needle not in srv.text:
            out.append(Finding(
                "wire-identity", "serve/server.py", 0,
                f"_finish_response funnel no longer echoes via {needle}",
                hint="the chokepoint must stamp request_id and trace_id"))
    fe_sites = response_building_functions(fe)
    for fn in sorted(set(fe_sites) - set(FRONTEND_SITES_OK)):
        out.append(Finding(
            "wire-identity", "serve/frontend.py", fe_sites[fn][0],
            f"new response-construction site {fn}() outside the server "
            f"funnel",
            hint="add to FRONTEND_SITES_OK with a reason"))
    for fn in sorted(set(FRONTEND_SITES_OK) - set(fe_sites)):
        out.append(Finding(
            "wire-identity", "serve/frontend.py", 0,
            f"stale FRONTEND_SITES_OK entry {fn!r}",
            hint="drop the entry", tag="stale-exclusion"))
    if "fail_pending" in fe_sites:
        fail_src = enclosing_scope_source(
            fe.text, fe_sites["fail_pending"][0], tree=fe.tree)
        if "request_id" not in fail_src or "conn.meta" not in fail_src:
            out.append(Finding(
                "wire-identity", "serve/frontend.py",
                fe_sites["fail_pending"][0],
                "drain filler no longer echoes request_id from "
                "conn.meta",
                hint="the filler must echo the identity captured at "
                     "dispatch"))
    return out

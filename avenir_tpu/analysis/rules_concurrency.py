"""Concurrency rules: static lock discipline and thread lifecycle.

**lock-discipline** — the Eraser lockset discipline (Savage et al.,
TOCS 1997) checked statically, scoped the way Engler et al. (SOSP 2001)
infer invariants from the codebase itself: a class that owns a lock or
starts a thread has DECLARED itself thread-shared, so every mutation of
its instance state outside ``__init__`` must honor a consistent lockset.
Per such class the rule classifies every mutation site of every
``self.<attr>``:

- **locked** — lexically under ``with self.<lock>`` (any attribute the
  class assigned from ``threading.Lock/RLock/Condition`` or the
  sanitizer's ``make_lock``/``make_rlock``/``make_condition``), or
  inside a PRIVATE method whose every intra-class call site is locked
  (the lock-held-by-caller helper pattern, e.g. ``ModelSLO._evaluate``);
- **worker-only** — reachable only from the class's thread-target
  scopes (single mutator thread: per-worker state like the batcher's
  ``_last_all_failed`` needs no lock);
- otherwise **unlocked-shared**.

A finding fires when an attribute's sites are inconsistent: a
read-modify-write (``+=``) or container mutation (``append``/``pop``/
``update``/subscript store/...) runs unlocked outside worker-only
scopes, or a plain rebind runs unlocked while OTHER sites of the same
attribute lock — the hole Eraser calls a lockset violation.  Module
globals get the same treatment in modules that own a module-level lock.
Deliberate exceptions sit on ``registries.SHARED_UNLOCKED`` with a
written reason (stale entries fail).

**thread-lifecycle** — every started ``threading.Thread`` must carry
``daemon=True`` or be joined somewhere in its module (a registered
shutdown path), or sit on ``registries.UNMANAGED_THREADS`` with a
reason: the static counterpart of the runtime no-leak hammers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .engine import Corpus, Finding, rule
from . import registries
from .registries import ExclusionRegistry

#: attribute calls that mutate a container in place
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "move_to_end", "rotate", "sort", "reverse",
}

#: call spellings that construct a lock (threading primitives + the
#: runtime sanitizer's factories)
LOCK_CONSTRUCTORS = {"Lock", "RLock", "Condition", "make_lock",
                     "make_rlock", "make_condition"}

#: mutable-container constructors that mark a module global as shared
#: mutable state
CONTAINER_CONSTRUCTORS = {"dict", "list", "set", "OrderedDict", "deque",
                          "defaultdict", "Counter"}


def _is_lock_ctor(call: ast.Call) -> bool:
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return name in LOCK_CONSTRUCTORS


def _self_attr(expr) -> Optional[str]:
    """``self.X`` -> ``X`` (peeling subscripts: ``self.X[k][j]`` -> X)."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


def _base_name(expr) -> Optional[str]:
    """``NAME[k][j]`` -> NAME (module-global mutation detection)."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


class _Scope:
    """One function scope (method or nested def) inside a class or
    module."""

    __slots__ = ("name", "qual", "parent", "calls", "is_method",
                 "mutations", "thread_targets", "makes_thread",
                 "_call_locks")

    def __init__(self, name: str, qual: str, parent: Optional["_Scope"],
                 is_method: bool):
        self.name = name
        self.qual = qual              # e.g. "method.worker"
        self.parent = parent
        self.is_method = is_method    # direct child of the class body
        self.calls: Set[str] = set()  # names of self.X() / local f() calls
        # call name -> [bool: ran under a held lock] (second pass)
        self._call_locks: Dict[str, List[bool]] = {}
        # attr -> [(line, kind, locked_lockset)] ; kind: rmw|mutate|assign
        self.mutations: Dict[str, List[Tuple[int, str, frozenset]]] = {}
        self.thread_targets: Set[str] = set()   # scope/method names
        self.makes_thread = False


class _ClassScan(ast.NodeVisitor):
    """One class: lock attrs, thread targets, per-scope mutation sites
    with the lexically-held lockset."""

    def __init__(self, cls_node: ast.ClassDef):
        self.cls = cls_node
        self.lock_attrs: Set[str] = set()
        self.scopes: Dict[str, _Scope] = {}
        self._scope: Optional[_Scope] = None
        self._held: List[str] = []
        # pass 1: collect lock attrs (self.X = Lock() in any method,
        # X = Lock() in the class body) so pass 2 can classify `with`s
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and _is_lock_ctor(node.value):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        self.lock_attrs.add(attr)
                    elif isinstance(t, ast.Name):
                        self.lock_attrs.add(t.id)   # class-body lock
        for stmt in cls_node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit_function(stmt, parent=None, is_method=True)

    # -- scope walking -----------------------------------------------------
    def _visit_function(self, node, parent: Optional[_Scope],
                        is_method: bool):
        qual = node.name if parent is None else f"{parent.qual}.{node.name}"
        scope = _Scope(node.name, qual, parent, is_method)
        self.scopes[scope.qual] = scope
        prev_scope, prev_held = self._scope, self._held
        self._scope, self._held = scope, []   # a nested def's body does
        #                                       NOT run under the
        #                                       enclosing `with`
        for stmt in node.body:
            self._visit(stmt)
        self._scope, self._held = prev_scope, prev_held

    def _visit(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._visit_function(node, parent=self._scope, is_method=False)
            return
        if isinstance(node, ast.With):
            held = []
            for item in node.items:
                lock = self._lock_name(item.context_expr)
                if lock is not None:
                    held.append(lock)
            self._held.extend(held)
            for stmt in node.body:
                self._visit(stmt)
            for _ in held:
                self._held.pop()
            return
        self._inspect(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _lock_name(self, expr) -> Optional[str]:
        """``with self._lock`` / ``with Cls._lock`` -> the lock attr
        name when it is one of the class's known lock attrs."""
        if isinstance(expr, ast.Attribute) and expr.attr in self.lock_attrs:
            return expr.attr
        if isinstance(expr, ast.Name) and expr.id in self.lock_attrs:
            return expr.id
        return None

    # -- site collection ---------------------------------------------------
    def _record(self, attr: str, line: int, kind: str):
        if attr in self.lock_attrs:
            return
        self._scope.mutations.setdefault(attr, []).append(
            (line, kind, frozenset(self._held)))

    def _inspect(self, node):
        s = self._scope
        if s is None:
            return
        if isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target)
            if attr is not None:
                kind = ("rmw" if not isinstance(node.target, ast.Subscript)
                        else "mutate")
                self._record(attr, node.lineno, kind)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t)
                    if attr is not None:
                        self._record(attr, node.lineno, "mutate")
                else:
                    attr = _self_attr(t)
                    if attr is not None:
                        self._record(attr, node.lineno, "assign")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    self._record(attr, node.lineno, "mutate")
        elif isinstance(node, ast.Call):
            fn = node.func
            # self.X.append(...) and friends
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in MUTATOR_METHODS):
                attr = _self_attr(fn.value)
                if attr is not None:
                    self._record(attr, node.lineno, "mutate")
            # intra-class call graph: self.m(...) / local f(...)
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "self"):
                s.calls.add(fn.attr)
            elif isinstance(fn, ast.Name):
                s.calls.add(fn.id)
            # thread creation + target resolution
            if (isinstance(fn, ast.Attribute) and fn.attr == "Thread") or (
                    isinstance(fn, ast.Name) and fn.id == "Thread"):
                s.makes_thread = True
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tgt = kw.value
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        s.thread_targets.add(tgt.attr)
                    elif isinstance(tgt, ast.Name):
                        s.thread_targets.add(f"{s.qual}.{tgt.id}")
                        s.thread_targets.add(tgt.id)


def _resolve_scopes(scan: _ClassScan):
    """(thread_roots, worker_only, locked_scopes): the reachability and
    helper-credit classification over the intra-class call graph."""
    scopes = scan.scopes

    def resolve_call(caller: _Scope, name: str) -> Optional[str]:
        # a local nested def shadows a method of the same name
        nested = f"{caller.qual}.{name}"
        if nested in scopes:
            return nested
        if name in scopes and scopes[name].is_method:
            return name
        return None

    edges: Dict[str, Set[str]] = {q: set() for q in scopes}
    for q, s in scopes.items():
        for name in s.calls:
            callee = resolve_call(s, name)
            if callee is not None:
                edges[q].add(callee)

    # thread roots: scopes named as Thread targets anywhere in the class
    roots: Set[str] = set()
    for s in scopes.values():
        for tname in s.thread_targets:
            if tname in scopes:
                roots.add(tname)

    def reach(starts: Set[str]) -> Set[str]:
        seen = set(starts)
        frontier = list(starts)
        while frontier:
            q = frontier.pop()
            for nxt in edges.get(q, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen

    from_roots = reach(roots)
    # public surface: every method not starting with "_" plus __init__
    # (external callers), and every nested def they reach
    public = {q for q, s in scopes.items()
              if s.is_method and (not s.name.startswith("_")
                                  or s.name == "__init__")}
    from_public = reach(public)
    worker_only = from_roots - from_public

    # helper credit: a PRIVATE method whose every intra-class call site
    # is locked counts as locked itself (lock held by caller); iterate
    # to fixpoint so credit flows through helper chains
    locked_scopes: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for q, s in scopes.items():
            if q in locked_scopes:
                continue
            if not (s.name.startswith("_") and s.name != "__init__"):
                continue
            callers = [(cq, cs) for cq, cs in scopes.items()
                       if q in edges.get(cq, ())]
            if not callers:
                continue
            if all(cs.qual in locked_scopes
                   or _all_calls_locked(scan, cs, s.name)
                   for _cq, cs in callers):
                locked_scopes.add(q)
                changed = True
    return roots, worker_only, locked_scopes


def _all_calls_locked(scan: _ClassScan, caller: _Scope,
                      callee_name: str) -> bool:
    """Every ``self.<callee_name>(...)`` / ``<callee_name>(...)`` call
    in ``caller`` runs under a held lock (per the caller's recorded
    call locksets)."""
    sites = getattr(caller, "_call_locks", {}).get(callee_name)
    return bool(sites) and all(sites)


class _CallLockScan(ast.NodeVisitor):
    """Second pass per scope: record whether each intra-class call runs
    under a held lock (feeds the helper credit)."""

    def __init__(self, scan: _ClassScan):
        self.scan = scan

    def run(self):
        for stmt in self.scan.cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(stmt, None)

    def _walk_function(self, node, parent_qual):
        qual = node.name if parent_qual is None else \
            f"{parent_qual}.{node.name}"
        scope = self.scan.scopes.get(qual)
        if scope is None:
            return
        scope._call_locks = {}        # type: ignore[attr-defined]
        self._held = 0
        self._scope = scope
        self._qual = qual
        for stmt in node.body:
            self._walk(stmt, qual)

    def _walk(self, node, qual):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            saved_held, saved_scope = self._held, self._scope
            self._walk_function(node, qual)
            self._held, self._scope = saved_held, saved_scope
            return
        if isinstance(node, ast.With):
            locked = sum(
                1 for item in node.items
                if self.scan._lock_name(item.context_expr) is not None)
            self._held += locked
            for stmt in node.body:
                self._walk(stmt, qual)
            self._held -= locked
            return
        if isinstance(node, ast.Call):
            fn = node.func
            name = None
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "self"):
                name = fn.attr
            elif isinstance(fn, ast.Name):
                name = fn.id
            if name is not None:
                self._scope._call_locks.setdefault(name, []).append(
                    self._held > 0)
        for child in ast.iter_child_nodes(node):
            self._walk(child, qual)


def _class_findings(rel: str, cls_node: ast.ClassDef,
                    reg: ExclusionRegistry,
                    candidates: List[str]) -> List[Finding]:
    scan = _ClassScan(cls_node)
    owns_thread = any(s.makes_thread for s in scan.scopes.values())
    if not scan.lock_attrs and not owns_thread:
        return []            # not declared thread-shared: out of scope
    _CallLockScan(scan).run()
    roots, worker_only, locked_scopes = _resolve_scopes(scan)

    # collect per-attr sites (scope, line, kind, locked?)
    attr_sites: Dict[str, List[Tuple[str, int, str, bool]]] = {}
    for q, s in scan.scopes.items():
        if s.is_method and s.name == "__init__":
            continue         # single-threaded construction
        for attr, sites in s.mutations.items():
            for line, kind, held in sites:
                locked = bool(held) or q in locked_scopes
                attr_sites.setdefault(attr, []).append(
                    (q, line, kind, locked))

    out: List[Finding] = []
    for attr, sites in sorted(attr_sites.items()):
        key = f"{rel}:{cls_node.name}.{attr}"
        any_locked = any(locked for _q, _l, _k, locked in sites)
        unlocked = [(q, line, kind) for q, line, kind, locked in sites
                    if not locked]
        if not unlocked:
            continue
        all_worker_only = all(q in worker_only for q, _l, _k, locked
                              in sites if not locked)
        problem = None
        if any(kind in ("rmw", "mutate") for _q, _l, kind in unlocked):
            if not (all_worker_only and not any_locked):
                problem = ("read-modify-write/container mutation outside "
                           "the lock")
        if problem is None and any_locked:
            # plain rebinds are only a finding when the attr is locked
            # elsewhere (inconsistent lockset)
            if not all_worker_only:
                problem = ("attribute locked at some sites but rebound "
                           "unlocked at others (inconsistent lockset)")
        if problem is None:
            continue
        candidates.append(key)
        if reg.excuses(key):
            continue
        q, line, kind = unlocked[0]
        lines = sorted({l for _q, l, _k in unlocked})
        out.append(Finding(
            "lock-discipline", rel, line,
            f"{cls_node.name}.{attr}: {problem} "
            f"(unlocked sites: {lines}; scopes: "
            f"{sorted({uq for uq, _l, _k in unlocked})})",
            hint="hold the class lock at every mutation site, or add "
                 f"{key!r} to analysis.registries.SHARED_UNLOCKED with "
                 "a reason"))
    return out


# ---------------------------------------------------------------------------
# module-global discipline
# ---------------------------------------------------------------------------

class _ModuleScan(ast.NodeVisitor):
    """Module-level locks + container globals + per-function mutations
    of them."""

    def __init__(self, tree: ast.Module):
        self.locks: Set[str] = set()
        self.containers: Set[str] = set()
        self.sites: Dict[str, List[Tuple[str, int, str, bool]]] = {}
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call):
                fn = stmt.value.func
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                for t in stmt.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if name in LOCK_CONSTRUCTORS:
                        self.locks.add(t.id)
                    elif name in CONTAINER_CONSTRUCTORS:
                        self.containers.add(t.id)
            elif isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, (ast.Dict, ast.List, ast.Set)):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.containers.add(t.id)
        if not self.locks:
            return
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(stmt)

    def _walk_function(self, node):
        self._qual = node.name
        self._held = 0
        for stmt in node.body:
            self._walk(stmt)

    def _walk(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            saved_q, saved_h = self._qual, self._held
            self._walk_function(node)
            self._qual, self._held = saved_q, saved_h
            return
        if isinstance(node, ast.With):
            locked = sum(1 for item in node.items
                         if isinstance(item.context_expr, ast.Name)
                         and item.context_expr.id in self.locks)
            self._held += locked
            for stmt in node.body:
                self._walk(stmt)
            self._held -= locked
            return
        if isinstance(node, ast.AugAssign):
            name = _base_name(node.target)
            if name in self.containers:
                self.sites.setdefault(name, []).append(
                    (self._qual, node.lineno, "rmw", self._held > 0))
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = _base_name(t)
                    if name in self.containers:
                        self.sites.setdefault(name, []).append(
                            (self._qual, node.lineno, "mutate",
                             self._held > 0))
        elif isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in MUTATOR_METHODS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in self.containers):
                self.sites.setdefault(fn.value.id, []).append(
                    (self._qual, node.lineno, "mutate", self._held > 0))
        for child in ast.iter_child_nodes(node):
            self._walk(child)


def _module_findings(rel: str, tree: ast.Module, reg: ExclusionRegistry,
                     candidates: List[str]) -> List[Finding]:
    scan = _ModuleScan(tree)
    out: List[Finding] = []
    for name, sites in sorted(scan.sites.items()):
        unlocked = [(q, line, kind) for q, line, kind, locked in sites
                    if not locked]
        if not unlocked:
            continue
        key = f"{rel}:<module>.{name}"
        candidates.append(key)
        if reg.excuses(key):
            continue
        _q, line, _k = unlocked[0]
        out.append(Finding(
            "lock-discipline", rel, line,
            f"module global {name!r} mutated outside the module lock "
            f"(sites: {sorted({l for _sq, l, _sk in unlocked})})",
            hint="hold the module lock, or add "
                 f"{key!r} to analysis.registries.SHARED_UNLOCKED with "
                 "a reason"))
    return out


def lock_discipline_findings(corpus: Corpus,
                             exclusions=None) -> List[Finding]:
    reg = ExclusionRegistry(
        "lock-discipline", "SHARED_UNLOCKED",
        registries.SHARED_UNLOCKED if exclusions is None else exclusions)
    out: List[Finding] = []
    candidates: List[str] = []
    for rel, sf in corpus.items():
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                out.extend(_class_findings(rel, node, reg, candidates))
        out.extend(_module_findings(rel, sf.tree, reg, candidates))
    out.extend(reg.hygiene_findings(candidates))
    return out


@rule("lock-discipline",
      "thread-shared mutable state (classes owning locks/threads, "
      "locked modules) is mutated under a consistent lockset or sits on "
      "SHARED_UNLOCKED with a reason")
def _lock_discipline(corpus: Corpus) -> List[Finding]:
    return lock_discipline_findings(corpus)


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------

class _ThreadScan(ast.NodeVisitor):
    """Every ``threading.Thread(...)`` creation: daemon kwarg, the
    target it was assigned to (for the join check), and its scope."""

    def __init__(self):
        self.sites: List[dict] = []
        self._stack: List[str] = []

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call) and self._is_thread(
                node.value):
            names = []
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                attr = _self_attr(t)
                if attr is not None:
                    names.append(attr)
            self._record(node.value, names)
        self.generic_visit(node)

    def visit_Call(self, node):
        if self._is_thread(node):
            # bare Thread(...) calls not captured by an Assign above
            if not any(s["node"] is node for s in self.sites):
                self._record(node, [])
        self.generic_visit(node)

    @staticmethod
    def _is_thread(call: ast.Call) -> bool:
        fn = call.func
        return ((isinstance(fn, ast.Attribute) and fn.attr == "Thread")
                or (isinstance(fn, ast.Name) and fn.id == "Thread"))

    def _record(self, call: ast.Call, names: List[str]):
        daemon = None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        self.sites.append({
            "node": call, "line": call.lineno, "daemon": daemon,
            "names": names,
            "qual": ".".join(self._stack) if self._stack else "<module>"})


def thread_lifecycle_findings(corpus: Corpus,
                              exclusions=None) -> List[Finding]:
    reg = ExclusionRegistry(
        "thread-lifecycle", "UNMANAGED_THREADS",
        registries.UNMANAGED_THREADS if exclusions is None
        else exclusions)
    out: List[Finding] = []
    candidates: List[str] = []
    for rel, sf in corpus.items():
        scan = _ThreadScan()
        scan.visit(sf.tree)
        for site in scan.sites:
            if site["daemon"] is True:
                continue
            # anchored matches: `out.join(` must not satisfy a thread
            # variable named `t`
            joined = any(
                re.search(rf"\b(?:self\.)?{re.escape(name)}\.join\(",
                          sf.text)
                for name in site["names"])
            # `.daemon = True` set post-construction on a named target
            daemonized = any(
                re.search(rf"\b(?:self\.)?{re.escape(name)}"
                          rf"\.daemon\s*=\s*True", sf.text)
                for name in site["names"])
            if joined or daemonized:
                continue
            key = f"{rel}:{site['qual']}"
            candidates.append(key)
            if reg.excuses(key):
                continue
            out.append(Finding(
                "thread-lifecycle", rel, site["line"],
                f"thread started in {site['qual']} has no daemon flag "
                f"and no join/shutdown path in its module",
                hint="pass daemon=True or join the thread on shutdown, "
                     f"or add {key!r} to "
                     "analysis.registries.UNMANAGED_THREADS with a "
                     "reason"))
    out.extend(reg.hygiene_findings(candidates))
    return out


@rule("thread-lifecycle",
      "every started threading.Thread has a daemon flag or a registered "
      "join/shutdown path (or sits on UNMANAGED_THREADS with a reason)")
def _thread_lifecycle(corpus: Corpus) -> List[Finding]:
    return thread_lifecycle_findings(corpus)

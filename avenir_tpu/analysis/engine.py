"""The analysis engine: shared-parse corpus, rule registry, findings.

Every rule used to re-walk the package with its own ``os.walk`` +
``ast.parse`` loop (four coverage test modules, ~900 lines); here the
package is parsed ONCE into a :class:`Corpus` and every registered rule
checks the shared trees.  Rules return structured :class:`Finding` s so
one CLI (``python -m avenir_tpu analyze``) and one tier-1 test can run
the whole catalog with text or JSON output.
"""

from __future__ import annotations

import ast
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence


class SourceFile:
    """One parsed package module (parse happens once, in Corpus)."""

    __slots__ = ("rel", "path", "text", "tree")

    def __init__(self, rel: str, path: str, text: str, tree: ast.AST):
        self.rel = rel          # package-relative, e.g. "core/io.py"
        self.path = path
        self.text = text
        self.tree = tree


class Corpus:
    """Every ``.py`` under one root, parsed once and shared by all
    rules.  ``readme`` is the documentation surface the config-key rule
    checks (None = no README check)."""

    def __init__(self, root: str, readme_path: Optional[str] = None):
        self.root = root
        self.readme_path = readme_path
        self.files: Dict[str, SourceFile] = {}
        self._readme: Optional[str] = None
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path) as fh:
                    text = fh.read()
                self.files[rel] = SourceFile(
                    rel, path, text, ast.parse(text, filename=path))

    @property
    def readme(self) -> str:
        if self._readme is None:
            if self.readme_path and os.path.exists(self.readme_path):
                with open(self.readme_path) as fh:
                    self._readme = fh.read()
            else:
                self._readme = ""
        return self._readme

    def get(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel)

    def items(self):
        return sorted(self.files.items())


class Finding:
    """One structured rule violation.

    ``tag`` subdivides a rule's findings: ``violation`` (the rule's own
    check), ``stale-exclusion`` (a registry entry whose site no longer
    exists or no longer violates), ``empty-reason`` (a registry entry
    without a written reason).  All three fail ``--strict``."""

    __slots__ = ("rule", "file", "line", "message", "hint", "tag")

    def __init__(self, rule: str, file: str, line: int, message: str,
                 hint: str = "", tag: str = "violation"):
        self.rule = rule
        self.file = file
        self.line = int(line)
        self.message = message
        self.hint = hint
        self.tag = tag

    def format(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        s = f"{self.rule}  {loc}  {self.message}"
        if self.hint:
            s += f"  [fix: {self.hint}]"
        return s

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message, "hint": self.hint,
                "tag": self.tag}

    def __repr__(self):
        return f"Finding({self.format()!r})"


class Rule:
    """One registered check: ``fn(corpus) -> [Finding]``.

    ``scope`` is ``"source"`` for pure-AST rules (they run on any
    corpus, including test fixtures) or ``"project"`` for rules that
    import the real package (driver registry introspection) and only
    make sense against the installed ``avenir_tpu``."""

    __slots__ = ("id", "doc", "fn", "scope")

    def __init__(self, rule_id: str, doc: str,
                 fn: Callable[[Corpus], List[Finding]],
                 scope: str = "source"):
        if scope not in ("source", "project"):
            raise ValueError(f"bad rule scope: {scope!r}")
        self.id = rule_id
        self.doc = doc
        self.fn = fn
        self.scope = scope

    def check(self, corpus: Corpus) -> List[Finding]:
        return self.fn(corpus)


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, doc: str, scope: str = "source"):
    """Decorator registering ``fn(corpus) -> [Finding]`` under a stable
    rule id (the id findings, exclusions, and ``--rules`` name)."""
    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id: {rule_id}")
        RULES[rule_id] = Rule(rule_id, doc, fn, scope)
        return fn
    return deco


def all_rule_ids() -> List[str]:
    return sorted(RULES)


_PACKAGE_CORPUS: Optional[Corpus] = None


def load_package_corpus(fresh: bool = False) -> Corpus:
    """The corpus every default run analyzes: the installed
    ``avenir_tpu`` package, with the repo README as the doc surface.
    Cached per process (one parse feeds the CLI, the tier-1 wrapper,
    and every coverage shim); ``fresh=True`` re-parses."""
    global _PACKAGE_CORPUS
    if _PACKAGE_CORPUS is None or fresh:
        import avenir_tpu
        pkg = os.path.dirname(os.path.abspath(avenir_tpu.__file__))
        _PACKAGE_CORPUS = Corpus(pkg, readme_path=os.path.join(
            os.path.dirname(pkg), "README.md"))
    return _PACKAGE_CORPUS


def run_rules(corpus: Corpus,
              rule_ids: Optional[Sequence[str]] = None,
              scopes: Sequence[str] = ("source", "project")):
    """Run the selected rules over one shared corpus.

    Returns ``(findings, report)`` where ``report`` is the JSON-ready
    run summary (per-rule finding counts and durations)."""
    if rule_ids is None:
        selected = [RULES[r] for r in all_rule_ids()
                    if RULES[r].scope in scopes]
    else:
        unknown = sorted(set(rule_ids) - set(RULES))
        if unknown:
            raise KeyError(
                f"unknown rule id(s): {unknown}; known: {all_rule_ids()}")
        selected = [RULES[r] for r in rule_ids]
    findings: List[Finding] = []
    per_rule = []
    t0 = time.monotonic()
    for r in selected:
        rt0 = time.monotonic()
        got = r.check(corpus)
        findings.extend(got)
        per_rule.append({"rule": r.id, "findings": len(got),
                         "ms": round((time.monotonic() - rt0) * 1e3, 2)})
    findings.sort(key=lambda f: (f.rule, f.file, f.line))
    report = {"root": corpus.root,
              "files": len(corpus.files),
              "rules": per_rule,
              "findings": [f.to_dict() for f in findings],
              "total_findings": len(findings),
              "duration_ms": round((time.monotonic() - t0) * 1e3, 2)}
    return findings, report


def write_json_report(path: str, report: dict) -> None:
    """Atomic JSON findings report (the CI artifact)."""
    from ..core.io import atomic_write_text
    atomic_write_text(path, json.dumps(report, indent=2) + "\n")


# ---------------------------------------------------------------------------
# shared AST helpers (used by several rule modules)
# ---------------------------------------------------------------------------

class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the enclosing class/function qualname stack
    (the ``Class.method`` / ``func.<locals>`` naming the legacy walkers
    used)."""

    def __init__(self):
        self.stack: List[str] = []

    def qual(self) -> str:
        return ".".join(self.stack) if self.stack else "<module>"

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def enclosing_scope_source(text: str, lineno: int, tree=None) -> str:
    """Source of the innermost function/class whose body spans
    ``lineno`` (1-based) — the scope a required call must live in.
    Pass the SourceFile's already-parsed ``tree`` to honor the
    one-parse-per-file contract; the re-parse is a fallback for raw
    text."""
    if tree is None:
        tree = ast.parse(text)
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.lineno <= lineno <= (node.end_lineno or node.lineno):
                if best is None or node.lineno > best.lineno:
                    best = node
    if best is None:
        return text
    return "\n".join(text.splitlines()[best.lineno - 1:best.end_lineno])
